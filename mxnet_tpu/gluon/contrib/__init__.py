"""Gluon contrib (ref: python/mxnet/gluon/contrib/__init__.py)."""
from . import estimator
from . import nn

__all__ = ["estimator", "nn"]
