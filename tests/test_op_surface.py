"""Tests for the round-3 op-surface push: linalg completion, CustomOp,
image ops, quantization (model: tests/python/unittest/test_operator.py
linalg section, test_operator.py::test_custom_op, test_image.py,
test_quantization.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.test_utils import assert_almost_equal


def _rand_pd(n, rng):
    a = rng.randn(n, n).astype("float32")
    return a @ a.T + n * np.eye(n, dtype="float32")


# ---------------------------------------------------------------------------
# linalg
# ---------------------------------------------------------------------------

def test_linalg_gemm():
    rng = np.random.RandomState(0)
    a = rng.randn(3, 4).astype("float32")
    b = rng.randn(4, 5).astype("float32")
    c = rng.randn(3, 5).astype("float32")
    got = nd.linalg_gemm(nd.array(a), nd.array(b), nd.array(c),
                         alpha=2.0, beta=0.5).asnumpy()
    assert_almost_equal(got, 2.0 * a @ b + 0.5 * c, rtol=1e-5, atol=1e-5)
    got_t = nd.linalg_gemm(nd.array(a.T), nd.array(b), nd.array(c),
                           transpose_a=True).asnumpy()
    assert_almost_equal(got_t, a @ b + c, rtol=1e-5, atol=1e-5)


def test_linalg_potri_inverts():
    rng = np.random.RandomState(1)
    a = _rand_pd(5, rng)
    l = nd.linalg_potrf(nd.array(a))
    ainv = nd.linalg_potri(l).asnumpy()
    assert_almost_equal(ainv @ a, np.eye(5, dtype="float32"), rtol=1e-3,
                        atol=1e-3)


def test_linalg_trmm_trsm_roundtrip():
    rng = np.random.RandomState(2)
    a = np.tril(rng.randn(4, 4).astype("float32")) + 4 * np.eye(4, dtype="f4")
    b = rng.randn(4, 3).astype("float32")
    prod = nd.linalg_trmm(nd.array(a), nd.array(b), alpha=2.0)
    back = nd.linalg_trsm(nd.array(a), prod, alpha=0.5).asnumpy()
    assert_almost_equal(back, b, rtol=1e-4, atol=1e-4)
    # rightside: X = B @ tril(A); solve recovers B
    prod_r = nd.linalg_trmm(nd.array(a), nd.array(b.T), rightside=True)
    back_r = nd.linalg_trsm(nd.array(a), prod_r, rightside=True).asnumpy()
    assert_almost_equal(back_r, b.T, rtol=1e-4, atol=1e-4)


def test_linalg_det_inverse_slogdet():
    rng = np.random.RandomState(3)
    a = _rand_pd(4, rng)
    det = float(nd.linalg_det(nd.array(a)).asnumpy())
    assert det == pytest.approx(np.linalg.det(a), rel=1e-3)
    inv = nd.linalg_inverse(nd.array(a)).asnumpy()
    assert_almost_equal(inv @ a, np.eye(4, dtype="f4"), rtol=1e-3, atol=1e-3)
    sign, logdet = nd.linalg_slogdet(nd.array(a))
    assert float(sign.asnumpy()) == 1.0
    assert float(logdet.asnumpy()) == pytest.approx(np.log(det), rel=1e-4)


def test_linalg_syevd_reconstructs():
    rng = np.random.RandomState(4)
    a = _rand_pd(5, rng)
    u, lam = nd.linalg_syevd(nd.array(a))
    u, lam = u.asnumpy(), lam.asnumpy()
    # reference convention: A = U^T diag(lam) U
    assert_almost_equal(u.T @ np.diag(lam) @ u, a, rtol=1e-3, atol=1e-3)


def test_linalg_gelqf():
    rng = np.random.RandomState(5)
    a = rng.randn(3, 6).astype("float32")
    l, q = nd.linalg_gelqf(nd.array(a))
    l, q = l.asnumpy(), q.asnumpy()
    assert_almost_equal(l @ q, a, rtol=1e-4, atol=1e-4)
    assert_almost_equal(q @ q.T, np.eye(3, dtype="f4"), rtol=1e-4,
                        atol=1e-4)
    assert_almost_equal(l, np.tril(l), rtol=1e-4, atol=1e-4)


def test_linalg_diag_trian_roundtrip():
    rng = np.random.RandomState(6)
    a = rng.randn(4, 4).astype("float32")
    d = nd.linalg_extractdiag(nd.array(a)).asnumpy()
    assert_almost_equal(d, np.diag(a))
    md = nd.linalg_makediag(nd.array(d)).asnumpy()
    assert_almost_equal(md, np.diag(np.diag(a)))
    packed = nd.linalg_extracttrian(nd.array(a))
    full = nd.linalg_maketrian(packed).asnumpy()
    assert_almost_equal(full, np.tril(a), rtol=1e-6, atol=1e-6)
    pd = _rand_pd(3, rng)
    assert float(nd.linalg_sumlogdiag(nd.array(pd)).asnumpy()) == \
        pytest.approx(np.sum(np.log(np.diag(pd))), rel=1e-4)


def test_linalg_grad_flows():
    from mxnet_tpu.test_utils import check_numeric_gradient

    rng = np.random.RandomState(7)
    a = _rand_pd(3, rng)
    check_numeric_gradient(lambda x: nd.linalg_det(x), [a], rtol=5e-2,
                           atol=5e-2)


# ---------------------------------------------------------------------------
# CustomOp
# ---------------------------------------------------------------------------

class _Sigmoid(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        self.assign(out_data[0], req[0], 1.0 / (1.0 + np.exp(-x)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0].asnumpy()
        self.assign(in_grad[0], req[0], out_grad[0].asnumpy() * y * (1 - y))


@mx.operator.register("test_sigmoid")
class _SigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return _Sigmoid()


def test_custom_op_eager_forward_backward():
    x = np.array([[-1.0, 0.0, 2.0]], "float32")
    xn = nd.array(x)
    xn.attach_grad()
    with mx.autograd.record():
        y = nd.Custom(xn, op_type="test_sigmoid")
    expect = 1.0 / (1.0 + np.exp(-x))
    assert_almost_equal(y.asnumpy(), expect, rtol=1e-5, atol=1e-6)
    y.backward()
    assert_almost_equal(xn.grad.asnumpy(), expect * (1 - expect),
                        rtol=1e-5, atol=1e-6)


def test_custom_op_inside_jit():
    """The host callback must work under jit/trace (hybridized nets)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.registry import apply_pure

    @jax.jit
    def f(v):
        return apply_pure("Custom", v, op_type="test_sigmoid") * 2.0

    v = jnp.asarray([0.0, 1.0], jnp.float32)
    out = np.asarray(f(v))
    assert_almost_equal(out, 2.0 / (1.0 + np.exp(-np.asarray(v))),
                        rtol=1e-5, atol=1e-6)
    g = jax.grad(lambda v: apply_pure(
        "Custom", v, op_type="test_sigmoid").sum())(v)
    s = 1.0 / (1.0 + np.exp(-np.asarray(v)))
    assert_almost_equal(np.asarray(g), s * (1 - s), rtol=1e-5, atol=1e-6)


class _TwoOut(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        self.assign(out_data[0], req[0], x * 2)
        self.assign(out_data[1], req[1], x + 1)


@mx.operator.register("test_twoout")
class _TwoOutProp(mx.operator.CustomOpProp):
    def list_outputs(self):
        return ["double", "plus1"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0], in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return _TwoOut()


def test_custom_op_multi_output():
    x = np.arange(4, dtype="float32")
    a, b = nd.Custom(nd.array(x), op_type="test_twoout")
    assert_almost_equal(a.asnumpy(), x * 2)
    assert_almost_equal(b.asnumpy(), x + 1)


def test_custom_op_unknown_type_is_loud():
    with pytest.raises(MXNetError, match="unknown custom op_type"):
        nd.Custom(nd.zeros((2,)), op_type="never_registered")


# ---------------------------------------------------------------------------
# image ops
# ---------------------------------------------------------------------------

def test_image_to_tensor_and_normalize():
    img = np.random.randint(0, 255, (8, 6, 3), np.uint8)
    t = nd.image.to_tensor(nd.array(img)).asnumpy()
    assert t.shape == (3, 8, 6)
    assert_almost_equal(t, img.transpose(2, 0, 1).astype("f4") / 255.0)
    n = nd.image.normalize(nd.array(t), mean=(0.5, 0.5, 0.5),
                           std=(0.1, 0.2, 0.5)).asnumpy()
    assert_almost_equal(n[1], (t[1] - 0.5) / 0.2, rtol=1e-5, atol=1e-6)


def test_image_resize_crop_flip():
    img = np.arange(4 * 6 * 3, dtype=np.uint8).reshape(4, 6, 3)
    r = nd.image.resize(nd.array(img), size=(3, 2)).asnumpy()  # (w,h)
    assert r.shape == (2, 3, 3)
    c = nd.image.crop(nd.array(img), 1, 0, 4, 3).asnumpy()
    assert c.shape == (3, 4, 3)
    assert np.array_equal(c, img[0:3, 1:5])
    f = nd.image.flip_left_right(nd.array(img)).asnumpy()
    assert np.array_equal(f, img[:, ::-1])


def test_image_random_ops_keyed():
    mx.random.seed(0)
    img = np.random.randint(0, 255, (6, 6, 3), np.uint8)
    outs = {nd.image.random_flip_left_right(nd.array(img))
            .asnumpy().tobytes() for _ in range(32)}
    assert len(outs) == 2  # flipped and unflipped both occur
    b = nd.image.random_brightness(nd.array(img), 0.5, 1.5).asnumpy()
    assert b.shape == img.shape


# ---------------------------------------------------------------------------
# quantization
# ---------------------------------------------------------------------------

def test_quantize_dequantize_roundtrip_uint8():
    rng = np.random.RandomState(0)
    x = rng.uniform(-3, 8, (4, 5)).astype("float32")
    q, qmin, qmax = nd.quantize(nd.array(x), nd.array([x.min()]),
                                nd.array([x.max()]), out_type="uint8")
    assert q.asnumpy().dtype == np.uint8
    back = nd.dequantize(q, qmin, qmax).asnumpy()
    scale = (x.max() - min(x.min(), 0)) / 255.0
    assert np.abs(back - x).max() <= scale + 1e-5


def test_quantize_v2_int8_self_calibrated():
    rng = np.random.RandomState(1)
    x = rng.uniform(-2, 2, (64,)).astype("float32")
    q, qmin, qmax = nd.quantize_v2(nd.array(x), out_type="int8")
    assert q.asnumpy().dtype == np.int8
    back = nd.dequantize(q, qmin, qmax).asnumpy()
    assert np.abs(back - x).max() <= (2.0 / 127) + 1e-5


def test_quantized_kernels_raise_informatively():
    # conv/fc/pooling are REAL int8 kernels now (test_quantization.py);
    # only the elementwise variants remain redundant-by-design stubs
    with pytest.raises(MXNetError, match="fuses the converts"):
        nd._contrib_quantized_act(nd.zeros((1, 3, 4, 4)))
    with pytest.raises(MXNetError, match="int8 data and weight"):
        nd.quantized_conv(
            nd.zeros((1, 3, 4, 4)), nd.zeros((4, 3, 3, 3)),
            nd.zeros((1,)), nd.zeros((1,)), nd.zeros((1,)),
            nd.zeros((1,)), kernel=(3, 3), num_filter=4)


# ---------------------------------------------------------------------------
# typed op descriptors (the dmlc::Parameter role)
# ---------------------------------------------------------------------------

def test_unknown_attr_is_loud_eager():
    x = nd.zeros((2, 3, 8, 8))
    w = nd.zeros((4, 3, 3, 3))
    with pytest.raises(MXNetError, match="no attribute 'kernal'"):
        nd.Convolution(x, w, kernal=(3, 3), num_filter=4)  # typo'd kernel


def test_unknown_attr_is_loud_symbol():
    from mxnet_tpu import symbol as sym

    d = sym.var("data")
    with pytest.raises(MXNetError, match="no attribute"):
        sym.Pooling(d, kernel=(2, 2), stridez=(2, 2))  # typo'd stride


def test_string_attrs_coerced():
    """Reference-style string attr values parse to the declared type."""
    x = nd.array(np.random.randn(2, 12).astype("f4"))
    got = nd.reshape(x, shape="(2, 3, 4)")
    assert got.shape == (2, 3, 4)
    bad = nd.zeros((2, 2))
    with pytest.raises(MXNetError, match="cannot parse"):
        nd.sum(bad, keepdims="not-a-bool(")


def test_generated_docstrings():
    assert "num_filter" in nd.Convolution.__doc__
    assert "Attributes:" in nd.Convolution.__doc__
    from mxnet_tpu import symbol as sym

    assert "pool_type" in sym.Pooling.__doc__


class _TrainAware(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        self.assign(out_data[0], req[0],
                    in_data[0].asnumpy() + (1.0 if is_train else 0.0))


@mx.operator.register("test_trainaware")
class _TrainAwareProp(mx.operator.CustomOpProp):
    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return _TrainAware()


def test_custom_op_sees_train_mode():
    x = nd.zeros((2,))
    assert nd.Custom(x, op_type="test_trainaware").asnumpy()[0] == 0.0
    with mx.autograd.record():
        y = nd.Custom(x, op_type="test_trainaware")
    assert y.asnumpy()[0] == 1.0


def test_linalg_gemm_axis():
    rng = np.random.RandomState(8)
    a = rng.randn(3, 2, 4).astype("f4")   # rows axis at 0
    b = rng.randn(4, 2, 5).astype("f4")
    c = rng.randn(3, 2, 5).astype("f4")
    got = nd.linalg_gemm(nd.array(a), nd.array(b), nd.array(c),
                         axis=0).asnumpy()
    expect = np.einsum("ibk,kbj->ibj", a, b) + c
    assert_almost_equal(got, expect, rtol=1e-5, atol=1e-5)
