"""A lightweight StableHLO text parser — the IR under mxir (MX014–18).

Parses the module text jax emits (``lowered.as_text()`` — the same
bytes the persistent compile cache stores under its ``stablehlo``
tier) into a flat, queryable structure: module attributes, functions
with per-argument sharding/donation attributes, per-result shardings,
and one record per op with operands, attribute dict, and input/output
tensor types.

This is deliberately NOT an MLIR parser.  It is a line-oriented
scanner with quote- and bracket-aware splitting that understands
exactly the textual shapes jax's StableHLO printer produces — enough
structure for the program-level rules, nothing more.  Anything it
does not recognize is skipped (an unknown line contributes no op);
anything *structurally* surprising raises :class:`IrParseError`, which
every caller converts to a counted ``parse_skipped``, never a crash.

Stdlib-only, like the rest of ``mxnet_tpu.analysis``.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = [
    "IrParseError", "TensorType", "FuncArg", "FuncResult", "Op",
    "Func", "Module", "parse_module", "parse_sharding", "Sharding",
]


class IrParseError(Exception):
    """The module text did not match the shapes this parser knows."""


# bytes per element for the dtypes jax programs actually carry; i4/i2
# round up to one byte (they pack on the wire, but the rules only
# compare against multi-megabyte thresholds where the factor-of-two
# never matters)
_ITEMSIZE = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4,
    "i16": 2, "ui16": 2, "i8": 1, "ui8": 1, "i4": 1, "ui4": 1,
    "i1": 1, "i2": 1,
}


def _itemsize(dtype: str) -> Optional[int]:
    if dtype in _ITEMSIZE:
        return _ITEMSIZE[dtype]
    if dtype.startswith("f8"):          # f8E4M3FN, f8E5M2, ...
        return 1
    if dtype.startswith("complex<f32"):
        return 8
    if dtype.startswith("complex<f64"):
        return 16
    return None


@dataclass(frozen=True)
class TensorType:
    """``tensor<4x8x256xf32>`` → shape (4, 8, 256), dtype 'f32'.
    Dynamic dims parse as None and poison ``nbytes``."""

    shape: Tuple[Optional[int], ...]
    dtype: str

    @property
    def nbytes(self) -> Optional[int]:
        size = _itemsize(self.dtype)
        if size is None:
            return None
        n = 1
        for d in self.shape:
            if d is None:
                return None
            n *= d
        return n * size


_TENSOR = re.compile(r"^tensor<(.*)>$", re.S)


def _parse_type(text: str) -> Optional[TensorType]:
    """TensorType for ``tensor<...>`` text; None for tokens/tuples/
    anything else (callers treat None as 'unknown, count nothing')."""
    m = _TENSOR.match(text.strip())
    if not m:
        return None
    inner = m.group(1)
    # encoding attribute tail: tensor<8x4xf32, #stablehlo.bounds<...>>
    inner = _split_top(inner, ",")[0].strip()
    parts = inner.split("x")
    dtype = parts[-1]
    dims: List[Optional[int]] = []
    for p in parts[:-1]:
        p = p.strip()
        if p == "?":
            dims.append(None)
        elif p.isdigit():
            dims.append(int(p))
        else:
            return None  # not a ranked tensor shape after all
    return TensorType(tuple(dims), dtype)


# ---------------------------------------------------------------------------
# quote/bracket-aware scanning: sharding strings embed braces INSIDE
# quoted attribute values ("{devices=[2,1]<=[2]}"), so depth tracking
# must ignore everything between double quotes
# ---------------------------------------------------------------------------

_OPEN = {"(": ")", "[": "]", "{": "}", "<": ">"}
_CLOSE = {v: k for k, v in _OPEN.items()}


def _split_top(s: str, sep: str) -> List[str]:
    """Split ``s`` at ``sep`` occurrences that sit at bracket depth 0
    and outside string quotes.  ``<`` / ``>`` count as brackets only in
    type position (``tensor<...>``); comparison text never appears at
    attribute top level in the printer's output."""
    out: List[str] = []
    depth = 0
    quoted = False
    start = 0
    i = 0
    n = len(s)
    ln = len(sep)
    while i < n:
        c = s[i]
        if quoted:
            if c == "\\":
                i += 2
                continue
            if c == '"':
                quoted = False
        elif c == '"':
            quoted = True
        elif c in _OPEN:
            depth += 1
        elif c in _CLOSE:
            depth = max(0, depth - 1)
        elif depth == 0 and s.startswith(sep, i):
            out.append(s[start:i])
            i += ln
            start = i
            continue
        i += 1
    out.append(s[start:])
    return out


def _find_top(s: str, sub: str, start: int = 0) -> int:
    """Index of the first ``sub`` at depth 0 outside quotes, else -1.
    The match test runs BEFORE depth bookkeeping so a ``sub`` that
    itself begins with a bracket ("{") is findable at depth 0."""
    depth = 0
    quoted = False
    i = start
    n = len(s)
    while i < n:
        c = s[i]
        if quoted:
            if c == "\\":
                i += 2
                continue
            if c == '"':
                quoted = False
        elif depth == 0 and c != '"' and s.startswith(sub, i):
            return i
        elif c == '"':
            quoted = True
        elif c in _OPEN:
            depth += 1
        elif c in _CLOSE:
            depth = max(0, depth - 1)
        i += 1
    return -1


def _matching(s: str, open_idx: int) -> int:
    """Index of the bracket closing ``s[open_idx]`` (quote-aware)."""
    opener = s[open_idx]
    closer = _OPEN[opener]
    depth = 0
    quoted = False
    i = open_idx
    n = len(s)
    while i < n:
        c = s[i]
        if quoted:
            if c == "\\":
                i += 2
                continue
            if c == '"':
                quoted = False
        elif c == '"':
            quoted = True
        elif c == opener:
            depth += 1
        elif c == closer:
            depth -= 1
            if depth == 0:
                return i
        i += 1
    raise IrParseError(f"unbalanced {opener!r} at index {open_idx}")


def _parse_attr_dict(body: str) -> Dict[str, str]:
    """``mhlo.sharding = "{replicated}", tf.aliasing_output = 0 : i32``
    → {"mhlo.sharding": "{replicated}", "tf.aliasing_output": "0"}.
    Values are raw text with surrounding quotes and ``: type`` suffixes
    stripped; flag attributes (no ``=``) map to ""."""
    attrs: Dict[str, str] = {}
    for item in _split_top(body, ","):
        item = item.strip()
        if not item:
            continue
        eq = _find_top(item, "=")
        if eq < 0:
            attrs[item] = ""
            continue
        key = item[:eq].strip()
        val = item[eq + 1:].strip()
        colon = _find_top(val, " : ")
        if colon >= 0:
            val = val[:colon].strip()
        if len(val) >= 2 and val[0] == '"' and val[-1] == '"':
            val = val[1:-1]
        attrs[key] = val
    return attrs


# ---------------------------------------------------------------------------
# sharding annotations
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Sharding:
    """Classified GSPMD sharding text.  ``kind`` is 'replicated',
    'devices', 'maximal', 'manual', or 'other'; ``tile`` holds the
    devices-form tile dims (the trailing replication dim already
    dropped when ``last_tile_dim_replicate`` was present)."""

    kind: str
    text: str
    tile: Tuple[int, ...] = ()

    @property
    def is_replicated(self) -> bool:
        if self.kind == "replicated":
            return True
        return self.kind == "devices" and all(t == 1 for t in self.tile)

    @property
    def sharded_dims(self) -> Tuple[int, ...]:
        return tuple(i for i, t in enumerate(self.tile) if t > 1)


_DEVICES = re.compile(r"devices=\[([0-9,]+)\]")


def parse_sharding(text: Optional[str]) -> Optional[Sharding]:
    if text is None:
        return None
    t = text.strip()
    if t.startswith("{") and t.endswith("}"):
        t = t[1:-1].strip()
    if t == "replicated":
        return Sharding("replicated", text)
    if t == "manual":
        return Sharding("manual", text)
    if t.startswith("maximal"):
        return Sharding("maximal", text)
    m = _DEVICES.search(t)
    if m:
        tile = tuple(int(x) for x in m.group(1).split(",") if x)
        if "last_tile_dim_replicate" in t and len(tile) > 1:
            tile = tile[:-1]
        return Sharding("devices", text, tile)
    return Sharding("other", text)


# ---------------------------------------------------------------------------
# module structure
# ---------------------------------------------------------------------------

@dataclass
class FuncArg:
    name: str                       # "%arg0"
    type: Optional[TensorType]
    attrs: Dict[str, str] = field(default_factory=dict)

    @property
    def sharding(self) -> Optional[Sharding]:
        return parse_sharding(self.attrs.get("mhlo.sharding"))

    @property
    def alias_output(self) -> Optional[int]:
        v = self.attrs.get("tf.aliasing_output")
        if v is None:
            v = self.attrs.get("jax.buffer_donor")
            return 0 if v == "true" else None
        try:
            return int(v)
        except ValueError:
            return None


@dataclass
class FuncResult:
    type: Optional[TensorType]
    attrs: Dict[str, str] = field(default_factory=dict)

    @property
    def sharding(self) -> Optional[Sharding]:
        return parse_sharding(self.attrs.get("mhlo.sharding"))


@dataclass
class Op:
    name: str                       # "stablehlo.add", "call", ...
    results: List[str]              # SSA ids ("%3"), may be empty
    operands: List[str]
    attrs: Dict[str, str]
    in_types: List[Optional[TensorType]]
    out_types: List[Optional[TensorType]]
    line: int                       # 1-based line in the module text
    target: str = ""                # custom_call "@Sharding" / call "@fn"

    @property
    def sharding(self) -> Optional[Sharding]:
        return parse_sharding(self.attrs.get("mhlo.sharding"))


@dataclass
class Func:
    name: str                       # "main"
    visibility: str                 # "public" / "private" / ""
    args: List[FuncArg]
    results: List[FuncResult]
    ops: List[Op] = field(default_factory=list)
    returns: List[str] = field(default_factory=list)  # returned SSA ids
    line: int = 0


@dataclass
class Module:
    name: str
    num_partitions: int
    num_replicas: int
    funcs: Dict[str, Func]

    @property
    def main(self) -> Optional[Func]:
        if "main" in self.funcs:
            return self.funcs["main"]
        for f in self.funcs.values():
            if f.visibility == "public":
                return f
        return next(iter(self.funcs.values()), None)


_SSA = re.compile(r"%[A-Za-z0-9_]+")
_RESULTS = re.compile(
    r"^%[A-Za-z0-9_]+(?::\d+)?(?:\s*,\s*%[A-Za-z0-9_]+(?::\d+)?)*$")
_INT_ATTR = re.compile(r"=\s*(-?\d+)\s*:\s*i\d+")


def _module_attr(header: str, key: str) -> int:
    m = re.search(re.escape(key) + r"\s*=\s*(\d+)", header)
    return int(m.group(1)) if m else 1


def _parse_func_header(header: str, line: int) -> Func:
    """``func.func public @main(%arg0: T {attrs}, ...) -> (T {attrs})``
    (the trailing `` {`` already stripped)."""
    at = header.index("@")
    lp = header.index("(", at)
    name = header[at + 1:lp].strip()
    visibility = ""
    for vis in ("public", "private"):
        if f" {vis} " in header[:at]:
            visibility = vis
    rp = _matching(header, lp)
    args: List[FuncArg] = []
    arg_body = header[lp + 1:rp]
    if arg_body.strip():
        for part in _split_top(arg_body, ","):
            part = part.strip()
            if not part.startswith("%"):
                continue
            colon = _find_top(part, ":")
            aname = part[:colon].strip()
            rest = part[colon + 1:].strip()
            attrs: Dict[str, str] = {}
            brace = _find_top(rest, "{")
            if brace >= 0:
                close = _matching(rest, brace)
                attrs = _parse_attr_dict(rest[brace + 1:close])
                rest = rest[:brace].strip()
            args.append(FuncArg(aname, _parse_type(rest), attrs))
    results: List[FuncResult] = []
    arrow = _find_top(header, "->", rp)
    if arrow >= 0:
        res = header[arrow + 2:].strip()
        if res.startswith("("):
            res = res[1:_matching(res, 0)]
            items = _split_top(res, ",")
        else:
            items = [res]
        for item in items:
            item = item.strip()
            if not item:
                continue
            attrs = {}
            brace = _find_top(item, "{")
            if brace >= 0:
                close = _matching(item, brace)
                attrs = _parse_attr_dict(item[brace + 1:close])
                item = item[:brace].strip()
            results.append(FuncResult(_parse_type(item), attrs))
    return Func(name, visibility, args, results, line=line)


def _parse_op_line(text: str, line: int) -> Optional[Op]:
    """One op statement → :class:`Op`, or None for text that is not an
    op (closing braces, region headers, anything unrecognized)."""
    s = text.strip()
    if not s or s.startswith("//") or s in ("}", "})", "},"):
        return None
    results: List[str] = []
    eq = _find_top(s, "=")
    if eq > 0 and _RESULTS.match(s[:eq].strip()):
        for r in _split_top(s[:eq], ","):
            results.append(r.strip().split(":")[0])
        s = s[eq + 1:].strip()
    if not s or s[0] in "}{)":
        return None
    # op name: "stablehlo.add", "call", "return", "func.return" ...
    m = re.match(r"^([A-Za-z_][A-Za-z0-9_.]*)", s)
    if not m:
        return None
    name = m.group(1)
    rest = s[m.end():].strip()
    target = ""
    if rest.startswith("@"):        # custom_call @Target / call @fn
        tm = re.match(r"^@([A-Za-z0-9_.$]+)", rest)
        if tm:
            target = "@" + tm.group(1)
            rest = rest[tm.end():].strip()
    # split off the trailing type signature (the last top-level " : ")
    sig = ""
    colon = _find_top(rest, " : ")
    while colon >= 0:
        nxt = _find_top(rest, " : ", colon + 3)
        if nxt < 0:
            sig = rest[colon + 3:].strip()
            rest = rest[:colon].strip()
            break
        colon = nxt
    # `stablehlo.constant dense<..> : tensor<f32>` — the dense literal
    # can contain commas/brackets; operands are just the SSA ids used
    operands = [] if name.endswith("constant") else _SSA.findall(rest)
    attrs: Dict[str, str] = {}
    i = 0
    while True:
        brace = _find_top(rest, "{", i)
        if brace < 0:
            break
        close = _matching(rest, brace)
        attrs.update(_parse_attr_dict(rest[brace + 1:close]))
        i = close + 1
    # structured attrs outside braces: `dims = [...]`, `dimensions = [..]`
    for am in re.finditer(
            r"\b(dims|dimensions|across dimensions)\s*=\s*\[([0-9,\s]*)\]",
            rest):
        attrs[am.group(1).replace("across ", "")] = am.group(2).strip()
    in_types: List[Optional[TensorType]] = []
    out_types: List[Optional[TensorType]] = []
    if sig:
        arrow = _find_top(sig, "->")
        if arrow >= 0:
            ins, outs = sig[:arrow].strip(), sig[arrow + 2:].strip()
            for side, dst in ((ins, in_types), (outs, out_types)):
                if side.startswith("("):
                    side = side[1:_matching(side, 0)]
                    dst.extend(_parse_type(p) for p in
                               _split_top(side, ",") if p.strip())
                elif side:
                    dst.append(_parse_type(side))
        else:
            # elementwise shorthand: one type, inputs == output
            t = _parse_type(sig)
            out_types.append(t)
            in_types.extend([t] * max(1, len(operands)))
    return Op(name, results, operands, attrs, in_types, out_types,
              line, target)


def parse_module(text: str) -> Module:
    """Parse one StableHLO module's text.  Raises :class:`IrParseError`
    when the text has no module/function structure to speak of."""
    try:
        return _parse_module(text)
    except IrParseError:
        raise
    except Exception as e:  # noqa: BLE001 — any slip becomes IrParseError
        raise IrParseError(f"{type(e).__name__}: {e}") from e


def _parse_module(text: str) -> Module:
    name = ""
    num_partitions = 1
    num_replicas = 1
    funcs: Dict[str, Func] = {}
    cur: Optional[Func] = None
    pending: List[str] = []     # multi-line func header accumulator
    pending_line = 0
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if pending:
            pending.append(line)
            joined = " ".join(pending)
            if joined.rstrip().endswith("{") and \
                    _find_top(joined.rstrip()[:-1], "(") >= 0:
                cur = _parse_func_header(
                    joined.rstrip()[:-1].strip(), pending_line)
                funcs[cur.name] = cur
                pending = []
            continue
        if line.startswith("module"):
            m = re.search(r"@([A-Za-z0-9_.$-]+)", line)
            name = m.group(1) if m else ""
            num_partitions = _module_attr(line, "mhlo.num_partitions")
            num_replicas = _module_attr(line, "mhlo.num_replicas")
            continue
        if line.startswith("func.func") or line.startswith("func @"):
            if line.rstrip().endswith("{"):
                cur = _parse_func_header(
                    line.rstrip()[:-1].strip(), lineno)
                funcs[cur.name] = cur
            else:
                pending = [line]
                pending_line = lineno
            continue
        if cur is None:
            continue
        op = _parse_op_line(line, lineno)
        if op is None:
            continue
        if op.name in ("return", "func.return", "stablehlo.return"):
            if op.name != "stablehlo.return":   # region yields ignored
                cur.returns = list(op.operands)
            continue
        cur.ops.append(op)
    if not funcs:
        raise IrParseError("no func.func found in module text")
    return Module(name, num_partitions, num_replicas, funcs)
