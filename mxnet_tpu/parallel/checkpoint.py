"""Sharded distributed checkpoints for SPMDTrainer.

TPU-native counterpart of the reference's checkpoint/resume story
(ref: python/mxnet/model.py save_checkpoint/load_checkpoint + the
kvstore server-side state): instead of gathering every parameter to one
host and writing a single `.params` blob, each host writes ITS shards of
params + optimizer state through orbax/tensorstore (the idiomatic jax
path SURVEY.md §5 prescribes).  Restore re-shards onto whatever mesh the
new trainer runs — resuming on a different mesh shape (dp=8 -> fsdp=4,
chip count changes, ...) is a first-class operation, not a special case.

The single-file `.params` path (serialization.py) remains for
reference-format interchange; this module is the scale path.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..base import MXNetError

__all__ = ["save_sharded", "load_sharded"]


def _checkpointer():
    try:
        import orbax.checkpoint as ocp
    except ImportError as e:  # pragma: no cover
        raise MXNetError(
            "sharded checkpoints need orbax-checkpoint (tensorstore "
            "backend); use Trainer/.params serialization instead") from e
    return ocp


def _saved_param_names(ckptr, path: str):
    """Param names recorded in the checkpoint's metadata, or None when
    the metadata shape is not recognized (older orbax layouts)."""
    try:
        meta = ckptr.metadata(path)
        tree = getattr(meta, "item_metadata", None) or getattr(
            meta, "tree", None) or meta
        return set(tree["params"].keys())
    except Exception:
        return None


def _tree_of(trainer) -> Dict[str, Any]:
    return {
        "params": dict(trainer.params),
        "opt_state": {n: tuple(s) for n, s in trainer.opt_state.items()},
        # 0-d array, not np.int64 scalar: orbax's StandardCheckpointer
        # validates leaves against (int, float, np.ndarray, jax.Array)
        # and rejects numpy scalar types
        "step": np.asarray(trainer._t, np.int64),
    }


def save_sharded(path: str, trainer, force: bool = True) -> None:
    """Write trainer params + optimizer state + step counter in sharded
    (tensorstore/zarr) layout.  Every process in a multi-host job calls
    this with the same path; each writes only its own shards.

    The write runs under the resilience retry policy (``OSError`` is
    transient — blob stores flake) so an auto-checkpoint cadence
    survives a storage blip instead of killing the step."""
    from ..resilience import retry as _retry

    ocp = _checkpointer()
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        _retry.default_policy().call(
            lambda: ckptr.save(path, _tree_of(trainer), force=force),
            site="checkpoint.sharded_save", retry_on=(OSError,))


def load_sharded(path: str, trainer) -> None:
    """Restore params + optimizer state + step INTO the trainer,
    re-sharding onto its current mesh (which may differ from the saving
    mesh in shape and axis layout)."""
    ocp = _checkpointer()
    path = os.path.abspath(path)

    def _abstract(n):
        def to_struct(x):
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=trainer._shardings[n])
        return to_struct

    abstract = {
        "params": {n: _abstract(n)(v) for n, v in trainer.params.items()},
        "opt_state": {
            n: tuple(_abstract(n)(s) for s in ss)
            for n, ss in trainer.opt_state.items()},
        "step": jax.ShapeDtypeStruct((), np.int64),
    }
    with ocp.StandardCheckpointer() as ckptr:
        # friendly mismatch error BEFORE orbax's structural restore check
        saved = _saved_param_names(ckptr, path)
        if saved is not None and saved != set(trainer.params):
            raise MXNetError(
                "checkpoint parameter set does not match the model: "
                f"missing from checkpoint "
                f"{sorted(set(trainer.params) - saved)}, "
                f"unexpected in checkpoint "
                f"{sorted(saved - set(trainer.params))}")
        from ..resilience import retry as _retry

        restored = _retry.default_policy().call(
            lambda: ckptr.restore(path, abstract),
            site="checkpoint.sharded_load", retry_on=(OSError,))
    trainer.params = dict(restored["params"])
    trainer.opt_state = {n: tuple(s)
                         for n, s in restored["opt_state"].items()}
    trainer._t = int(restored["step"])
