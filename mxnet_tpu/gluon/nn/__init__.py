"""Gluon neural-network layers (ref: python/mxnet/gluon/nn/__init__.py)."""
from .basic_layers import *  # noqa: F401,F403
from .conv_layers import *  # noqa: F401,F403
from . import basic_layers, conv_layers
from ..block import Block, HybridBlock  # noqa: F401

__all__ = basic_layers.__all__ + conv_layers.__all__
