"""Serving-path telemetry (ISSUE 2): /metrics + /healthz endpoints,
request trace linking, and AOT-compile observability.

Tier-1 smoke (the CI satellite): boot the HTTP front end, scrape
/metrics and /healthz, check the Prometheus exposition parses — one
line per sample, `# TYPE` headers present — and that the request
latency histogram buckets and compile counters are in it.  Plus: one
served request yields ONE trace id linking admission → queue-wait →
batch-assembly → execute → respond spans, with flow arrows that
resolve.
"""
import importlib.util
import json
import os
import re
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, profiler, serving, telemetry
from mxnet_tpu.contrib import deploy
from mxnet_tpu.gluon import nn

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_trace_report():
    spec = importlib.util.spec_from_file_location(
        "trace_report_under_test",
        os.path.join(_REPO, "tools", "trace_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    d = tmp_path_factory.mktemp("serve_tel")
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8))
        net.add(nn.Dense(4, in_units=16))
    net.initialize(mx.initializer.Xavier(rnd_type="gaussian"),
                   ctx=mx.cpu())
    x = nd.array(np.random.RandomState(0).rand(8, 8).astype("float32"))
    deploy.export_model(net, str(d), [x], dynamic_batch=True)
    return str(d)


@pytest.fixture(autouse=True)
def _clean(tmp_path):
    telemetry.disable()
    profiler.stop()
    profiler.dump(finished=True, filename=str(tmp_path / "_flush.json"))
    yield
    telemetry.disable()
    profiler.stop()
    profiler.dump(finished=True, filename=str(tmp_path / "_flush2.json"))


def _get(url, timeout=30):
    r = urllib.request.urlopen(url, timeout=timeout)
    return r.status, r.read().decode()


def test_http_metrics_and_healthz_smoke(artifact):
    """The tier-1 scrape smoke: /healthz drain-aware, /metrics valid
    Prometheus text with latency buckets + compile counters."""
    repo = serving.ModelRepository()
    repo.add("mlp", artifact)
    srv = serving.InferenceServer(
        repo, serving.ServingConfig(max_batch_size=8,
                                    batch_timeout_ms=2.0))
    httpd = serve = None
    try:
        httpd = serving.serve_http(srv, port=0)
        port = httpd.server_address[1]
        base = f"http://127.0.0.1:{port}"

        status, body = _get(f"{base}/healthz")
        assert status == 200 and json.loads(body)["status"] == "serving"

        # traffic so the latency histogram + compile counters move
        body_req = json.dumps(
            {"inputs": [np.zeros((1, 8), "float32").tolist()]}).encode()
        r = urllib.request.urlopen(urllib.request.Request(
            f"{base}/v1/models/mlp:predict", data=body_req,
            headers={"Content-Type": "application/json"}), timeout=120)
        assert r.status == 200

        status, text = _get(f"{base}/metrics")
        assert status == 200
        sample_re = re.compile(
            r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? '
            r'[0-9eE\.\+\-]+$')
        families = set()
        n_samples = 0
        for ln in text.strip().split("\n"):
            if ln.startswith("# TYPE"):
                families.add(ln.split()[2])
                continue
            if ln.startswith("#"):
                continue
            assert sample_re.match(ln), f"bad exposition line {ln!r}"
            n_samples += 1
        assert n_samples > 0
        # every sample's family has a # TYPE header
        for ln in text.strip().split("\n"):
            if ln.startswith("#") or not ln:
                continue
            name = re.split(r"[{ ]", ln, 1)[0]
            base_name = re.sub(r"_(bucket|sum|count)$", "", name)
            assert name in families or base_name in families, \
                f"sample {name!r} lacks a # TYPE header"
        # acceptance: request latency histogram buckets + AOT compile
        # counters are scrapeable
        assert re.search(
            r'mx_serving_request_latency_seconds_bucket\{.*model="mlp"'
            r'.*le=', text)
        m = re.search(
            r'mx_serving_compile_total\{model="mlp",version="1"\} '
            r'(\d+)', text)
        assert m and int(m.group(1)) >= 1
        assert re.search(r'mx_serving_requests_total\{model="mlp",'
                         r'version="1"\} 1', text)

        # drain-aware healthz: 503 once shutdown begins
        srv.shutdown(drain=True)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/healthz", timeout=30)
        assert ei.value.code == 503
        assert json.loads(ei.value.read().decode())["status"] == \
            "draining"
    finally:
        if httpd is not None:
            httpd.shutdown()
        srv.shutdown(drain=False)


REQUEST_PHASES = ("admission", "queue-wait", "batch-assembly",
                  "execute", "respond")


def test_served_request_has_one_trace_linking_all_phases(
        artifact, tmp_path):
    repo = serving.ModelRepository()
    repo.add("mlp", artifact)
    srv = serving.InferenceServer(
        repo, serving.ServingConfig(max_batch_size=8,
                                    batch_timeout_ms=2.0))
    try:
        # warm the compile OUTSIDE the capture so the trace is lean
        srv.infer("mlp", [nd.array(np.zeros((1, 8), "float32"))],
                  timeout_ms=120000)
        telemetry.enable()
        profiler.start()
        fut = srv.submit("mlp",
                         [nd.array(np.ones((1, 8), "float32"))])
        fut.result(timeout=120)
        profiler.stop()
        telemetry.disable()
    finally:
        srv.shutdown(drain=True)
    assert fut.trace_id is not None
    fn = str(tmp_path / "req.json")
    profiler.dump(finished=True, filename=fn)
    evs = json.load(open(fn))["traceEvents"]
    mine = [e for e in evs if e.get("ph") == "X"
            and isinstance(e.get("args"), dict)
            and e["args"].get("trace_id") == fut.trace_id]
    names = {e["name"] for e in mine}
    assert set(REQUEST_PHASES) <= names, \
        f"trace {fut.trace_id} spans {sorted(names)}"
    # one trace id covers the whole request path
    adm = next(e for e in mine if e["name"] == "admission")
    qw = next(e for e in mine if e["name"] == "queue-wait")
    assert qw["args"]["parent_id"] == adm["args"]["span_id"]
    # flow arrows: an "s" where the request was enqueued, an "f" at
    # the batch, both carrying the trace id
    flows = {e["ph"] for e in evs if e.get("ph") in ("s", "f")
             and e.get("id") == fut.trace_id}
    assert flows == {"s", "f"}
    # and the whole dump passes the integrity gate
    tr = _load_trace_report()
    assert tr.check_events(evs) == []


def test_model_metrics_reset_on_new_entry(artifact):
    """A fresh _ModelEntry for the same (model, version) restarts its
    counters (lifecycle restart semantics) — per-test counts stay
    hermetic even though the registry is process-global."""
    repo1 = serving.ModelRepository()
    repo1.add("mlp", artifact)
    srv1 = serving.InferenceServer(repo1)
    srv1.infer("mlp", [nd.array(np.zeros((1, 8), "float32"))],
               timeout_ms=120000)
    assert repo1.get("mlp").metrics.snapshot()["requests"] == 1
    srv1.shutdown(drain=True)
    repo2 = serving.ModelRepository()
    repo2.add("mlp", artifact)
    assert repo2.get("mlp").metrics.snapshot()["requests"] == 0


def test_compile_seconds_histogram_records(artifact):
    reg = telemetry.get_registry()
    repo = serving.ModelRepository()
    repo.add("mlp", artifact)
    entry = repo.get("mlp")
    before = reg.get("mx_serving_compile_total") \
        .labels("mlp", "1").value
    entry.warmup([2])
    fam = reg.get("mx_serving_compile_total")
    assert fam.labels("mlp", "1").value == before + 1
    h = reg.get("mx_serving_compile_seconds").labels("mlp", "1")
    assert h.count >= 1 and h.sum > 0
