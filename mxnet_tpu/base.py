"""Foundations: errors, registries, env-var config, dtype tables.

TPU-native counterpart of the reference's dmlc-core surface
(ref: 3rdparty/dmlc-core — CHECK/LOG, dmlc::GetEnv, Registry<T>) and
python/mxnet/base.py (error type, handle plumbing).  Here the Python layer
is the primary frontend, so the "registry" and "env" helpers live natively
in Python; the C ABI (src/c_api) is used for the native engine/IO modules
only (see mxnet_tpu/lib.py).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Generic, Optional, TypeVar

import numpy as np

__all__ = [
    "MXNetError",
    "get_env",
    "Registry",
    "string_types",
    "numeric_types",
    "integer_types",
]

string_types = (str,)
numeric_types = (float, int, np.generic)
integer_types = (int, np.integer)


class MXNetError(RuntimeError):
    """Framework error type (ref: python/mxnet/base.py::MXNetError)."""


def check(cond: bool, msg: str = "") -> None:
    """CHECK() analogue (ref: dmlc-core logging.h). Raises MXNetError."""
    if not cond:
        raise MXNetError(msg or "check failed")


_TRUTHY = {"1", "true", "yes", "on"}
_FALSY = {"0", "false", "no", "off"}


def convert_env(name: str, raw: str, typ: type) -> Any:
    """Parse an env-var string with env-var semantics (truthy/falsy
    spellings for bools, numeric fallback).  Shared by :func:`get_env`
    and the autotune env-overlay, which must convert stored values
    exactly the way the environment would have."""
    if typ is bool:
        low = raw.strip().lower()
        if low in _TRUTHY:
            return True
        if low in _FALSY:
            return False
        try:
            # reference knobs are int-typed booleans (MXNET_TELEMETRY=2
            # historically meant true); keep any numeric value working
            return bool(int(low))
        except ValueError:
            pass
        raise MXNetError(f"env var {name}={raw!r} is not a boolean")
    try:
        return typ(raw)
    except ValueError as e:
        raise MXNetError(f"env var {name}={raw!r} is not a {typ.__name__}") from e


def get_env(name: str, default: Any = None, typ: Optional[type] = None) -> Any:
    """dmlc::GetEnv analogue — typed env-var lookup.

    Env vars keep MXNET_-compatible names where the knob has a reference
    equivalent (ref: docs/faq/env_var.md).
    """
    raw = os.environ.get(name)
    if raw is None or raw == "":
        # empty string means unset: launchers commonly export every knob
        # with VAR="" as the 'use the default' spelling
        return default
    if typ is None:
        typ = type(default) if default is not None else str
    return convert_env(name, raw, typ)


T = TypeVar("T")


class Registry(Generic[T]):
    """Typed name->entry registry (ref: dmlc-core registry.h Registry<T>).

    Used for ops, optimizers, initializers, metrics, data iterators —
    mirroring how the reference registers everything through
    DMLC_REGISTRY_* macros and lists entries through the C API.
    """

    def __init__(self, kind: str, lowercase: bool = True):
        self._kind = kind
        self._entries: Dict[str, T] = {}
        self._lock = threading.Lock()
        self._lowercase = lowercase

    def _key(self, name: str) -> str:
        return name.lower() if self._lowercase else name

    def register(self, name: Optional[str] = None, allow_override: bool = False):
        def _do(entry: T, _name=name) -> T:
            key = self._key(_name if _name is not None else getattr(entry, "__name__"))
            with self._lock:
                if key in self._entries and not allow_override:
                    raise MXNetError(
                        f"{self._kind} '{key}' is already registered")
                self._entries[key] = entry
            return entry

        return _do

    def get(self, name: str) -> T:
        try:
            return self._entries[self._key(name)]
        except KeyError:
            raise MXNetError(
                f"unknown {self._kind} '{name}'; registered: "
                f"{sorted(self._entries)[:50]}") from None

    def __contains__(self, name: str) -> bool:
        return self._key(name) in self._entries

    def list(self):
        return sorted(self._entries)

    def items(self):
        return self._entries.items()


def classproperty(fn: Callable):
    class _CP:
        def __get__(self, obj, owner):
            return fn(owner)

    return _CP()
