"""2-bit gradient compression for the DCN (dist kvstore) path.

Counterpart of the reference's GradientCompression
(ref: src/kvstore/gradient_compression.cc, 2bit quantization):
each gradient element is sent as one of {0, +threshold, -threshold},
packed 4 elements per byte (16x smaller than fp32 on the wire), with the
quantization error accumulated into a per-key RESIDUAL that is added to
the next gradient — so small gradients are delayed, never lost.

The compress/decompress kernels run on host numpy: this path feeds the
gloo/DCN transport, which is host-side by construction; the ICI/SPMD
path keeps uncompressed in-graph collectives (bf16 over ICI is already
cheap; see PERF.md).
"""
from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from .base import MXNetError

__all__ = ["TwoBitCompressor", "create"]

_CODE_POS = 1  # 0b01 -> +threshold
_CODE_NEG = 2  # 0b10 -> -threshold


class TwoBitCompressor:
    """Stateful 2-bit quantizer with per-key residual accumulation."""

    def __init__(self, threshold: float = 0.5):
        t = float(threshold)
        if t <= 0:
            raise MXNetError("2bit compression threshold must be > 0")
        self.threshold = t
        self._residual: Dict[str, np.ndarray] = {}

    def compress(self, key, grad: np.ndarray) -> Tuple[np.ndarray, tuple]:
        """grad (+ residual) -> packed uint8 codes; updates the residual.
        Returns (packed, original_shape)."""
        g = np.asarray(grad, np.float32).ravel()
        r = self._residual.get(key)
        if r is None or r.shape != g.shape:
            r = np.zeros_like(g)
        acc = g + r
        codes = np.zeros(g.shape, np.uint8)
        codes[acc >= self.threshold] = _CODE_POS
        codes[acc <= -self.threshold] = _CODE_NEG
        sent = np.where(codes == _CODE_POS, self.threshold,
                        np.where(codes == _CODE_NEG, -self.threshold, 0.0)
                        ).astype(np.float32)
        self._residual[key] = acc - sent
        # pack 4 x 2-bit codes per byte, little-end first
        pad = (-len(codes)) % 4
        if pad:
            codes = np.concatenate([codes, np.zeros(pad, np.uint8)])
        quads = codes.reshape(-1, 4)
        packed = (quads[:, 0] | (quads[:, 1] << 2) | (quads[:, 2] << 4) |
                  (quads[:, 3] << 6)).astype(np.uint8)
        return packed, tuple(np.shape(grad))

    def decompress(self, packed: np.ndarray, shape: tuple) -> np.ndarray:
        n = int(np.prod(shape)) if shape else 1
        p = np.asarray(packed, np.uint8)
        codes = np.empty((len(p), 4), np.uint8)
        codes[:, 0] = p & 0b11
        codes[:, 1] = (p >> 2) & 0b11
        codes[:, 2] = (p >> 4) & 0b11
        codes[:, 3] = (p >> 6) & 0b11
        codes = codes.ravel()[:n]
        out = np.where(codes == _CODE_POS, self.threshold,
                       np.where(codes == _CODE_NEG, -self.threshold, 0.0))
        return out.astype(np.float32).reshape(shape)


def create(params: dict):
    """Build a compressor from set_gradient_compression params
    (ref: KVStore::SetGradientCompression) — unknown types fail loud."""
    p = dict(params)
    ctype = p.pop("type", None)
    if ctype in ("2bit", "2-bit"):
        return TwoBitCompressor(threshold=float(p.pop("threshold", 0.5)))
    if ctype in ("1bit", "signum"):
        raise MXNetError(
            "gradient compression type '1bit' is not implemented; "
            "supported: '2bit'")
    raise MXNetError(
        f"unknown gradient compression type {ctype!r}; supported: '2bit'")
