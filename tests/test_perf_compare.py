"""tools/perf_compare.py (ISSUE 10 satellite): the nightly bench-JSON
regression gate — >10% throughput drop or a new trace-integrity
failure vs the committed artifacts fails the run."""
import importlib.util
import json
import os

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load():
    spec = importlib.util.spec_from_file_location(
        "perf_compare_under_test",
        os.path.join(_REPO, "tools", "perf_compare.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


pc = _load()


def _scaling(tp2=1.28, check_ok=True, parity_ok=True):
    return {"sweep": [
        {"path": "spmd", "processes": 1, "global_throughput": 1.0,
         "trace_check_ok": True,
         "merged_trace": {"check_ok": check_ok}},
        {"path": "spmd", "processes": 2, "global_throughput": tp2},
    ], "parity": {"ok": parity_ok}}


class TestCompareArtifact:
    def test_within_tolerance_ok(self):
        res = pc.compare_artifact("SCALING.json", _scaling(1.28),
                                  _scaling(1.20), tolerance=0.10)
        assert res["ok"] and not res["regressions"]

    def test_throughput_regression_fails(self):
        res = pc.compare_artifact("SCALING.json", _scaling(1.28),
                                  _scaling(1.0), tolerance=0.10)
        assert not res["ok"]
        assert "global_throughput" in res["regressions"][0]

    def test_improvement_never_fails(self):
        res = pc.compare_artifact("SCALING.json", _scaling(1.0),
                                  _scaling(10.0), tolerance=0.10)
        assert res["ok"]

    def test_new_integrity_failure_fails(self):
        res = pc.compare_artifact("SCALING.json", _scaling(),
                                  _scaling(check_ok=False),
                                  tolerance=0.10)
        assert not res["ok"]
        assert "merged_trace.check_ok" in \
            res["new_integrity_failures"][0]

    def test_preexisting_false_is_not_new(self):
        res = pc.compare_artifact("SCALING.json",
                                  _scaling(check_ok=False),
                                  _scaling(check_ok=False),
                                  tolerance=0.10)
        assert res["ok"]

    def test_fresh_only_check_lane_still_gates(self):
        base = _scaling()
        del base["parity"]
        res = pc.compare_artifact("SCALING.json", base,
                                  _scaling(parity_ok=False),
                                  tolerance=0.10)
        assert not res["ok"]
        assert "parity.ok" in res["new_integrity_failures"][0]

    def test_metric_only_on_one_side_skipped(self):
        base = {"sweep": [{"path": "spmd", "processes": 4,
                           "global_throughput": 9.0}]}
        res = pc.compare_artifact("SCALING.json", base, _scaling(),
                                  tolerance=0.10)
        assert res["ok"] and res["metrics"] == []

    def test_fused_and_compile_cache_extractors(self):
        fused_b = {"sizes": {"100": {"speedup": 2.3}}}
        fused_f = {"sizes": {"100": {"speedup": 1.5}}}
        res = pc.compare_artifact("FUSED_BENCH.json", fused_b, fused_f,
                                  tolerance=0.10)
        assert not res["ok"]
        cc_b = {"serving": {"speedup": 4.0}, "fused": {"speedup": 4.0},
                "gate_ok": True}
        cc_f = {"serving": {"speedup": 3.9}, "fused": {"speedup": 3.8},
                "gate_ok": False}
        res = pc.compare_artifact("COMPILE_CACHE.json", cc_b, cc_f,
                                  tolerance=0.10)
        assert not res["ok"]
        assert "gate_ok" in res["new_integrity_failures"][0]

    def test_serving_extractor(self):
        b = {"unbatched": {"qps": 588.7}, "batched": {"qps": 987.9},
             "batched_over_unbatched": 1.68}
        f = {"unbatched": {"qps": 600.0}, "batched": {"qps": 700.0},
             "batched_over_unbatched": 1.17}
        res = pc.compare_artifact("SERVING_BENCH.json", b, f,
                                  tolerance=0.10)
        assert not res["ok"]
        names = [r["metric"] for r in res["metrics"]
                 if r.get("regression")]
        assert "batched.qps" in names


class TestCli:
    def _dirs(self, tmp_path, base, fresh):
        bd, fd = tmp_path / "base", tmp_path / "fresh"
        bd.mkdir(), fd.mkdir()
        for d, payload in ((bd, base), (fd, fresh)):
            for name, doc in payload.items():
                (d / name).write_text(json.dumps(doc))
        return str(bd), str(fd)

    def test_clean_run_rc0_and_report(self, tmp_path):
        bd, fd = self._dirs(tmp_path,
                            {"SCALING.json": _scaling()},
                            {"SCALING.json": _scaling(1.25)})
        out = str(tmp_path / "rep.json")
        rc = pc.main(["--baseline-dir", bd, "--fresh-dir", fd,
                      "--artifacts", "SCALING.json", "--out", out])
        assert rc == 0
        rep = json.load(open(out))
        assert rep["ok"] and "SCALING.json" in rep["artifacts"]

    def test_regression_rc1(self, tmp_path):
        bd, fd = self._dirs(tmp_path,
                            {"SCALING.json": _scaling()},
                            {"SCALING.json": _scaling(0.5)})
        assert pc.main(["--baseline-dir", bd, "--fresh-dir", fd,
                        "--artifacts", "SCALING.json"]) == 1

    def test_missing_artifact_skips_not_fails(self, tmp_path):
        bd, fd = self._dirs(tmp_path, {},
                            {"SCALING.json": _scaling()})
        out = str(tmp_path / "rep.json")
        rc = pc.main(["--baseline-dir", bd, "--fresh-dir", fd,
                      "--artifacts", "SCALING.json", "--out", out])
        assert rc == 0
        assert json.load(open(out))["artifacts"]["SCALING.json"][
            "skipped"]

    def test_unknown_artifact_usage_error(self):
        assert pc.main(["--artifacts", "NOPE.json"]) == 2

    def test_git_baseline_against_head(self):
        """The nightly invocation shape: committed artifacts vs the
        work tree.  Committed == work tree unless a bench just ran, so
        this asserts the plumbing, not a verdict."""
        rc = pc.main(["--ref", "HEAD", "--fresh-dir", _REPO,
                      "--artifacts", "FUSED_BENCH.json"])
        assert rc in (0, 1)
