"""End-to-end ImageNet-format training: im2rec shards -> native decode
pipeline -> SPMDTrainer ResNet-50 (BASELINE.md config 2's real-data path).

Counterpart of the reference's
example/image-classification/train_imagenet.py: data arrives as
RecordIO shards produced by tools/im2rec.py, is decoded+augmented by the
native C++ pipeline (src/image_pipeline.cc), and feeds the one-program
SPMD train step.

With --synthetic-data it first builds a small fake ImageNet tree (N
classes x M images) and packs it through the real im2rec path, so the
whole flow is runnable anywhere:

    python examples/imagenet_train.py --synthetic-data --epochs 2

Point --rec-prefix at real ImageNet shards for the full run:

    python tools/im2rec.py imagenet /data/imagenet/train --list --recursive
    python tools/im2rec.py imagenet /data/imagenet/train --resize 256 \
        --num-thread 16
    python examples/imagenet_train.py --rec-prefix imagenet \
        --batch-size 256 --image-size 224
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import parallel  # noqa: E402
from mxnet_tpu.gluon import loss as gloss  # noqa: E402
from mxnet_tpu.gluon.model_zoo import vision  # noqa: E402
from mxnet_tpu.io import ImageRecordIter  # noqa: E402


def make_synthetic_imagenet(root: str, classes: int, per_class: int,
                            size: int) -> None:
    import cv2

    rng = np.random.RandomState(0)
    for c in range(classes):
        d = os.path.join(root, f"class_{c:03d}")
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            img = rng.randint(0, 255, (size, size, 3), np.uint8)
            img = cv2.GaussianBlur(img, (5, 5), 2)
            cv2.imwrite(os.path.join(d, f"{i}.jpg"), img,
                        [cv2.IMWRITE_JPEG_QUALITY, 90])


def pack_with_im2rec(prefix: str, root: str, resize: int) -> None:
    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "im2rec.py")
    for extra in (["--list", "--recursive", "--shuffle"],
                  ["--resize", str(resize), "--num-thread",
                   str(os.cpu_count() or 1)]):
        r = subprocess.run([sys.executable, tool, prefix, root] + extra,
                           capture_output=True, text=True)
        if r.returncode != 0:
            raise RuntimeError(f"im2rec failed: {r.stderr[-2000:]}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rec-prefix", default=None,
                    help="prefix of .rec/.idx shards (from tools/im2rec.py)")
    ap.add_argument("--synthetic-data", action="store_true",
                    help="build + pack a small fake ImageNet tree first")
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--per-class", type=int, default=32)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--preprocess-threads", type=int,
                    default=os.cpu_count() or 1)
    args = ap.parse_args()

    prefix = args.rec_prefix
    if args.synthetic_data or prefix is None:
        tmp = tempfile.mkdtemp(prefix="imagenet_synth_")
        root = os.path.join(tmp, "train")
        os.makedirs(root)
        print(f"building synthetic ImageNet tree under {root} ...")
        make_synthetic_imagenet(root, args.classes, args.per_class,
                                args.image_size + 32)
        prefix = os.path.join(tmp, "synth")
        pack_with_im2rec(prefix, root, args.image_size + 16)
    rec, idx = prefix + ".rec", prefix + ".idx"

    hw = args.image_size
    train_iter = ImageRecordIter(
        path_imgrec=rec, path_imgidx=idx, data_shape=(3, hw, hw),
        batch_size=args.batch_size, shuffle=True, rand_crop=True,
        rand_mirror=True, resize=hw + 8,
        mean_r=123.68, mean_g=116.78, mean_b=103.94,
        std_r=58.4, std_g=57.12, std_b=57.38,
        preprocess_threads=args.preprocess_threads)
    engaged = "native C++ pipeline" if train_iter._pipe is not None \
        else "python decode path"
    print(f"data pipeline: {engaged}")

    net = vision.resnet50_v1(classes=args.classes, layout="NHWC")
    net.initialize(mx.initializer.Xavier(magnitude=2.0), ctx=mx.cpu())
    with mx.autograd.pause():
        net(mx.nd.zeros((1, 32, 32, 3), ctx=mx.cpu()))
    if args.dtype != "float32":
        net.cast(args.dtype)

    mesh = parallel.make_mesh(dp=1)
    with mesh:
        trainer = parallel.SPMDTrainer(
            net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": args.lr, "momentum": 0.9, "wd": 1e-4})
        for epoch in range(args.epochs):
            t0 = time.time()
            n, loss = 0, None
            train_iter.reset()
            for batch in train_iter:
                # NCHW float from the pipeline -> NHWC for the TPU net
                x = batch.data[0].asnumpy().transpose(0, 2, 3, 1)
                y = batch.label[0].asnumpy().astype(np.int32)
                loss = trainer.step(x.astype(args.dtype), y)
                n += x.shape[0] - batch.pad
            lval = float(loss.asnumpy())
            dt = time.time() - t0
            print(f"epoch {epoch}: {n} images, {n / dt:.1f} img/s "
                  f"end-to-end, loss {lval:.4f}")
        assert np.isfinite(lval)
    print("done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
