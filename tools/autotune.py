#!/usr/bin/env python
"""mxtune driver: sweep knob configs through short measured runs and
commit the winners (ISSUE 16).

Each trial is a BOUNDED SUBPROCESS running one scenario (below) with
the candidate config injected as ``MXNET_*`` environment variables —
the same spelling an operator would use, so the env-overlay precedence
rules are exercised for real.  The child embeds a fresh mxgoodput
ledger and the mxprof flight recorder; its objective is the goodput
ratio, tiebroken by mxprof MFU and throughput.  A child that crashes,
hangs past MXNET_AUTOTUNE_TRIAL_TIMEOUT_S, or prints garbage is a
PRUNED trial, never a crashed tune.

Scenarios (one training, one io-bound, per the AUTOTUNE.json gate):

* ``mlp_train`` — the goodput_report clean-run MLP (Dense 32x64, sgd
  momentum), warmup outside the ledger; sweeps the fused-step /
  cache-size knobs.
* ``io_bound`` — DataLoader with thread workers over a numpy-decode
  dataset feeding a tiny train step; sweeps MXNET_PREFETCH_DEPTH (the
  host->device prefetch dimension) where the goodput ratio directly
  prices data-wait.

Winners persist to the autotune config store (beside the compile
cache — ``mxnet_tpu/autotune/store.py``) keyed by (scenario, mesh,
device_kind, framework version), so a fresh process on this machine
boots already-tuned via the startup overlay.  Explicit env settings
always override stored winners.

    python tools/autotune.py --quick --out AUTOTUNE.json
    python tools/autotune.py --from-suspects PERF_COMPARE.json
    python tools/autotune.py --scenarios io_bound --store-dir /tmp/tuned

Exit: 0 when every scenario's tuned config >= its measured default
(gate_ok), 1 otherwise, always 0 under --no-gate.
"""
from __future__ import annotations

import argparse
import itertools
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# scenario -> default sweep dimensions (overridden by --from-suspects)
SCENARIO_DIMS = {
    "mlp_train": ["MXNET_FUSED_BUCKET_BYTES", "MXNET_FUSED_CACHE_MAX",
                  "MXNET_OP_CACHE_MAX", "MXNET_ZERO_MIN_SIZE"],
    "io_bound": ["MXNET_PREFETCH_DEPTH", "MXNET_OP_CACHE_MAX"],
}


# ---------------------------------------------------------------------------
# trial child (--_trial): one measured run, one JSON line on stdout
# ---------------------------------------------------------------------------

def _trial_mlp_train(steps: int):
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.gluon import Trainer, nn
    from mxnet_tpu.telemetry import mxgoodput

    np.random.seed(0)
    mx.random.seed(0)
    net = nn.Dense(32, in_units=64)
    net.initialize()
    tr = Trainer(net.collect_params(), "sgd",
                 {"learning_rate": 1e-3, "momentum": 0.9})
    x = nd.array(np.random.rand(64, 64).astype("float32"))

    def one_step():
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        tr.step(64)

    for _ in range(2):  # warmup (and its compiles) outside the ledger
        one_step()
    mxgoodput.enable(fresh=True)
    t0 = time.perf_counter()
    for _ in range(steps):
        one_step()
    wall = time.perf_counter() - t0
    return mxgoodput.snapshot(), steps / max(wall, 1e-9)


def _trial_io_bound(steps: int):
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.gluon import Trainer, nn
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.telemetry import mxgoodput

    class _Decode:
        """Simulated decode/augment (GIL released inside numpy) — the
        bench_dataloader NumpyHeavy shape, sized for short trials."""

        def __init__(self, n):
            self.n = n
            self.img = np.random.RandomState(0) \
                .rand(128, 128, 3).astype(np.float32)

        def __len__(self):
            return self.n

        def __getitem__(self, i):
            x = self.img * (1.0 + 0.01 * (i % 7))
            x = x[::-1].copy()
            x = (x - x.mean()) / (x.std() + 1e-6)
            return x.astype(np.float32)

    batch = 8
    np.random.seed(0)
    mx.random.seed(0)
    net = nn.Dense(8, in_units=64)
    net.initialize()
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 1e-3})
    xs = nd.array(np.random.rand(batch, 64).astype("float32"))

    def one_step():
        with autograd.record():
            loss = (net(xs) ** 2).sum()
        loss.backward()
        tr.step(batch)

    # prefetch depth is read at construction: build the loader AFTER the
    # config landed in the env (it did — we are the subprocess)
    dl = DataLoader(_Decode((steps + 4) * batch), batch_size=batch,
                    num_workers=2, worker_pool="thread")
    one_step()  # compile warmup outside the ledger
    it = iter(dl)
    next(it)    # thread-pool spin-up outside the ledger too
    mxgoodput.enable(fresh=True)
    t0 = time.perf_counter()
    n = 0
    for b in itertools.islice(it, steps):
        n += b.shape[0] if hasattr(b, "shape") else len(b)
        one_step()
    wall = time.perf_counter() - t0
    return mxgoodput.snapshot(), n / max(wall, 1e-9)


def run_trial(scenario: str, steps: int) -> int:
    from mxnet_tpu.telemetry import mxprof

    mxprof.enable()
    fn = {"mlp_train": _trial_mlp_train,
          "io_bound": _trial_io_bound}[scenario]
    snap, throughput = fn(steps)
    prof = mxprof.snapshot(live_hbm=False, include_records=False)
    mfu = (prof.get("summary") or {}).get("mfu_mean")
    result = {
        "ok": True,
        "objective": snap["goodput_ratio"],
        "tiebreak": [mfu if mfu is not None else 0.0, throughput],
        "goodput": {k: snap[k] for k in
                    ("goodput_ratio", "wall_s", "productive_s", "steps")},
        "throughput": throughput,
    }
    print(json.dumps(result))
    # skip interpreter teardown: the measurement is on stdout, and the
    # loader's worker threads + jax occasionally SIGABRT during exit
    # cleanup — that must not read as a crashed trial
    sys.stdout.flush()
    os._exit(0)


# ---------------------------------------------------------------------------
# parent: subprocess runner + sweep
# ---------------------------------------------------------------------------

def _subprocess_runner(scenario: str, timeout_s: float, log):
    def runner(config, budget):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = os.environ.get("JAX_PLATFORMS", "cpu")
        # measure THIS config, not a previously stored winner
        env["MXNET_AUTOTUNE"] = "0"
        for name, value in config.items():
            env[name] = ("1" if value else "0") \
                if isinstance(value, bool) else str(value)
        cmd = [sys.executable, os.path.abspath(__file__),
               "--_trial", scenario, "--steps", str(budget)]
        try:
            p = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=timeout_s, env=env, cwd=_REPO)
        except subprocess.TimeoutExpired:
            log(f"  trial timeout ({timeout_s:.0f}s) — pruned: {config}")
            return None
        # a result line on stdout is the measurement — accept it even
        # on a dirty exit status (teardown crashes after the print are
        # the child's problem, not the config's); no line = pruned
        for line in reversed(p.stdout.strip().splitlines()):
            try:
                return json.loads(line)
            except ValueError:
                continue
        log(f"  trial rc={p.returncode}, no result line — "
            f"pruned: {config}")
        return None

    return runner


def sweep_scenario(scenario: str, dim_names, *, seed: int, quick: bool,
                   timeout_s: float, log) -> dict:
    import random

    from mxnet_tpu import autotune

    dims = autotune.dimensions(dim_names)
    runner = _subprocess_runner(scenario, timeout_s, log)
    result = autotune.successive_halving(
        runner, dims,
        rng=random.Random(seed),
        n_initial=4 if quick else 8,
        rungs=2 if quick else 3,
        base_budget=3 if quick else 4,
        log=lambda m: log(f"  {m}"))
    result["dims"] = [d.name for d in dims]
    # the scenario gate: a measured default AND tuned >= default on the
    # objective (the latter holds by argmax construction whenever both
    # measurements exist — see autotune/search.py)
    result["ok"] = bool(result["ok"]
                        and result["default_objective"] is not None
                        and result["delta"] is not None
                        and result["delta"] >= 0)
    return result


def _priority_from_file(path: str, log):
    from mxnet_tpu import autotune

    with open(path) as f:
        report = json.load(f)
    suspects = report.get("suspects")
    if not isinstance(suspects, list):
        log(f"{path} has no top-level suspects array — regenerate it "
            "with tools/perf_compare.py")
        return None
    names = autotune.priority_from_suspects(suspects)
    if not names:
        log(f"{path}: no tunable knob suspects among "
            f"{len(suspects)} suspects — using scenario defaults")
        return None
    log(f"priority dimensions from {path}: {names}")
    return names


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="sweep knob configs, persist winners, emit "
                    "AUTOTUNE.json")
    ap.add_argument("--scenarios", default="mlp_train,io_bound",
                    help="comma-separated scenario names "
                         f"(known: {sorted(SCENARIO_DIMS)})")
    ap.add_argument("--from-suspects", default=None, metavar="PERF_COMPARE",
                    help="read a perf_compare report and sweep its "
                         "ranked tunable knob suspects first (the "
                         "mxtriage feedback channel)")
    ap.add_argument("--quick", action="store_true",
                    help="bounded nightly sweep: fewer arms, fewer "
                         "rungs, smaller budgets")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--store-dir", default=None,
                    help="persist winners here (default: "
                         "MXNET_AUTOTUNE_DIR, else "
                         "<MXNET_COMPILE_CACHE_DIR>/autotune, else "
                         "no persistence)")
    ap.add_argument("--out", default="AUTOTUNE.json")
    ap.add_argument("--no-gate", action="store_true",
                    help="emit the report but exit 0 regardless "
                         "(tier-1 CLI smoke lane)")
    ap.add_argument("--_trial", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--steps", type=int, default=4, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args._trial is not None:
        return run_trial(args._trial, args.steps)

    def log(msg):
        print(msg, file=sys.stderr)

    import mxnet_tpu as mx
    from mxnet_tpu import autotune
    from mxnet_tpu.util import env as _env

    scenarios = [s.strip() for s in args.scenarios.split(",") if s.strip()]
    unknown = [s for s in scenarios if s not in SCENARIO_DIMS]
    if unknown:
        print(f"error: unknown scenario(s) {unknown} "
              f"(known: {sorted(SCENARIO_DIMS)})", file=sys.stderr)
        return 2

    priority = None
    if args.from_suspects:
        priority = _priority_from_file(args.from_suspects, log)

    timeout_s = _env.get_float("MXNET_AUTOTUNE_TRIAL_TIMEOUT_S")
    store_dir = args.store_dir if args.store_dir is not None \
        else autotune.default_dir()
    report = {
        "metric": "autotune_goodput",
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
        "framework_version": mx.__version__,
        "quick": bool(args.quick),
        "priority": priority,
        "scenarios": {},
        "store": {"dir": store_dir or None, "persisted": []},
    }
    for scenario in scenarios:
        dim_names = priority or SCENARIO_DIMS[scenario]
        log(f"sweeping {scenario} over {dim_names} "
            f"({'quick' if args.quick else 'full'}) ...")
        res = sweep_scenario(scenario, dim_names, seed=args.seed,
                             quick=args.quick, timeout_s=timeout_s,
                             log=log)
        report["scenarios"][scenario] = res
        log(f"{scenario}: objective {res['default_objective']} -> "
            f"{res['best_objective']} (delta {res['delta']}, "
            f"{res['trials']} trials, {res['crashed']} crashed)"
            + ("" if res["ok"] else " — GATE LANE FALSE"))
        if res["ok"] and res["best_config"] and store_dir:
            store = autotune.ConfigStore(store_dir)
            key = autotune.entry_key(
                scenario=scenario, mesh=[1], device_kind="",
                framework_version=mx.__version__,
                platform=os.environ.get("JAX_PLATFORMS", "") or "")
            path = store.put(key, res["best_config"],
                             res["best_objective"],
                             meta={"quick": bool(args.quick),
                                   "dims": res["dims"]})
            report["store"]["persisted"].append(path)
            log(f"  persisted winner -> {path}")

    report["gate_ok"] = all(r["ok"]
                            for r in report["scenarios"].values())
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps({"gate_ok": report["gate_ok"],
                      "scenarios": {s: r["ok"] for s, r in
                                    report["scenarios"].items()}}))
    print(f"wrote {args.out}", file=sys.stderr)
    if not report["gate_ok"]:
        print("GATE " + ("SKIPPED" if args.no_gate else "FAILED")
              + ": a scenario's tuned config failed to match its "
                "measured default", file=sys.stderr)
        return 0 if args.no_gate else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
