"""NDArray frontend tests (model: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_creation_and_basic_props():
    x = nd.array(np.arange(12, dtype=np.float64).reshape(3, 4))
    assert x.shape == (3, 4)
    assert x.size == 12
    assert x.ndim == 2
    assert x.dtype == np.float32  # float64 source narrows by default
    assert nd.array(np.arange(3)).dtype == np.int32  # int64 narrows too
    assert nd.zeros((2, 2)).asnumpy().sum() == 0
    assert nd.ones((2, 2)).asnumpy().sum() == 4
    assert nd.full((2,), 7).asnumpy().tolist() == [7, 7]
    np.testing.assert_allclose(nd.arange(0, 6, 2).asnumpy(), [0, 2, 4])


def test_arithmetic_matches_numpy():
    a = np.random.randn(3, 4).astype("float32")
    b = np.random.randn(3, 4).astype("float32")
    x, y = nd.array(a), nd.array(b)
    np.testing.assert_allclose((x + y).asnumpy(), a + b, rtol=1e-6)
    np.testing.assert_allclose((x - y).asnumpy(), a - b, rtol=1e-6)
    np.testing.assert_allclose((x * y).asnumpy(), a * b, rtol=1e-6)
    np.testing.assert_allclose((x / y).asnumpy(), a / b, rtol=1e-5)
    np.testing.assert_allclose((x + 2).asnumpy(), a + 2, rtol=1e-6)
    np.testing.assert_allclose((2 - x).asnumpy(), 2 - a, rtol=1e-6)
    np.testing.assert_allclose((1.0 / (x + 10)).asnumpy(), 1 / (a + 10), rtol=1e-5)
    np.testing.assert_allclose((-x).asnumpy(), -a)
    np.testing.assert_allclose((x ** 2).asnumpy(), a ** 2, rtol=1e-5)
    # numpy-array rhs
    np.testing.assert_allclose((x + b).asnumpy(), a + b, rtol=1e-6)


def test_broadcast_and_comparison():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    row = nd.array([10.0, 20.0])
    np.testing.assert_allclose((x + row).asnumpy(), [[11, 22], [13, 24]])
    assert (x > 2).asnumpy().tolist() == [[0, 0], [1, 1]]
    assert (x == 3).asnumpy().tolist() == [[0, 0], [1, 0]]


def test_reshape_transpose_slice():
    x = nd.array(np.arange(24).reshape(2, 3, 4))
    assert x.reshape((6, 4)).shape == (6, 4)
    assert x.reshape((0, -1)).shape == (2, 12)
    assert x.reshape((-3, 0)).shape == (6, 4)
    assert x.transpose().shape == (4, 3, 2)
    assert x.transpose((1, 0, 2)).shape == (3, 2, 4)
    assert x.T.shape == (4, 3, 2)
    assert x[1].shape == (3, 4)
    assert x[:, 1:3].shape == (2, 2, 4)
    assert x.flatten().shape == (2, 12)
    assert x.expand_dims(0).shape == (1, 2, 3, 4)
    assert nd.slice_axis(x, axis=2, begin=1, end=3).shape == (2, 3, 2)


def test_setitem():
    x = nd.zeros((3, 3))
    x[1] = 5
    x[0, 2] = 7
    a = x.asnumpy()
    assert a[1].tolist() == [5, 5, 5]
    assert a[0, 2] == 7
    x[:] = 1
    assert x.asnumpy().sum() == 9


def test_reductions():
    a = np.random.rand(3, 4, 5).astype("float32")
    x = nd.array(a)
    np.testing.assert_allclose(x.sum().asscalar(), a.sum(), rtol=1e-5)
    np.testing.assert_allclose(x.mean(axis=1).asnumpy(), a.mean(1), rtol=1e-5)
    np.testing.assert_allclose(x.max(axis=(0, 2)).asnumpy(), a.max((0, 2)))
    np.testing.assert_allclose(x.argmax(axis=1).asnumpy(), a.argmax(1))
    np.testing.assert_allclose(x.norm().asscalar(),
                               np.sqrt((a ** 2).sum()), rtol=1e-5)


def test_concat_stack_split():
    x, y = nd.ones((2, 3)), nd.zeros((2, 3))
    assert nd.concat(x, y, dim=0).shape == (4, 3)
    assert nd.stack(x, y, axis=0).shape == (2, 2, 3)
    parts = nd.split(nd.ones((2, 6)), num_outputs=3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 2)
    sq = nd.split(nd.ones((2, 3)), num_outputs=3, axis=1, squeeze_axis=True)
    assert sq[0].shape == (2,)


def test_dot():
    a = np.random.randn(3, 4).astype("float32")
    b = np.random.randn(4, 5).astype("float32")
    np.testing.assert_allclose(nd.dot(nd.array(a), nd.array(b)).asnumpy(),
                               a @ b, rtol=1e-5)
    np.testing.assert_allclose(
        nd.dot(nd.array(a), nd.array(b.T), transpose_b=True).asnumpy(),
        a @ b, rtol=1e-5)
    bd = nd.batch_dot(nd.ones((2, 3, 4)), nd.ones((2, 4, 5)))
    assert bd.shape == (2, 3, 5)


def test_scalar_and_truthiness():
    s = nd.array(3.5)
    assert s.asscalar() == pytest.approx(3.5)
    with pytest.raises(mx.MXNetError):
        bool(nd.ones((3,)))


def test_astype_copy_context():
    x = nd.array([1.5, 2.5])
    assert str(x.astype("int32").data.dtype) == "int32"
    y = x.copy()
    y[:] = 0
    assert x.asnumpy().sum() == 4.0
    z = x.as_in_context(mx.cpu(0))
    assert z.ctx == mx.cpu(0)


def test_save_load_roundtrip(tmp_path):
    f = str(tmp_path / "t.params")
    d = {"a": nd.array(np.random.rand(3, 2).astype("float32")),
         "b": nd.arange(0, 5, dtype="int32")}
    nd.save(f, d)
    back = nd.load(f)
    assert set(back) == {"a", "b"}
    np.testing.assert_allclose(back["a"].asnumpy(), d["a"].asnumpy())
    np.testing.assert_array_equal(back["b"].asnumpy(), d["b"].asnumpy())
    nd.save(f, [nd.ones((2,))])
    assert isinstance(nd.load(f), list)


def test_take_pick_onehot_where():
    x = nd.array(np.arange(12).reshape(3, 4))
    t = nd.take(x, nd.array([0, 2]), axis=0)
    assert t.shape == (2, 4)
    p = nd.pick(x, nd.array([0, 1, 2]), axis=1)
    np.testing.assert_allclose(p.asnumpy(), [0, 5, 10])
    oh = nd.one_hot(nd.array([0, 2]), depth=3)
    np.testing.assert_allclose(oh.asnumpy(), [[1, 0, 0], [0, 0, 1]])
    w = nd.where(nd.array([1.0, 0.0]), nd.array([1.0, 1.0]), nd.array([2.0, 2.0]))
    np.testing.assert_allclose(w.asnumpy(), [1, 2])


def test_random_reproducibility():
    mx.random.seed(42)
    a = nd.random.uniform(shape=(5,)).asnumpy()
    mx.random.seed(42)
    b = nd.random.uniform(shape=(5,)).asnumpy()
    np.testing.assert_array_equal(a, b)
    c = nd.random.normal(loc=2.0, scale=0.001, shape=(1000,)).asnumpy()
    assert abs(c.mean() - 2.0) < 0.01


# ---------------------------------------------------------------------------
# N2: view aliasing semantics (ref: NDArray::Slice/Reshape/At share
# storage; writes through a view are visible in the base and siblings)
# ---------------------------------------------------------------------------

def test_view_write_through_slice():
    x = mx.nd.zeros((4, 3))
    v = x[1:3]
    assert v.is_view and v.shape == (2, 3)
    v[:] = 7.0
    np.testing.assert_array_equal(x.asnumpy()[1:3], np.full((2, 3), 7.0))
    np.testing.assert_array_equal(x.asnumpy()[0], np.zeros(3))
    # base write visible through the view
    x[:] = 1.0
    np.testing.assert_array_equal(v.asnumpy(), np.ones((2, 3)))


def test_view_reshape_aliases():
    x = mx.nd.arange(6)
    m = x.reshape((2, 3))
    assert m.is_view
    m[0, 0] = 100.0
    assert float(x.asnumpy()[0]) == 100.0
    x[5] = -1.0
    assert float(m.asnumpy()[1, 2]) == -1.0


def test_view_at_and_sibling_views():
    x = mx.nd.zeros((3, 2))
    a = x.at(0)
    b = x[0]          # overlapping sibling view
    a[:] = 5.0
    np.testing.assert_array_equal(b.asnumpy(), np.full((2,), 5.0))


def test_view_of_view_chain():
    x = mx.nd.zeros((4, 4))
    v1 = x[1:3]            # (2,4)
    v2 = v1.reshape((8,))  # view of view
    v2[0] = 9.0
    assert float(x.asnumpy()[1, 0]) == 9.0
    v3 = v2.reshape((2, 4))[1]
    v3[:] = 4.0
    np.testing.assert_array_equal(x.asnumpy()[2], np.full((4,), 4.0))


def test_view_slice_axis_and_slice():
    x = mx.nd.zeros((4, 6))
    s = x.slice_axis(1, 2, 5)
    assert s.is_view and s.shape == (4, 3)
    s[:] = 3.0
    assert float(x.asnumpy()[:, 2:5].min()) == 3.0
    t = x.slice((0, 0), (2, 2))
    t[:] = -2.0
    assert float(x.asnumpy()[:2, :2].max()) == -2.0


def test_view_iadd_writes_through():
    x = mx.nd.ones((4,))
    v = x[1:3]
    v += 10.0
    np.testing.assert_array_equal(x.asnumpy(), [1.0, 11.0, 11.0, 1.0])


def test_advanced_indexing_still_copies():
    x = mx.nd.zeros((4,))
    idx = mx.nd.array(np.array([0, 2], np.int32))
    g = x[idx]
    assert not g.is_view  # advanced indexing -> copy (reference parity)


def test_views_not_aliased_under_autograd():
    """Inside record() these methods must produce tape-backed op outputs
    so gradients flow; aliasing is an eager-mode-only contract."""
    x = mx.nd.ones((2, 3))
    x.attach_grad()
    with mx.autograd.record():
        y = x.reshape((6,))
        assert not y.is_view
        z = (y * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * np.ones((2, 3)))


def test_view_numpy_int_index_aliases():
    x = mx.nd.zeros((4, 3))
    i = np.int64(1)
    v = x[i]
    assert v.is_view  # np.integer must behave exactly like int
    v[:] = 2.0
    np.testing.assert_array_equal(x.asnumpy()[1], np.full((3,), 2.0))


def test_view_reshape_special_codes_alias():
    x = mx.nd.zeros((2, 3, 4))
    for spec, shape in ((( -3, 0), (6, 4)), ((0, -2), (2, 3, 4)),
                        ((-4, 1, 2, -2), (1, 2, 3, 4)), ((0, 0, -1), (2, 3, 4))):
        v = x.reshape(spec)
        assert v.is_view and v.shape == shape, (spec, v.shape)
        v[(0,) * len(shape)] = 5.0
        assert float(x.asnumpy().ravel()[0]) == 5.0
        x[:] = 0.0
