#!/usr/bin/env python
"""Offline compile-cache warmup (ISSUE 7): populate the persistent
AOT executable store BEFORE a process needs it, so a deploy /
preemption restart / autoscale-up serves its first request and takes
its first fused step with zero XLA compiles.

Two warmup modes (combine freely across invocations — entries are
content-addressed, re-warming is idempotent):

  * serving — given a deploy artifact directory and a bucket ladder,
    compile one executable per allowed bucket into the cache::

        python tools/warm_cache.py --cache-dir /var/mx-cache \\
            --artifact /models/mlp/3 --buckets 1,4,8,16

  * optimizer — given an optimizer config and the parameter shapes of
    a training job, compile the fused-step executable::

        python tools/warm_cache.py --cache-dir /var/mx-cache \\
            --optimizer sgd --opt-args learning_rate=0.1,momentum=0.9 \\
            --shapes 256x128,128

The warmer runs on the SAME backend the consumer will (the cache key
pins jax/jaxlib versions, platform, and device kind): warm on a TPU
host for TPU serving, on CPU for CPU tests.  Output is one JSON line —
entries written, cache stats, bytes on disk — suitable for a deploy
pipeline log.

See docs/compile_cache.md for the full warmup workflow.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _parse_shapes(spec: str):
    """"256x128,128" -> [(256, 128), (128,)]"""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if part:
            out.append(tuple(int(d) for d in part.split("x")))
    return out


def _parse_opt_args(spec: str) -> dict:
    out = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition("=")
        try:
            out[k] = float(v)
        except ValueError:
            out[k] = v
    return out


def warm_serving(artifact: str, buckets) -> dict:
    from mxnet_tpu import serving

    repo = serving.ModelRepository()
    repo.add("__warm__", artifact)
    entry = repo.get("__warm__")
    allowed = entry.allowed_buckets(list(buckets))
    done = []
    for b in (allowed or [entry.fixed_batch() or 1]):
        entry.executable(b)
        done.append(b)
    return {"artifact": artifact, "buckets_warmed": done}


def warm_optimizer(name: str, opt_args: dict, shapes, dtype: str,
                   multi_precision: bool) -> dict:
    from mxnet_tpu import nd, optimizer as opt_mod
    from mxnet_tpu.optimizer.fused import FusedUpdater

    if multi_precision:
        opt_args = dict(opt_args, multi_precision=True)
    opt = opt_mod.create(name, **opt_args)
    updater = FusedUpdater(opt)
    rng = np.random.RandomState(0)
    weights = [nd.array(rng.rand(*s).astype(dtype)) for s in shapes]
    grads = [nd.array(np.zeros(s, dtype)) for s in shapes]
    indices = list(range(len(weights)))
    updater.update_all(indices, grads, weights)
    return {"optimizer": name, "shapes": [list(s) for s in shapes],
            "dtype": dtype}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cache-dir", required=True,
                    help="the persistent compile-cache directory "
                    "(MXNET_COMPILE_CACHE_DIR of the consumers)")
    ap.add_argument("--cache-bytes", type=int, default=0,
                    help="byte cap to enforce while warming "
                    "(0 = unbounded)")
    ap.add_argument("--artifact", default=None,
                    help="deploy artifact directory to warm serving "
                    "executables for")
    ap.add_argument("--buckets", default="1,4,8",
                    help="padded-batch bucket ladder to warm")
    ap.add_argument("--optimizer", default=None,
                    help="optimizer name to warm a fused-step "
                    "executable for (e.g. sgd, adam)")
    ap.add_argument("--opt-args", default="",
                    help="optimizer kwargs, k=v comma-separated")
    ap.add_argument("--shapes", default=None,
                    help="parameter shapes, e.g. 256x128,128")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--multi-precision", action="store_true")
    args = ap.parse_args()

    if not args.artifact and not args.optimizer:
        ap.error("nothing to warm: pass --artifact and/or --optimizer")
    if args.optimizer and not args.shapes:
        ap.error("--optimizer needs --shapes")

    from mxnet_tpu import compile_cache as cc

    cc.reset(cc.CompileCache(disk_dir=args.cache_dir,
                             cap_bytes=args.cache_bytes))
    report = {"tool": "warm_cache", "cache_dir": args.cache_dir}
    if args.artifact:
        buckets = [int(b) for b in args.buckets.split(",") if b.strip()]
        report["serving"] = warm_serving(args.artifact, buckets)
    if args.optimizer:
        report["optimizer"] = warm_optimizer(
            args.optimizer, _parse_opt_args(args.opt_args),
            _parse_shapes(args.shapes), args.dtype,
            args.multi_precision)
    report["stats"] = cc.stats()
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
