"""Op registry package — importing this module registers the core op set.

Counterpart of the reference's operator registration at library-load time
(ref: src/operator/** static NNVM_REGISTER_OP initialisers, listed through
MXListAllOpNames and surfaced to Python by generated wrappers).
"""
from . import registry
from .registry import (OP_REGISTRY, Operator, apply_pure, get_op, invoke,
                       list_ops, register_op)

# registration side effects
from . import tensor  # noqa: F401
from . import nn  # noqa: F401
from . import rnn  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import random_ops  # noqa: F401
from . import contrib  # noqa: F401
from . import pallas_attention  # noqa: F401
from . import pallas_convbn  # noqa: F401
from . import linalg  # noqa: F401
from . import image_ops  # noqa: F401
from . import quantization  # noqa: F401

__all__ = ["registry", "OP_REGISTRY", "Operator", "apply_pure", "get_op",
           "invoke", "list_ops", "register_op"]
