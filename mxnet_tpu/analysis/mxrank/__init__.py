"""mxrank — cross-rank collective-schedule verification (static half).

The SPMD spine assumes every rank issues the same sequence of
collectives; GSPMD-style sharding and portable redistribution plans
take it as an axiom.  mxrank makes it a *checked invariant*:

  * ``taint.py`` — a two-bit rank/data taint lattice with collective
    results as the sanitizer;
  * ``rules.py`` — MX019 (rank-divergent schedule) and MX020
    (data-divergent schedule) on top of the mxflow project index.

The runtime half — the rolling schedule fingerprint every collective
site appends to, compared across ranks on watchdog timeout — lives in
``mxnet_tpu/parallel/schedule.py``; see docs/static_analysis.md for
the rule catalogue and docs/resilience.md for the ScheduleDivergence
failure classification the fingerprints feed.

Stdlib-only like the rest of the analysis package (the CLI loads it
without jax).
"""
# NOTE one-level relative imports only — see analysis/__init__ for why
# the two-level form breaks the standalone (jax-free) load.
from .rules import (  # noqa: F401  — registers MX019–MX020
    DataDivergentSchedule, RankDivergentSchedule,
)
from .taint import (  # noqa: F401
    COLLECTIVE_NAMES, DATA, RANK, Divergence, ModuleTaint, taint_names,
)

__all__ = [
    "RankDivergentSchedule", "DataDivergentSchedule",
    "ModuleTaint", "Divergence", "RANK", "DATA", "taint_names",
    "COLLECTIVE_NAMES",
]
