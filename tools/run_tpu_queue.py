"""Run the whole on-chip measurement queue in one command.

The TPU tunnel in this container dies for hours at a time (see
CHANGES_r04.md), so when a window opens, everything must land in one
shot — run this the moment a probe succeeds:

    python tools/run_tpu_queue.py [--round 4]

Sequential bounded steps (the tunnel is single-client — nothing may run
concurrently with this):
  1. tools/run_tpu_tests.py      -> TPU_TESTS_r0N.json (29-case lane)
  2. bench.py                    -> BENCH snapshot (unfused + fused in one run)
  3. bench_all.py                -> BENCH_ALL.json (5 configs + variants)
  4. tools/opperf.py --large     -> OPPERF_TPU.json
Each step's outcome is recorded in TPU_QUEUE_RESULTS.json; a failed or
timed-out step does not stop the rest.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", type=int, default=4)
    ap.add_argument("--out",
                    default=os.path.join(_REPO, "TPU_QUEUE_RESULTS.json"))
    args = ap.parse_args()

    n = args.round
    steps = [
        ("tpu_tests",
         [sys.executable, "tools/run_tpu_tests.py",
          "--out", f"TPU_TESTS_r{n:02d}.json"], 1800),
        ("bench",
         [sys.executable, "bench.py"], 2400),
        ("bench_all",
         [sys.executable, "bench_all.py"], 7200),
        ("opperf_tpu",
         [sys.executable, "tools/opperf.py", "--large",
          "--out", "OPPERF_TPU.json"], 2400),
    ]

    results = []
    for name, cmd, timeout in steps:
        t0 = time.time()
        try:
            p = subprocess.run(cmd, cwd=_REPO, capture_output=True,
                               text=True, timeout=timeout)
            tail = "\n".join((p.stdout + p.stderr).splitlines()[-5:])
            rec = {"step": name, "rc": p.returncode,
                   "seconds": round(time.time() - t0, 1), "tail": tail}
        except subprocess.TimeoutExpired:
            rec = {"step": name, "rc": -1, "timeout_s": timeout,
                   "seconds": round(time.time() - t0, 1)}
        results.append(rec)
        print(json.dumps(rec))
        with open(args.out, "w") as f:
            json.dump({"when": time.strftime("%Y-%m-%d %H:%M:%S"),
                       "round": n, "results": results}, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
