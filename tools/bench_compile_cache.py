#!/usr/bin/env python
"""Compile-cache bench (ISSUE 7 gate): measure what a FRESH PROCESS
pays before its first unit of work, cold vs warm.

Two scenarios, each timed inside a child process (the restart is the
thing being measured — in-process numbers would flatter the cache):

  * serving — construct a repository over a deploy artifact and serve
    the first request.  Cold: empty cache directory (artifact import +
    trace/lower + XLA compile).  Warm: the directory the cold child
    just populated (import + lower + disk load; zero XLA compiles,
    asserted via the serving compile counter).
  * fused — construct a FusedUpdater and take the first optimizer
    step.  Same cold/warm pair, asserted via
    ``optimizer.fused.compile_stats()``.

The measured window starts AFTER ``import mxnet_tpu`` and jax backend
init in the child: interpreter startup is identical cold and warm, and
the metric of record is "first request/step latency once the process
is up" — the number a deploy budget uses.

Gate (skipped with --no-gate, enforced strictly in
tests/nightly/test_bench_compile_cache.py and by the run_nightly
stage): warm serving must be >= --min-speedup (default 3x) faster than
cold, warm fused >= --min-fused-speedup (default 1.2x), and BOTH warm
children must report zero XLA compiles with at least one disk hit.

CPU smoke: JAX_PLATFORMS=cpu python tools/bench_compile_cache.py --no-gate
Writes COMPILE_CACHE.json (one JSON line also on stdout).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import numpy as np  # noqa: E402


# ---------------------------------------------------------------------------
# child halves (re-exec'd: `bench_compile_cache.py --child serving ...`)
# ---------------------------------------------------------------------------

def child_serving(artifact: str, cache_dir: str, bucket: int,
                  units: int) -> dict:
    from mxnet_tpu import compile_cache as cc
    from mxnet_tpu import nd, serving
    from mxnet_tpu.telemetry import instruments as ins

    import jax

    cc.reset(cc.CompileCache(disk_dir=cache_dir))
    x = nd.array(np.random.RandomState(7).rand(
        bucket, units).astype("float32"))
    # pre-warm jax machinery the MODEL's executable does not own
    # (PRNGKey program, dispatch plumbing): identical cold and warm,
    # and not something a compile cache could ever save — the measured
    # window is the model-attributable first-request latency
    jax.block_until_ready(jax.random.PRNGKey(0))

    t0 = time.perf_counter()
    repo = serving.ModelRepository()
    repo.add("bench", artifact)
    entry = repo.get("bench")
    out = entry.execute(bucket, [x.data])
    jax.block_until_ready(out)  # the response really materialized
    first_request_s = time.perf_counter() - t0

    return {
        "first_request_s": first_request_s,
        "xla_compiles": ins.serving_compile_total("bench", 1).value,
        "cache": cc.stats(),
    }


def child_fused(cache_dir: str, params: int, units: int) -> dict:
    from mxnet_tpu import compile_cache as cc
    from mxnet_tpu import nd, optimizer as opt_mod
    from mxnet_tpu.optimizer import fused

    cc.reset(cc.CompileCache(disk_dir=cache_dir))
    rng = np.random.RandomState(3)
    shapes = [(units, units)] * params

    t0 = time.perf_counter()
    opt = opt_mod.create("sgd", learning_rate=0.05, momentum=0.9)
    updater = fused.FusedUpdater(opt)
    weights = [nd.array(rng.rand(*s).astype("float32"))
               for s in shapes]
    grads = [nd.array(rng.rand(*s).astype("float32")) for s in shapes]
    updater.update_all(list(range(params)), grads, weights)
    weights[0].asnumpy()  # sync: the step really finished
    first_step_s = time.perf_counter() - t0

    return {
        "first_step_s": first_step_s,
        "xla_compiles": fused.compile_stats()["count"],
        "cache": cc.stats(),
    }


# ---------------------------------------------------------------------------
# parent: build artifact, run cold/warm children, gate
# ---------------------------------------------------------------------------

def _make_artifact(units: int, hidden: int, depth: int) -> str:
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.contrib import deploy
    from mxnet_tpu.gluon import nn

    art = tempfile.mkdtemp(prefix="mx-ccbench-art-")
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(hidden, activation="relu", in_units=units))
        for _ in range(depth - 2):
            net.add(nn.Dense(hidden, activation="relu",
                             in_units=hidden))
        net.add(nn.Dense(4, in_units=hidden))
    net.initialize(ctx=mx.cpu())
    x = nd.array(np.random.RandomState(0).rand(2, units).astype("f4"))
    deploy.export_model(net, art, [x], dynamic_batch=True)
    return art


def _run_child(kind: str, repeats: int, fresh_dir_each: bool = False,
               **kw) -> dict:
    """Best-of-N child runs (first-request latency is noisy on a
    shared CPU box; the best run is the least-interfered one).

    ``fresh_dir_each`` is REQUIRED for cold measurements: a cold child
    populates its cache directory, so a second repeat against the same
    directory would silently measure the warm path."""
    best = None
    for _ in range(repeats):
        run_kw = dict(kw)
        if fresh_dir_each:
            run_kw["cache_dir"] = tempfile.mkdtemp(
                prefix="mx-ccbench-cold-")
        argv = [sys.executable, os.path.abspath(__file__),
                "--child", kind]
        for k, v in run_kw.items():
            argv += [f"--{k.replace('_', '-')}", str(v)]
        p = subprocess.run(argv, capture_output=True, text=True,
                           cwd=_REPO, timeout=600)
        if p.returncode != 0:
            raise RuntimeError(
                f"child {kind} failed:\n{p.stdout[-2000:]}"
                f"\n{p.stderr[-2000:]}")
        row = json.loads([ln for ln in p.stdout.splitlines()
                          if ln.startswith("{")][-1])
        metric = row.get("first_request_s", row.get("first_step_s"))
        if best is None or metric < best[0]:
            best = (metric, row)
    return best[1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", default=None,
                    choices=("serving", "fused"))
    ap.add_argument("--artifact", default=None)
    ap.add_argument("--cache-dir", default=None)
    ap.add_argument("--bucket", type=int, default=4)
    ap.add_argument("--units", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=192)
    ap.add_argument("--depth", type=int, default=48,
                    help="dense layers in the serving artifact")
    ap.add_argument("--params", type=int, default=64,
                    help="parameter tensors in the fused scenario")
    ap.add_argument("--fused-units", type=int, default=32)
    ap.add_argument("--repeats", type=int, default=1,
                    help="children per measurement (best-of)")
    ap.add_argument("--scenarios", default="serving,fused",
                    help="comma subset of serving,fused (the tier-1 "
                    "smoke runs one scenario to stay cheap; the "
                    "nightly gate runs both)")
    ap.add_argument("--min-speedup", type=float, default=3.0)
    ap.add_argument("--min-fused-speedup", type=float, default=1.2)
    ap.add_argument("--no-gate", action="store_true",
                    help="report only (tier-1 smoke); the strict gate "
                    "runs in tests/nightly/test_bench_compile_cache.py")
    ap.add_argument("--out", default=None,
                    help="also write the report JSON here "
                    "(COMPILE_CACHE.json)")
    args = ap.parse_args()

    if args.child == "serving":
        print(json.dumps(child_serving(args.artifact, args.cache_dir,
                                       args.bucket, args.units)))
        return 0
    if args.child == "fused":
        print(json.dumps(child_fused(args.cache_dir, args.params,
                                     args.fused_units)))
        return 0

    scenarios = [s.strip() for s in args.scenarios.split(",")
                 if s.strip()]
    bad = [s for s in scenarios if s not in ("serving", "fused")]
    if bad:
        ap.error(f"unknown scenario(s) {bad}")

    report = {
        "bench": "compile_cache",
        "backend": "cpu" if os.environ.get("JAX_PLATFORMS") == "cpu"
        else "auto",
        "gate": {"min_speedup": args.min_speedup,
                 "min_fused_speedup": args.min_fused_speedup},
    }
    gate_ok = True

    if "serving" in scenarios:
        artifact = args.artifact or _make_artifact(
            args.units, args.hidden, args.depth)
        sv_dir = tempfile.mkdtemp(prefix="mx-ccbench-sv-")
        # warm the shared dir once (timing discarded), THEN measure:
        # cold children each get a fresh empty directory, warm
        # children share the pre-populated one
        _run_child("serving", 1, artifact=artifact, cache_dir=sv_dir,
                   bucket=args.bucket, units=args.units)
        sv_cold = _run_child("serving", args.repeats,
                             fresh_dir_each=True, artifact=artifact,
                             bucket=args.bucket, units=args.units)
        sv_warm = _run_child("serving", args.repeats,
                             artifact=artifact, cache_dir=sv_dir,
                             bucket=args.bucket, units=args.units)
        sv_speed = sv_cold["first_request_s"] / \
            max(sv_warm["first_request_s"], 1e-9)
        report["serving"] = {
            "cold_first_request_s": round(
                sv_cold["first_request_s"], 4),
            "warm_first_request_s": round(
                sv_warm["first_request_s"], 4),
            "speedup": round(sv_speed, 2),
            "cold_xla_compiles": sv_cold["xla_compiles"],
            "warm_xla_compiles": sv_warm["xla_compiles"],
            "warm_disk_hits": sv_warm["cache"].get("disk_hits", 0),
        }
        gate_ok = (gate_ok and sv_speed >= args.min_speedup
                   and report["serving"]["cold_xla_compiles"] > 0
                   and report["serving"]["warm_xla_compiles"] == 0
                   and report["serving"]["warm_disk_hits"] > 0)

    if "fused" in scenarios:
        fu_dir = tempfile.mkdtemp(prefix="mx-ccbench-fu-")
        _run_child("fused", 1, cache_dir=fu_dir, params=args.params,
                   fused_units=args.fused_units)
        fu_cold = _run_child("fused", args.repeats,
                             fresh_dir_each=True, params=args.params,
                             fused_units=args.fused_units)
        fu_warm = _run_child("fused", args.repeats, cache_dir=fu_dir,
                             params=args.params,
                             fused_units=args.fused_units)
        fu_speed = fu_cold["first_step_s"] / \
            max(fu_warm["first_step_s"], 1e-9)
        report["fused"] = {
            "cold_first_step_s": round(fu_cold["first_step_s"], 4),
            "warm_first_step_s": round(fu_warm["first_step_s"], 4),
            "speedup": round(fu_speed, 2),
            "cold_xla_compiles": fu_cold["xla_compiles"],
            "warm_xla_compiles": fu_warm["xla_compiles"],
            "warm_disk_hits": fu_warm["cache"].get("disk_hits", 0),
        }
        gate_ok = (gate_ok and fu_speed >= args.min_fused_speedup
                   and report["fused"]["cold_xla_compiles"] > 0
                   and report["fused"]["warm_xla_compiles"] == 0
                   and report["fused"]["warm_disk_hits"] > 0)

    report["gate_ok"] = bool(gate_ok)
    line = json.dumps(report)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    if args.no_gate:
        return 0
    return 0 if gate_ok else 1


if __name__ == "__main__":
    sys.exit(main())
