"""Per-rule mxlint fixtures: at least one positive (flagged) and one
negative (clean) program per rule, plus pragma suppression and the
baseline ratchet (ISSUE 4)."""
import textwrap

import pytest

from mxnet_tpu import analysis


def lint_source(tmp_path, source, enable=None, name="fixture.py"):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    eng = analysis.LintEngine(root=str(tmp_path), enable=enable)
    return eng.run([str(f)])


def rules_hit(violations):
    return sorted({v.rule for v in violations})


# ---------------------------------------------------------------------------
# MX001 — recompile hazard
# ---------------------------------------------------------------------------

class TestMX001:
    def test_flags_int_coercion_in_jitted_function(self, tmp_path):
        vs = lint_source(tmp_path, """
            import jax

            @jax.jit
            def step(x):
                return x * int(x)
            """, enable=["MX001"])
        assert rules_hit(vs) == ["MX001"]
        assert "int()" in vs[0].message

    def test_flags_item_in_jit_wrapped_local_function(self, tmp_path):
        vs = lint_source(tmp_path, """
            import jax

            def loss(x):
                return x.item() + 1.0

            loss_c = jax.jit(loss, donate_argnums=())
            """, enable=["MX001"])
        assert rules_hit(vs) == ["MX001"]

    def test_flags_np_asarray_under_partial_jit_decorator(self, tmp_path):
        vs = lint_source(tmp_path, """
            import functools
            import jax
            import numpy as np

            @functools.partial(jax.jit, static_argnums=(1,))
            def fwd(x, n):
                return np.asarray(x) + n
            """, enable=["MX001"])
        assert rules_hit(vs) == ["MX001"]

    def test_clean_shape_derived_scalars_and_unjitted_code(self, tmp_path):
        vs = lint_source(tmp_path, """
            import jax

            @jax.jit
            def step(x):
                n = int(x.shape[0])
                k = float(len(x.shape))
                return x * n + k

            def eager(x):
                return int(x)  # not a jit context
            """, enable=["MX001"])
        assert vs == []


# ---------------------------------------------------------------------------
# MX002 — host sync in the hot path
# ---------------------------------------------------------------------------

class TestMX002:
    def test_flags_asnumpy_inside_record_block(self, tmp_path):
        vs = lint_source(tmp_path, """
            def train(net, x, autograd):
                with autograd.record():
                    y = net(x)
                    v = y.asnumpy()
                return v
            """, enable=["MX002"])
        assert rules_hit(vs) == ["MX002"]
        assert "record()" in vs[0].message

    def test_flags_np_asarray_in_trainer_step_chain(self, tmp_path):
        vs = lint_source(tmp_path, """
            import numpy as np

            class MyTrainer:
                def step(self, batch_size):
                    g = self._grads[0]
                    return np.asarray(g)
            """, enable=["MX002"])
        assert rules_hit(vs) == ["MX002"]

    def test_clean_outside_hot_paths(self, tmp_path):
        vs = lint_source(tmp_path, """
            import numpy as np

            class MyTrainer:
                def save_states(self, fname):
                    # serialization is a cold path
                    return np.asarray(self._state)

            def evaluate(y):
                return y.asnumpy()
            """, enable=["MX002"])
        assert vs == []

    def test_helper_syncs_are_not_mx002s_job(self, tmp_path):
        # the one-level special case moved to MX009 (mxflow follows
        # the whole call graph); MX002 is direct-sync-only now
        vs = lint_source(tmp_path, """
            class MyTrainer:
                def _log_grads(self):
                    return self._grads[0].asnumpy()

                def step(self, batch_size):
                    self._log_grads()
            """, enable=["MX002"])
        assert vs == []


# ---------------------------------------------------------------------------
# MX009 — transitive host sync (mxflow)
# ---------------------------------------------------------------------------

class TestMX009:
    def test_flags_self_helper_sync_at_step_call_site(self, tmp_path):
        vs = lint_source(tmp_path, """
            class MyTrainer:
                def _log_grads(self):
                    return self._grads[0].asnumpy()

                def step(self, batch_size):
                    self._log_grads()
            """, enable=["MX009"])
        assert rules_hit(vs) == ["MX009"]
        # flagged at the CALL site inside step, naming the helper
        assert vs[0].symbol == "MyTrainer.step"
        assert "_log_grads()" in vs[0].message

    def test_flags_module_helper_called_inside_record(self, tmp_path):
        vs = lint_source(tmp_path, """
            def log_loss(y):
                return y.asnumpy()

            def train(net, x, autograd):
                with autograd.record():
                    v = log_loss(net(x))
                return v
            """, enable=["MX009"])
        assert rules_hit(vs) == ["MX009"]
        assert vs[0].symbol == "train"
        assert "log_loss()" in vs[0].message

    def test_transitive_sync_two_calls_deep_is_flagged(self, tmp_path):
        # exactly what MX002's one-level special case could not see
        vs = lint_source(tmp_path, """
            def inner(y):
                return y.asnumpy()

            def outer(y):
                return inner(y)  # sync is TWO calls away from step

            class MyTrainer:
                def step(self, batch_size):
                    return outer(self._g)
            """, enable=["MX009"])
        assert rules_hit(vs) == ["MX009"]
        assert vs[0].symbol == "MyTrainer.step"
        assert "outer()" in vs[0].message
        assert "inner()" in vs[0].message  # the witness path

    def test_clean_helper_without_sync_and_cold_callers(self, tmp_path):
        vs = lint_source(tmp_path, """
            def pure_helper(y):
                return y * 2

            def syncing_helper(y):
                return y.asnumpy()

            class MyTrainer:
                def step(self, batch_size):
                    return pure_helper(self._g)  # no sync inside

                def save_states(self, fname):
                    return syncing_helper(self._g)  # cold path caller
            """, enable=["MX009"])
        assert vs == []

    def test_helper_pragma_suppresses_the_call_site_too(self, tmp_path):
        # a pragma ON the sync line blesses the whole transitive chain
        vs = lint_source(tmp_path, """
            import numpy as np

            class MyTrainer:
                def _pack(self):
                    # host floats, not device arrays
                    return np.asarray(self._hypers)  # mxlint: disable=MX002

                def step(self, batch_size):
                    return self._pack()
            """, enable=["MX009"])
        assert vs == []

    def test_flags_self_helper_in_record_block_inside_method(self, tmp_path):
        # record() blocks written in methods resolve self.<helper> too
        vs = lint_source(tmp_path, """
            class Runner:
                def _log(self):
                    return self._y.asnumpy()

                def fit(self, net, x, autograd):
                    with autograd.record():
                        self._y = net(x)
                        self._log()
            """, enable=["MX009"])
        assert rules_hit(vs) == ["MX009"]
        assert "_log()" in vs[0].message

    def test_unresolvable_call_is_conservatively_clean(self, tmp_path):
        vs = lint_source(tmp_path, """
            from some_third_party import mystery

            class MyTrainer:
                def step(self, batch_size):
                    return mystery(self._g)  # cannot resolve: no claim
            """, enable=["MX009"])
        assert vs == []


# ---------------------------------------------------------------------------
# MX003 — untracked env knob
# ---------------------------------------------------------------------------

class TestMX003:
    def test_flags_raw_reads_of_mxnet_names(self, tmp_path):
        vs = lint_source(tmp_path, """
            import os
            from .base import get_env

            a = os.environ.get("MXNET_FOO")
            b = os.environ["MXNET_BAR"]
            c = os.getenv("MXNET_BAZ", "0")
            d = get_env("MXNET_QUX", 1, int)
            """, enable=["MX003"])
        assert len(vs) == 4
        assert rules_hit(vs) == ["MX003"]

    def test_clean_registry_reads_and_foreign_vars(self, tmp_path):
        vs = lint_source(tmp_path, """
            import os
            from .util import env

            a = env.get_bool("MXNET_FOO")
            b = os.environ.get("DMLC_ROLE")
            os.environ["JAX_PLATFORMS"] = "cpu"
            """, enable=["MX003"])
        assert vs == []


# ---------------------------------------------------------------------------
# MX004 — unguarded shared state
# ---------------------------------------------------------------------------

class TestMX004:
    def test_flags_unguarded_cache_write(self, tmp_path):
        vs = lint_source(tmp_path, """
            _CACHE = {}
            _LOG = []

            def put(k, v):
                _CACHE[k] = v

            def note(msg):
                _LOG.append(msg)
            """, enable=["MX004"])
        assert len(vs) == 2
        assert rules_hit(vs) == ["MX004"]

    def test_clean_with_lock_and_module_level_init(self, tmp_path):
        vs = lint_source(tmp_path, """
            import threading

            _CACHE = {}
            _lock = threading.Lock()
            _CACHE["seed"] = 1  # import-time init is single-threaded

            def put(k, v):
                with _lock:
                    _CACHE[k] = v

            def put_method(self, k, v):
                with self._jit_lock:
                    _CACHE[k] = v
            """, enable=["MX004"])
        assert vs == []

    def test_local_shadowing_dict_is_clean(self, tmp_path):
        vs = lint_source(tmp_path, """
            _CACHE = {}

            def pure(k, v):
                local = {}
                local[k] = v
                return local
            """, enable=["MX004"])
        assert vs == []


# ---------------------------------------------------------------------------
# MX005 — donation misuse
# ---------------------------------------------------------------------------

class TestMX005:
    def test_flags_read_after_donating_call(self, tmp_path):
        vs = lint_source(tmp_path, """
            import jax

            def run(fn, x, y):
                f = jax.jit(fn, donate_argnums=(0,))
                out = f(x, y)
                return out + x
            """, enable=["MX005"])
        assert rules_hit(vs) == ["MX005"]
        assert "`x`" in vs[0].message

    def test_flags_inline_jit_donation(self, tmp_path):
        vs = lint_source(tmp_path, """
            import jax

            def run(fn, w, g):
                new_w = jax.jit(fn, donate_argnums=(0,))(w, g)
                stale = w.sum()
                return new_w, stale
            """, enable=["MX005"])
        assert rules_hit(vs) == ["MX005"]

    def test_clean_same_statement_rebind_idiom(self, tmp_path):
        # `state = step(state, batch)` is THE canonical donation
        # pattern — it must never be flagged
        vs = lint_source(tmp_path, """
            import jax

            def train(step_fn, w, batches):
                f = jax.jit(step_fn, donate_argnums=(0,))
                for b in batches:
                    w = f(w, b)
                return w
            """, enable=["MX005"])
        assert vs == []

    def test_clean_undonated_and_rebound_reads(self, tmp_path):
        vs = lint_source(tmp_path, """
            import jax

            def run(fn, x, y):
                f = jax.jit(fn, donate_argnums=(0,))
                out = f(x, y)
                use = y + 1  # position 1 is NOT donated
                x = out      # rebound: the old buffer is gone
                return x + use
            """, enable=["MX005"])
        assert vs == []


# ---------------------------------------------------------------------------
# MX006 — op-registry contract
# ---------------------------------------------------------------------------

class TestMX006:
    def test_flags_duplicate_name_and_missing_docstring(self, tmp_path):
        vs = lint_source(tmp_path, """
            from .registry import register_op

            @register_op("relu")
            def relu(x):
                return x

            @register_op("relu6", aliases=("relu",))
            def relu6(x):
                \"\"\"Clipped relu.\"\"\"
                return x
            """, enable=["MX006"])
        # relu: missing docstring; relu6's alias duplicates 'relu'
        assert len(vs) == 2
        assert any("no docstring" in v.message for v in vs)
        assert any("already registered" in v.message for v in vs)

    def test_duplicates_detected_across_files(self, tmp_path):
        (tmp_path / "a.py").write_text(textwrap.dedent("""
            @register_op("Conv")
            def conv(x):
                \"\"\"doc\"\"\"
                return x
            """))
        (tmp_path / "b.py").write_text(textwrap.dedent("""
            @register_op("Conv")
            def conv2(x):
                \"\"\"doc\"\"\"
                return x
            """))
        eng = analysis.LintEngine(root=str(tmp_path), enable=["MX006"])
        vs = eng.run([str(tmp_path)])
        assert len(vs) == 1 and "already registered" in vs[0].message

    def test_clean_unique_documented_op(self, tmp_path):
        vs = lint_source(tmp_path, """
            @register_op("Softmax", aliases=("softmax",))
            def softmax(x, axis=-1):
                \"\"\"Normalized exponentials along `axis`.\"\"\"
                return x
            """, enable=["MX006"])
        assert vs == []


# ---------------------------------------------------------------------------
# MX007 — swallowed exception in a hot path
# ---------------------------------------------------------------------------

class TestMX007:
    def test_flags_bare_except_pass_in_hot_class(self, tmp_path):
        vs = lint_source(tmp_path, """
            class Trainer:
                def step(self, batch_size):
                    try:
                        self._update()
                    except:
                        pass
            """, enable=["MX007"])
        assert rules_hit(vs) == ["MX007"]
        assert "bare except:" in vs[0].message

    def test_flags_except_exception_continue(self, tmp_path):
        vs = lint_source(tmp_path, """
            class KVStore:
                def push(self, keys):
                    for k in keys:
                        try:
                            self._send(k)
                        except Exception:
                            continue
            """, enable=["MX007"])
        assert rules_hit(vs) == ["MX007"]

    def test_flags_broad_tuple_and_named_binding(self, tmp_path):
        vs = lint_source(tmp_path, """
            class DynamicBatcher:
                def _loop(self):
                    try:
                        self._run()
                    except (ValueError, Exception) as e:
                        pass
            """, enable=["MX007"])
        assert rules_hit(vs) == ["MX007"]

    def test_clean_narrow_catch_is_eafp(self, tmp_path):
        vs = lint_source(tmp_path, """
            class Trainer:
                def step(self):
                    try:
                        del self._cache[0]
                    except KeyError:
                        pass
            """, enable=["MX007"])
        assert vs == []

    def test_clean_handler_with_a_body(self, tmp_path):
        vs = lint_source(tmp_path, """
            class InferenceServer:
                def submit(self, x):
                    try:
                        return self._go(x)
                    except Exception as e:
                        self._metrics.bump("failed")
                        raise
            """, enable=["MX007"])
        assert vs == []

    def test_cold_path_code_is_out_of_scope(self, tmp_path):
        # a fixture file with no hot class and a non-hot-path name:
        # broad swallows elsewhere are some other linter's business
        vs = lint_source(tmp_path, """
            def viz_helper(fig):
                try:
                    fig.close()
                except Exception:
                    pass
            """, enable=["MX007"])
        assert vs == []

    def test_pragma_suppresses(self, tmp_path):
        vs = lint_source(tmp_path, """
            class ModelRepository:
                def get(self, name):
                    try:
                        return self._m[name]
                    except Exception:  # mxlint: disable=MX007
                        pass
            """, enable=["MX007"])
        assert vs == []


# ---------------------------------------------------------------------------
# MX008 — blocking call while a first-party lock is held (mxflow)
# ---------------------------------------------------------------------------

class TestMX008:
    def test_flags_direct_blocking_call_under_lock(self, tmp_path):
        vs = lint_source(tmp_path, """
            import threading
            import time

            _lock = threading.Lock()

            def tick():
                with _lock:
                    time.sleep(0.5)
            """, enable=["MX008"])
        assert rules_hit(vs) == ["MX008"]
        assert "_lock" in vs[0].message and "sleep" in vs[0].message

    def test_flags_blocking_reached_through_helpers(self, tmp_path):
        vs = lint_source(tmp_path, """
            import threading

            _lock = threading.Lock()

            def _read_blob(path):
                with open(path, "rb") as f:
                    return f.read()

            def _load(path):
                return _read_blob(path)

            def cached_get(path):
                with _lock:
                    return _load(path)  # blocks two calls deep
            """, enable=["MX008"])
        assert rules_hit(vs) == ["MX008"]
        assert vs[0].symbol == "cached_get"
        assert "_load()" in vs[0].message
        assert "open()" in vs[0].message  # witness path to the IO

    def test_clean_blocking_outside_lock_double_checked(self, tmp_path):
        vs = lint_source(tmp_path, """
            import threading
            import time

            _lock = threading.Lock()
            _cache = {}

            def get(key):
                v = _cache.get(key)
                if v is None:
                    built = time.sleep(0.5) or 42  # OUTSIDE the lock
                    with _lock:
                        v = _cache.setdefault(key, built)
                return v
            """, enable=["MX008"])
        assert vs == []

    def test_condition_variables_are_not_lock_regions(self, tmp_path):
        # `with self._cv: self._cv.wait()` RELEASES the lock — the
        # batcher idiom must not be flagged
        vs = lint_source(tmp_path, """
            class Loop:
                def run(self):
                    with self._cv:
                        self._cv.wait(0.5)
            """, enable=["MX008"])
        assert vs == []


# ---------------------------------------------------------------------------
# MX010 — exception-path resource leak (mxflow CFG)
# ---------------------------------------------------------------------------

class TestMX010:
    def test_flags_release_not_reached_on_exception_path(self, tmp_path):
        vs = lint_source(tmp_path, """
            def run(entry, work):
                entry.begin_use()
                out = work()        # may raise: end_use never runs
                entry.end_use()
                return out
            """, enable=["MX010"])
        assert rules_hit(vs) == ["MX010"]
        assert "begin_use" in vs[0].message
        assert "finally" in vs[0].message

    def test_flags_manual_lock_acquire_without_finally(self, tmp_path):
        vs = lint_source(tmp_path, """
            def update(lock, cache, key, build):
                lock.acquire()
                cache[key] = build()  # raising build() wedges the lock
                lock.release()
            """, enable=["MX010"])
        assert rules_hit(vs) == ["MX010"]

    def test_clean_try_finally_release(self, tmp_path):
        vs = lint_source(tmp_path, """
            def run(entry, work):
                entry.begin_use()
                try:
                    return work()
                finally:
                    entry.end_use()
            """, enable=["MX010"])
        assert vs == []

    def test_clean_release_via_callback_escape(self, tmp_path):
        # the serving submit shape: the release lives in a closure
        # handed to add_done_callback, the error path releases inline
        vs = lint_source(tmp_path, """
            def submit(entry, batcher):
                entry.begin_use()

                def _release():
                    entry.end_use()

                try:
                    fut = batcher.submit()
                    fut.add_done_callback(lambda f: _release())
                    fut.add_done_callback(_release)
                except BaseException:
                    _release()
                    raise
                return fut
            """, enable=["MX010"])
        assert vs == []

    def test_acquire_without_any_local_release_is_out_of_scope(
            self, tmp_path):
        # cross-function protocols (acquire here, release elsewhere)
        # are deliberately not guessed at
        vs = lint_source(tmp_path, """
            def pin(entry):
                entry.begin_use()
                return entry
            """, enable=["MX010"])
        assert vs == []

    def test_with_block_acquire_is_clean(self, tmp_path):
        vs = lint_source(tmp_path, """
            def run(sem, work):
                with sem.acquire():
                    return work()
            """, enable=["MX010"])
        assert vs == []


# ---------------------------------------------------------------------------
# MX011 — retry-unsafe side effects (mxflow CFG)
# ---------------------------------------------------------------------------

class TestMX011:
    def test_flags_mutation_before_fallible_operation(self, tmp_path):
        vs = lint_source(tmp_path, """
            def flush(self, policy, bucket, push):
                def attempt():
                    self.sent += 1       # replayed on every retry
                    return push(bucket)

                return policy.call(attempt, site="kv.bucket")
            """, enable=["MX011"])
        assert rules_hit(vs) == ["MX011"]
        assert "self.sent" in vs[0].message
        assert "retry" in vs[0].message

    def test_flags_container_publish_before_risky_call(self, tmp_path):
        vs = lint_source(tmp_path, """
            def save(results, policy, fetch):
                def attempt():
                    results.append("started")  # caller-visible
                    return fetch()

                return policy.call(attempt, site="ckpt.io")
            """, enable=["MX011"])
        assert rules_hit(vs) == ["MX011"]

    def test_clean_compute_then_publish(self, tmp_path):
        # the kvstore contract: write only after the last fallible op
        vs = lint_source(tmp_path, """
            def flush(self, policy, bucket, push):
                def attempt():
                    out = push(bucket)
                    self.sent += 1       # after success: never replayed
                    return out

                return policy.call(attempt, site="kv.bucket")
            """, enable=["MX011"])
        assert vs == []

    def test_clean_attempt_local_state(self, tmp_path):
        vs = lint_source(tmp_path, """
            def load(policy, read):
                def attempt():
                    buf = []
                    buf.append(read())   # attempt-local: retry-safe
                    return buf

                return policy.call(attempt, site="cache.load")
            """, enable=["MX011"])
        assert vs == []

    def test_non_retry_callables_are_ignored(self, tmp_path):
        vs = lint_source(tmp_path, """
            def run(self, executor, fetch):
                def task():
                    self.count += 1
                    return fetch()

                return executor.call(task)  # not a RetryPolicy site
            """, enable=["MX011"])
        assert vs == []


# ---------------------------------------------------------------------------
# MX012 — donation flow across helpers (mxflow)
# ---------------------------------------------------------------------------

class TestMX012:
    # NOTE: indented like the per-test snippets it is concatenated
    # with, so textwrap.dedent sees one consistent block
    SRC = """
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def _apply(w, g):
                return w - g

            def helper(w, g):
                return _apply(w, g)
            """

    def test_flags_read_after_helper_donates(self, tmp_path):
        vs = lint_source(tmp_path, self.SRC + """
            def train(w, g):
                new_w = helper(w, g)   # helper donates its arg #0
                return new_w + w       # stale read of the donated buffer
            """, enable=["MX012"])
        assert rules_hit(vs) == ["MX012"]
        assert "`w`" in vs[0].message
        assert "helper()" in vs[0].message
        assert "donate_argnums" in vs[0].message  # the witness chain

    def test_flags_donation_two_helpers_deep(self, tmp_path):
        vs = lint_source(tmp_path, self.SRC + """
            def outer(w, g):
                return helper(w, g)

            def train(w, g):
                new_w = outer(w, g)
                return new_w + w
            """, enable=["MX012"])
        assert rules_hit(vs) == ["MX012"]

    def test_clean_rebind_idiom_and_undonated_arg(self, tmp_path):
        vs = lint_source(tmp_path, self.SRC + """
            def train(w, g, batches):
                for b in batches:
                    w = helper(w, g)   # canonical rebind: never flagged
                use = g + 1            # position 1 is NOT donated
                return w, use
            """, enable=["MX012"])
        assert vs == []

    def test_direct_donation_stays_mx005(self, tmp_path):
        # the same-scope case is MX005's; MX012 must not double-flag
        vs = lint_source(tmp_path, """
            import jax

            def run(fn, x, y):
                f = jax.jit(fn, donate_argnums=(0,))
                out = f(x, y)
                return out + x
            """, enable=["MX012"])
        assert vs == []


# ---------------------------------------------------------------------------
# pragmas, enable/disable, baseline ratchet
# ---------------------------------------------------------------------------

class TestPragma:
    def test_line_pragma_suppresses_named_rule(self, tmp_path):
        vs = lint_source(tmp_path, """
            _CACHE = {}

            def put(k, v):
                _CACHE[k] = v  # mxlint: disable=MX004
            """, enable=["MX004"])
        assert vs == []

    def test_pragma_with_other_code_does_not_suppress(self, tmp_path):
        vs = lint_source(tmp_path, """
            _CACHE = {}

            def put(k, v):
                _CACHE[k] = v  # mxlint: disable=MX001
            """, enable=["MX004"])
        assert rules_hit(vs) == ["MX004"]

    def test_bare_pragma_suppresses_everything(self, tmp_path):
        vs = lint_source(tmp_path, """
            import os

            _CACHE = {}

            def put(k):
                _CACHE[k] = os.environ.get("MXNET_FOO")  # mxlint: disable
            """)
        assert vs == []


class TestEngineConfig:
    def test_enable_selects_exactly(self, tmp_path):
        src = """
            import os

            _CACHE = {}

            def put(k):
                _CACHE[k] = os.environ.get("MXNET_FOO")
            """
        assert rules_hit(lint_source(tmp_path, src)) == ["MX003", "MX004"]
        assert rules_hit(lint_source(
            tmp_path, src, enable=["MX003"])) == ["MX003"]

    def test_disable_subtracts(self, tmp_path):
        f = tmp_path / "fixture.py"
        f.write_text("_C = {}\n\ndef p(k, v):\n    _C[k] = v\n")
        eng = analysis.LintEngine(root=str(tmp_path), disable=["MX004"])
        assert eng.run([str(f)]) == []

    def test_unknown_rule_id_raises(self):
        with pytest.raises(ValueError):
            analysis.LintEngine(enable=["MX999"])

    def test_syntax_error_reported_not_fatal(self, tmp_path):
        (tmp_path / "bad.py").write_text("def broken(:\n")
        eng = analysis.LintEngine(root=str(tmp_path))
        assert eng.run([str(tmp_path)]) == []
        assert len(eng.errors) == 1 and "bad.py" in eng.errors[0]


class TestBaseline:
    SRC = """
        _CACHE = {}

        def put(k, v):
            _CACHE[k] = v
        """

    def test_ratchet_suppresses_old_flags_new(self, tmp_path):
        vs = lint_source(tmp_path, self.SRC, enable=["MX004"])
        baseline = analysis.make_baseline(vs)["entries"]
        # same tree: everything baselined
        new, suppressed, stale = analysis.diff_baseline(vs, baseline)
        assert (new, len(suppressed), stale) == ([], 1, [])
        # a NEW violation elsewhere fails even with the baseline
        vs2 = lint_source(tmp_path, self.SRC + """
            def put2(k, v):
                _CACHE[k] = v
            """, enable=["MX004"], name="fixture2.py")
        new, _, _ = analysis.diff_baseline(vs2, baseline)
        assert len(new) == 2  # different file: neither matches baseline

    def test_fixed_violation_reported_stale(self, tmp_path):
        vs = lint_source(tmp_path, self.SRC, enable=["MX004"])
        baseline = analysis.make_baseline(vs)["entries"]
        new, suppressed, stale = analysis.diff_baseline([], baseline)
        assert (new, suppressed) == ([], [])
        assert len(stale) == 1

    def test_fingerprint_survives_line_drift(self, tmp_path):
        vs1 = lint_source(tmp_path, self.SRC, enable=["MX004"])
        vs2 = lint_source(tmp_path, self.SRC, enable=["MX004"],
                          name="fixture2.py")
        f = tmp_path / "fixture.py"
        f.write_text("\n\n\n" + textwrap.dedent(self.SRC))
        eng = analysis.LintEngine(root=str(tmp_path), enable=["MX004"])
        vs_shifted = eng.run([str(f)])
        assert vs_shifted[0].line != vs1[0].line
        assert vs_shifted[0].fingerprint == vs1[0].fingerprint
        assert vs2[0].fingerprint != vs1[0].fingerprint  # path differs

    def test_every_entry_carries_a_justification(self, tmp_path):
        vs = lint_source(tmp_path, self.SRC, enable=["MX004"])
        doc = analysis.make_baseline(vs, justifications={"MX004": "why"})
        assert all(e["justification"] == "why" for e in doc["entries"])


# ---------------------------------------------------------------------------
# MX013 — per-replica dispatch in step-chain code
# ---------------------------------------------------------------------------

class TestMX013:
    def test_flags_per_replica_update_loop(self, tmp_path):
        vs = lint_source(tmp_path, """
            class Trainer:
                def _update_fused(self):
                    for r in range(self.nrep):
                        self._updaters[r].update_all(
                            self.idxs, self.grads[r], self.weights[r])
            """, enable=["MX013"])
        assert rules_hit(vs) == ["MX013"]
        assert "update_all()" in vs[0].message

    def test_flags_updater_subscript_call_loop(self, tmp_path):
        vs = lint_source(tmp_path, """
            class Trainer:
                def _update(self):
                    for r, grad in enumerate(self.grads):
                        self._updaters[r](0, grad, self.data[r])
            """, enable=["MX013"])
        assert rules_hit(vs) == ["MX013"]
        assert "_updaters[r](...)" in vs[0].message

    def test_flags_per_key_pushpull_loop(self, tmp_path):
        vs = lint_source(tmp_path, """
            class KVStore:
                def pushpull_fused(self, keys, vals):
                    for k, v in zip(keys, vals):
                        self.pushpull(k, v)
            """, enable=["MX013"])
        assert rules_hit(vs) == ["MX013"]

    def test_flags_raw_device_put_in_step_chain(self, tmp_path):
        vs = lint_source(tmp_path, """
            import jax

            class KVStore:
                def _reduce(self, vals):
                    dev = vals[0].ctx.jax_device
                    return [jax.device_put(v.data, dev) for v in vals]
            """, enable=["MX013"])
        assert rules_hit(vs) == ["MX013"]
        assert "device_put" in vs[0].message

    def test_flags_device_keyword_device_put(self, tmp_path):
        """The keyword spelling of raw-device pinning is the same
        violation — `device=` must not read as a sharding."""
        vs = lint_source(tmp_path, """
            import jax

            class KVStore:
                def _reduce(self, vals):
                    dev = vals[0].ctx.jax_device
                    return [jax.device_put(v.data, device=dev)
                            for v in vals]
            """, enable=["MX013"])
        assert rules_hit(vs) == ["MX013"]

    def test_sharding_keyword_device_put_is_clean(self, tmp_path):
        vs = lint_source(tmp_path, """
            import jax
            from jax.sharding import NamedSharding, PartitionSpec

            class Trainer:
                def step(self, batch):
                    return jax.device_put(batch, device=NamedSharding(
                        self.mesh, PartitionSpec("dp")))
            """, enable=["MX013"])
        assert vs == []

    def test_sharded_device_put_is_clean(self, tmp_path):
        vs = lint_source(tmp_path, """
            import jax

            class Trainer:
                def step(self, batch):
                    sh = self.rules.sharding_for("w", batch.shape,
                                                 self.mesh)
                    return jax.device_put(batch, sh)
            """, enable=["MX013"])
        assert vs == []

    def test_named_sharding_call_argument_is_clean(self, tmp_path):
        vs = lint_source(tmp_path, """
            import jax
            from jax.sharding import NamedSharding, PartitionSpec

            class SpmdUpdater:
                def update_all_mesh(self, mesh, grads):
                    return jax.device_put(
                        grads, NamedSharding(mesh, PartitionSpec("dp")))
            """, enable=["MX013"])
        assert vs == []

    def test_single_mesh_dispatch_is_clean(self, tmp_path):
        vs = lint_source(tmp_path, """
            class Trainer:
                def _step_spmd(self):
                    self._spmd_updater.update_all_mesh(
                        self.idxs, self.grads, self.weights)
                    return True
            """, enable=["MX013"])
        assert vs == []

    def test_loop_outside_hot_class_is_clean(self, tmp_path):
        vs = lint_source(tmp_path, """
            class DataPipeline:
                def step(self, batches):
                    for b in batches:
                        self.push(0, b)
            """, enable=["MX013"])
        assert vs == []

    def test_pragma_suppresses(self, tmp_path):
        vs = lint_source(tmp_path, """
            class Trainer:
                def _update(self):
                    for r, grad in enumerate(self.grads):
                        self._updaters[r](0, grad, self.data[r])  # mxlint: disable=MX013
            """, enable=["MX013"])
        assert vs == []
