"""Optimizers (ref: python/mxnet/optimizer/optimizer.py): registry,
Optimizer base (lr/wd mults, multi-precision fp32 master weights, state
creation), SGD/NAG/Adam/AdaGrad/AdaDelta/Adamax/Nadam/RMSProp/Ftrl/Signum,
and the serializable Updater used by KVStore servers.

Update math runs through the registered optimizer update ops
(ops/optimizer_ops.py) — one cached XLA executable per parameter shape.
"""
from __future__ import annotations

import pickle
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..base import MXNetError, Registry
from ..ndarray.ndarray import NDArray, zeros
from ..ops.registry import apply_pure, invoke

__all__ = ["Optimizer", "Updater", "create", "register", "get_updater"]

_REG: Registry = Registry("optimizer")
register = _REG.register


def create(name, **kwargs) -> "Optimizer":
    if isinstance(name, Optimizer):
        return name
    return _REG.get(name)(**kwargs)


class Optimizer:
    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count: Dict[int, int] = {}
        self.multi_precision = multi_precision
        self.idx2name = param_idx2name or {}
        self.param_dict = param_dict or {}
        self.lr_mult: Dict[str, float] = {}
        self.wd_mult: Dict[str, float] = {}

    # ---- state -----------------------------------------------------------
    def create_state(self, index, weight) -> Any:
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and str(weight.data.dtype) in ("float16", "bfloat16"):
            w32 = weight.astype("float32")
            return (self.create_state(index, w32), w32)
        return self.create_state(index, weight)

    # ---- bookkeeping (ref: Optimizer._update_count / _get_lr / _get_wd) --
    def _update_count(self, index):
        self._index_update_count.setdefault(index, self.begin_num_update)
        self._index_update_count[index] += 1
        self.num_update = max(self.num_update, self._index_update_count[index])

    def _get_lr(self, index) -> float:
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index) -> float:
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def set_learning_rate(self, lr):
        self.lr = lr

    @property
    def learning_rate(self):
        return self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _common(self, index) -> Dict[str, float]:
        return dict(lr=self._get_lr(index), wd=self._get_wd(index),
                    rescale_grad=self.rescale_grad,
                    clip_gradient=self.clip_gradient
                    if self.clip_gradient is not None else -1.0)

    # ---- fused step (pure-function view of the update math) -------------
    #
    # The eager path above dispatches one registered update op per
    # parameter.  The fused path (optimizer/fused.py) applies the SAME
    # registered pure functions over the whole parameter pytree in one
    # jitted program.  The split of responsibilities:
    #
    #   _FUSED_STATIC : names of the attrs the math reads at trace time
    #       (momentum, betas, clip_gradient, ...).  They key the
    #       executable cache; changing one retraces, which is correct.
    #       None (the base default) marks an optimizer as not fusible.
    #   fused_hyper   : per-step host-side scalars (lr with mults and
    #       bias correction folded in, wd, rescale_grad, the step count
    #       t where the kernel needs it).  These enter the program as
    #       TRACED arguments, so set_learning_rate / a new
    #       rescale_grad = scale/batch_size never retrigger a compile.
    #   fused_apply   : the pure math, (weight, grad, state, hyper) ->
    #       (new_weight, new_state) on raw jax values.

    _FUSED_STATIC: Optional[Tuple[str, ...]] = None
    # True when fused_hyper carries the raw step count "t" (bias
    # correction computed INSIDE the kernel).  t participates in the
    # per-parameter dtype cast, and half floats cannot represent
    # integers past 256 (bf16) / 2048 (f16) — so these optimizers take
    # the eager loop for half-precision weights without a multi-
    # precision master copy (see FusedUpdater.update_all).
    _FUSED_T_HYPER = False
    # True when fused_apply is purely ELEMENTWISE (every output element
    # depends only on the matching input elements + scalars).  The SPMD
    # step (optimizer/spmd.py) may then concatenate many parameters
    # into one flat ZeRO bucket — one reduce-scatter/update/all-gather
    # per bucket instead of per parameter.  Norm-based updates (LAMB's
    # per-tensor trust ratio) must keep per-parameter tensors and set
    # this False.
    _FUSED_ELEMENTWISE = True

    def fused_static_key(self) -> Optional[Tuple]:
        """Hashable fingerprint of the trace-time attrs, or None when
        this optimizer has no fused path (fall back to the eager loop)."""
        if self._FUSED_STATIC is None:
            return None
        return tuple((a, getattr(self, a)) for a in self._FUSED_STATIC)

    def fused_hyper(self, index, t) -> Dict[str, float]:
        """Per-step scalars for parameter `index` at update count `t`,
        computed on the host and passed as traced jit arguments."""
        return {"lr": float(self._get_lr(index)),
                "wd": float(self._get_wd(index)),
                "rescale_grad": float(self.rescale_grad)}

    def _fused_clip(self) -> float:
        return self.clip_gradient if self.clip_gradient is not None else -1.0

    def _fused_common(self, hyper) -> Dict[str, Any]:
        return dict(lr=hyper["lr"], wd=hyper["wd"],
                    rescale_grad=hyper["rescale_grad"],
                    clip_gradient=self._fused_clip())

    def fused_apply(self, weight, grad, state, hyper):
        """Pure update math on jax values: returns (new_weight, new_state)
        with new_state mirroring the structure of `state`."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement the fused step")

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def _mp_active(self, weight, state) -> bool:
        """Whether `state` carries an fp32 master copy for a half-
        precision weight — THE multi-precision predicate, shared by the
        eager dispatch below and the fused path (optimizer/fused.py)."""
        return (self.multi_precision and isinstance(state, tuple)
                and isinstance(state[-1], NDArray)
                and str(state[-1].data.dtype) == "float32"
                and str(weight.data.dtype) in ("float16", "bfloat16"))

    def update_multi_precision(self, index, weight, grad, state):
        if self._mp_active(weight, state):
            self._update_mp(index, weight, grad, state)
        else:
            self.update(index, weight, grad, state)

    def _update_mp(self, index, weight, grad, state):
        inner_state, w32 = state
        self.update(index, w32, grad.astype("float32"), inner_state)
        weight._data = w32.data.astype(weight.data.dtype)


def _rebind(targets, results):
    """Write update-op results back into the mutated NDArrays."""
    if isinstance(results, NDArray):
        results = [results]
    for t, r in zip(targets, results):
        t._data = r.data


@register("sgd")
class SGD(Optimizer):
    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, ctx=weight.ctx, dtype=str(weight.data.dtype))

    def _sparse_update(self, index, weight, grad, state, kw):
        """Lazy update: only the rows present in the row_sparse gradient
        are touched (ref: sgd_update FComputeEx on kRowSparseStorage +
        SGDUpdateDnsRspImpl lazy_update path)."""
        import jax.numpy as jnp

        rows = grad._aux["indices"]
        g = jnp.take(grad._data, rows, axis=0).astype(weight.data.dtype)
        g = g * kw["rescale_grad"]
        if kw["clip_gradient"] > 0:
            g = jnp.clip(g, -kw["clip_gradient"], kw["clip_gradient"])
        w_rows = jnp.take(weight.data, rows, axis=0)
        g = g + kw["wd"] * w_rows
        if state is None:
            weight._data = weight.data.at[rows].add(-kw["lr"] * g)
        else:
            m_rows = jnp.take(state.data, rows, axis=0)
            m_rows = self.momentum * m_rows - kw["lr"] * g
            state._data = state.data.at[rows].set(m_rows)
            weight._data = weight.data.at[rows].add(m_rows)

    def update(self, index, weight, grad, state):
        from ..ndarray.sparse import RowSparseNDArray

        self._update_count(index)
        kw = self._common(index)
        if isinstance(grad, RowSparseNDArray):
            if self.lazy_update:
                return self._sparse_update(index, weight, grad, state, kw)
            grad = NDArray(grad._data, ctx=grad.ctx)  # std_update: densify
        if state is None:
            _rebind([weight], invoke("sgd_update", weight, grad, **kw))
        else:
            _rebind([weight, state],
                    invoke("sgd_mom_update", weight, grad, state,
                           momentum=self.momentum, **kw))

    def _update_mp(self, index, weight, grad, state):
        inner, w32 = state
        self._update_count(index)
        kw = self._common(index)
        if inner is None:
            _rebind([weight, w32], invoke("mp_sgd_update", weight, grad, w32, **kw))
        else:
            _rebind([weight, inner, w32],
                    invoke("mp_sgd_mom_update", weight, grad, inner, w32,
                           momentum=self.momentum, **kw))

    _FUSED_STATIC = ("momentum", "clip_gradient")

    def fused_apply(self, weight, grad, state, hyper):
        kw = self._fused_common(hyper)
        if state is None:
            return apply_pure("sgd_update", weight, grad, **kw), None
        return apply_pure("sgd_mom_update", weight, grad, state,
                          momentum=self.momentum, **kw)


@register("nag")
class NAG(SGD):
    def update(self, index, weight, grad, state):
        from ..ndarray.sparse import RowSparseNDArray

        if isinstance(grad, RowSparseNDArray):
            # NAG has no lazy sparse kernel (ref: nag_mom_update is
            # dense-only); densify = std_update semantics
            grad = NDArray(grad._data, ctx=grad.ctx)
        self._update_count(index)
        kw = self._common(index)
        if state is None:
            _rebind([weight], invoke("sgd_update", weight, grad, **kw))
        else:
            _rebind([weight, state],
                    invoke("nag_mom_update", weight, grad, state,
                           momentum=self.momentum, **kw))

    def fused_apply(self, weight, grad, state, hyper):
        kw = self._fused_common(hyper)
        if state is None:
            return apply_pure("sgd_update", weight, grad, **kw), None
        return apply_pure("nag_mom_update", weight, grad, state,
                          momentum=self.momentum, **kw)


@register("adam")
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        dt = str(weight.data.dtype)
        return (zeros(weight.shape, ctx=weight.ctx, dtype=dt),
                zeros(weight.shape, ctx=weight.ctx, dtype=dt))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        kw = self._common(index)
        # bias correction folded into lr (ref: Adam.update)
        kw["lr"] *= (1.0 - self.beta2 ** t) ** 0.5 / (1.0 - self.beta1 ** t)
        mean, var = state
        _rebind([weight, mean, var],
                invoke("adam_update", weight, grad, mean, var,
                       beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon, **kw))

    _FUSED_STATIC = ("beta1", "beta2", "epsilon", "clip_gradient")

    def fused_hyper(self, index, t):
        h = super().fused_hyper(index, t)
        # same host-side bias-correction fold as the eager path — a new
        # t only changes a traced scalar, never the program
        h["lr"] *= (1.0 - self.beta2 ** t) ** 0.5 / (1.0 - self.beta1 ** t)
        return h

    def fused_apply(self, weight, grad, state, hyper):
        mean, var = state
        nw, nm, nv = apply_pure("adam_update", weight, grad, mean, var,
                                beta1=self.beta1, beta2=self.beta2,
                                epsilon=self.epsilon,
                                **self._fused_common(hyper))
        return nw, (nm, nv)


@register("adagrad")
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, ctx=weight.ctx, dtype=str(weight.data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common(index)
        _rebind([weight, state],
                invoke("adagrad_update", weight, grad, state,
                       epsilon=self.float_stable_eps, **kw))

    _FUSED_STATIC = ("float_stable_eps", "clip_gradient")

    def fused_apply(self, weight, grad, state, hyper):
        return apply_pure("adagrad_update", weight, grad, state,
                          epsilon=self.float_stable_eps,
                          **self._fused_common(hyper))


@register("adadelta")
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        dt = str(weight.data.dtype)
        return (zeros(weight.shape, ctx=weight.ctx, dtype=dt),
                zeros(weight.shape, ctx=weight.ctx, dtype=dt))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common(index)
        kw.pop("lr")
        acc_g, acc_d = state
        _rebind([weight, acc_g, acc_d],
                invoke("adadelta_update", weight, grad, acc_g, acc_d,
                       rho=self.rho, epsilon=self.epsilon, lr=1.0, **kw))

    _FUSED_STATIC = ("rho", "epsilon", "clip_gradient")

    def fused_apply(self, weight, grad, state, hyper):
        acc_g, acc_d = state
        nw, ng, ndel = apply_pure(
            "adadelta_update", weight, grad, acc_g, acc_d, rho=self.rho,
            epsilon=self.epsilon, lr=1.0, wd=hyper["wd"],
            rescale_grad=hyper["rescale_grad"],
            clip_gradient=self._fused_clip())
        return nw, (ng, ndel)


@register("adamax")
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        dt = str(weight.data.dtype)
        return (zeros(weight.shape, ctx=weight.ctx, dtype=dt),
                zeros(weight.shape, ctx=weight.ctx, dtype=dt))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        kw = self._common(index)
        mean, var = state
        _rebind([weight, mean, var],
                invoke("adamax_update", weight, grad, mean, var,
                       beta1=self.beta1, beta2=self.beta2, t=t, **kw))

    _FUSED_STATIC = ("beta1", "beta2", "clip_gradient")
    _FUSED_T_HYPER = True

    def fused_hyper(self, index, t):
        h = super().fused_hyper(index, t)
        h["t"] = float(t)
        return h

    def fused_apply(self, weight, grad, state, hyper):
        mean, var = state
        nw, nm, nv = apply_pure("adamax_update", weight, grad, mean, var,
                                beta1=self.beta1, beta2=self.beta2,
                                t=hyper["t"], **self._fused_common(hyper))
        return nw, (nm, nv)


@register("nadam")
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay

    def create_state(self, index, weight):
        dt = str(weight.data.dtype)
        return (zeros(weight.shape, ctx=weight.ctx, dtype=dt),
                zeros(weight.shape, ctx=weight.ctx, dtype=dt))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        kw = self._common(index)
        mean, var = state
        _rebind([weight, mean, var],
                invoke("nadam_update", weight, grad, mean, var,
                       beta1=self.beta1, beta2=self.beta2,
                       epsilon=self.epsilon, t=t,
                       schedule_decay=self.schedule_decay, **kw))

    _FUSED_STATIC = ("beta1", "beta2", "epsilon", "schedule_decay",
                     "clip_gradient")
    _FUSED_T_HYPER = True

    def fused_hyper(self, index, t):
        h = super().fused_hyper(index, t)
        h["t"] = float(t)
        return h

    def fused_apply(self, weight, grad, state, hyper):
        mean, var = state
        nw, nm, nv = apply_pure("nadam_update", weight, grad, mean, var,
                                beta1=self.beta1, beta2=self.beta2,
                                epsilon=self.epsilon, t=hyper["t"],
                                schedule_decay=self.schedule_decay,
                                **self._fused_common(hyper))
        return nw, (nm, nv)


@register("rmsprop")
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.epsilon = epsilon
        self.centered = centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        dt = str(weight.data.dtype)
        if self.centered:
            return (zeros(weight.shape, ctx=weight.ctx, dtype=dt),
                    zeros(weight.shape, ctx=weight.ctx, dtype=dt),
                    zeros(weight.shape, ctx=weight.ctx, dtype=dt))
        return zeros(weight.shape, ctx=weight.ctx, dtype=dt)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common(index)
        kw["clip_weights"] = self.clip_weights if self.clip_weights else -1.0
        if self.centered:
            n, g, delta = state
            _rebind([weight, n, g, delta],
                    invoke("rmspropalex_update", weight, grad, n, g, delta,
                           gamma1=self.gamma1, gamma2=self.gamma2,
                           epsilon=self.epsilon, **kw))
        else:
            _rebind([weight, state],
                    invoke("rmsprop_update", weight, grad, state,
                           gamma1=self.gamma1, epsilon=self.epsilon, **kw))

    _FUSED_STATIC = ("gamma1", "gamma2", "epsilon", "centered",
                     "clip_weights", "clip_gradient")

    def fused_apply(self, weight, grad, state, hyper):
        kw = self._fused_common(hyper)
        kw["clip_weights"] = self.clip_weights if self.clip_weights else -1.0
        if self.centered:
            n, g, delta = state
            nw, nn, ng, nd = apply_pure(
                "rmspropalex_update", weight, grad, n, g, delta,
                gamma1=self.gamma1, gamma2=self.gamma2,
                epsilon=self.epsilon, **kw)
            return nw, (nn, ng, nd)
        return apply_pure("rmsprop_update", weight, grad, state,
                          gamma1=self.gamma1, epsilon=self.epsilon, **kw)


@register("ftrl")
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        dt = str(weight.data.dtype)
        return (zeros(weight.shape, ctx=weight.ctx, dtype=dt),
                zeros(weight.shape, ctx=weight.ctx, dtype=dt))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common(index)
        z, n = state
        _rebind([weight, z, n],
                invoke("ftrl_update", weight, grad, z, n, lamda1=self.lamda1,
                       beta=self.beta, **kw))

    _FUSED_STATIC = ("lamda1", "beta", "clip_gradient")

    def fused_apply(self, weight, grad, state, hyper):
        z, n = state
        nw, nz, nn = apply_pure("ftrl_update", weight, grad, z, n,
                                lamda1=self.lamda1, beta=self.beta,
                                **self._fused_common(hyper))
        return nw, (nz, nn)


@register("signum")
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, ctx=weight.ctx, dtype=str(weight.data.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common(index)
        if state is None:
            _rebind([weight], invoke("signsgd_update", weight, grad, **kw))
        else:
            _rebind([weight, state],
                    invoke("signum_update", weight, grad, state,
                           momentum=self.momentum, wd_lh=self.wd_lh, **kw))

    _FUSED_STATIC = ("momentum", "wd_lh", "clip_gradient")

    def fused_apply(self, weight, grad, state, hyper):
        kw = self._fused_common(hyper)
        if state is None:
            return apply_pure("signsgd_update", weight, grad, **kw), None
        return apply_pure("signum_update", weight, grad, state,
                          momentum=self.momentum, wd_lh=self.wd_lh, **kw)


@register("signsgd")
class SignSGD(Signum):
    def __init__(self, **kwargs):
        kwargs.setdefault("momentum", 0.0)
        super().__init__(**kwargs)


@register("lamb")
class LAMB(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2
        self.epsilon = epsilon
        self.lower_bound, self.upper_bound = lower_bound, upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        dt = str(weight.data.dtype)
        return (zeros(weight.shape, ctx=weight.ctx, dtype=dt),
                zeros(weight.shape, ctx=weight.ctx, dtype=dt))

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        mean, var = state
        g, new_mean, new_var = invoke(
            "lamb_update_phase1", weight, grad, mean, var,
            beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
            t=t, bias_correction=self.bias_correction, wd=wd,
            rescale_grad=self.rescale_grad,
            clip_gradient=self.clip_gradient or -1.0)
        mean._data = new_mean.data
        var._data = new_var.data
        r1 = weight.norm()
        r2 = g.norm()
        new_w = invoke("lamb_update_phase2", weight, g, r1, r2, lr=lr,
                       lower_bound=self.lower_bound or -1.0,
                       upper_bound=self.upper_bound or -1.0)
        weight._data = new_w.data

    _FUSED_STATIC = ("beta1", "beta2", "epsilon", "lower_bound",
                     "upper_bound", "bias_correction", "clip_gradient")
    _FUSED_T_HYPER = True
    # the phase-2 trust ratio is per-TENSOR (norm(w)/norm(update)):
    # concatenating params would corrupt the norms
    _FUSED_ELEMENTWISE = False

    def fused_hyper(self, index, t):
        h = super().fused_hyper(index, t)
        h["t"] = float(t)
        return h

    def fused_apply(self, weight, grad, state, hyper):
        mean, var = state
        direction, nm, nv = apply_pure(
            "lamb_update_phase1", weight, grad, mean, var,
            beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
            t=hyper["t"], bias_correction=self.bias_correction,
            wd=hyper["wd"], rescale_grad=hyper["rescale_grad"],
            clip_gradient=self._fused_clip())
        r1 = apply_pure("norm", weight)
        r2 = apply_pure("norm", direction)
        nw = apply_pure("lamb_update_phase2", weight, direction, r1, r2,
                        lr=hyper["lr"],
                        lower_bound=self.lower_bound or -1.0,
                        upper_bound=self.upper_bound or -1.0)
        return nw, (nm, nv)


@register("test")
class Test(Optimizer):
    def create_state(self, index, weight):
        return zeros(weight.shape, ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        weight._data = (weight + grad * self.rescale_grad).data

    _FUSED_STATIC = ()

    def fused_apply(self, weight, grad, state, hyper):
        return weight + grad * hyper["rescale_grad"], state


class Updater:
    """Serializable updater (ref: optimizer.py::Updater, get_updater) —
    the object a KVStore server runs to apply gradients."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict[int, Any] = {}
        self.states_synced: Dict[int, bool] = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def get_states(self, dump_optimizer=False):
        def to_np(s):
            if isinstance(s, NDArray):
                return s.asnumpy()
            if isinstance(s, (tuple, list)):
                return tuple(to_np(x) for x in s)
            return s

        payload = {k: to_np(v) for k, v in self.states.items()}
        if dump_optimizer:
            return pickle.dumps((payload, self.optimizer.__class__.__name__,
                                 self.optimizer.__dict__.copy()))
        return pickle.dumps(payload)

    def set_states(self, states, ctx=None):
        """Restore a payload; `ctx` places the buffers on a specific
        device — a replica updater's state must live WITH its replica,
        not on the default device."""
        data = pickle.loads(states)
        if isinstance(data, tuple) and len(data) == 3:
            payload, _cls, _odict = data
        else:
            payload = data
        self._pending = payload
        for k, v in payload.items():
            self.states[k] = self._restore(v, ctx)

    def _restore(self, v, ctx=None):
        if isinstance(v, np.ndarray):
            from ..ndarray.ndarray import array

            return array(v, ctx=ctx) if ctx is not None else array(v)
        if isinstance(v, tuple):
            return tuple(self._restore(x, ctx) for x in v)
        return v


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)
