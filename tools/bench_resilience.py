#!/usr/bin/env python
"""Resilience bench (ISSUE 6 gate): measure the recovery paths, don't
just test them.

Scenarios, one report (stdout JSON line + RESILIENCE.json):

  * recovery — train a small data-parallel job with auto-checkpointing,
    inject a preemption mid-epoch, then measure RECOVERY TIME TO FIRST
    STEP: constructing a fresh trainer, ``resume()``-ing the
    checkpoint, and completing the first post-resume optimizer step.
    Also verifies the resumed run reaches BIT-CONSISTENT parameters vs
    an uninterrupted twin (``resume_bit_consistent``).

  * breaker — serve a model whose executor is chaos-failed until the
    per-model circuit breaker opens, keep firing requests during the
    trip, and count what was DROPPED (fast 503s) vs the window; then
    let the half-open probe close the breaker and verify the model
    serves again and ``/healthz`` stayed 200 throughout
    (``process_survived``).

  * elastic (opt-in ``--elastic``; the nightly elastic stage runs it —
    process-spawning, so the tier-1 smoke skips it) — the ISSUE 15
    chaos known-answer e2e: a REAL 2-process job under
    ``tools/elastic_run.py`` with chaos killing (and separately
    hanging) exactly rank 1 mid-training, recovered in BOTH replace
    and shrink mode.  Each cell of the (die|hang) x (replace|shrink)
    matrix must recover in exactly one restart naming rank 1 as the
    failure, land within the loss-parity bar of an UNINTERRUPTED twin
    (same seed/steps, world 1 — the scaling_bench fixed-global-batch
    argument makes losses comparable across world sizes), and commit
    a measured MTTR (supervisor detection -> first post-resume step).

Gate (skipped with --no-gate, enforced in
tests/nightly/test_bench_resilience.py): resume must be bit-consistent,
recovery under --max-recovery-s (generous: CPU compile included),
breaker must have opened and recovered, healthz must never have
flapped; with --elastic, every matrix cell must have recovered with
loss parity and an MTTR under --max-recovery-s.

CPU smoke: JAX_PLATFORMS=cpu python tools/bench_resilience.py --no-gate
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _batches(n, rows, units):
    rng = np.random.RandomState(0)
    return [(rng.rand(rows, units).astype("f4"),
             rng.rand(rows, 4).astype("f4")) for _ in range(n)]


def _make_net(prefix, units, seed=3):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn

    np.random.seed(seed)
    mx.random.seed(seed)
    net = nn.Dense(4, in_units=units, prefix=prefix)
    net.initialize(ctx=mx.cpu())
    return net


def _one_step(net, trainer, xb, yb):
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd

    with autograd.record():
        loss = ((net(nd.array(xb, ctx=mx.cpu()))
                 - nd.array(yb, ctx=mx.cpu())) ** 2).sum()
    loss.backward()
    trainer.step(len(xb))


def _params(net):
    return {p.name: p.list_data()[0].asnumpy().copy()
            for p in net.collect_params().values()}


def scenario_recovery(steps: int, preempt_at: int, units: int) -> dict:
    import mxnet_tpu as mx
    from mxnet_tpu import resilience
    from mxnet_tpu.resilience import chaos

    data = _batches(steps, 16, units)
    opt = {"learning_rate": 0.05, "momentum": 0.9}

    net_a = _make_net("bench_a_", units)
    tr_a = mx.gluon.Trainer(net_a.collect_params(), "sgd", dict(opt))
    for xb, yb in data:
        _one_step(net_a, tr_a, xb, yb)
    want = _params(net_a)

    ckdir = tempfile.mkdtemp(prefix="mx-resil-bench-")
    net_b = _make_net("bench_b_", units)
    tr_b = mx.gluon.Trainer(net_b.collect_params(), "sgd", dict(opt))
    cursor = [0]
    resilience.AutoCheckpoint(ckdir, tr_b, every_n_steps=2,
                              state_provider=lambda:
                              {"next_batch": cursor[0]})
    preempted_dir = None
    with chaos.inject("trainer.preempt", at=preempt_at):
        try:
            for i, (xb, yb) in enumerate(data):
                cursor[0] = i + 1
                _one_step(net_b, tr_b, xb, yb)
        except resilience.Preempted as e:
            preempted_dir = e.checkpoint_dir

    # --- the measured window: fresh trainer -> resume -> first step ---
    t0 = time.perf_counter()
    net_c = _make_net("bench_b_", units, seed=99)
    tr_c = mx.gluon.Trainer(net_c.collect_params(), "sgd", dict(opt))
    ck_c = resilience.AutoCheckpoint(ckdir, tr_c)
    meta = ck_c.resume()
    nxt = meta["position"]["next_batch"]
    _one_step(net_c, tr_c, *data[nxt])
    recovery_s = time.perf_counter() - t0

    for xb, yb in data[nxt + 1:]:
        _one_step(net_c, tr_c, xb, yb)
    got = _params(net_c)
    bit_consistent = all(
        np.array_equal(want[k.replace("bench_b_", "bench_a_")], v)
        for k, v in got.items())
    return {
        "preempted_at_step": meta["step"],
        "preempted_checkpoint": os.path.basename(preempted_dir or ""),
        "recovery_time_to_first_step_s": round(recovery_s, 3),
        "resume_bit_consistent": bool(bit_consistent),
    }


def scenario_breaker(trip_requests: int, units: int) -> dict:
    import mxnet_tpu as mx
    from mxnet_tpu import nd, serving
    from mxnet_tpu.contrib import deploy
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.resilience import chaos

    art = tempfile.mkdtemp(prefix="mx-resil-art-")
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu", in_units=units),
                nn.Dense(4, in_units=8))
    net.initialize(ctx=mx.cpu())
    x = nd.array(np.random.RandomState(0).rand(4, units).astype("f4"))
    deploy.export_model(net, art, [x], dynamic_batch=True)

    repo = serving.ModelRepository()
    repo.add("m", art)
    srv = serving.InferenceServer(repo, serving.ServingConfig(
        max_batch_size=4, batch_timeout_ms=1.0,
        breaker_threshold=3, breaker_cooldown_ms=300.0,
        execute_retries=1))
    httpd = serving.serve_http(srv, port=0)
    port = httpd.server_address[1]

    def healthz_ok():
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
                return r.status == 200
        except Exception:  # noqa: BLE001 — a probe failure is a result
            return False

    x1 = nd.array(np.random.RandomState(1).rand(1, units).astype("f4"))
    entry = repo.get("m")
    out = {"process_survived": True}
    try:
        srv.infer("m", [x1])  # warm compile
        healthz_always_up = healthz_ok()
        dropped = failed = 0
        t0 = time.perf_counter()
        with chaos.inject("serving.execute", times=10_000):
            for _ in range(trip_requests):
                try:
                    srv.infer("m", [x1], timeout_ms=10000)
                except serving.ModelUnavailable:
                    dropped += 1     # fast 503 from the open breaker
                except Exception:    # noqa: BLE001 — counted result
                    failed += 1      # executor failures pre-trip
            healthz_always_up = healthz_always_up and healthz_ok()
        trip_s = time.perf_counter() - t0
        opened = entry.breaker.state() == "open"
        time.sleep(0.35)             # cooldown -> half-open
        y = srv.infer("m", [x1], timeout_ms=10000)
        recovered = y is not None and entry.breaker.state() == "closed"
        healthz_always_up = healthz_always_up and healthz_ok()
        out.update({
            "trip_window_s": round(trip_s, 3),
            "requests_during_trip": trip_requests,
            "requests_failed_pre_trip": failed,
            "requests_dropped_during_trip": dropped,
            "breaker_opened": bool(opened),
            "breaker_recovered": bool(recovered),
            "healthz_always_up": bool(healthz_always_up),
            "breaker_rejected_metric":
                entry.metrics.value("breaker_rejected"),
        })
    finally:
        httpd.shutdown()
        srv.shutdown(drain=True, timeout=10.0)
    return out


def _run_elastic(mode: str, chaos_spec: str, workers: int = 2,
                 steps: int = 8, timeout: float = 420.0) -> dict:
    """One supervised job under tools/elastic_run.py (fresh process —
    the supervisor + workers must not inherit this bench's jax/chaos
    state)."""
    import subprocess
    import tempfile

    out = os.path.join(tempfile.mkdtemp(prefix="mx-elastic-bench-"),
                       "report.json")
    cmd = [sys.executable,
           os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "elastic_run.py"),
           "--workers", str(workers), "--demo", "--cpu",
           "--mode", mode, "--steps", str(steps), "--ckpt-every", "2",
           "--hb-timeout", "8", "--collective-timeout", "6",
           "--grace", "12", "--out", out]
    if chaos_spec:
        cmd += ["--chaos", chaos_spec]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("MXNET_CHAOS", None)
    env.pop("MXNET_CHAOS_SPEC", None)
    import signal as _sig

    # own session: a timeout can kill the supervisor AND its worker
    # processes as one group instead of orphaning the generation
    p = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True, env=env,
                         start_new_session=True)
    try:
        stdout, _ = p.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        # one wedged cell must fail ITS cell, never crash the bench
        # before RESILIENCE.json is written (the goodput_report
        # multi_rank_merge lesson).  SIGTERM first so the supervisor's
        # own teardown reaps its workers; SIGKILL the group as the
        # backstop.
        try:
            os.killpg(p.pid, _sig.SIGTERM)
            p.communicate(timeout=20)
        except Exception:  # noqa: BLE001
            try:
                os.killpg(p.pid, _sig.SIGKILL)
            except OSError:
                pass  # mxlint: disable=MX007 — group already gone
            p.communicate()
        return {"ok": False, "error": f"supervisor timed out after "
                                      f"{timeout:.0f}s"}
    try:
        with open(out) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {"ok": False,
                "error": f"supervisor rc={p.returncode}",
                "tail": "\n".join(stdout.splitlines()[-8:])}


def scenario_elastic(max_recovery_s: float, steps: int = 8) -> dict:
    """The (die|hang) x (replace|shrink) known-answer matrix plus the
    uninterrupted twin, each cell gated on recovery + parity + MTTR."""
    parity_tol = 1e-3  # the scaling_bench loss-parity bar
    twin = _run_elastic("replace", "", workers=1, steps=steps)
    twin_loss = (twin.get("result") or {}).get("loss")
    runs = {}
    specs = {"die": "elastic.worker@4:die:rank=1",
             "hang": "elastic.worker@4:hang=600:rank=1"}
    for fault, spec in specs.items():
        for mode in ("replace", "shrink"):
            rep = _run_elastic(mode, spec, steps=steps)
            epochs = rep.get("epochs") or []
            loss = (rep.get("result") or {}).get("loss")
            mttr = epochs[0].get("mttr_s") if epochs else None
            detection_ok = bool(epochs) and \
                epochs[0].get("failed_ranks") == [1]
            parity = abs(loss - twin_loss) / max(abs(twin_loss), 1e-6) \
                if None not in (loss, twin_loss) else None
            row = {
                "ok": bool(
                    rep.get("ok") and rep.get("restarts") == 1
                    and detection_ok
                    and parity is not None and parity <= parity_tol
                    and mttr is not None and 0 < mttr < max_recovery_s
                    and rep.get("final_world")
                    == (1 if mode == "shrink" else 2)),
                "recovered": bool(rep.get("ok")),
                "restarts": rep.get("restarts"),
                "failed_ranks": epochs[0].get("failed_ranks")
                if epochs else None,
                # the mxblackbox postmortem id for this cell's failure
                # epoch — RESILIENCE.json names the incident it
                # recovered from, not just that it recovered
                "incident_id": epochs[0].get("incident_id")
                if epochs else None,
                "final_world": rep.get("final_world"),
                "mttr_s": mttr,
                "loss": loss,
                "loss_rel_err_vs_twin": round(parity, 8)
                if parity is not None else None,
            }
            if not row["ok"]:
                row["report"] = rep
            runs[f"{fault}_{mode}"] = row
    return {
        "ok": twin_loss is not None and all(r["ok"]
                                            for r in runs.values()),
        "twin_loss": twin_loss,
        "parity_tol": parity_tol,
        "steps": steps,
        "runs": runs,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--preempt-at", type=int, default=5)
    ap.add_argument("--trip-requests", type=int, default=12)
    ap.add_argument("--units", type=int, default=6)
    ap.add_argument("--max-recovery-s", type=float, default=60.0)
    ap.add_argument("--elastic", action="store_true",
                    help="also run the multi-process elastic recovery "
                         "matrix (slow; the nightly elastic stage "
                         "does — the tier-1 smoke must not spawn "
                         "2-process jobs)")
    ap.add_argument("--no-gate", action="store_true",
                    help="report only (tier-1 smoke); the strict gate "
                    "runs in tests/nightly/test_bench_resilience.py")
    ap.add_argument("--out", default=None,
                    help="also write the report JSON here "
                    "(RESILIENCE.json)")
    args = ap.parse_args()

    report = {
        "bench": "resilience",
        "backend": "cpu" if os.environ.get("JAX_PLATFORMS") == "cpu"
        else "auto",
        "recovery": scenario_recovery(args.steps, args.preempt_at,
                                      args.units),
        "breaker": scenario_breaker(args.trip_requests, args.units),
    }
    if args.elastic:
        report["elastic"] = scenario_elastic(args.max_recovery_s)
    gate_ok = (
        report["recovery"]["resume_bit_consistent"]
        and report["recovery"]["recovery_time_to_first_step_s"]
        < args.max_recovery_s
        and report["breaker"]["breaker_opened"]
        and report["breaker"]["breaker_recovered"]
        and report["breaker"]["requests_dropped_during_trip"] > 0
        and report["breaker"]["healthz_always_up"]
        and report["breaker"]["process_survived"]
        and report.get("elastic", {}).get("ok", True))
    report["gate_ok"] = bool(gate_ok)
    line = json.dumps(report)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    if args.no_gate:
        return 0
    return 0 if gate_ok else 1


if __name__ == "__main__":
    sys.exit(main())
