"""Ring attention — sequence/context parallelism over the 'sp' mesh axis.

Beyond-reference capability (SURVEY.md §5: the reference predates
attention-era long context; its tools were RNN bucketing + grad mirroring).
Here long sequences shard over the 'sp' axis: every device holds a
[B, H, L/n, D] slice of Q/K/V, and K/V blocks rotate around the ring via
`ppermute` (one ICI hop per step) while each device accumulates its
queries' attention with an online (flash-style) softmax — so the full
[L, L] score matrix never materializes and sequence length scales linearly
with the number of chips.

Pattern sources: Ring Attention (Liu et al.) / blockwise-parallel
attention; the shard_map+ppermute formulation is the idiomatic TPU one
(collectives ride ICI neighbours on the torus).

Causal-compute note (a considered non-feature): zigzag/striped chunk
orderings that "load-balance" causal ring attention do not help THIS
formulation — it is SPMD, every device executes the same program, and
masked blocks are computed-then-zeroed (XLA lowers data-dependent
skips to select, running both sides).  Reordering chunks would shuffle
which blocks are masked without removing their FLOPs.  The real win
would be a Pallas blockwise kernel that skips intra-block triangles;
until that exists, causal ring attention pays ~2x the unmasked FLOPs,
like the public blockwise-parallel baselines.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..base import MXNetError
from ._compat import shard_map_unchecked
from .mesh import DeviceMesh, current_mesh

__all__ = ["ring_attention", "ring_attention_sharded",
           "sharded_seq_attention", "local_attention"]


def local_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None,
                    q_offset=0, k_offset=0):
    """Plain blockwise attention [B,H,Lq,D]x[B,H,Lk,D] with optional causal
    mask in GLOBAL coordinates (offsets give each block its position)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(q.dtype)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        qpos = q_offset + jnp.arange(q.shape[2])[:, None]
        kpos = k_offset + jnp.arange(k.shape[2])[None, :]
        s = jnp.where(qpos >= kpos, s, jnp.finfo(s.dtype).min)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def ring_attention(q, k, v, axis_name: str = "sp", *, causal: bool = False,
                   scale: Optional[float] = None):
    """Per-shard body: call INSIDE shard_map/pjit with q,k,v already sharded
    [B, H, L_local, D] along the sequence axis `axis_name`.

    Online-softmax accumulation in float32; K/V rotate n-1 times.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    bq, hq, lq, d = q.shape
    lk = k.shape[2]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    qf = q.astype(jnp.float32) * scale
    neg = jnp.finfo(jnp.float32).min

    def scores(k_blk, src):
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_blk.astype(jnp.float32))
        if causal:
            qpos = idx * lq + jnp.arange(lq)[:, None]
            kpos = src * lk + jnp.arange(lk)[None, :]
            s = jnp.where(qpos >= kpos, s, neg)
        return s

    def block_update(carry, k_blk, v_blk, src):
        o, m, l = carry
        s = scores(k_blk, src)                       # [B,H,Lq,Lk]
        m_new = jnp.maximum(m, s.max(axis=-1))       # [B,H,Lq]
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        # fully-masked rows produce exp(neg - neg)=1 garbage; zero them
        if causal:
            valid = s > neg / 2
            p = jnp.where(valid, p, 0.0)
        l_new = l * alpha + p.sum(axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        return o_new, m_new, l_new

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(i, carry):
        (o, m, l), k_blk, v_blk = carry
        src = (idx - i) % n            # global block index of current K/V
        o, m, l = block_update((o, m, l), k_blk, v_blk, src)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (o, m, l), k_blk, v_blk

    o0 = jnp.zeros((bq, hq, lq, d), jnp.float32)
    m0 = jnp.full((bq, hq, lq), neg, jnp.float32)
    l0 = jnp.zeros((bq, hq, lq), jnp.float32)
    (o, m, l), _, _ = lax.fori_loop(0, n, body, ((o0, m0, l0), k, v))
    l = jnp.maximum(l, 1e-20)
    return (o / l[..., None]).astype(q.dtype)


def sharded_seq_attention(body, q, k, v, *,
                          mesh: Optional[DeviceMesh] = None,
                          axis_name: str = "sp", causal: bool = False,
                          scale: Optional[float] = None,
                          batch_axes=("dp", "fsdp"), entry_name="attention"):
    """Shared entry-point plumbing for every sequence-parallel attention
    layout (ring, ulysses): shard batch over the data axes and sequence
    over `axis_name`, fall back to dense when the axis is absent/size 1,
    and shard_map the per-shard `body`."""
    mesh = mesh or current_mesh()
    if mesh is None:
        raise MXNetError(f"{entry_name} requires an active mesh")
    if axis_name not in mesh or mesh.size(axis_name) == 1:
        return local_attention(q, k, v, causal=causal, scale=scale)
    batch = tuple(a for a in batch_axes if a in mesh) or None
    spec = P(batch, None, axis_name, None)
    fn = shard_map_unchecked(
        functools.partial(body, axis_name=axis_name,
                          causal=causal, scale=scale),
        mesh=mesh.mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def ring_attention_sharded(q, k, v, **kw):
    """User entry: q,k,v are [B, H, L, D] global arrays; shards batch over
    the data axes and sequence over `axis_name`, runs the ring."""
    return sharded_seq_attention(ring_attention, q, k, v,
                                 entry_name="ring_attention_sharded", **kw)
