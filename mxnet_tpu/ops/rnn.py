"""Fused multi-layer RNN op (ref: src/operator/rnn.cc + cudnn_rnn-inl.h).

The reference's fused LSTM/GRU kernels exist to make long unrolls cheap on
GPU; the TPU-native equivalent is a single ``lax.scan`` over time per
layer/direction inside one XLA program — the scan body is one fused
matmul+gates kernel on the MXU, and XLA pipelines the whole stack.

Packed parameter layout follows the reference's cudnn convention:
all layer weights first (per layer, per direction: W_i2h then W_h2h,
row-major flattened), then all biases (b_i2h then b_h2h).
Gate order: LSTM [i, f, g, o]; GRU [r, z, n].

Layout: data is TNC (seq, batch, input).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _cell_step(mode):
    if mode == "rnn_relu":
        def step(h_c, pre):
            return (jnp.maximum(pre, 0),), jnp.maximum(pre, 0)
    elif mode == "rnn_tanh":
        def step(h_c, pre):
            return (jnp.tanh(pre),), jnp.tanh(pre)
    elif mode == "lstm":
        def step(h_c, pre):
            h, c = h_c
            i, f, g, o = jnp.split(pre, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return (h_new, c_new), h_new
    else:
        raise ValueError(mode)
    return step


def _layer_forward(x, w_i2h, w_h2h, b_i2h, b_h2h, h0, c0, mode, reverse):
    """One direction of one layer: scan over time. x: (T, N, I)."""
    if reverse:
        x = jnp.flip(x, axis=0)

    if mode == "gru":
        # hoist the input projection out of the scan: one big MXU matmul.
        # GRU keeps b_h2h separate (applied before the r-gate product).
        xw = jnp.einsum("tni,gi->tng", x, w_i2h) + b_i2h

        def body(carry, xt):
            (h,) = carry
            hw = h @ w_h2h.T + b_h2h
            xr, xz, xn = jnp.split(xt, 3, axis=-1)
            hr, hz, hn = jnp.split(hw, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h_new = (1 - z) * n + z * h
            return (h_new,), h_new

        carry0 = (h0,)
        carry, ys = lax.scan(body, carry0, xw)
        hT = carry[0]
        cT = None
    else:
        xw = jnp.einsum("tni,gi->tng", x, w_i2h) + b_i2h + b_h2h
        step = _cell_step(mode)

        def body(carry, xt):
            pre = xt + carry[0] @ w_h2h.T
            return step(carry, pre)

        carry0 = (h0,) if mode != "lstm" else (h0, c0)
        carry, ys = lax.scan(body, carry0, xw)
        hT = carry[0]
        cT = carry[1] if mode == "lstm" else None
    if reverse:
        ys = jnp.flip(ys, axis=0)
    return ys, hT, cT


def _unpack_params(params, mode, input_size, hidden, num_layers, dirs):
    """Slice the flat cudnn-style parameter vector into per-layer mats."""
    g = _GATES[mode]
    mats = []
    off = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else hidden * dirs
        for d in range(dirs):
            wi = params[off:off + g * hidden * in_sz].reshape(g * hidden, in_sz)
            off += g * hidden * in_sz
            wh = params[off:off + g * hidden * hidden].reshape(g * hidden, hidden)
            off += g * hidden * hidden
            mats.append((wi, wh))
    biases = []
    for layer in range(num_layers):
        for d in range(dirs):
            bi = params[off:off + g * hidden]
            off += g * hidden
            bh = params[off:off + g * hidden]
            off += g * hidden
            biases.append((bi, bh))
    return mats, biases


def rnn_param_size(mode, input_size, hidden, num_layers, bidirectional):
    g = _GATES[mode]
    dirs = 2 if bidirectional else 1
    total = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else hidden * dirs
        total += dirs * (g * hidden * in_sz + g * hidden * hidden
                         + 2 * g * hidden)
    return total


def _rnn_nout(attrs):
    mode = attrs.get("mode", "lstm")
    if not attrs.get("state_outputs", True):
        return 1
    return 3 if mode == "lstm" else 2


@register_op("RNN", num_outputs=_rnn_nout)
def _rnn(data, parameters, state=None, state_cell=None, key=None,
         state_size=0, num_layers=1, mode="lstm", bidirectional=False,
         p=0.0, state_outputs=True, projection_size=None,
         lstm_state_clip_min=None, lstm_state_clip_max=None,
         lstm_state_clip_nan=False, use_sequence_length=False, _train=False):
    """data: (T, N, I); state: (L*dirs, N, H); returns out (T, N, H*dirs).
    Omitted state/state_cell default to zeros (the symbolic path's
    begin_state contract — cudnn_rnn-inl.h starts from zeros too)."""
    T, N, I = data.shape
    H = state_size
    dirs = 2 if bidirectional else 1
    if state is None:
        state = jnp.zeros((num_layers * dirs, N, H), data.dtype)
    if state_cell is None and mode == "lstm":
        state_cell = jnp.zeros((num_layers * dirs, N, H), data.dtype)
    mats, biases = _unpack_params(parameters, mode, I, H, num_layers, dirs)
    x = data
    h_outs, c_outs = [], []
    idx = 0
    for layer in range(num_layers):
        ys_dirs = []
        for d in range(dirs):
            wi, wh = mats[idx]
            bi, bh = biases[idx]
            h0 = state[layer * dirs + d]
            c0 = state_cell[layer * dirs + d] if mode == "lstm" else None
            ys, hT, cT = _layer_forward(x, wi, wh, bi, bh, h0, c0, mode,
                                        reverse=(d == 1))
            ys_dirs.append(ys)
            h_outs.append(hT)
            if mode == "lstm":
                c_outs.append(cT)
            idx += 1
        x = jnp.concatenate(ys_dirs, axis=-1) if dirs > 1 else ys_dirs[0]
        if p > 0 and _train and layer < num_layers - 1 and key is not None:
            sub = jax.random.fold_in(key, layer)
            mask = jax.random.bernoulli(sub, 1 - p, x.shape).astype(x.dtype)
            x = x * mask / (1 - p)
    if not state_outputs:
        return x
    h_stack = jnp.stack(h_outs)
    if mode == "lstm":
        return x, h_stack, jnp.stack(c_outs)
    return x, h_stack
