"""mxir — static verification of compiled StableHLO step programs.

The missing layer under mxlint/mxflow: those verify the Python that
*builds* programs; mxir verifies the programs themselves.  A
line-oriented parser (:mod:`parser`) turns the module text jax emits
(`lowered.as_text()` — the exact bytes the persistent compile cache
stores under its ``stablehlo`` tier) into a queryable IR, and five
program rules (:mod:`rules`, MX014–MX018) check the invariants PR 9's
ZeRO spine and PR 18's quantized collectives made load-bearing:
donation actually landing in the module, no oversized replicated
tensors under a multi-device mesh, no precision round trips around
the comm-quant path, collective hygiene plus a static wire-bytes
model cross-checked against the measured counters, and no host
transfers inside a step.

Entry points: :func:`audit_module` for one program's text,
:class:`.report.ProgramAudit`/:func:`.report.render_ir_json` for the
MXIR.json artifact.  The runtime hook lives framework-side in
:mod:`mxnet_tpu.compile_cache.audit` (it needs env knobs and
instruments); the offline CLI is ``tools/mxir.py``.

Stdlib-only, like the rest of ``mxnet_tpu.analysis``.
"""
# NOTE one-level `from .parser import X` forms throughout — the
# two-level / `from . import x` forms route through the ROOT package
# and break the mxlint CLI's standalone (jax-free) load; see
# analysis/__init__.py.
from .parser import (  # noqa: F401
    IrParseError, TensorType, FuncArg, FuncResult, Op, Func, Module,
    Sharding, parse_module, parse_sharding,
)
from .rules import (  # noqa: F401  — registers MX014–MX018 on import
    IrContext, IrRule, DonationDropped, OversizedReplicated,
    PrecisionLeak, CollectiveAudit, HostTransfer, WireEstimate,
    estimate_wire_bytes, wire_drift, audit_module, IR_RULE_IDS,
)
from .report import ProgramAudit, render_ir_json  # noqa: F401
from .fixtures import FIXTURES  # noqa: F401

__all__ = [
    "FIXTURES",
    "IrParseError", "TensorType", "FuncArg", "FuncResult", "Op",
    "Func", "Module", "Sharding", "parse_module", "parse_sharding",
    "IrContext", "IrRule", "DonationDropped", "OversizedReplicated",
    "PrecisionLeak", "CollectiveAudit", "HostTransfer", "WireEstimate",
    "estimate_wire_bytes", "wire_drift", "audit_module", "IR_RULE_IDS",
    "ProgramAudit", "render_ir_json",
]
