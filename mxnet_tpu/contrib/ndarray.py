"""mx.contrib.ndarray — imperative contrib op wrappers
(ref: python/mxnet/ndarray/contrib.py generated namespace)."""
from __future__ import annotations

from ..ndarray import register as _register
from .control_flow import cond, foreach, while_loop  # noqa: F401


def __getattr__(name):
    return _register.lookup(name)
