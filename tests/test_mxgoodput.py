"""mxgoodput (ISSUE 14): job-level goodput/badput accounting.

Tier-1 coverage:
  * ledger unit semantics — closure (productive + badput +
    unattributed == wall, nothing silently vanishes), category
    precedence (a data-wait second is never double-counted as
    comm_stall; interval badput inside a step's wall is peeled off
    before the step decomposition), fresh-ledger high-water mark (a
    live recorder's old records are never back-attributed);
  * the attribution hooks — retry backoff (counter independent of the
    ledger, category + per-site when on), checkpoint save/restore
    (blocking-portion-only for async saves), preemption recovery
    known-answer closing at the first post-resume step entry;
  * listener lifecycle across an ``mxprof.enable(ring=N)`` recorder
    swap, and deregistration from the LIVE recorder on disable;
  * the disabled-path zero-overhead gate (mxprof-style);
  * surfaces — the goodput block riding mxprof dumps, the /statusz
    line, the stock goodput_rules alert table, the report tool's
    multi-rank rollup + skew.

The multi-process chaos known-answer e2e (tools/goodput_report.py
strict) is slow-marked at the bottom — the nightly goodput stage runs
it before perf-compare.
"""
import gc
import importlib.util
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd, resilience, telemetry
from mxnet_tpu.gluon import nn, Trainer
from mxnet_tpu.resilience import chaos, preemption
from mxnet_tpu.telemetry import alerts, instruments as _ins
from mxnet_tpu.telemetry import mxgoodput, mxprof
from mxnet_tpu.telemetry import tracing as _tracing
from mxnet_tpu.telemetry.mxgoodput import CATEGORIES, GoodputLedger
from mxnet_tpu.util import env as _env

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_goodput_report():
    spec = importlib.util.spec_from_file_location(
        "goodput_report_under_test",
        os.path.join(_REPO, "tools", "goodput_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _detached():
    """Every test starts and ends with goodput off and the mxprof sink
    detached, so cross-test ledgers/listeners never leak."""
    mxgoodput.disable()
    mxprof.disable()
    mxprof.clear()
    preemption.clear()
    yield
    mxgoodput.disable()
    mxprof.disable()
    mxprof.clear()
    preemption.clear()


class _FakeRecorder:
    """records_since/current_step protocol over a fixed record list."""

    def __init__(self, records):
        self._records = list(records)

    def records_since(self, step):
        return [r for r in self._records if r["step"] > step]

    def current_step(self):
        return self._records[-1]["step"] if self._records else 0


def _rec(step, wall=1.0, data_wait=0.0, compile_s=0.0, phases=None,
         collectives=None):
    return {"step": step, "wall_s": wall, "data_wait_s": data_wait,
            "compile_s": compile_s, "phases": phases or {},
            "collectives": collectives or {}}


def _train_tools(units=16, steps=0):
    np.random.seed(0)
    mx.random.seed(0)
    net = nn.Dense(4, in_units=units)
    net.initialize()
    tr = Trainer(net.collect_params(), "sgd",
                 {"learning_rate": 1e-3, "momentum": 0.9})
    x = nd.array(np.random.rand(8, units).astype("float32"))

    def one_step():
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        tr.step(8)

    for _ in range(steps):
        one_step()
    return net, tr, one_step


# ---------------------------------------------------------------------------
# ledger unit semantics
# ---------------------------------------------------------------------------

class TestLedgerClosure:
    def test_closure_sums_to_wall(self):
        clock = [100.0]
        led = GoodputLedger(clock=lambda: clock[0])
        clock[0] = 110.0
        led.consume(_FakeRecorder([
            _rec(1, wall=2.0, data_wait=0.5,
                 phases={"grad-allreduce": 0.75}),
            _rec(2, wall=3.0, compile_s=1.0),
        ]))
        led.record_badput("retry_backoff", 0.25, site="s")
        snap = led.snapshot()
        total = (snap["productive_s"] + sum(snap["badput_s"].values())
                 + snap["unattributed_s"])
        assert abs(total - snap["wall_s"]) < 1e-9
        assert snap["closure"]["ok"]
        assert snap["badput_s"]["data_wait"] == pytest.approx(0.5)
        assert snap["badput_s"]["comm_stall"] == pytest.approx(0.75)
        assert snap["badput_s"]["compile"] == pytest.approx(1.0)
        assert snap["badput_s"]["retry_backoff"] == pytest.approx(0.25)
        # productive = (2.0 - 0.75) + (3.0 - 1.0)
        assert snap["productive_s"] == pytest.approx(3.25)
        assert snap["steps"] == 2

    def test_unknown_category_raises(self):
        led = GoodputLedger()
        with pytest.raises(ValueError):
            led.record_badput("coffee_break", 1.0)

    def test_over_attribution_is_exposed_not_hidden(self):
        """Feeds claiming more than the wall: the snapshot clamps
        unattributed at 0 but reports the closure error."""
        clock = [0.0]
        led = GoodputLedger(clock=lambda: clock[0])
        clock[0] = 1.0
        led.record_badput("checkpoint_save", 5.0)
        snap = led.snapshot()
        assert snap["unattributed_s"] == 0.0
        assert snap["closure"]["error_s"] < 0
        assert not snap["closure"]["ok"]

    def test_fresh_ledger_skips_preexisting_records(self):
        """Records a live recorder closed BEFORE the ledger existed
        must not be back-attributed (regression: stage N of a report
        run consumed stage N-1's ring and broke closure)."""
        rec = _FakeRecorder([_rec(1, wall=50.0), _rec(2, wall=50.0)])
        clock = [0.0]
        led = GoodputLedger(clock=lambda: clock[0])
        led.set_record_high_water(rec.current_step())
        clock[0] = 1.0
        assert led.consume(rec) == 0
        snap = led.snapshot()
        assert snap["productive_s"] == 0.0
        assert snap["closure"]["ok"]

    def test_racing_consume_never_folds_twice(self):
        """Two consumes racing on the same new records (listener vs
        snapshot) must fold them once: the under-lock re-filter drops
        records the other consume already took."""
        class _Stale(_FakeRecorder):
            # simulates the racing reader: returns records as if the
            # high-water mark had not advanced yet
            def records_since(self, step):
                return list(self._records)

        clock = [0.0]
        led = GoodputLedger(clock=lambda: clock[0])
        clock[0] = 10.0
        rec = _Stale([_rec(1, wall=2.0)])
        assert led.consume(rec) == 1
        assert led.consume(rec) == 0  # same records offered again
        snap = led.snapshot()
        assert snap["productive_s"] == pytest.approx(2.0)
        assert snap["closure"]["ok"]

    def test_recorder_swap_resets_high_water(self):
        """A clear()ed/swapped recorder restarts step numbering below
        the ledger's mark — consume must notice and not go deaf."""
        clock = [0.0]
        led = GoodputLedger(clock=lambda: clock[0])
        led.set_record_high_water(100)
        clock[0] = 10.0
        n = led.consume(_FakeRecorder([_rec(1, wall=2.0)]))
        assert n == 1
        assert led.snapshot()["productive_s"] == pytest.approx(2.0)


class TestCategoryPrecedence:
    def test_data_wait_never_double_counted_as_comm(self):
        """A step whose collectives nominally exceed its wall: comm is
        capped at the wall, and data-wait (which rides BESIDE the
        wall) is untouched — one second lands in exactly one
        category."""
        clock = [0.0]
        led = GoodputLedger(clock=lambda: clock[0])
        clock[0] = 10.0
        led.consume(_FakeRecorder([
            _rec(1, wall=1.0, data_wait=2.0,
                 collectives={"allreduce": 5.0}),
        ]))
        snap = led.snapshot()
        assert snap["badput_s"]["comm_stall"] == pytest.approx(1.0)
        assert snap["badput_s"]["data_wait"] == pytest.approx(2.0)
        assert snap["productive_s"] == 0.0
        assert snap["closure"]["ok"]

    def test_compile_peeled_before_comm(self):
        clock = [0.0]
        led = GoodputLedger(clock=lambda: clock[0])
        clock[0] = 10.0
        led.consume(_FakeRecorder([
            _rec(1, wall=1.0, compile_s=0.8,
                 collectives={"allreduce": 0.8}),
        ]))
        snap = led.snapshot()
        assert snap["badput_s"]["compile"] == pytest.approx(0.8)
        # only 0.2 of wall left for comm after the compile peel
        assert snap["badput_s"]["comm_stall"] == pytest.approx(0.2)
        assert snap["closure"]["ok"]

    def test_overlapping_interval_peeled_off_step(self):
        """A retry sleep recorded with overlaps_step=True during a
        step is peeled off that step's wall — the seconds keep their
        retry_backoff attribution and are not ALSO productive/comm."""
        clock = [0.0]
        led = GoodputLedger(clock=lambda: clock[0])
        led.record_badput("retry_backoff", 0.4, site="kv",
                          overlaps_step=True)
        clock[0] = 10.0
        led.consume(_FakeRecorder([
            _rec(1, wall=1.0, collectives={"allreduce": 1.0}),
        ]))
        snap = led.snapshot()
        assert snap["badput_s"]["retry_backoff"] == pytest.approx(0.4)
        # the remaining 0.6 of the wall is comm (capped), none doubled
        assert snap["badput_s"]["comm_stall"] == pytest.approx(0.6)
        assert snap["productive_s"] == 0.0
        assert snap["closure"]["ok"]

    def test_between_step_sleep_never_robs_productive(self):
        """Overlap credit from a sleep BETWEEN steps (the next record
        has no comm to peel it from) is discarded, not peeled off
        genuine compute — productive stays whole and the credit does
        not linger to shave a later step either."""
        clock = [0.0]
        led = GoodputLedger(clock=lambda: clock[0])
        led.record_badput("retry_backoff", 0.4, site="between",
                          overlaps_step=True)
        clock[0] = 10.0
        led.consume(_FakeRecorder([_rec(1, wall=1.0)]))  # no comm
        led.consume(_FakeRecorder([
            _rec(1, wall=1.0),
            _rec(2, wall=1.0, collectives={"allreduce": 0.3})]))
        snap = led.snapshot()
        # both steps' compute intact; record 2's comm untouched by the
        # long-gone credit (it was drained at record 1's consume)
        assert snap["productive_s"] == pytest.approx(1.7)
        assert snap["badput_s"]["comm_stall"] == pytest.approx(0.3)
        assert snap["badput_s"]["retry_backoff"] == pytest.approx(0.4)
        assert snap["closure"]["ok"]

    def test_retry_mark_is_thread_scoped(self):
        """A daemon thread's retry sleeps (an async writer retrying a
        flaky filesystem) must not appear in another thread's
        backoff mark — autockpt would deduct them from a concurrent
        sync save's blocking time."""
        import threading

        led = GoodputLedger()

        def daemon_retry():
            led.record_badput("retry_backoff", 0.7, site="ckpt.io",
                              overlaps_step=True)

        t = threading.Thread(target=daemon_retry)
        t.start()
        t.join()
        assert led.category_seconds("retry_backoff") == \
            pytest.approx(0.7)  # global total sees it
        assert led.retry_backoff_this_thread() == 0.0  # this thread's
        led.record_badput("retry_backoff", 0.2, site="here")
        assert led.retry_backoff_this_thread() == pytest.approx(0.2)

    def test_consume_overlap_cancels_credit(self):
        """autockpt deducting retry sleeps from its own measurement
        cancels the step-overlap credit — the next step is not
        shaved."""
        clock = [0.0]
        led = GoodputLedger(clock=lambda: clock[0])
        led.record_badput("retry_backoff", 0.4, site="ckpt",
                          overlaps_step=True)
        led.consume_overlap(0.4)
        clock[0] = 10.0
        led.consume(_FakeRecorder([_rec(1, wall=1.0)]))
        snap = led.snapshot()
        assert snap["productive_s"] == pytest.approx(1.0)
        assert snap["closure"]["ok"]


# ---------------------------------------------------------------------------
# attribution hooks: retry / checkpoint / preemption
# ---------------------------------------------------------------------------

class TestRetryHook:
    def test_backoff_counter_independent_of_goodput(self):
        """mx_retry_backoff_seconds_total grows with goodput DISABLED
        — the sleeps are measured wall-clock either way."""
        assert not mxgoodput.enabled()
        from mxnet_tpu.parallel import dist

        before = _ins.retry_backoff_seconds_total("dist.barrier").value
        with chaos.inject("dist.collective", times=1):
            dist.barrier()
        after = _ins.retry_backoff_seconds_total("dist.barrier").value
        assert after > before

    def test_backoff_lands_in_category_with_site(self):
        from mxnet_tpu.parallel import dist

        mxgoodput.enable(fresh=True)
        with chaos.inject("dist.collective", times=2):
            dist.barrier()
        snap = mxgoodput.snapshot()
        got = snap["badput_s"]["retry_backoff"]
        assert got > 0
        assert snap["retry_backoff_by_site"]["dist.barrier"] == \
            pytest.approx(got)
        assert snap["closure"]["ok"]


class TestCheckpointHook:
    def test_sync_save_and_restore_histograms(self, tmp_path):
        net, tr, one_step = _train_tools(steps=2)
        ck = resilience.AutoCheckpoint(str(tmp_path), tr,
                                       every_n_steps=0,
                                       async_save=False)
        h_save = _ins.ckpt_seconds("save", "sync")
        h_restore = _ins.ckpt_seconds("restore", "sync")
        n0, r0 = h_save.count, h_restore.count
        ck.save(sync=True)
        assert h_save.count == n0 + 1
        ck.resume()
        assert h_restore.count == r0 + 1

    def test_async_save_blocking_portion_only(self, tmp_path,
                                              monkeypatch):
        """A slow daemon write must NOT land in badput (it overlaps
        training); only the snapshot/enqueue half blocks the step
        path.  The daemon time is still recorded, labeled async."""
        net, tr, one_step = _train_tools(steps=2)
        mxgoodput.enable(fresh=True)
        ck = resilience.AutoCheckpoint(str(tmp_path), tr,
                                       every_n_steps=0,
                                       async_save=True)
        orig = resilience.AutoCheckpoint._write_once

        def slow_write(self, snap):
            time.sleep(0.12)
            return orig(self, snap)

        monkeypatch.setattr(resilience.AutoCheckpoint, "_write_once",
                            slow_write)
        h_async = _ins.ckpt_seconds("save", "async")
        a0, s0 = h_async.count, h_async.sum
        ck.save(sync=False)
        ck.flush()
        blocking = mxgoodput.category_seconds("checkpoint_save")
        assert blocking < 0.1, \
            f"daemon write leaked into blocking badput: {blocking}"
        assert h_async.count == a0 + 1
        assert h_async.sum - s0 >= 0.12

    def test_restore_attributed(self, tmp_path):
        net, tr, one_step = _train_tools(steps=2)
        ck = resilience.AutoCheckpoint(str(tmp_path), tr,
                                       every_n_steps=0)
        ck.save(sync=True)
        mxgoodput.enable(fresh=True)
        ck.resume()
        assert mxgoodput.category_seconds("checkpoint_restore") > 0
        assert mxgoodput.snapshot()["closure"]["ok"]


class TestPreemptionRecovery:
    DOWNTIME = 0.15

    def _preempt_resume(self, tmp_path, steps_after=1):
        net, tr, one_step = _train_tools(steps=2)
        mxgoodput.enable(fresh=True)
        ck = resilience.AutoCheckpoint(str(tmp_path), tr,
                                       every_n_steps=0)
        with pytest.raises(preemption.Preempted):
            with chaos.inject("trainer.preempt", at=2):
                for _ in range(4):
                    one_step()
        time.sleep(self.DOWNTIME)
        ck2 = resilience.AutoCheckpoint(str(tmp_path), tr,
                                        every_n_steps=0)
        meta = ck2.resume()
        assert isinstance(meta.get("preempt"), dict)  # stamped save
        for _ in range(steps_after):
            one_step()
        return mxgoodput.snapshot(), tr

    def test_known_answer_downtime(self, tmp_path):
        snap, _tr = self._preempt_resume(tmp_path)
        got = snap["badput_s"]["preemption_recovery"]
        assert self.DOWNTIME - 0.02 <= got <= self.DOWNTIME + 0.5, got
        assert snap["closure"]["ok"]

    def test_preempt_stamp_consumed_on_resume(self, tmp_path):
        """A SECOND resume from the same checkpoint (crash after the
        first resumed run) must not re-open a recovery window back to
        the original SIGTERM — the stamp is consumed by the first
        resume (demoted to preempt_consumed on disk)."""
        snap, tr = self._preempt_resume(tmp_path)
        assert not mxgoodput.ledger().recovery_open()
        ck = resilience.AutoCheckpoint(str(tmp_path), tr,
                                       every_n_steps=0)
        meta = ck.resume()
        assert "preempt" not in meta
        assert "preempt_consumed" in meta  # forensics survive
        assert not mxgoodput.ledger().recovery_open()

    def test_recovery_closes_at_first_step_entry(self, tmp_path):
        net, tr, one_step = _train_tools(steps=2)
        mxgoodput.enable(fresh=True)
        ck = resilience.AutoCheckpoint(str(tmp_path), tr,
                                       every_n_steps=0)
        with pytest.raises(preemption.Preempted):
            with chaos.inject("trainer.preempt", at=1):
                one_step()
        ck2 = resilience.AutoCheckpoint(str(tmp_path), tr,
                                        every_n_steps=0)
        ck2.resume()
        assert mxgoodput.ledger().recovery_open()
        one_step()
        assert not mxgoodput.ledger().recovery_open()
        assert mxgoodput.category_seconds("preemption_recovery") > 0


# ---------------------------------------------------------------------------
# listener lifecycle + enable/disable
# ---------------------------------------------------------------------------

class TestListenerLifecycle:
    def test_listener_survives_ring_swap(self):
        mxgoodput.enable(fresh=True)
        rec = mxprof.enable(ring=64)  # recorder SWAP mid-job
        assert mxgoodput._on_step in rec._listeners
        with _tracing.span("step", cat="training"):
            time.sleep(0.002)
        assert mxgoodput.snapshot()["steps"] == 1

    def test_disable_deregisters_from_live_recorder(self):
        """disable() must remove the listener from the recorder that
        is LIVE NOW — after an enable(ring=N) swap, a removal against
        the stale recorder object would leak the listener."""
        mxgoodput.enable(fresh=True)
        rec = mxprof.enable(ring=32)
        assert mxgoodput._on_step in rec._listeners
        mxgoodput.disable()
        assert mxgoodput._on_step not in mxprof.recorder()._listeners

    def test_fresh_enable_sets_high_water_before_publish(self):
        """enable(fresh=True) on a live recorder: the published ledger
        already carries the recorder's current step as its high-water
        mark (set before publication, so a concurrently-closing step
        can never back-attribute the ring into it)."""
        mxgoodput.enable(fresh=True)
        for _ in range(3):
            with _tracing.span("step", cat="training"):
                pass
        cur = mxprof.recorder().current_step()
        assert cur == 3
        led = mxgoodput.enable(fresh=True)
        assert led._last_step == cur
        assert led.snapshot()["steps"] == 0

    def test_enable_idempotent_one_listener(self):
        mxgoodput.enable(fresh=True)
        mxgoodput.enable()
        mxgoodput.enable()
        n = sum(1 for f in mxprof.recorder()._listeners
                if f is mxgoodput._on_step)
        assert n == 1

    def test_knobs_registered(self):
        for name in ("MXNET_GOODPUT", "MXNET_GOODPUT_MIN",
                     "MXNET_GOODPUT_UNATTRIBUTED_MAX"):
            assert _env.is_declared(name), name


# ---------------------------------------------------------------------------
# the disabled-path zero-overhead gate (mxprof-style)
# ---------------------------------------------------------------------------

def test_goodput_disabled_overhead_within_3pct_of_step():
    """With mxgoodput imported but DISABLED and only the mxprof sink
    attached, the per-step attribution feed must stay within the same
    3% budget mxprof holds — goodput must add literally nothing to the
    disabled path (no listener, one falsy module check)."""
    net, tr, one_step_train = _train_tools(units=16)
    for _ in range(5):
        one_step_train()

    assert not telemetry.enabled()
    assert not mxgoodput.enabled()
    mxprof.disable()

    def best_window(loops, reps, fn):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(loops):
                fn()
            best = min(best, time.perf_counter() - t0)
        return best

    gc.disable()
    try:
        t_step = best_window(20, 5, one_step_train) / 20
        mxprof.enable(ring=64)
        assert not mxgoodput.enabled()  # imported, idle
        assert mxgoodput._on_step not in mxprof.recorder()._listeners

        def per_step_feed():
            with _tracing.span("forward", cat="training"):
                pass
            with _tracing.span("backward", cat="training"):
                pass
            with _tracing.span("step", cat="training"):
                with _tracing.span("grad-allreduce", cat="training"):
                    pass
                with _tracing.span("optimizer-update",
                                   cat="training"):
                    pass

        t_attr = best_window(2000, 7, per_step_feed) / 2000
    finally:
        gc.enable()
        mxprof.disable()
        mxprof.clear()
    assert t_attr <= 0.03 * t_step, \
        (f"per-step feed with goodput imported-but-disabled costs "
         f"{t_attr * 1e6:.2f}us vs step {t_step * 1e6:.1f}us — "
         f"{t_attr / t_step * 100:.2f}% exceeds the 3% budget")


# ---------------------------------------------------------------------------
# surfaces: dump embed, /statusz, alert rules, report rollup
# ---------------------------------------------------------------------------

class TestSurfaces:
    def test_dump_embeds_goodput_block(self):
        mxgoodput.enable(fresh=True)
        with _tracing.span("step", cat="training"):
            time.sleep(0.002)
        snap = mxprof.snapshot(live_hbm=False)
        assert "goodput" in snap
        assert snap["goodput"]["closure"]["ok"]
        assert snap["goodput"]["steps"] == 1

    def test_dump_omits_goodput_when_disabled(self):
        mxprof.enable()
        snap = mxprof.snapshot(live_hbm=False)
        assert "goodput" not in snap

    def test_statusz_renders_goodput_line(self):
        from mxnet_tpu.serving.http import _render_statusz

        class _Stub:
            draining = False

            @staticmethod
            def metrics():
                return {"pending": 0, "max_queue": 8, "models": []}

        page = _render_statusz(_Stub())
        assert "goodput: (mxgoodput not enabled)" in page
        mxgoodput.enable(fresh=True)
        with _tracing.span("step", cat="training"):
            time.sleep(0.002)
        page = _render_statusz(_Stub())
        assert "goodput: 0." in page or "goodput: 1." in page
        assert "unattributed" in page

    def test_goodput_rules_fire_and_resolve(self):
        clock = [0.0]
        eng = alerts.AlertEngine(clock=lambda: clock[0])
        alerts.goodput_rules(eng, min_ratio=0.9, for_s=2.0)
        # absent family: stays inactive, never compares against 0
        assert not eng.tick()
        _ins.goodput_ratio().set(0.4)
        assert not eng.tick()          # pending, inside for-window
        clock[0] = 3.0
        fired = [e for e in eng.tick() if e["state"] == "firing"]
        assert [e["rule"] for e in fired] == ["goodput_below_min"]
        _ins.goodput_ratio().set(0.97)
        resolved = [e for e in eng.tick()
                    if e["state"] == "resolved"]
        assert [e["rule"] for e in resolved] == ["goodput_below_min"]

    def test_preemption_recovery_rule_increase_semantics(self):
        clock = [0.0]
        eng = alerts.AlertEngine(clock=lambda: clock[0])
        alerts.goodput_rules(eng, min_ratio=0.9)
        c = _ins.badput_seconds_total("preemption_recovery")
        eng.tick()                     # baseline the delta
        c.inc(12.5)
        fired = [e for e in eng.tick() if e["state"] == "firing"]
        assert [e["rule"] for e in fired] == ["preemption_recovery"]
        # growth stopped -> the rule RESOLVES (a raw-value rule over a
        # monotone counter would page forever)
        resolved = [e for e in eng.tick()
                    if e["state"] == "resolved"]
        assert [e["rule"] for e in resolved] == ["preemption_recovery"]

    def test_report_merge_rollup_and_skew(self, tmp_path):
        gr = _load_goodput_report()

        def dump(rank, retry_s):
            bad = {c: 0.0 for c in CATEGORIES}
            bad["retry_backoff"] = retry_s
            return {"rank": rank, "goodput": {
                "wall_s": 10.0, "productive_s": 10.0 - retry_s - 1.0,
                "unattributed_s": 1.0, "steps": 5, "badput_s": bad,
                "goodput_ratio": (9.0 - retry_s) / 10.0,
                "closure": {"ok": True, "error_s": 0.0,
                            "accounted_s": 10.0},
            }}

        p0 = tmp_path / "mxprof-rank0.json"
        p1 = tmp_path / "mxprof-rank1.json"
        p0.write_text(json.dumps(dump(0, 0.0)))
        p1.write_text(json.dumps(dump(1, 3.0)))
        merged = gr.merge_dumps([str(p0), str(p1)])
        job = merged["job"]
        assert job["wall_s"] == pytest.approx(20.0)
        assert job["badput_s"]["retry_backoff"] == pytest.approx(3.0)
        assert job["goodput_ratio"] == pytest.approx(
            (9.0 + 6.0) / 20.0)
        skew = merged["badput_skew"]["retry_backoff"]
        assert skew["worst_rank"] == "1"
        assert skew["spread_s"] == pytest.approx(3.0)

    def test_report_merge_rejects_dump_without_goodput(self, tmp_path):
        gr = _load_goodput_report()
        p = tmp_path / "mxprof-rank0.json"
        p.write_text(json.dumps({"rank": 0}))
        with pytest.raises(ValueError):
            gr.merge_dumps([str(p)])

    def test_report_quick_smoke(self, tmp_path):
        """tier-1 smoke: the in-process scenarios run and write the
        artifact (--no-gate; the strict run is the nightly's)."""
        out = tmp_path / "GOODPUT.json"
        p = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools",
                                          "goodput_report.py"),
             "--no-gate", "--quick", "--out", str(out)],
            capture_output=True, text=True, timeout=300, cwd=_REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert p.returncode == 0, p.stdout + p.stderr
        rep = json.loads(out.read_text())
        assert set(rep["stages"]) == {"clean_run", "retry_storm",
                                      "forced_checkpoint",
                                      "preemption"}
        for name, stage in rep["stages"].items():
            assert stage["ok"], (name, stage)


# ---------------------------------------------------------------------------
# nightly (slow): the strict multi-process chaos known-answer e2e
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_goodput_report_e2e_strict():
    """The full chaos known-answer run, STRICT (incl. the 2-process
    rank-dump merge): every injected disruption must land in its own
    category at the injected magnitude, and gate_ok must commit."""
    with tempfile.TemporaryDirectory() as d:
        out = os.path.join(d, "GOODPUT.json")
        p = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools",
                                          "goodput_report.py"),
             "--out", out],
            capture_output=True, text=True, timeout=600, cwd=_REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert p.returncode == 0, p.stdout + p.stderr
        with open(out) as f:
            rep = json.load(f)
    assert rep["gate_ok"]
    mr = rep["stages"]["multi_rank_merge"]
    assert mr["ok"] and mr["badput_skew"]["worst_rank"] == "1"
