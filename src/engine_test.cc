// Native engine stress test (ref: tests/cpp/engine/threaded_engine_test.cc
// — the reference's randomized dependency-graph stress, here with plain
// asserts instead of gtest, which is not in this image).
//
// Built + run by tests/test_native.py::test_cpp_engine_stress_binary:
//   g++ -std=c++17 -O2 -pthread src/engine_test.cc src/engine.cc -o <bin>
//
// Checks, directly in C++ (no Python in the loop):
//   1. Writes to one variable execute in FIFO push order (version order).
//   2. Readers never run concurrently with a writer on the same var
//      (RAW/WAR/WAW hazards), while independent readers DO overlap.
//   3. A randomized DAG of ops over many vars executes a serialization
//      consistent with per-var hazards (final counters match a serial
//      replay).
//   4. WaitForVar only waits for that var's pending ops.

#include <atomic>
#include <cassert>
#include <chrono>
#include <condition_variable>
#include <functional>
#include <cstdint>
#include <cstdio>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

#include "engine.h"

using mxt::Engine;

// Engine::PushAsync takes a C fn pointer + arg; wrap std::function so the
// tests can use capturing lambdas.
static void Tramp(void* arg) {
  auto* f = static_cast<std::function<void()>*>(arg);
  (*f)();
  delete f;
}

static void Push(Engine& e, std::function<void()> fn,
                 std::vector<int64_t> rs, std::vector<int64_t> ws,
                 int prio) {
  e.PushAsync(&Tramp, new std::function<void()>(std::move(fn)),
              rs.data(), static_cast<int>(rs.size()), ws.data(),
              static_cast<int>(ws.size()), prio);
}

static void test_write_fifo() {
  Engine eng(4);
  auto v = eng.NewVariable();
  std::vector<int> order;
  std::mutex m;
  for (int i = 0; i < 200; ++i) {
    Push(eng, [&, i] {
      std::lock_guard<std::mutex> g(m);
      order.push_back(i);
    }, {}, {v}, 0);
  }
  eng.WaitForAll();
  assert(order.size() == 200);
  for (int i = 0; i < 200; ++i) assert(order[i] == i);
  std::printf("  write FIFO: ok\n");
}

static void test_reader_writer_exclusion() {
  Engine eng(8);
  auto v = eng.NewVariable();
  std::atomic<int> readers{0}, writers{0};
  std::atomic<bool> violation{false};
  std::atomic<int> max_readers{0};
  for (int i = 0; i < 400; ++i) {
    if (i % 4 == 0) {
      Push(eng, [&] {
        if (readers.load() != 0 || writers.fetch_add(1) != 0)
          violation = true;
        std::this_thread::sleep_for(std::chrono::microseconds(50));
        writers.fetch_sub(1);
      }, {}, {v}, 0);
    } else {
      Push(eng, [&] {
        if (writers.load() != 0) violation = true;
        int r = readers.fetch_add(1) + 1;
        int prev = max_readers.load();
        while (r > prev && !max_readers.compare_exchange_weak(prev, r)) {}
        std::this_thread::sleep_for(std::chrono::microseconds(50));
        readers.fetch_sub(1);
      }, {v}, {}, 0);
    }
  }
  eng.WaitForAll();
  assert(!violation.load());
  // with 8 workers and batches of 3 readers between writes, SOME reads
  // must have overlapped
  assert(max_readers.load() >= 2);
  std::printf("  reader/writer exclusion: ok (max concurrent readers %d)\n",
              max_readers.load());
}

static void test_random_dag() {
  Engine eng(8);
  constexpr int kVars = 16, kOps = 2000;
  std::vector<int64_t> vars;
  for (int i = 0; i < kVars; ++i) vars.push_back(eng.NewVariable());
  // engine-executed counters: PLAIN (non-atomic) int64, updated with a
  // NON-COMMUTATIVE function — correct only if the engine really
  // serializes writes per var in FIFO order; lost/torn/reordered
  // updates change the final value
  std::vector<int64_t> val(kVars, 0);
  // serial replay oracle (push order == required write order per var)
  std::vector<int64_t> oracle(kVars, 0);
  std::mt19937 rng(42);
  for (int op = 0; op < kOps; ++op) {
    // draw a DISTINCT var set, then split into writes + reads — an op
    // must never name the same var as both read and write (the
    // reference engine contract; it would deadlock on itself)
    int nr = rng() % 3, nw = 1 + rng() % 2;
    std::vector<int> picked;
    while (static_cast<int>(picked.size()) < nr + nw) {
      int i = rng() % kVars;
      bool dup = false;
      for (int j : picked) dup |= (j == i);
      if (!dup) picked.push_back(i);
    }
    std::vector<int64_t> rs, ws;
    std::vector<int> ri, wi;
    for (int k = 0; k < nw; ++k) {
      wi.push_back(picked[k]); ws.push_back(vars[picked[k]]);
    }
    for (int k = nw; k < nw + nr; ++k) {
      ri.push_back(picked[k]); rs.push_back(vars[picked[k]]);
    }
    int64_t addend = 1 + (op % 7);
    constexpr int64_t kMod = 1000003;  // keep values bounded
    Push(eng, [&, wi, addend] {
      for (int i : wi) val[i] = (val[i] * 3 + addend) % kMod;
    }, rs, ws, static_cast<int>(rng() % 3));
    for (int i : wi) oracle[i] = (oracle[i] * 3 + addend) % kMod;
  }
  eng.WaitForAll();
  for (int i = 0; i < kVars; ++i) assert(val[i] == oracle[i]);
  std::printf("  randomized DAG (%d ops, %d vars): ok\n", kOps, kVars);
}

static void test_wait_for_var_is_selective() {
  Engine eng(4);
  auto a = eng.NewVariable();
  auto b = eng.NewVariable();
  // the op on b blocks on a latch the MAIN thread releases AFTER the
  // selectivity assertion — no wall-clock race: if WaitForVar(a) also
  // waited for b, this test would deadlock (and time out) rather than
  // pass or flake
  std::mutex latch_m;
  std::condition_variable latch_cv;
  bool release = false;
  std::atomic<bool> slow_done{false};
  Push(eng, [&] {
    std::unique_lock<std::mutex> lk(latch_m);
    latch_cv.wait(lk, [&] { return release; });
    slow_done = true;
  }, {}, {b}, 0);
  std::atomic<bool> fast_done{false};
  Push(eng, [&] { fast_done = true; }, {}, {a}, 0);
  eng.WaitForVar(a);
  assert(fast_done.load());
  assert(!slow_done.load());  // b's op is still parked on the latch
  {
    std::lock_guard<std::mutex> lk(latch_m);
    release = true;
  }
  latch_cv.notify_all();
  eng.WaitForAll();
  assert(slow_done.load());
  std::printf("  WaitForVar selectivity: ok\n");
}

int main() {
  setvbuf(stdout, nullptr, _IONBF, 0);
  std::printf("engine_test (C++)\n");
  test_write_fifo();
  test_reader_writer_exclusion();
  test_random_dag();
  test_wait_for_var_is_selective();
  std::printf("ALL_OK\n");
  return 0;
}
