"""SSD detection model tests (BASELINE config 4 plumbing)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import Trainer
from mxnet_tpu.gluon.model_zoo.detection import (SSD, SSDMultiBoxLoss,
                                                 SSDTargetGenerator,
                                                 get_detection_model,
                                                 ssd_300_mobilenet1_0)


@pytest.fixture(scope="module")
def small_ssd():
    net = get_detection_model("ssd_300_mobilenet1.0", classes=3)
    net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    return net


def test_ssd_forward_shapes(small_ssd):
    x = nd.zeros((2, 3, 128, 128))
    cls_preds, box_preds, anchors = small_ssd(x)
    n = anchors.shape[1]
    assert anchors.shape == (1, n, 4)
    assert cls_preds.shape == (2, n, 4)   # 3 classes + background
    assert box_preds.shape == (2, n, 4)
    a = anchors.asnumpy()
    assert a.min() >= 0.0 and a.max() <= 1.0  # clipped priors


@pytest.mark.slow  # ~20s: SSD target-gen + train step; nightly
def test_ssd_train_step(small_ssd):
    net = small_ssd
    target_gen = SSDTargetGenerator(negative_mining_ratio=-1.0)
    loss_fn = SSDMultiBoxLoss()
    trainer = Trainer(net.collect_params(), "sgd", {"learning_rate": 1e-3})

    x = nd.array(np.random.randn(2, 3, 128, 128).astype("float32"))
    # one gt box per image: [cls, x1, y1, x2, y2]
    labels = nd.array(np.array(
        [[[0, 0.1, 0.1, 0.4, 0.4]], [[2, 0.5, 0.5, 0.9, 0.9]]], "float32"))

    with autograd.record():
        cls_preds, box_preds, anchors = net(x)
        box_t, box_m, cls_t = target_gen(anchors, labels, cls_preds)
        loss = loss_fn(cls_preds, box_preds, cls_t, box_t)
    loss.backward()
    trainer.step(2)
    lval = loss.asnumpy()
    assert lval.shape == (2,)
    assert np.isfinite(lval).all()
    # a second step decreases loss on the same batch (sanity: gradients flow)
    with autograd.record():
        cls_preds, box_preds, anchors = net(x)
        box_t, box_m, cls_t = target_gen(anchors, labels, cls_preds)
        loss2 = loss_fn(cls_preds, box_preds, cls_t, box_t)
    loss2.backward()
    trainer.step(2)
    assert np.isfinite(loss2.asnumpy()).all()


def test_ssd_detection_inference(small_ssd):
    net = small_ssd
    x = nd.array(np.random.randn(1, 3, 128, 128).astype("float32"))
    cls_preds, box_preds, anchors = net(x)
    cls_probs = nd.softmax(cls_preds, axis=-1)
    out = nd.MultiBoxDetection(
        nd.transpose(cls_probs, axes=(0, 2, 1)),
        nd.reshape(box_preds, shape=(0, -1)),
        anchors, nms_topk=50)
    assert out.shape[2] == 6
    o = out.asnumpy()
    kept = o[0][o[0, :, 0] >= 0]
    if kept.size:  # scores are valid probabilities
        assert (kept[:, 1] >= 0).all() and (kept[:, 1] <= 1).all()


def test_ssd_hybridize_matches_eager(small_ssd):
    net = small_ssd
    x = nd.array(np.random.randn(1, 3, 128, 128).astype("float32"))
    eager = [o.asnumpy() for o in net(x)]
    net.hybridize()
    hybrid = [o.asnumpy() for o in net(x)]
    for e, h in zip(eager, hybrid):
        np.testing.assert_allclose(e, h, rtol=1e-4, atol=1e-4)
    net.hybridize(active=False)


def test_ssd_resnet50_constructs():
    # construction + param structure only (forward is heavy for unit CI;
    # the bench drives it on TPU)
    net = get_detection_model("ssd_300_resnet50_v1", classes=20)
    names = list(net.collect_params().keys())
    assert any("cls" in n for n in names)
    assert any("extra" in n for n in names)


# ---------------------------------------------------------------------------
# box_encode/box_decode + Proposal (ref: bounding_box.cc, proposal.cc)
# ---------------------------------------------------------------------------

def test_box_encode_decode_roundtrip():
    anchors = np.array([[[10., 10, 30, 30], [40, 40, 80, 100]]],
                       np.float32)
    gt = np.array([[[12., 8, 28, 35], [35, 45, 90, 95]]], np.float32)
    samples = np.array([[1., 1.]], np.float32)
    matches = np.array([[0., 1.]], np.float32)
    t, m = nd.box_encode(nd.array(samples), nd.array(matches),
                         nd.array(anchors), nd.array(gt))
    np.testing.assert_allclose(m.asnumpy(), np.ones((1, 2, 4)))
    # reference-default stds: encode (0.1,0.1,0.2,0.2) <-> decode stdN
    dec = nd.box_decode(t, nd.array(anchors), std0=0.1, std1=0.1,
                        std2=0.2, std3=0.2)
    np.testing.assert_allclose(dec.asnumpy(), gt, rtol=1e-4, atol=1e-3)
    # unmatched rows (samples<=0.5) encode to zeroed targets + zero mask
    t2, m2 = nd.box_encode(nd.array(np.array([[1., 0.]], np.float32)),
                           nd.array(matches), nd.array(anchors),
                           nd.array(gt))
    assert (m2.asnumpy()[0, 1] == 0).all()
    assert (t2.asnumpy()[0, 1] == 0).all()


def test_proposal_rpn():
    B, A, H, W = 1, 3, 8, 8
    rng = np.random.RandomState(0)
    cls = rng.rand(B, 2 * A, H, W).astype(np.float32) * 0.1
    cls[0, A + 1, 4, 4] = 0.99  # one strong anchor
    bbox = np.zeros((B, 4 * A, H, W), np.float32)
    im_info = np.array([[128., 128., 1.0]], np.float32)
    rois, score = nd.Proposal(
        nd.array(cls), nd.array(bbox), nd.array(im_info),
        rpn_pre_nms_top_n=50, rpn_post_nms_top_n=10,
        rpn_min_size=1, feature_stride=16,
        scales=(2,), ratios=(0.5, 1, 2), output_score=True)
    r = rois.asnumpy()
    assert r.shape == (10, 5)          # [batch_idx, x1, y1, x2, y2]
    assert (r[:, 0] == 0).all()        # batch index first (ROI contract)
    assert float(score.asnumpy()[0, 0]) > 0.9  # strong anchor leads
    assert (r[:, 1:] >= 0).all() and (r[:, 1:] <= 127).all()
    # rois feed ROIPooling directly (the Faster R-CNN wiring)
    feat = nd.array(np.random.RandomState(1).randn(1, 4, 8, 8)
                    .astype(np.float32))
    pooled = nd.ROIPooling(feat, rois, pooled_size=(3, 3),
                           spatial_scale=1.0 / 16)
    assert pooled.shape == (10, 4, 3, 3)
    # MultiProposal alias, single output without scores
    out2 = nd.MultiProposal(nd.array(cls), nd.array(bbox),
                            nd.array(im_info), rpn_post_nms_top_n=10,
                            rpn_min_size=1, scales=(2,))
    assert out2.shape == (10, 5)
