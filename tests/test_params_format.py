"""Byte-level `.params` format pinning (round-4 advisor finding #4).

The nightly compat fixtures were produced by this repo's own
serializers, so they pin self round-trip stability only.  These tests
pin the FORMAT itself against the reference's documented layout (ref:
src/ndarray/ndarray.cc NDArray::Save/Load — magic-tagged little-endian
records; constants from include/mxnet/c_api.h kMXAPINDArrayListMagic
and ndarray.cc NDARRAY_V2_MAGIC):

  * a fixture hand-crafted with struct.pack — independent of
    serialization.py's writer — must load, and
  * a file written by mx.nd.save must parse with an independent
    struct-unpack reader written against the documented layout.

Either direction drifting from the published constants/field order now
fails here, not in a user's interchange with MXNet-1.x tooling.
"""
import struct

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd

LIST_MAGIC = 0x112            # kMXAPINDArrayListMagic
NDARRAY_V2_MAGIC = 0xF993FAC9


def _craft_dense_params(arrays):
    """Reference-layout writer built ONLY from the documented format."""
    out = struct.pack("<QQ", LIST_MAGIC, 0)
    out += struct.pack("<Q", len(arrays))
    for _, a in arrays:
        a = np.ascontiguousarray(a)
        out += struct.pack("<II", NDARRAY_V2_MAGIC, 0)     # dense stype
        out += struct.pack("<I", a.ndim)
        out += struct.pack(f"<{a.ndim}q", *a.shape)
        out += struct.pack("<ii", 1, 0)                    # ctx cpu(0)
        flag = {"float32": 0, "float64": 1, "int32": 4,
                "int64": 6}[str(a.dtype)]
        out += struct.pack("<i", flag)
        out += a.tobytes()
    out += struct.pack("<Q", len(arrays))
    for name, _ in arrays:
        b = name.encode("utf-8")
        out += struct.pack("<Q", len(b)) + b
    return out


def test_hand_crafted_reference_bytes_load(tmp_path):
    # f32/i32 only: the 64-bit type_flags parse fine but the NDArray
    # layer truncates them to 32-bit widths under default (x64-off) JAX
    # — a width policy, not a format property, so not pinned here
    arrays = [("arg:w", np.arange(12, dtype=np.float32).reshape(3, 4)),
              ("aux:mean", np.array([1.5, -2.0], np.float32)),
              ("idx", np.array([[7, 8], [9, 10]], np.int32))]
    p = tmp_path / "crafted.params"
    p.write_bytes(_craft_dense_params(arrays))
    loaded = nd.load(str(p))
    assert sorted(loaded) == sorted(n for n, _ in arrays)
    for name, a in arrays:
        got = loaded[name].asnumpy()
        assert got.dtype == a.dtype and got.shape == a.shape
        np.testing.assert_array_equal(got, a)


def test_saved_bytes_parse_with_independent_reader(tmp_path):
    p = tmp_path / "written.params"
    data = {"w": mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3)),
            "b": mx.nd.array(np.array([3, -1], np.int32), dtype="int32")}
    nd.save(str(p), data)
    buf = p.read_bytes()

    off = 0

    def take(fmt):
        nonlocal off
        vals = struct.unpack_from(fmt, buf, off)
        off += struct.calcsize(fmt)
        return vals

    magic, reserved = take("<QQ")
    assert magic == LIST_MAGIC and reserved == 0
    (n_arr,) = take("<Q")
    assert n_arr == 2
    parsed = []
    for _ in range(n_arr):
        amagic, stype = take("<II")
        assert amagic == NDARRAY_V2_MAGIC and stype == 0
        (ndim,) = take("<I")
        shape = take(f"<{ndim}q")
        dev_type, _dev_id = take("<ii")
        assert dev_type == 1                       # saved as cpu, like ref
        (flag,) = take("<i")
        dt = {0: np.float32, 4: np.int32}[flag]
        count = int(np.prod(shape))
        a = np.frombuffer(buf, dt, count, off).reshape(shape)
        off += count * np.dtype(dt).itemsize
        parsed.append(a)
    (n_names,) = take("<Q")
    names = []
    for _ in range(n_names):
        (ln,) = take("<Q")
        names.append(buf[off:off + ln].decode("utf-8"))
        off += ln
    assert off == len(buf)
    got = dict(zip(names, parsed))
    np.testing.assert_array_equal(got["w"], data["w"].asnumpy())
    np.testing.assert_array_equal(got["b"], data["b"].asnumpy())
