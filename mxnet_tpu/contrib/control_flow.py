"""Control-flow operators: foreach / while_loop / cond.

TPU-native counterpart of the reference's control-flow ops
(ref: src/operator/control_flow.cc; python/mxnet/ndarray/contrib.py
foreach/while_loop/cond).  Where the reference builds subgraphs executed
by a C++ loop executor, here the natural lowering IS the XLA structured
primitive — `lax.scan` / `lax.while_loop` / `lax.cond` — so a hybridized
block containing `foreach` compiles to ONE fused scan on the MXU instead
of an unrolled chain (the whole point of SURVEY.md's "compiler-friendly
control flow" design stance).

Three execution regimes, chosen automatically:

- **autograd.record**: a Python loop of tape-registered ops (slice/
  stack), so gradients flow through the existing tape exactly like the
  reference's imperative loop.
- **eager (no grad)**: `lax.scan`/`lax.while_loop`/`lax.cond` over the
  jax values — one compiled program per (body, shapes).
- **inside a trace** (hybridize / CachedOp / symbolic executor): same
  lax path; the tracer values compose into the enclosing program.
"""
from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["foreach", "while_loop", "cond"]


def _as_list(x) -> Tuple[List, bool]:
    if isinstance(x, (list, tuple)):
        return list(x), True
    return [x], False


def _unlist(xs: List, was_list: bool):
    return list(xs) if was_list else xs[0]


def _values(nds: Sequence[NDArray]):
    return [a._data if isinstance(a, NDArray) else jnp.asarray(a)
            for a in nds]


def _wrap(vals, ctx) -> List[NDArray]:
    # tracers can't answer .devices(): wrap without a ctx pin (the ctx
    # of a traced value is decided by the enclosing program)
    return [NDArray(v, ctx=None if isinstance(v, jax.core.Tracer)
                    else ctx) for v in vals]


def _recording() -> bool:
    from ..autograd import is_recording

    return is_recording()


def foreach(body: Callable, data, init_states):
    """Iterate `body(data_slice, states) -> (outputs, new_states)` over
    axis 0 of `data`; returns (stacked outputs, final states)
    (ref: mx.nd.contrib.foreach).  `data`/`outputs`/`states` may each be
    an NDArray or a list of NDArrays."""
    data_l, data_is_list = _as_list(data)
    states_l, states_is_list = _as_list(init_states)
    if not data_l:
        raise MXNetError("foreach: data must contain at least one array")
    length = data_l[0].shape[0]
    for d in data_l:
        if d.shape[0] != length:
            raise MXNetError("foreach: all data arrays must share axis-0 "
                             f"length (got {d.shape[0]} vs {length})")
    ctx = data_l[0].ctx if isinstance(data_l[0], NDArray) else None

    if _recording() and length > 0:
        # tape-backed unrolled loop (gradient path); zero-length data
        # falls to the scan path below (no iterations -> constant
        # outputs, nothing for the tape to record)
        outs: List[List[NDArray]] = []
        states = list(states_l)
        for i in range(length):
            sl = [d.slice_axis(0, i, i + 1).reshape(d.shape[1:])
                  for d in data_l]
            o, states = body(_unlist(sl, data_is_list),
                             _unlist(states, states_is_list))
            states, _ = _as_list(states)
            o_l, o_is_list = _as_list(o)
            outs.append(o_l)
        from .. import nd

        stacked = [nd.stack(*[step[j] for step in outs], axis=0)
                   for j in range(len(outs[0]))]
        return (_unlist(stacked, o_is_list),
                _unlist(states, states_is_list))

    out_is_list = [None]
    from .. import random as rnd

    # the body must NOT split the ambient PRNG provider's key inside the
    # scan trace (the side effect would leak an inner tracer into the
    # outer scope); instead one key is drawn OUTSIDE and a per-iteration
    # key folded from it is scoped around the body
    base_key = rnd.next_key()

    def scan_body(carry, xs):
        i, carry = carry[0], carry[1:]
        prov = rnd.KeyProvider(jax.random.fold_in(base_key, i))
        with rnd.key_provider(prov):
            o, new_states = body(
                _unlist(_wrap(xs, ctx), data_is_list),
                _unlist(_wrap(list(carry), ctx), states_is_list))
        o_l, o_is = _as_list(o)
        out_is_list[0] = o_is
        ns_l, _ = _as_list(new_states)
        return ((i + 1,) + tuple(_values(ns_l)), tuple(_values(o_l)))

    carry, ys = lax.scan(
        scan_body, (jnp.asarray(0),) + tuple(_values(states_l)),
        tuple(_values(data_l)))
    outs = _wrap(list(ys), ctx)
    final = _wrap(list(carry[1:]), ctx)
    return (_unlist(outs, out_is_list[0]),
            _unlist(final, states_is_list))


def while_loop(cond_fn: Callable, func: Callable, loop_vars,
               max_iterations: int = None):
    """`while cond_fn(*loop_vars): outputs, loop_vars = func(*loop_vars)`
    (ref: mx.nd.contrib.while_loop).  Returns (stacked outputs, final
    loop_vars); outputs are padded to `max_iterations` rows (the
    reference's symbolic contract — XLA needs static shapes)."""
    lv, _ = _as_list(loop_vars)
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations (static "
                         "output shape on TPU)")
    ctx = lv[0].ctx if lv and isinstance(lv[0], NDArray) else None

    def _pred(*vars_):
        p = cond_fn(*vars_)
        if isinstance(p, NDArray):
            return bool(p.asnumpy().reshape(()))
        return bool(p)

    concrete = all(not isinstance(v._data, jax.core.Tracer) for v in lv
                   if isinstance(v, NDArray))
    if _recording() or concrete:
        # imperative loop: exact trip count, taped when recording
        outs = []
        o_is_list = False
        n = 0
        while n < max_iterations and _pred(*lv):
            o, new_lv = func(*lv)
            lv, _ = _as_list(new_lv)
            o_l, o_is_list = _as_list(o)
            outs.append(o_l)
            n += 1
        from .. import nd

        if not outs:
            # cond false on entry: zero-filled padded buffers, exactly
            # like the traced lax.while_loop path (no eager/traced
            # behavior split); shapes come from one probe call
            probe_o, _ = func(*lv)
            probe_l, o_is_list = _as_list(probe_o)
            stacked = [nd.zeros((max_iterations,) + tuple(p.shape),
                                dtype=p.dtype) for p in probe_l]
            return (_unlist(stacked, o_is_list),
                    _unlist(lv, isinstance(loop_vars, (list, tuple))))

        stacked = []
        for j in range(len(outs[0])):
            rows = nd.stack(*[step[j] for step in outs], axis=0)
            if n < max_iterations:  # pad to the static contract
                pad = nd.zeros((max_iterations - n,) + rows.shape[1:],
                               dtype=rows.dtype)
                rows = nd.concat(rows, pad, dim=0)
            stacked.append(rows)
        return (_unlist(stacked, o_is_list),
                _unlist(lv, isinstance(loop_vars, (list, tuple))))

    # traced: fixed-trip lax loop with padded output buffers
    from .. import random as rnd

    base_key = rnd.next_key()  # see foreach: keep body draws scan-local
    with rnd.key_provider(rnd.KeyProvider(base_key)):
        probe_o, _ = func(*lv)
    probe_l, o_is_list = _as_list(probe_o)
    bufs = tuple(jnp.zeros((max_iterations,) + tuple(p.shape),
                           p._data.dtype) for p in probe_l)

    def body(state):
        i, vals, bufs_ = state
        nds = _wrap(list(vals), ctx)
        with rnd.key_provider(
                rnd.KeyProvider(jax.random.fold_in(base_key, i))):
            o, new_lv = func(*nds)
        o_l, _ = _as_list(o)
        new_l, _ = _as_list(new_lv)
        bufs_ = tuple(b.at[i].set(v) for b, v in
                      zip(bufs_, _values(o_l)))
        return i + 1, tuple(_values(new_l)), bufs_

    def keep_going(state):
        i, vals, _ = state
        ok = cond_fn(*_wrap(list(vals), ctx))
        # same coercion as the eager _pred: NDArray, jnp array, or bool
        okv = ok._data if isinstance(ok, NDArray) else jnp.asarray(ok)
        return jnp.logical_and(i < max_iterations, okv.reshape(()))

    n, final, bufs = lax.while_loop(
        keep_going, body, (jnp.asarray(0), tuple(_values(lv)), bufs))
    return (_unlist(_wrap(list(bufs), ctx), o_is_list),
            _unlist(_wrap(list(final), ctx),
                    isinstance(loop_vars, (list, tuple))))


def cond(pred, then_func: Callable, else_func: Callable):
    """`then_func() if pred else else_func()` with both branches traced
    on TPU (ref: mx.nd.contrib.cond)."""
    pv = pred._data if isinstance(pred, NDArray) else jnp.asarray(pred)
    if _recording() or not isinstance(pv, jax.core.Tracer):
        take_then = bool(jnp.asarray(pv).reshape(()))
        return then_func() if take_then else else_func()
    ctx = pred.ctx if isinstance(pred, NDArray) else None
    is_list = [False]
    from .. import random as rnd

    base_key = rnd.next_key()  # see foreach: keep branch draws local

    def _branch(fn, salt):
        def run(_):
            prov = rnd.KeyProvider(jax.random.fold_in(base_key, salt))
            with rnd.key_provider(prov):
                o, o_is = _as_list(fn())
            is_list[0] = o_is
            return tuple(_values(o))
        return run

    # each branch traces exactly ONCE, inside lax.cond; structure
    # mismatches (and user errors) surface with lax.cond's own message
    out = lax.cond(jnp.asarray(pv).reshape(()).astype(bool),
                   _branch(then_func, 0), _branch(else_func, 1), None)
    return _unlist(_wrap(list(out), ctx), is_list[0])
