"""Quantized + compute-overlapped gradient collectives (ISSUE 18).

The SPMD bucket collectives optionally move int8/fp8 codes (1 byte per
element + one f32 scale per 512-element block) instead of f32
payloads, with
error-feedback residuals carried as optimizer state, and the bucket
reduces can dispatch in gradient-ready order overlapping compute.
Pinned here:

  * encode/decode round-trip error is bounded by half a quantization
    step, and the wire-byte arithmetic matches the documented layout;
  * convergence parity — int8 + error feedback tracks the fp32
    trajectory within 1e-3 AND strictly beats the same run without
    feedback (the residuals are what make 1-byte wire traffic safe);
  * `MXNET_COMM_OVERLAP=1` is bit-identical to the monolithic step;
  * residuals are durable state: get/set_states round-trip them, a
    4-replica run resumes onto a 2-replica mesh, and the per-replica
    fallback hand-off carries them through verbatim;
  * the `mx_collective_wire_bytes_total` counter records <= 0.30x the
    logical bytes on quantized legs (the nightly gate's source);
  * chaos site `comm.quant` (a flipped dequant scale) lights up the
    mxhealth nonfinite detector instead of silently corrupting;
  * the kvstore SPMD bucket all-reduce quantizes under the same knob;
  * `MXNET_COMM_QUANT=none` (the default) and the min-size gate keep
    the step bit-identical to the unquantized path.

The conftest pins an 8-virtual-device CPU backend.  ZeRO and quant
minimum sizes drop to 1: the suite's parameters are tiny and would
otherwise (correctly) stay replicated / unquantized.
"""
import pickle

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon.parameter import Parameter
from mxnet_tpu.gluon.trainer import Trainer
from mxnet_tpu.ndarray.ndarray import array as nd_array
from mxnet_tpu.optimizer import comm as _comm
from mxnet_tpu.resilience import chaos
from mxnet_tpu.telemetry import instruments as _ins
from mxnet_tpu.telemetry import mxhealth, tracing

SHAPES = [(16, 8), (33,), (4, 3, 2)]


@pytest.fixture(autouse=True)
def _small_mins(monkeypatch):
    monkeypatch.setenv("MXNET_ZERO_MIN_SIZE", "1")
    monkeypatch.setenv("MXNET_COMM_QUANT_MIN_SIZE", "1")


def _make_params(ctx, seed=0, shapes=SHAPES):
    rng = np.random.RandomState(seed)
    params = []
    for i, shp in enumerate(shapes):
        p = Parameter(f"w{i}", shape=shp, dtype="float32")
        p.initialize(ctx=ctx)
        p.set_data(nd_array(rng.randn(*shp).astype("float32")))
        params.append(p)
    return params


def _set_grads(params, step):
    rng = np.random.RandomState(1000 + step)
    for p in params:
        g = rng.randn(*p.shape).astype("float32")
        for r, gnd in enumerate(p.list_grad()):
            gnd._data = nd_array(g * (r + 1), ctx=gnd.ctx).data


def _run(monkeypatch, mode, overlap=False, ef=True, steps=6, nctx=2,
         optimizer="adam"):
    monkeypatch.setenv("MXNET_COMM_QUANT", mode)
    monkeypatch.setenv("MXNET_COMM_OVERLAP", "1" if overlap else "0")
    monkeypatch.setenv("MXNET_COMM_QUANT_EF", "1" if ef else "0")
    ctx = [mx.cpu(i) for i in range(nctx)]
    ps = _make_params(ctx)
    t = Trainer(ps, optimizer, {}, kvstore="device", spmd=True)
    for s in range(steps):
        _set_grads(ps, s)
        t.step(nctx)
    assert t._spmd_active
    out = [p.list_data()[0].asnumpy().copy() for p in ps]
    return t, ps, out


def _relerr(a, b):
    return max(np.max(np.abs(x - y)) / (np.max(np.abs(x)) + 1e-12)
               for x, y in zip(a, b))


# ---------------------------------------------------------------- unit


def test_encode_decode_error_bounded():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 257).astype("float32") * 3.0
    for mode in ("int8", "fp8"):
        codes, scale = _comm.encode(x, mode)
        assert codes.dtype.itemsize == 1
        assert scale.shape == (4, 1)
        err = np.abs(np.asarray(_comm.decode(codes, scale)) - x)
        # int8: half a step; fp8 e4m3: 2^-3 relative per element
        bound = np.asarray(scale) * (0.5 if mode == "int8" else 1.0) \
            + np.abs(x) * (0.0 if mode == "int8" else 0.0625)
        assert np.all(err <= bound + 1e-7)


def test_wire_nbytes_layout():
    # 1 byte/element + one f32 scale per BLOCK elements (at least one
    # per row), per leg
    nb = -(-4096 // _comm.BLOCK)
    assert _comm.wire_nbytes(4096, 4, "int8") == 4096 + 4 * nb
    assert _comm.wire_nbytes(4096, 4, "fp8") == 4096 + 4 * nb
    # tiny leg: the per-row floor dominates
    assert _comm.wire_nbytes(64, 8, "int8") == 64 + 32


def test_config_rejects_unknown_encoding(monkeypatch):
    monkeypatch.setenv("MXNET_COMM_QUANT", "int4")
    with pytest.raises(MXNetError):
        _comm.config()


def test_config_defaults_inactive(monkeypatch):
    monkeypatch.delenv("MXNET_COMM_QUANT", raising=False)
    q = _comm.config()
    assert not q.active
    assert not q.applies(1 << 30)


# -------------------------------------------------- convergence parity


def test_int8_parity_and_error_feedback_strictly_helps(monkeypatch):
    _, _, w_f = _run(monkeypatch, "none")
    _, _, w_q = _run(monkeypatch, "int8")
    _, _, w_n = _run(monkeypatch, "int8", ef=False)
    e_ef, e_ne = _relerr(w_f, w_q), _relerr(w_f, w_n)
    assert e_ef <= 1e-3  # ISSUE 18 acceptance tolerance
    assert e_ef < e_ne  # feedback strictly beats drop-the-remainder


def test_fp8_parity(monkeypatch):
    _, _, w_f = _run(monkeypatch, "none")
    _, _, w_q = _run(monkeypatch, "fp8")
    assert _relerr(w_f, w_q) <= 1e-3


def test_replicas_stay_in_sync_under_quant(monkeypatch):
    _, ps, _ = _run(monkeypatch, "int8")
    for p in ps:
        reps = [d.asnumpy() for d in p.list_data()]
        np.testing.assert_array_equal(reps[0], reps[1])


# ------------------------------------------------------------- overlap


def test_overlap_bit_identical(monkeypatch):
    for mode in ("none", "int8"):
        _, _, w_mono = _run(monkeypatch, mode)
        _, _, w_ovl = _run(monkeypatch, mode, overlap=True)
        for a, b in zip(w_mono, w_ovl):
            np.testing.assert_array_equal(a, b)


def test_overlap_collapses_reduce_scatter_span(monkeypatch):
    monkeypatch.setenv("MXNET_COMM_QUANT", "int8")
    monkeypatch.setenv("MXNET_COMM_OVERLAP", "1")
    ctx = [mx.cpu(0), mx.cpu(1)]
    ps = _make_params(ctx)
    t = Trainer(ps, "sgd", {"momentum": 0.9}, kvstore="device",
                spmd=True)
    _set_grads(ps, 0)
    t.step(2)  # untraced warmup engages the mesh
    tracing.enable()
    try:
        rs0 = _ins.training_phase_seconds("reduce-scatter").count
        su0 = _ins.training_phase_seconds("shard-update").count
        _set_grads(ps, 1)
        t.step(2)
        # the overlap dispatch still reports both spans: the reduce
        # span wraps the non-blocking issue loop, the tail blocks
        assert _ins.training_phase_seconds("reduce-scatter").count \
            == rs0 + 1
        assert _ins.training_phase_seconds("shard-update").count \
            == su0 + 1
    finally:
        tracing.disable()


# ---------------------------------------------------------- wire bytes


def test_wire_bytes_counter_under_030x(monkeypatch):
    monkeypatch.setenv("MXNET_COMM_QUANT", "int8")
    ctx = [mx.cpu(0), mx.cpu(1)]
    ps = _make_params(ctx)
    t = Trainer(ps, "sgd", {"momentum": 0.9}, kvstore="device",
                spmd=True)
    _set_grads(ps, 0)
    t.step(2)
    tracing.enable()
    try:
        l0 = _ins.collective_bytes_total("reduce-scatter", "dp").value
        w0 = _ins.collective_wire_bytes_total(
            "reduce-scatter", "dp", "int8").value
        _set_grads(ps, 1)
        t.step(2)
        logical = _ins.collective_bytes_total(
            "reduce-scatter", "dp").value - l0
        wire = _ins.collective_wire_bytes_total(
            "reduce-scatter", "dp", "int8").value - w0
        assert logical > 0 and wire > 0
        assert wire <= 0.30 * logical  # the nightly gate's threshold
    finally:
        tracing.disable()


# ---------------------------------------------- residuals as state


def test_residuals_roundtrip_get_set_states(monkeypatch):
    t, _, _ = _run(monkeypatch, "int8")
    u = t._spmd_updater
    st = u.get_states()
    d = pickle.loads(st)
    res = d[_comm.RESIDUAL_KEY]
    assert res["encoding"] == "int8"
    assert any(np.abs(v).max() > 0 for v in res["grads"].values())
    assert set(res["grads"]) == set(res["weights"])
    u.set_states(st)
    r2 = pickle.loads(u.get_states())[_comm.RESIDUAL_KEY]
    for k in res["grads"]:
        np.testing.assert_array_equal(res["grads"][k], r2["grads"][k])
        np.testing.assert_array_equal(res["weights"][k],
                                      r2["weights"][k])


def test_residuals_resume_onto_smaller_mesh(monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_COMM_QUANT", "int8")
    ctx4 = [mx.cpu(i) for i in range(4)]
    ps = _make_params(ctx=ctx4)
    ts = Trainer(ps, "sgd", {"momentum": 0.9, "learning_rate": 0.1},
                 kvstore="device", spmd=True)
    for step in range(2):
        _set_grads(ps, step)
        ts.step(4)
    fname = str(tmp_path / "quant.states")
    ts.save_states(fname)
    saved = pickle.loads(ts._spmd_updater.get_states())
    assert _comm.RESIDUAL_KEY in saved

    ctx2 = [mx.cpu(0), mx.cpu(1)]
    p2 = _make_params(ctx=ctx2)
    for pa, pb in zip(p2, ps):
        pa.set_data(pb.list_data()[0])
    t2 = Trainer(p2, "sgd", {"momentum": 0.9, "learning_rate": 0.1},
                 kvstore="device", spmd=True)
    t2.load_states(fname)
    _set_grads(p2, 9)
    t2.step(2)  # residuals re-sharded onto the 2-replica mesh
    assert t2._spmd_active
    resumed = pickle.loads(t2._spmd_updater.get_states())
    res = resumed[_comm.RESIDUAL_KEY]
    assert res["encoding"] == "int8"
    for p in p2:  # replicas still exactly in sync after the resume
        reps = [d.asnumpy() for d in p.list_data()]
        np.testing.assert_array_equal(reps[0], reps[1])


def test_residuals_survive_per_replica_fallback_handoff(monkeypatch,
                                                        tmp_path):
    t, _, _ = _run(monkeypatch, "int8", steps=2)
    fname = str(tmp_path / "quant.states")
    t.save_states(fname)

    # the per-replica fused path never quantizes, but the base Updater
    # carries unknown string keys verbatim — the residual payload must
    # survive a save from the fallback untouched
    ctx = [mx.cpu(0), mx.cpu(1)]
    pf = _make_params(ctx=ctx)
    tf = Trainer(pf, "adam", {}, kvstore="device", fuse_step=True)
    tf.load_states(fname)
    _set_grads(pf, 9)
    tf.step(2)
    out = pickle.loads(tf._updaters[0].get_states())
    res = out[_comm.RESIDUAL_KEY]
    assert res["encoding"] == "int8"
    assert any(np.abs(v).max() > 0 for v in res["grads"].values())


def test_quant_off_payload_has_no_residual_key(monkeypatch):
    t, _, _ = _run(monkeypatch, "none", steps=2)
    assert _comm.RESIDUAL_KEY not in pickle.loads(
        t._spmd_updater.get_states())


# --------------------------------------------------------------- chaos


def test_chaos_comm_quant_lights_up_mxhealth(monkeypatch):
    """A corrupted dequant scale (site comm.quant flips it to inf)
    must surface as a nonfinite event on the mesh program — never a
    silent weight corruption."""
    monkeypatch.setenv("MXNET_COMM_QUANT", "int8")
    ctx = [mx.cpu(0), mx.cpu(1)]
    ps = _make_params(ctx)
    t = Trainer(ps, "sgd", {"momentum": 0.9}, kvstore="device",
                spmd=True)
    mon = mxhealth.enable(policy="record", every=1, fresh=True)
    try:
        with chaos.inject("comm.quant", at=2, action="corrupt"):
            for step in range(3):
                _set_grads(ps, step)
                t.step(2)
        mxhealth.flush()
        evs = mon.events("nonfinite")
        assert evs and evs[0]["step"] == 2
        assert evs[0]["site"] == "optimizer.spmd_step"
        assert chaos.stats()["comm.quant"]["injected"] == 1
    finally:
        mxhealth.disable()
        chaos.reset_stats()


# ------------------------------------------------------------- kvstore


def test_kvstore_spmd_bucket_quantizes(monkeypatch):
    from mxnet_tpu import kvstore as kvs

    monkeypatch.setenv("MXNET_SPMD", "1")
    monkeypatch.setenv("MXNET_COMM_QUANT", "int8")
    rng = np.random.RandomState(3)
    keys, shapes = [0, 1], [(16, 8), (33,)]
    kv = kvs.create("device")
    vals, raw = [], []
    for k, s in zip(keys, shapes):
        arrs = [rng.randn(*s).astype("f4") for _ in range(2)]
        raw.append(arrs)
        reps = [nd_array(v, ctx=mx.cpu(r)) for r, v in enumerate(arrs)]
        kv.init(k, reps[0])
        vals.append(reps)
    tracing.enable()
    try:
        w0 = _ins.collective_wire_bytes_total(
            "all-reduce", "dp", "int8").value
        kv.pushpull_fused(keys, vals, out=vals)
        assert _ins.collective_wire_bytes_total(
            "all-reduce", "dp", "int8").value > w0
    finally:
        tracing.disable()
    for (a, b), reps in zip(raw, vals):
        # per-replica error <= scale/2; two replicas' worth, no EF yet
        atol = (np.abs(a).max() + np.abs(b).max()) / 127.0
        for r in reps:
            np.testing.assert_allclose(r.asnumpy(), a + b, atol=atol)


def test_kvstore_quant_error_feedback_across_calls(monkeypatch):
    """Repeated reduces of the SAME payload: with feedback the
    residual from call n re-enters call n+1, so the time-averaged
    reduced value converges toward the exact sum; without it the same
    rounding bias repeats every call and the average never improves."""
    from mxnet_tpu import kvstore as kvs

    monkeypatch.setenv("MXNET_SPMD", "1")
    monkeypatch.setenv("MXNET_COMM_QUANT", "int8")
    rng = np.random.RandomState(7)
    a, b = (rng.randn(16, 8).astype("f4") for _ in range(2))
    exact = (a + b).astype("f8")

    def cum_err(ef, n=8):
        monkeypatch.setenv("MXNET_COMM_QUANT_EF", "1" if ef else "0")
        kv = kvs.create("device")
        kv.init(0, nd_array(a, ctx=mx.cpu(0)))
        cum = np.zeros_like(exact)
        for _ in range(n):
            reps = [nd_array(v, ctx=mx.cpu(r))
                    for r, v in enumerate((a, b))]
            kv.pushpull_fused([0], [reps], out=[reps])
            cum += reps[0].asnumpy()
        return float(np.abs(cum / n - exact).mean())

    assert cum_err(True) < cum_err(False)


# ---------------------------------------------------------- off / gate


def test_min_size_gate_keeps_small_buckets_fp32(monkeypatch):
    monkeypatch.setenv("MXNET_COMM_QUANT_MIN_SIZE", str(1 << 20))
    _, _, w_f = _run(monkeypatch, "none")
    _, _, w_q = _run(monkeypatch, "int8")  # gated out: nothing encodes
    for a, b in zip(w_f, w_q):
        np.testing.assert_array_equal(a, b)


def test_quant_none_is_bit_identical_to_seed_path(monkeypatch):
    """The default MXNET_COMM_QUANT=none must not perturb the step:
    same program shape, same bits, no residual state allocated."""
    monkeypatch.delenv("MXNET_COMM_QUANT", raising=False)
    ctx = [mx.cpu(0), mx.cpu(1)]
    ps = _make_params(ctx)
    t = Trainer(ps, "adam", {}, kvstore="device", spmd=True)
    for s in range(3):
        _set_grads(ps, s)
        t.step(2)
    u = t._spmd_updater
    assert not u._quant.active
    assert not u._qstate
