"""Tensor ops: elementwise, broadcast, reduce, shape, indexing, linalg.

TPU-native counterpart of the reference's src/operator/tensor/** —
elemwise_unary_op, elemwise_binary_op(+_scalar), broadcast_reduce_op,
matrix_op (reshape/transpose/slice/concat/...), indexing_op (take/one_hot/
gather_nd/...), ordering_op (sort/topk), dot, la_op.  Every op lowers to
XLA HLO via jax.numpy/lax instead of mshadow/CUDA kernels; gradients come
from jax.vjp (no hand-written FGradient needed).

Op names match the reference's registry names so generated frontends and
user code line up.
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import register_op

# ---------------------------------------------------------------------------
# elementwise unary (ref: elemwise_unary_op_basic.cc / _trig.cc / _logexp.cc)
# ---------------------------------------------------------------------------

_UNARY = {
    "abs": jnp.abs, "sign": jnp.sign, "round": jnp.round, "rint": jnp.rint,
    "ceil": jnp.ceil, "floor": jnp.floor, "trunc": jnp.trunc, "fix": jnp.trunc,
    "square": jnp.square, "sqrt": jnp.sqrt,
    "rsqrt": lambda x: lax.rsqrt(x), "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp, "log": jnp.log, "log10": jnp.log10, "log2": jnp.log2,
    "log1p": jnp.log1p, "expm1": jnp.expm1,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "arcsin": jnp.arcsin, "arccos": jnp.arccos, "arctan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh, "arccosh": jnp.arccosh, "arctanh": jnp.arctanh,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "relu": lambda x: jnp.maximum(x, 0),
    "sigmoid": jax.nn.sigmoid,
    "softsign": lambda x: x / (1 + jnp.abs(x)),
    "reciprocal": lambda x: 1.0 / x,
    "negative": jnp.negative,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "logical_not": lambda x: (x == 0).astype(x.dtype) if jnp.issubdtype(x.dtype, jnp.number) else jnp.logical_not(x),
    "identity": lambda x: x,
}

for _name, _fn in _UNARY.items():
    register_op(_name)(partial(lambda x, _f=None: _f(x), _f=_fn))

register_op("copy", aliases=("_copy",))(lambda x: jnp.copy(x))
register_op("zeros_like")(lambda x: jnp.zeros_like(x))
register_op("ones_like")(lambda x: jnp.ones_like(x))
# int32 not int64: TPU-native narrowing (no x64 mode); reference returns i64
register_op("shape_array", differentiable=False)(
    lambda x: jnp.asarray(x.shape, jnp.int32))
# arange from static shape info — creation op usable inside traces
# (ref: contrib arange_like: axis=None → same-shape flat arange)
@register_op("_arange_like", aliases=("arange_like",), differentiable=False)
def _arange_like(x, axis=None, start=0.0, step=1.0, dtype="float32"):
    """Value-independent arange shaped like `x` (axis=None) or along one of
    its axes (ref: contrib arange_like)."""
    dt = jnp.dtype(dtype)
    if axis is None:
        n = math.prod(x.shape) if x.shape else 1
        return (start + step * jnp.arange(n, dtype=dt)).reshape(x.shape)
    return start + step * jnp.arange(x.shape[axis], dtype=dt)
register_op("size_array", differentiable=False)(
    lambda x: jnp.asarray(math.prod(x.shape) if x.shape else 1, jnp.int32))


@register_op("cast", aliases=("Cast",))
def _cast(x, dtype="float32"):
    """Elementwise dtype cast (ref: Cast)."""
    return x.astype(jnp.dtype(dtype))


@register_op("clip")
def _clip(x, a_min=None, a_max=None):
    """Clamp every element to [a_min, a_max]."""
    return jnp.clip(x, a_min, a_max)


# ---------------------------------------------------------------------------
# broadcast binary (ref: elemwise_binary_broadcast_op_*.cc)
# ---------------------------------------------------------------------------

_BINARY = {
    "broadcast_add": jnp.add, "broadcast_sub": jnp.subtract,
    "broadcast_mul": jnp.multiply, "broadcast_div": jnp.divide,
    "broadcast_mod": jnp.mod, "broadcast_power": jnp.power,
    "broadcast_maximum": jnp.maximum, "broadcast_minimum": jnp.minimum,
    "broadcast_hypot": jnp.hypot,
    "arctan2": jnp.arctan2,
}
for _name, _fn in _BINARY.items():
    register_op(_name)(partial(lambda a, b, _f=None: _f(a, b), _f=_fn))

# aliases used by the reference's elemwise (non-broadcast) registry names
for _al, _tgt in [("elemwise_add", jnp.add), ("elemwise_sub", jnp.subtract),
                  ("elemwise_mul", jnp.multiply), ("elemwise_div", jnp.divide)]:
    register_op(_al)(partial(lambda a, b, _f=None: _f(a, b), _f=_tgt))

_CMP = {
    "broadcast_equal": jnp.equal, "broadcast_not_equal": jnp.not_equal,
    "broadcast_greater": jnp.greater, "broadcast_greater_equal": jnp.greater_equal,
    "broadcast_lesser": jnp.less, "broadcast_lesser_equal": jnp.less_equal,
    "broadcast_logical_and": jnp.logical_and,
    "broadcast_logical_or": jnp.logical_or,
    "broadcast_logical_xor": jnp.logical_xor,
}
for _name, _fn in _CMP.items():
    register_op(_name, differentiable=False)(
        partial(lambda a, b, _f=None: _f(a, b).astype(jnp.result_type(a, b)
                if jnp.issubdtype(jnp.result_type(a, b), jnp.number) else jnp.float32),
                _f=_fn))


# scalar rhs/lhs variants (ref: elemwise_binary_scalar_op_*.cc; scalar is an
# attr so the executable cache keys on its value)
def _scalar_op(fn, swap=False):
    if swap:
        return lambda x, scalar=1.0: fn(jnp.asarray(scalar, x.dtype), x)
    return lambda x, scalar=1.0: fn(x, jnp.asarray(scalar, x.dtype))


_SCALAR = {
    "_plus_scalar": (jnp.add, False), "_minus_scalar": (jnp.subtract, False),
    "_rminus_scalar": (jnp.subtract, True), "_mul_scalar": (jnp.multiply, False),
    "_div_scalar": (jnp.divide, False), "_rdiv_scalar": (jnp.divide, True),
    "_mod_scalar": (jnp.mod, False), "_rmod_scalar": (jnp.mod, True),
    "_power_scalar": (jnp.power, False), "_rpower_scalar": (jnp.power, True),
    "_maximum_scalar": (jnp.maximum, False), "_minimum_scalar": (jnp.minimum, False),
    "_hypot_scalar": (jnp.hypot, False),
}
for _name, (_fn, _swap) in _SCALAR.items():
    register_op(_name)(_scalar_op(_fn, _swap))

_SCALAR_CMP = {
    "_equal_scalar": jnp.equal, "_not_equal_scalar": jnp.not_equal,
    "_greater_scalar": jnp.greater, "_greater_equal_scalar": jnp.greater_equal,
    "_lesser_scalar": jnp.less, "_lesser_equal_scalar": jnp.less_equal,
    "_logical_and_scalar": jnp.logical_and, "_logical_or_scalar": jnp.logical_or,
    "_logical_xor_scalar": jnp.logical_xor,
}
for _name, _fn in _SCALAR_CMP.items():
    register_op(_name, differentiable=False)(
        partial(lambda x, scalar=1.0, _f=None: _f(x, scalar).astype(x.dtype
                if jnp.issubdtype(x.dtype, jnp.number) else jnp.float32),
                _f=_fn))


# ---------------------------------------------------------------------------
# reductions (ref: broadcast_reduce_op_value.cc)
# ---------------------------------------------------------------------------

def _red(fn):
    def impl(x, axis=None, keepdims=False, exclude=False):
        if exclude and axis is not None:
            ax = (axis,) if isinstance(axis, int) else tuple(axis)
            axis = tuple(i for i in range(x.ndim) if i not in ax)
        return fn(x, axis=axis, keepdims=keepdims)

    return impl


register_op("sum", aliases=("sum_axis",))(_red(jnp.sum))
register_op("mean")(_red(jnp.mean))
register_op("max", aliases=("max_axis",))(_red(jnp.max))
register_op("min", aliases=("min_axis",))(_red(jnp.min))
register_op("prod")(_red(jnp.prod))
register_op("nansum")(_red(jnp.nansum))
register_op("nanprod")(_red(jnp.nanprod))


@register_op("norm")
def _norm(x, ord=2, axis=None, keepdims=False):
    """L1 or L2 norm reduction over `axis` (ord in {1, 2}, ref: norm)."""
    if ord == 1:
        return jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdims))


@register_op("argmax", differentiable=False)
def _argmax(x, axis=None, keepdims=False):
    """Index of the maximum along `axis`, returned as float32 (reference index
    dtype)."""
    out = jnp.argmax(x, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@register_op("argmin", differentiable=False)
def _argmin(x, axis=None, keepdims=False):
    """Index of the minimum along `axis`, returned as float32."""
    return jnp.argmin(x, axis=axis, keepdims=keepdims).astype(jnp.float32)


@register_op("argmax_channel", differentiable=False)
def _argmax_channel(x):
    """Argmax over the trailing axis per leading position, as float32."""
    return jnp.argmax(x, axis=-1).astype(jnp.float32)


# ---------------------------------------------------------------------------
# shape manipulation (ref: matrix_op.cc)
# ---------------------------------------------------------------------------

@register_op("reshape", aliases=("Reshape",))
def _reshape(x, shape=(), reverse=False):
    # supports the reference's special codes 0 (keep), -1 (infer),
    # -2 (copy rest), -3 (merge two), -4 (split)
    """Reshape with the reference's special codes: 0 keep, -1 infer, -2
    copy-rest, -3 merge-two, -4 split."""
    shape = list(shape)
    if not any(s in (0, -2, -3, -4) for s in shape):
        return jnp.reshape(x, tuple(shape))
    src = list(x.shape)
    out = []
    si = 0
    k = 0
    while k < len(shape):
        s = shape[k]
        if s == 0:
            out.append(src[si]); si += 1
        elif s == -2:
            out.extend(src[si:]); si = len(src)
        elif s == -3:
            out.append(src[si] * src[si + 1]); si += 2
        elif s == -4:
            a, b = shape[k + 1], shape[k + 2]
            if a == -1:
                a = src[si] // b
            if b == -1:
                b = src[si] // a
            out.extend([a, b]); si += 1; k += 2
        else:
            out.append(s)
            if s != -1:
                si += 1
        k += 1
    return jnp.reshape(x, tuple(out))


@register_op("transpose")
def _transpose(x, axes=None):
    """Permute axes (full reversal when `axes` is None)."""
    return jnp.transpose(x, axes)


@register_op("flatten", aliases=("Flatten",))
def _flatten(x):
    """Collapse all trailing axes into one: (N, ...) -> (N, prod(...))."""
    return jnp.reshape(x, (x.shape[0], -1) if x.ndim > 1 else x.shape)


@register_op("expand_dims")
def _expand_dims(x, axis=0):
    """Insert a size-1 axis at `axis`."""
    return jnp.expand_dims(x, axis)


@register_op("squeeze")
def _squeeze(x, axis=None):
    """Drop size-1 axes (all of them when `axis` is None)."""
    return jnp.squeeze(x, axis)


@register_op("broadcast_to")
def _broadcast_to(x, shape=()):
    # reference semantics: 0 in target shape means keep source dim
    """Broadcast to `shape`; a 0 entry keeps the source dimension (reference
    semantics)."""
    tgt = tuple(s if s != 0 else x.shape[i] for i, s in enumerate(shape))
    return jnp.broadcast_to(x, tgt)


@register_op("broadcast_like")
def _broadcast_like(x, y):
    """Broadcast `x` to the shape of `y`."""
    return jnp.broadcast_to(x, y.shape)


@register_op("broadcast_axis", aliases=("broadcast_axes",))
def _broadcast_axis(x, axis=(), size=()):
    """Broadcast the named size-1 axes out to the requested sizes."""
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(x.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return jnp.broadcast_to(x, tuple(tgt))


@register_op("swapaxes", aliases=("SwapAxis",))
def _swapaxes(x, dim1=0, dim2=0):
    """Exchange axes `dim1` and `dim2` (ref: SwapAxis)."""
    return jnp.swapaxes(x, dim1, dim2)


@register_op("slice")
def _slice(x, begin=(), end=(), step=None):
    """Multi-axis strided slice from per-axis begin/end/step tuples."""
    idx = []
    for i in range(len(begin)):
        st = step[i] if step else 1
        idx.append(slice(begin[i], end[i], st))
    return x[tuple(idx)]


@register_op("slice_axis")
def _slice_axis(x, axis=0, begin=0, end=None):
    """Slice [begin, end) along a single axis."""
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(begin, end)
    return x[tuple(idx)]


@register_op("slice_like")
def _slice_like(x, y, axes=()):
    """Crop `x` to `y`'s extent along `axes` (every shared axis by default)."""
    axes = tuple(axes) if axes else tuple(range(min(x.ndim, y.ndim)))
    idx = [slice(None)] * x.ndim
    for a in axes:
        idx[a] = slice(0, y.shape[a])
    return x[tuple(idx)]


@register_op("concat", aliases=("Concat",))
def _concat(*xs, dim=1, num_args=None):
    """Concatenate along `dim` (ref: Concat, channel axis by default)."""
    return jnp.concatenate(xs, axis=dim)


@register_op("stack")
def _stack(*xs, axis=0, num_args=None):
    """Stack the inputs along a NEW axis."""
    return jnp.stack(xs, axis=axis)


def _split_nout(attrs):
    return attrs.get("num_outputs", 1)


@register_op("split", aliases=("SliceChannel",), num_outputs=_split_nout)
def _split(x, num_outputs=1, axis=1, squeeze_axis=False):
    """Split into `num_outputs` equal parts along `axis`, optionally squeezing
    it (ref: SliceChannel)."""
    parts = jnp.split(x, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register_op("tile")
def _tile(x, reps=()):
    """Repeat the whole array `reps` times per axis (numpy tile semantics)."""
    return jnp.tile(x, reps)


@register_op("repeat")
def _repeat(x, repeats=1, axis=None):
    """Repeat each element `repeats` times along `axis` (flattens first when
    `axis` is None)."""
    return jnp.repeat(x, repeats, axis=axis)


@register_op("pad", aliases=("Pad",))
def _pad(x, mode="constant", pad_width=(), constant_value=0.0):
    # reference pad_width is a flat tuple of (before, after) per axis
    """Pad in constant/edge/reflect mode; `pad_width` is the reference's flat
    (before, after)-per-axis tuple."""
    pw = list(pad_width)
    pairs = [(pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(x, pairs, mode="constant", constant_values=constant_value)
    return jnp.pad(x, pairs, mode=jmode)


@register_op("reverse", aliases=("flip",))
def _reverse(x, axis=()):
    """Reverse element order along the given axes."""
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(x, axis=axes)


@register_op("depth_to_space")
def _depth_to_space(x, block_size=1):
    """Rearrange NCHW channel blocks into spatial blocks: (C/b^2, H*b, W*b)."""
    b, c, h, w = x.shape
    bs = block_size
    y = x.reshape(b, bs, bs, c // (bs * bs), h, w)
    y = y.transpose(0, 3, 4, 1, 5, 2)
    return y.reshape(b, c // (bs * bs), h * bs, w * bs)


@register_op("space_to_depth")
def _space_to_depth(x, block_size=1):
    """Inverse of depth_to_space: fold b x b spatial blocks into channels."""
    b, c, h, w = x.shape
    bs = block_size
    y = x.reshape(b, c, h // bs, bs, w // bs, bs)
    y = y.transpose(0, 3, 5, 1, 2, 4)
    return y.reshape(b, c * bs * bs, h // bs, w // bs)


# ---------------------------------------------------------------------------
# indexing (ref: indexing_op.cc)
# ---------------------------------------------------------------------------

@register_op("take")
def _take(x, indices, axis=0, mode="clip"):
    """Gather slices along `axis` by integer index, out-of-range entries
    resolved per `mode`."""
    idx = indices.astype(jnp.int32)
    jmode = {"clip": "clip", "wrap": "wrap", "raise": "clip"}[mode]
    return jnp.take(x, idx, axis=axis, mode=jmode)


@register_op("pick")
def _pick(x, index, axis=-1, keepdims=False, mode="clip"):
    """Select one element along `axis` per position of `index` (ref: pick)."""
    idx = jnp.clip(index.astype(jnp.int32), 0, x.shape[axis] - 1)
    out = jnp.take_along_axis(x, jnp.expand_dims(idx, axis=axis), axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register_op("one_hot", differentiable=False)
def _one_hot(indices, depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
    """Expand integer indices into depth-length one-hot vectors scaled to
    on/off values."""
    return jax.nn.one_hot(indices.astype(jnp.int32), depth,
                          dtype=jnp.dtype(dtype)) * (on_value - off_value) + off_value


@register_op("gather_nd")
def _gather_nd(data, indices):
    """Gather elements addressed by leading-axis multi-indices (ref:
    gather_nd)."""
    idx = tuple(indices.astype(jnp.int32))
    return data[idx]


@register_op("scatter_nd")
def _scatter_nd(data, indices, shape=()):
    """Scatter `data` into zeros of `shape` at multi-indices (colliding writes
    pick one value)."""
    out = jnp.zeros(shape, data.dtype)
    idx = tuple(indices.astype(jnp.int32))
    return out.at[idx].set(data)


@register_op("where")
def _where(cond, x, y):
    """Elementwise select: `x` where `cond` is nonzero, else `y`."""
    return jnp.where(cond.astype(bool) if jnp.issubdtype(cond.dtype, jnp.number)
                     else cond, x, y)


@register_op("sequence_mask", aliases=("SequenceMask",))
def _sequence_mask(data, sequence_length=None, use_sequence_length=False,
                   value=0.0, axis=0):
    """Overwrite positions past each sequence's length with `value` along the
    time axis (ref: SequenceMask)."""
    if not use_sequence_length or sequence_length is None:
        return data
    # data: (seq, batch, ...) if axis==0 else (batch, seq, ...)
    seq_len = data.shape[axis]
    pos = jnp.arange(seq_len)
    mask = pos[:, None] < sequence_length[None, :].astype(jnp.int32)  # (seq, batch)
    if axis == 1:
        mask = mask.T
    extra = data.ndim - 2
    mask = mask.reshape(mask.shape + (1,) * extra)
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


@register_op("sequence_last", aliases=("SequenceLast",))
def _sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    """Select each sequence's last valid element along the time axis (ref:
    SequenceLast)."""
    if not use_sequence_length or sequence_length is None:
        idx = [slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    last = (sequence_length.astype(jnp.int32) - 1)
    moved = jnp.moveaxis(data, axis, 0)  # (seq, batch, ...)
    return jnp.take_along_axis(
        moved, last.reshape((1, -1) + (1,) * (moved.ndim - 2)), axis=0)[0]


@register_op("sequence_reverse", aliases=("SequenceReverse",))
def _sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    """Reverse each sequence's valid prefix along the time axis, leaving
    padding in place."""
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    moved = jnp.moveaxis(data, axis, 0)
    seq = moved.shape[0]
    lens = sequence_length.astype(jnp.int32)
    pos = jnp.arange(seq)[:, None]
    src = jnp.where(pos < lens[None, :], lens[None, :] - 1 - pos, pos)
    out = jnp.take_along_axis(moved, src.reshape(src.shape + (1,) * (moved.ndim - 2)), axis=0)
    return jnp.moveaxis(out, 0, axis)


# ---------------------------------------------------------------------------
# ordering (ref: ordering_op.cc)
# ---------------------------------------------------------------------------

@register_op("sort", differentiable=False)
def _sort(x, axis=-1, is_ascend=True):
    """Sort values along `axis`; descending when is_ascend=False."""
    out = jnp.sort(x, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@register_op("argsort", differentiable=False)
def _argsort(x, axis=-1, is_ascend=True, dtype="float32"):
    """Sorting permutation along `axis`, cast to `dtype` (the reference
    returns float indices)."""
    out = jnp.argsort(x, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(jnp.dtype(dtype))


def _topk_nout(attrs):
    rt = attrs.get("ret_typ", "indices")
    return 2 if rt == "both" else 1


@register_op("topk", differentiable=False, num_outputs=_topk_nout)
def _topk(x, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    """Top-k along `axis`; `ret_typ` selects indices, values, or both."""
    vals = -x if is_ascend else x
    if axis != -1 and axis != x.ndim - 1:
        moved = jnp.moveaxis(vals, axis, -1)
    else:
        moved = vals
    v, i = lax.top_k(moved, k)
    if is_ascend:
        v = -v
    if axis != -1 and axis != x.ndim - 1:
        v = jnp.moveaxis(v, -1, axis)
        i = jnp.moveaxis(i, -1, axis)
    i = i.astype(jnp.dtype(dtype))
    if ret_typ == "value":
        return v
    if ret_typ == "both":
        return v, i
    return i


# ---------------------------------------------------------------------------
# linalg (ref: dot.cc, la_op.cc)
# ---------------------------------------------------------------------------

@register_op("dot")
def _dot(a, b, transpose_a=False, transpose_b=False):
    """Reference dot: contract a's LAST axis with b's FIRST (1-D operands
    reduce to a scalar)."""
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    # reference dot: contract last axis of a with first axis of b
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register_op("batch_dot")
def _batch_dot(a, b, transpose_a=False, transpose_b=False):
    """Batched matrix product over leading axes, with optional operand
    transposes."""
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@register_op("matmul")
def _matmul(a, b):
    """numpy-semantics matrix product."""
    return jnp.matmul(a, b)


@register_op("khatri_rao")
def _khatri_rao(*xs):
    """Khatri-Rao product: per-column Kronecker crossing of the inputs'
    leading axes."""
    out = xs[0]
    for x in xs[1:]:
        out = jnp.einsum("i...,j...->ij...", out, x).reshape((-1,) + out.shape[1:])
    return out


@register_op("L2Normalization")
def _l2norm(x, eps=1e-10, mode="instance"):
    """L2-normalize each instance/channel/spatial slice (ref:
    L2Normalization)."""
    if mode == "instance":
        axes = tuple(range(1, x.ndim))
    elif mode == "channel":
        axes = (1,)
    else:  # spatial
        axes = tuple(range(2, x.ndim))
    n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + eps)
    return x / n


@register_op("smooth_l1")
def _smooth_l1(x, scalar=1.0):
    """Smooth (Huber-style) L1: quadratic below 1/scalar^2, linear beyond."""
    s2 = scalar * scalar
    return jnp.where(jnp.abs(x) < 1.0 / s2, 0.5 * s2 * jnp.square(x),
                     jnp.abs(x) - 0.5 / s2)


@register_op("diag")
def _diag(x, k=0):
    """k-th diagonal of a (batched) matrix, or the diagonal matrix of a
    vector."""
    if x.ndim == 1:
        return jnp.diag(x, k)
    return jnp.diagonal(x, offset=k, axis1=-2, axis2=-1)


@register_op("linalg_gemm2")
def _linalg_gemm2(a, b, transpose_a=False, transpose_b=False, alpha=1.0):
    """alpha * a @ b with optional transposes (ref: linalg_gemm2)."""
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b)


@register_op("linalg_potrf")
def _linalg_potrf(a):
    """Lower Cholesky factor of a symmetric positive-definite matrix."""
    return jnp.linalg.cholesky(a)


@register_op("linalg_syrk")
def _linalg_syrk(a, transpose=False, alpha=1.0):
    """Symmetric rank-k product: alpha * a @ a^T (a^T @ a when transpose)."""
    at = jnp.swapaxes(a, -1, -2)
    return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))


# cumulative
register_op("cumsum")(lambda x, axis=None, dtype=None: jnp.cumsum(
    x, axis=axis, dtype=jnp.dtype(dtype) if dtype else None))
register_op("cumprod")(lambda x, axis=None: jnp.cumprod(x, axis=axis))


@register_op("isnan", differentiable=False)
def _isnan(x):
    """Elementwise NaN test as float32 {0, 1}."""
    return jnp.isnan(x).astype(jnp.float32)


@register_op("isinf", differentiable=False)
def _isinf(x):
    """Elementwise infinity test as float32 {0, 1}."""
    return jnp.isinf(x).astype(jnp.float32)


@register_op("isfinite", differentiable=False)
def _isfinite(x):
    """Elementwise finiteness test as float32 {0, 1}."""
    return jnp.isfinite(x).astype(jnp.float32)


# ---------------------------------------------------------------------------
# misc parity batch (ref: matrix_op.cc, elemwise_unary_op.cc, amp_cast.cc)
# ---------------------------------------------------------------------------

@register_op("trace")
def _trace(data, offset=0, axis1=0, axis2=1):
    """Sum of the (axis1, axis2) diagonal at `offset`."""
    return jnp.trace(data, offset=offset, axis1=axis1, axis2=axis2)


@register_op("_ravel_multi_index", aliases=("ravel_multi_index",),
             differentiable=False)
def _ravel_multi_index(data, shape=()):
    """data (d, n) of d-dim indices -> (n,) flat indices
    (ref: ravel.cc)."""
    strides = np.cumprod([1] + list(shape[::-1]))[::-1][1:]
    s = jnp.asarray(strides.copy(), data.dtype)
    return (data * s[:, None]).sum(axis=0)


@register_op("_unravel_index", aliases=("unravel_index",),
             differentiable=False)
def _unravel_index(data, shape=()):
    """(n,) flat indices -> (d, n) multi-indices (ref: ravel.cc)."""
    out = jnp.stack(jnp.unravel_index(data.astype(jnp.int32),
                                      tuple(shape)))
    return out.astype(data.dtype)


@register_op("digamma")
def _digamma(data):
    """Elementwise digamma (logarithmic derivative of gamma)."""
    return jax.scipy.special.digamma(data)


@register_op("bitwise_and", differentiable=False)
def _bitwise_and(lhs, rhs):
    """Elementwise bitwise AND of integer-coerced operands, returned in lhs
    dtype."""
    return jnp.bitwise_and(lhs.astype(jnp.int64), rhs.astype(jnp.int64)) \
        .astype(lhs.dtype)


@register_op("bitwise_or", differentiable=False)
def _bitwise_or(lhs, rhs):
    """Elementwise bitwise OR of integer-coerced operands, returned in lhs
    dtype."""
    return jnp.bitwise_or(lhs.astype(jnp.int64), rhs.astype(jnp.int64)) \
        .astype(lhs.dtype)


@register_op("bitwise_xor", differentiable=False)
def _bitwise_xor(lhs, rhs):
    """Elementwise bitwise XOR of integer-coerced operands, returned in lhs
    dtype."""
    return jnp.bitwise_xor(lhs.astype(jnp.int64), rhs.astype(jnp.int64)) \
        .astype(lhs.dtype)


@register_op("all_finite", differentiable=False)
def _all_finite(data, init_output=True):
    """-> (1,) float {0,1}: every element finite (ref: all_finite.cc,
    the AMP gradient-overflow probe)."""
    return jnp.isfinite(data).all().reshape((1,)).astype(jnp.float32)


@register_op("multi_all_finite", differentiable=False)
def _multi_all_finite(*arrays, num_arrays=1, init_output=True):
    """-> (1,) float {0, 1}: every element of every input is finite (AMP
    overflow probe)."""
    ok = jnp.asarray(True)
    for a in arrays:
        ok = jnp.logical_and(ok, jnp.isfinite(a).all())
    return ok.reshape((1,)).astype(jnp.float32)


@register_op("amp_cast")
def _amp_cast(data, dtype="float32"):
    """AMP-inserted cast (ref: amp_cast.cc) — identical to Cast but a
    distinct node type so AMP graph passes can find/remove them.
    float16 maps to bfloat16, the TPU-native half type (same documented
    deviation as Cast)."""
    dt = {"float16": jnp.bfloat16}.get(str(dtype), dtype)
    return data.astype(dt)


@register_op("amp_multicast",
             num_outputs=lambda attrs: int(attrs.get("num_outputs", 1)))
def _amp_multicast(*data, num_outputs=1, cast_narrow=False):
    """Cast all inputs to a common dtype: widest by default, narrowest
    with cast_narrow (ref: amp_cast.cc amp_multicast)."""
    order = {jnp.dtype(jnp.bfloat16): 0, jnp.dtype(jnp.float32): 1,
             jnp.dtype(jnp.float64): 2}
    ranked = [order.get(jnp.dtype(d.dtype), 1) for d in data]
    pick = min(range(len(data)), key=lambda i: ranked[i]) if cast_narrow \
        else max(range(len(data)), key=lambda i: ranked[i])
    target = data[pick].dtype
    return tuple(d.astype(target) for d in data)
