#!/usr/bin/env python
"""Perf-regression gate: diff freshly produced bench JSONs against the
committed ones.

The nightly refreshes the tracked bench artifacts (FUSED_BENCH.json,
SCALING.json, SERVING_BENCH.json, COMPILE_CACHE.json) in the work
tree; this tool compares each against the version committed at --ref
(``git show REF:NAME``) and fails on

  * a **throughput regression**: any tracked higher-is-better metric
    (speedups, qps, samples/s) dropping more than ``--tolerance``
    (default 10%) below its committed value, or
  * a **new trace-integrity failure**: any ``trace_check_ok`` /
    ``merged_trace.check_ok`` / ``parity.ok`` / ``gate_ok`` verdict
    that was true in the committed artifact and is false in the fresh
    one (a verdict already false at the baseline is pre-existing, not
    new).

Artifacts missing on either side are reported and skipped — a bench
stage that timed out must fail the nightly through its own return
code, not by making the diff un-runnable.  ``--baseline-dir`` swaps
the git baseline for a directory of files (what the tests use).

    python tools/perf_compare.py                      # HEAD vs work tree
    python tools/perf_compare.py --tolerance 0.15 --out PERF_COMPARE.json
    python tools/perf_compare.py --baseline-dir /tmp/old --fresh-dir .

Exit: 0 clean, 1 regression / new integrity failure, 2 usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Dict, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_ARTIFACTS = ("FUSED_BENCH.json", "SCALING.json",
                     "SERVING_BENCH.json", "COMPILE_CACHE.json")


# ---------------------------------------------------------------------------
# per-artifact extractors: dict -> (higher_is_better metrics, bool checks)
# ---------------------------------------------------------------------------

def _fused(d) -> Tuple[Dict[str, float], Dict[str, bool]]:
    m = {}
    for n, row in d.get("sizes", {}).items():
        if "speedup" in row:
            m[f"sizes.{n}.speedup"] = row["speedup"]
    return m, {}


def _serving(d) -> Tuple[Dict[str, float], Dict[str, bool]]:
    m = {}
    for mode in ("unbatched", "batched"):
        row = d.get(mode) or {}
        if "qps" in row:
            m[f"{mode}.qps"] = row["qps"]
    if "batched_over_unbatched" in d:
        m["batched_over_unbatched"] = d["batched_over_unbatched"]
    return m, {}


def _compile_cache(d) -> Tuple[Dict[str, float], Dict[str, bool]]:
    m = {}
    for site in ("serving", "fused"):
        row = d.get(site) or {}
        if "speedup" in row:
            m[f"{site}.speedup"] = row["speedup"]
    c = {}
    if "gate_ok" in d:
        c["gate_ok"] = bool(d["gate_ok"])
    return m, c


def _scaling(d) -> Tuple[Dict[str, float], Dict[str, bool]]:
    m, c = {}, {}
    for r in d.get("sweep", []):
        key = f"{r.get('path', '?')}.{r.get('processes', '?')}proc"
        if "global_throughput" in r:
            m[f"{key}.global_throughput"] = r["global_throughput"]
        if "trace_check_ok" in r:
            c[f"{key}.trace_check_ok"] = bool(r["trace_check_ok"])
        mt = r.get("merged_trace")
        if isinstance(mt, dict) and "check_ok" in mt:
            c[f"{key}.merged_trace.check_ok"] = bool(mt["check_ok"])
    p = d.get("parity")
    if isinstance(p, dict) and "ok" in p:
        c["parity.ok"] = bool(p["ok"])
    return m, c


EXTRACTORS = {
    "FUSED_BENCH.json": _fused,
    "SERVING_BENCH.json": _serving,
    "COMPILE_CACHE.json": _compile_cache,
    "SCALING.json": _scaling,
}


# ---------------------------------------------------------------------------
# comparison
# ---------------------------------------------------------------------------

def compare_artifact(name: str, base: dict, fresh: dict,
                     tolerance: float) -> dict:
    """One artifact's verdict: metric deltas + integrity transitions.
    Only metrics present on BOTH sides gate (a renamed/new lane has no
    baseline to regress from)."""
    extract = EXTRACTORS[name]
    bm, bc = extract(base)
    fm, fc = extract(fresh)
    regressions, rows = [], []
    for k in sorted(set(bm) & set(fm)):
        b, f = float(bm[k]), float(fm[k])
        ratio = (f / b) if b else None
        row = {"metric": k, "baseline": b, "fresh": f,
               "ratio": None if ratio is None else round(ratio, 4)}
        if b > 0 and f < b * (1.0 - tolerance):
            row["regression"] = True
            regressions.append(
                f"{name}: {k} {b:g} -> {f:g} "
                f"({(1 - f / b) * 100:.1f}% drop > "
                f"{tolerance * 100:.0f}% tolerance)")
        rows.append(row)
    new_failures = []
    for k in sorted(set(bc) & set(fc)):
        if bc[k] and not fc[k]:
            new_failures.append(f"{name}: {k} was true at baseline, "
                                f"false in the fresh run")
    # a check lane that only exists fresh (e.g. first --phases run)
    # still hard-fails when false: integrity is never grandfathered in
    for k in sorted(set(fc) - set(bc)):
        if not fc[k]:
            new_failures.append(f"{name}: {k} false in the fresh run "
                                f"(no baseline)")
    return {"metrics": rows, "regressions": regressions,
            "new_integrity_failures": new_failures,
            "ok": not regressions and not new_failures}


def _load_git(ref: str, name: str, repo: str):
    p = subprocess.run(["git", "-C", repo, "show", f"{ref}:{name}"],
                       capture_output=True, text=True, timeout=60)
    if p.returncode != 0:
        return None, f"not in {ref}"
    try:
        return json.loads(p.stdout), None
    except ValueError as e:
        return None, f"unparsable at {ref}: {e}"


def _load_file(path: str):
    if not os.path.exists(path):
        return None, "missing"
    try:
        with open(path) as f:
            return json.load(f), None
    except (OSError, ValueError) as e:
        return None, str(e)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fail on bench-JSON throughput regressions vs the "
                    "committed artifacts")
    ap.add_argument("--artifacts",
                    default=",".join(DEFAULT_ARTIFACTS),
                    help="comma-separated artifact names to diff")
    ap.add_argument("--ref", default="HEAD",
                    help="git ref the committed baseline is read from")
    ap.add_argument("--baseline-dir", default=None,
                    help="read baselines from this directory instead "
                         "of git (tests)")
    ap.add_argument("--fresh-dir", default=_REPO,
                    help="directory holding the freshly produced "
                         "artifacts (default: repo root)")
    ap.add_argument("--tolerance", type=float, default=0.10,
                    help="max tolerated fractional throughput drop "
                         "(default 0.10 = 10%%)")
    ap.add_argument("--out", default=None,
                    help="write the comparison report JSON here")
    args = ap.parse_args(argv)

    names = [n.strip() for n in args.artifacts.split(",") if n.strip()]
    unknown = [n for n in names if n not in EXTRACTORS]
    if unknown:
        print(f"error: no extractor for {unknown} "
              f"(known: {sorted(EXTRACTORS)})", file=sys.stderr)
        return 2

    report = {"ref": args.ref if args.baseline_dir is None
              else args.baseline_dir,
              "tolerance": args.tolerance, "artifacts": {}, "ok": True}
    failures = []
    for name in names:
        fresh, ferr = _load_file(os.path.join(args.fresh_dir, name))
        if args.baseline_dir is not None:
            base, berr = _load_file(os.path.join(args.baseline_dir,
                                                 name))
        else:
            base, berr = _load_git(args.ref, name, args.fresh_dir)
        if base is None or fresh is None:
            report["artifacts"][name] = {
                "skipped": True,
                "reason": f"baseline: {berr or 'ok'}; "
                          f"fresh: {ferr or 'ok'}"}
            continue
        res = compare_artifact(name, base, fresh, args.tolerance)
        report["artifacts"][name] = res
        failures += res["regressions"] + res["new_integrity_failures"]
    report["ok"] = not failures
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
    for msg in failures:
        print(f"PERF GATE FAIL: {msg}", file=sys.stderr)
    compared = [n for n, r in report["artifacts"].items()
                if not r.get("skipped")]
    skipped = [n for n, r in report["artifacts"].items()
               if r.get("skipped")]
    print(f"perf_compare: {len(compared)} artifact(s) compared"
          + (f", {len(skipped)} skipped ({', '.join(skipped)})"
             if skipped else "")
          + f" — {'OK' if report['ok'] else f'{len(failures)} failure(s)'}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
