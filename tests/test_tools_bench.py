"""Smoke lane for the measurement tooling (bench_all / opperf /
scaling_bench): each harness must produce a parseable JSON row on the
CPU backend.  Real numbers come from the on-chip runs (BENCH_ALL.json,
OPPERF.json, SCALING.json artifacts)."""
import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cmd, timeout=420):
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    p = subprocess.run(cmd, capture_output=True, text=True, cwd=_REPO,
                       timeout=timeout, env=env)
    assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
    lines = [ln for ln in p.stdout.splitlines() if ln.startswith("{")]
    assert lines, p.stdout[-2000:]
    return [json.loads(ln) for ln in lines]


def test_opperf_subset():
    rows = _run([sys.executable, "tools/opperf.py",
                 "--ops", "softmax,FullyConnected",
                 "--repeat", "2", "--number", "3"])
    by_op = {r["op"]: r for r in rows}
    assert set(by_op) == {"softmax", "FullyConnected"}
    for r in rows:
        assert r["eager_us"] > 0 and r["jit_fwd_us"] > 0
        assert r["jit_bwd_us"] > 0


def _load_opperf():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "opperf_under_test", os.path.join(_REPO, "tools", "opperf.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_opperf_gate_flags_baseline_present_now_missing(tmp_path):
    """An op that regresses from working to not-running-at-all (its jit
    column is now None) must be REPORTED by the gate, not silently
    skipped — that's the worst regression class (ADVICE round 5)."""
    opperf = _load_opperf()
    base = {"backend": "cpu", "rows": [
        {"op": "dot", "shape": "s", "jit_fwd_us": 120.0,
         "jit_bwd_us": 150.0},
        {"op": "exp", "shape": "s", "jit_fwd_us": 80.0,
         "jit_bwd_us": 90.0},
    ]}
    bpath = tmp_path / "base.json"
    bpath.write_text(json.dumps(base))
    current = {"backend": "cpu", "rows": [
        # dot's backward no longer runs; forward is fine
        {"op": "dot", "shape": "s", "jit_fwd_us": 125.0,
         "jit_bwd_us": None},
        {"op": "exp", "shape": "s", "jit_fwd_us": 82.0,
         "jit_bwd_us": 91.0},
    ]}
    regressions, compared = opperf.compare(current, str(bpath),
                                           fail_over=1.0)
    assert compared == 4  # the missing column still counts as compared
    assert [(r["op"], r["col"], r["now_us"]) for r in regressions] == \
        [("dot", "jit_bwd_us", None)]
    assert "missing" in regressions[0]["note"]
    # a real slowdown and a missing column are both reported
    current["rows"][1]["jit_fwd_us"] = 400.0
    regressions, _ = opperf.compare(current, str(bpath), fail_over=1.0)
    assert {(r["op"], r["col"]) for r in regressions} == \
        {("dot", "jit_bwd_us"), ("exp", "jit_fwd_us")}


def test_opperf_gate_flags_baseline_row_entirely_missing(tmp_path):
    """An op whose ROW vanished from the current sweep (spec dropped,
    crashed before measuring) is the same working-to-not-running class
    as a missing column — reported, never a silent skip.  A deliberate
    subset run opts out via expect_all_baseline_rows=False."""
    opperf = _load_opperf()
    base = {"backend": "cpu", "rows": [
        {"op": "dot", "shape": "s", "jit_fwd_us": 120.0,
         "jit_bwd_us": 150.0},
        {"op": "exp", "shape": "s", "jit_fwd_us": 80.0},
    ]}
    bpath = tmp_path / "base.json"
    bpath.write_text(json.dumps(base))
    current = {"backend": "cpu", "rows": [
        {"op": "exp", "shape": "s", "jit_fwd_us": 82.0},
    ]}
    regressions, _ = opperf.compare(current, str(bpath), fail_over=1.0)
    assert {(r["op"], r["col"]) for r in regressions} == \
        {("dot", "jit_fwd_us"), ("dot", "jit_bwd_us")}
    assert all(r["now_us"] is None and r["row_missing"]
               for r in regressions)
    regressions, _ = opperf.compare(current, str(bpath), fail_over=1.0,
                                    expect_all_baseline_rows=False)
    assert regressions == []


def test_bench_serving_smoke(tmp_path):
    """CLI smoke only: the load generator runs and emits a well-formed
    report.  The strict batched>unbatched throughput gate lives in
    tests/nightly/test_bench_serving.py (perf lane)."""
    out = tmp_path / "SERVING_BENCH.json"
    rows = _run([sys.executable, "tools/bench_serving.py", "--no-gate",
                 "--duration", "0.4", "--repeats", "1",
                 "--max-batch-size", "4", "--in-units", "16",
                 "--hidden", "32", "--out-units", "8",
                 "--out", str(out)], timeout=420)
    report = rows[-1]
    for mode in ("unbatched", "batched"):
        r = report[mode]
        assert r["qps"] > 0 and r["p50_latency_ms"] > 0
        assert r["p99_latency_ms"] >= r["p50_latency_ms"]
    assert report["batched"]["concurrency"] >= 8
    assert json.loads(out.read_text()) == report


def test_bench_fused_step_smoke(tmp_path):
    """CLI smoke only: the fused-step bench runs and emits a
    well-formed report with the compile count.  The strict
    fused>=1.2x-eager gate lives in tests/nightly/
    test_bench_fused_step.py (perf lane)."""
    out = tmp_path / "FUSED_BENCH.json"
    rows = _run([sys.executable, "tools/bench_fused_step.py",
                 "--no-gate", "--params", "8", "--steps", "4",
                 "--out", str(out)], timeout=420)
    report = rows[-1]
    assert report["metric"] == "fused_step_speedup"
    assert set(report["sizes"]) == {"8"}
    for row in report["sizes"].values():
        assert row["eager_ms_per_step"] > 0
        assert row["fused_ms_per_step"] > 0
        # the no-recompile invariant is NOT noise-prone — a smoke run
        # must already hold it (one executable per size, lr change
        # included)
        assert row["fused_compiles"] == 1
    assert report["gate_params"] == 8
    assert json.loads(out.read_text()) == report


def test_bench_all_mnist_smoke():
    rows = _run([sys.executable, "bench_all.py", "--cpu-smoke",
                 "--config", "mnist_mlp"])
    assert rows[-1]["metric"] == "mnist_mlp_train_throughput"
    assert rows[-1]["value"] > 0


@pytest.mark.slow  # 12s CLI smoke of a tool the nightly spmd stage
# already runs for real (scaling_bench --spmd --phases) — runs nightly
def test_scaling_bench_single_proc():
    """CLI smoke on the SPMD path (the unified spine — ISSUE 9) with
    per-phase attribution; the multi-process sweep, the loss-parity
    gate, and the replica-path comparison live in the run_nightly spmd
    stage."""
    rows = _run([sys.executable, "tools/scaling_bench.py",
                 "--model", "resnet18", "--procs", "1", "--steps", "2",
                 "--warmup", "1", "--batch-per-device", "2",
                 "--image-size", "32", "--spmd", "--phases",
                 "--out", "/tmp/scaling_test.json"])
    assert rows[-1]["processes"] == 1
    assert rows[-1]["efficiency_vs_1proc"] == 1.0
    assert rows[-1]["path"] == "spmd"
    # attribution present (collected after the timed window)
    assert rows[-1]["phase_seconds"].get("spmd-step", {}).get("count")


def test_bench_resilience_smoke(tmp_path):
    """CLI smoke only: the resilience bench runs both scenarios and
    emits a well-formed report.  The strict gate (bit-consistent
    resume, breaker opened+recovered, healthz up) lives in
    tests/nightly/test_bench_resilience.py."""
    out = tmp_path / "RESILIENCE.json"
    rows = _run([sys.executable, "tools/bench_resilience.py",
                 "--no-gate", "--steps", "4", "--preempt-at", "3",
                 "--trip-requests", "8", "--out", str(out)],
                timeout=420)
    report = rows[-1]
    assert report["bench"] == "resilience"
    rec = report["recovery"]
    assert rec["recovery_time_to_first_step_s"] > 0
    assert rec["preempted_checkpoint"].startswith("step-")
    br = report["breaker"]
    assert br["requests_during_trip"] == 8
    assert br["requests_failed_pre_trip"] \
        + br["requests_dropped_during_trip"] == 8
    assert json.loads(out.read_text()) == report


def test_bench_compile_cache_smoke(tmp_path):
    """CLI smoke only: the warm-start bench runs a cold/warm
    subprocess pair and emits a well-formed report.  One scenario at
    tiny sizes — tier-1 runs near its wall-clock cap; the strict
    both-scenario >=3x-speedup / zero-warm-compiles gate lives in
    tests/nightly/test_bench_compile_cache.py."""
    out = tmp_path / "COMPILE_CACHE.json"
    rows = _run([sys.executable, "tools/bench_compile_cache.py",
                 "--no-gate", "--scenarios", "fused",
                 "--params", "4", "--fused-units", "8",
                 "--repeats", "1", "--out", str(out)], timeout=420)
    report = rows[-1]
    assert report["bench"] == "compile_cache"
    assert "serving" not in report  # subset run stays a subset
    r = report["fused"]
    assert r["cold_first_step_s"] > 0 and r["warm_first_step_s"] > 0
    # the structural invariants hold even at smoke sizes: cold
    # compiled, warm did not (it loaded from disk instead)
    assert r["cold_xla_compiles"] > 0
    assert r["warm_xla_compiles"] == 0
    assert r["warm_disk_hits"] > 0
    assert json.loads(out.read_text()) == report
