#!/usr/bin/env python
"""mxlint CLI — framework-aware static analysis for mxnet_tpu.

    python tools/mxlint.py mxnet_tpu --baseline MXLINT_BASELINE.json
    python tools/mxlint.py mxnet_tpu --json --check --out MXLINT.json
    python tools/mxlint.py --env-docs docs/env_vars.md
    python tools/mxlint.py --list-rules

Exit status: 0 when no NEW violations (baselined ones do not fail);
non-zero when any new violation, unparsable file, or (with --check)
stale baseline entry is found.

The analysis package is loaded standalone — WITHOUT importing
mxnet_tpu/__init__.py — so a full-package lint stays a few seconds of
pure-AST work instead of paying the jax import.  Only --env-docs
imports the framework (it reads the live knob registry).
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _changed_files(ref: str, scope_paths):
    """``.py`` files changed vs `ref` (plus untracked ones), filtered
    to the requested scope — the <1s pre-commit loop behind ``--diff``."""
    def _git(*args):
        p = subprocess.run(["git", "-C", _REPO, *args],
                           capture_output=True, text=True, timeout=30)
        if p.returncode != 0:
            raise RuntimeError(f"git {' '.join(args)}: "
                               f"{p.stderr.strip() or p.returncode}")
        return [ln for ln in p.stdout.splitlines() if ln.strip()]

    names = set(_git("diff", "--name-only", ref, "--"))
    names.update(_git("ls-files", "--others", "--exclude-standard"))
    # a relative scope path that doesn't exist from the cwd resolves
    # against the repo root — otherwise `mxlint mxnet_tpu --diff` run
    # from any other directory silently matches nothing and exits 0
    scope = []
    for p in scope_paths:
        ap = os.path.abspath(p)
        if not os.path.exists(ap) and not os.path.isabs(p):
            rp = os.path.join(_REPO, p)
            if os.path.exists(rp):
                ap = rp
        scope.append(ap)
    out = []
    for rel in sorted(names):
        if not rel.endswith(".py"):
            continue
        path = os.path.join(_REPO, rel)
        if not os.path.isfile(path):
            continue  # deleted in the working tree
        if any(os.path.commonpath([path, s]) == s for s in scope):
            out.append(path)
    return out


def _load_analysis():
    """Load mxnet_tpu.analysis without executing mxnet_tpu/__init__.py.

    Seeding sys.modules['mxnet_tpu.analysis'] first means the package's
    internal relative imports resolve against it directly and never
    consult the (absent) parent package.
    """
    if "mxnet_tpu.analysis" in sys.modules:
        return sys.modules["mxnet_tpu.analysis"]
    pkg_dir = os.path.join(_REPO, "mxnet_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "mxnet_tpu.analysis", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["mxnet_tpu.analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def _env_docs(out_path: str | None) -> int:
    sys.path.insert(0, _REPO)
    from mxnet_tpu.util import env

    text = env.generate_docs()
    if out_path:
        with open(out_path, "w", encoding="utf-8") as f:
            f.write(text)
        print(f"wrote {out_path} ({len(env.knobs())} knobs)")
    else:
        sys.stdout.write(text)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="mxlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=[],
                    help="files/directories to lint (default: mxnet_tpu)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON; listed violations are "
                    "suppressed (ratchet)")
    ap.add_argument("--json", action="store_true",
                    help="print the JSON report instead of text")
    ap.add_argument("--check", action="store_true",
                    help="CI mode: also fail on stale baseline entries "
                    "(forces the baseline to ratchet down)")
    ap.add_argument("--out", default=None,
                    help="also write the JSON report to this file "
                    "(the MXLINT.json artifact)")
    ap.add_argument("--sarif", default=None, metavar="FILE",
                    help="also write the NEW violations as SARIF 2.1.0 "
                    "to FILE (diff-annotation in review UIs); '-' "
                    "prints to stdout instead of the text report")
    ap.add_argument("--drift", action="store_true",
                    help="cross-artifact drift check: telemetry "
                    "instruments vs docs/observability.md, chaos "
                    "sites vs docs/resilience.md; exits non-zero on "
                    "drift")
    ap.add_argument("--write-baseline", default=None, metavar="FILE",
                    help="write every current violation to FILE as the "
                    "new baseline and exit 0")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite --baseline with stale entries removed "
                    "(never adds entries)")
    ap.add_argument("--diff", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="lint only .py files changed vs REF (default "
                    "HEAD) plus untracked ones — the <1s pre-commit "
                    "loop. Stale-baseline reporting is disabled (a "
                    "partial lint cannot judge staleness)")
    ap.add_argument("--enable", default=None,
                    help="comma-separated rule ids to run exclusively")
    ap.add_argument("--disable", default=None,
                    help="comma-separated rule ids to skip")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    ap.add_argument("--env-docs", nargs="?", const="", default=None,
                    metavar="FILE",
                    help="generate docs/env_vars.md content from the "
                    "knob registry (to FILE, or stdout)")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the incremental findings cache "
                    "(.mxlint_cache.json, keyed on content sha256 + "
                    "rules-version; cold and warm runs are "
                    "finding-identical — this flag exists for "
                    "debugging the cache itself)")
    ap.add_argument("--cache-file",
                    default=os.path.join(_REPO, ".mxlint_cache.json"),
                    metavar="FILE",
                    help="incremental cache location (default: "
                    ".mxlint_cache.json at the repo root)")
    args = ap.parse_args(argv)

    if args.env_docs is not None:
        return _env_docs(args.env_docs or None)

    analysis = _load_analysis()

    if args.drift:
        findings = analysis.drift_findings(_REPO)
        for f in findings:
            print(f"drift: {f}")
        print(f"mxlint --drift: {'FAIL' if findings else 'OK'} — "
              f"{len(findings)} drift finding(s)")
        return 1 if findings else 0

    if args.list_rules:
        for rid, cls in sorted(analysis.RULE_REGISTRY.items()):
            print(f"{rid}  {cls.name:<24} {cls.description}")
        return 0

    paths = args.paths or [os.path.join(_REPO, "mxnet_tpu")]
    if args.diff is not None:
        try:
            paths = _changed_files(args.diff, paths)
        except RuntimeError as e:
            print(f"mxlint --diff: {e}", file=sys.stderr)
            return 2
        if not paths:
            print(f"mxlint --diff: no .py files changed vs "
                  f"{args.diff} — OK")
            return 0
    t0 = time.perf_counter()
    engine = analysis.LintEngine(
        root=_REPO,
        enable=[s.strip() for s in args.enable.split(",")]
        if args.enable else None,
        disable=[s.strip() for s in args.disable.split(",")]
        if args.disable else None)
    violations = engine.run(
        paths, cache_path=None if args.no_cache else args.cache_file)
    elapsed = time.perf_counter() - t0

    if args.write_baseline:
        doc = analysis.make_baseline(violations)
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.write_baseline}: {len(violations)} entries")
        return 0

    entries = analysis.load_baseline(args.baseline) if args.baseline \
        else []
    new, suppressed, stale = analysis.diff_baseline(violations, entries)
    if args.diff is not None:
        # a subset lint sees only a slice of the tree: every baseline
        # entry outside the changed files would read as "stale"
        stale = []

    if args.update_baseline:
        if not args.baseline:
            ap.error("--update-baseline requires --baseline")
        drop: dict = {}
        for e in stale:
            drop[e["fingerprint"]] = drop.get(e["fingerprint"], 0) + 1
        kept = []
        for e in entries:
            if drop.get(e["fingerprint"], 0) > 0:
                drop[e["fingerprint"]] -= 1
            else:
                kept.append(e)
        with open(args.baseline, "w", encoding="utf-8") as f:
            json.dump({"version": 1, "comment":
                       "mxlint suppression baseline — existing "
                       "violations ratchet down; new ones fail. See "
                       "docs/static_analysis.md.",
                       "entries": kept}, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"pruned {len(stale)} stale entries from {args.baseline}")
        stale = []

    report = analysis.render_json(new, suppressed, stale, engine.errors)
    report["elapsed_seconds"] = round(elapsed, 3)
    report["cache"] = {"hits": engine.cache_hits,
                       "misses": engine.cache_misses,
                       "enabled": not args.no_cache}
    if args.sarif is not None:
        sarif = analysis.render_sarif(new)
        if args.sarif == "-":
            json.dump(sarif, sys.stdout, indent=1, sort_keys=True)
            sys.stdout.write("\n")
        else:
            with open(args.sarif, "w", encoding="utf-8") as f:
                json.dump(sarif, f, indent=1, sort_keys=True)
                f.write("\n")
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1, sort_keys=True)
            f.write("\n")
    if args.json:
        json.dump(report, sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write("\n")
    else:
        print(analysis.render_text(new, suppressed, stale, engine.errors))
        print(f"({elapsed:.2f}s, cache: {engine.cache_hits} hit / "
              f"{engine.cache_misses} miss)")

    failed = bool(new) or bool(engine.errors) or \
        (args.check and bool(stale))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
