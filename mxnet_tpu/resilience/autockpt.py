"""Preemption-safe auto-checkpoint for the Gluon Trainer.

The resume contract (docs/resilience.md): a training job killed at any
step boundary restarts and continues BIT-CONSISTENT with the run that
was never killed.  That requires checkpointing, atomically and off the
step path, everything the next step depends on:

  * parameters        — replica 0's values (data-parallel sync training
                        keeps replicas identical; restore broadcasts)
  * optimizer state   — the Trainer's per-replica updater payload
                        (PR 3's save_states format, every replica)
  * step counter      — the auto-checkpointer's own monotone counter
  * RNG               — every device stream of the resource manager
                        (``kRandom``), so dropout/augmentation streams
                        continue instead of restarting
  * data position     — an opaque JSON dict from the training loop's
                        ``state_provider`` (epoch/batch), replayed into
                        ``DataLoader.resume_from``

Layout: ``<dir>/step-<N>/ {params.npz, trainer.states, meta.json}``.
Writes land in ``<dir>/.tmp-step-<N>`` and are ``os.replace``d into
place — a crash mid-write leaves a ``.tmp-`` dir that resume ignores
and the next save sweeps, never a half-readable checkpoint.  The last
``keep_last`` checkpoints are retained.  Checkpoint I/O runs through
the retry policy (site ``checkpoint.save``, ``OSError`` transient) —
blob stores flake, and a failed save must not kill the step that
triggered it unless retries exhaust.

Saves are asynchronous by default: the step path only snapshots state
to host numpy (cheap at every-N-steps cadence) and hands the blob to a
writer thread.  A PREEMPTION save is synchronous — the process is
about to die, the write must complete before the grace window closes.
"""
from __future__ import annotations

import io
import json
import os
import pickle
import queue
import shutil
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..base import MXNetError
from . import preemption
from .preemption import Preempted
from .retry import RetryPolicy

__all__ = ["AutoCheckpoint", "latest_step_dir"]

_STEP_PREFIX = "step-"
_TMP_PREFIX = ".tmp-"


def latest_step_dir(directory: str) -> Optional[str]:
    """Newest complete checkpoint under `directory` (None when empty).
    ``.tmp-`` dirs — interrupted writes — are ignored."""
    best, best_step = None, -1
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    for name in names:
        if not name.startswith(_STEP_PREFIX):
            continue
        try:
            step = int(name[len(_STEP_PREFIX):])
        except ValueError:
            continue
        if step > best_step:
            best, best_step = os.path.join(directory, name), step
    return best


class AutoCheckpoint:
    """Attach to a Trainer; it calls :meth:`on_step` after every
    optimizer step (the hook is one ``is not None`` check when no
    checkpointer is attached).

        ck = resilience.AutoCheckpoint(dir, trainer, every_n_steps=50,
                                       state_provider=lambda: pos)
        ...
        pos_meta = ck.resume()        # None on a fresh start
        for epoch ...:
            for i, batch in enumerate(loader):
                pos = {"epoch": epoch, "next_batch": i + 1}
                ... trainer.step(bs)  # a checkpoint cut inside this
                #   step records `pos` — so set the position BEFORE
                #   step() to where training resumes once THIS batch
                #   has committed

    On a preemption signal (real SIGTERM via ``preemption.install()``,
    or injected chaos) the NEXT step boundary saves synchronously and
    raises :class:`Preempted`."""

    def __init__(self, directory: str, trainer,
                 every_n_steps: Optional[int] = None,
                 keep_last: Optional[int] = None,
                 async_save: bool = True,
                 state_provider: Optional[Callable[[], dict]] = None,
                 retry: Optional[RetryPolicy] = None):
        from ..util import env

        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._trainer = trainer
        self._every = every_n_steps if every_n_steps is not None \
            else env.get_int("MXNET_CKPT_EVERY")
        self._keep = keep_last if keep_last is not None \
            else env.get_int("MXNET_CKPT_KEEP")
        if self._keep < 1:
            raise MXNetError("keep_last must be >= 1")
        self._async = bool(async_save)
        self._state_provider = state_provider
        self._retry = retry or RetryPolicy()
        self.step = 0
        self.saves = 0          # completed checkpoint writes
        self._q: "queue.Queue" = queue.Queue()
        self._writer: Optional[threading.Thread] = None
        self._writer_error: List[BaseException] = []
        # set on the preemption branch of on_step so the sync save's
        # meta records WHY (and when) it was cut — resume() uses it to
        # open the goodput preemption-recovery window cross-process
        self._preempt_info: Optional[dict] = None
        trainer._auto_ckpt = self

    # ---- the step hook --------------------------------------------------

    @staticmethod
    def _recovery_kind(reason: str) -> str:
        """Classify a preemption trigger: a supervisor wind-down / peer
        failure (mxelastic marks its reasons ``peer-failure: ...``) is
        accounted as ``rank_failure_recovery``; everything else is a
        genuine preemption."""
        return "peer_failure" if reason.startswith("peer-failure") \
            else "preempt"

    @staticmethod
    def _recovery_category(kind: str) -> str:
        return "rank_failure_recovery" if kind == "peer_failure" \
            else "preemption_recovery"

    def stamp_failure(self, reason: str,
                      kind: str = "peer_failure") -> None:
        """Mark the NEXT save as cut by a failure (the elastic guard
        calls this before its peer-failure sync save): the checkpoint
        meta records why, and a resume from it opens the matching
        goodput recovery window cross-process."""
        self._preempt_info = {"reason": reason,
                              "t_unix": time.time(), "kind": kind}
        from ..telemetry import mxblackbox as _bb

        if _bb._ACTIVE:
            _bb.emit("checkpoint", "failure stamped",
                     step=self.step, reason=reason, kind=kind)

    def on_step(self, trainer) -> None:
        """Called by Trainer.step after the update.  Preemption wins
        over cadence: save NOW (sync) and raise Preempted."""
        self.step += 1
        if preemption.triggered():
            from ..telemetry import mxgoodput as _goodput

            kind = self._recovery_kind(preemption.reason())
            if _goodput._ACTIVE:
                # recovery starts where the step boundary OBSERVES the
                # trigger (never from the signal handler itself)
                _goodput.on_preemption_trigger(
                    category=self._recovery_category(kind))
            t = preemption.trigger_time()
            self._preempt_info = {"reason": preemption.reason(),
                                  "t_unix": t[0] if t else time.time(),
                                  "kind": kind}
            path = self.save(sync=True)
            raise Preempted(
                f"preempted ({preemption.reason()}); checkpoint for "
                f"step {self.step} saved to {path}",
                checkpoint_dir=path)
        if self._every and self.step % self._every == 0:
            self.save(sync=not self._async)

    # ---- save path ------------------------------------------------------

    def save(self, sync: bool = False) -> str:
        """Snapshot now; write now (sync) or on the writer thread.
        Returns the FINAL step-dir path (the one resume will find).

        Timing contract (``mx_ckpt_seconds`` + the goodput ledger):
        everything this method does BLOCKS the step path and is
        observed as ``mode="sync"`` — for an async save that is just
        the host snapshot + enqueue; the daemon thread's disk time
        overlaps training and lands in ``mode="async"`` instead
        (recorded, never badput)."""
        self._raise_writer_error()
        retry_mark = self._retry_backoff_mark()
        t0 = time.monotonic()
        snap = self._snapshot()
        final = os.path.join(self._dir, f"{_STEP_PREFIX}{snap['step']:08d}")
        if sync:
            self.flush()
            self._write(snap)
        else:
            self._ensure_writer()
            self._q.put(snap)
        self._record_blocking("save", time.monotonic() - t0, retry_mark)
        from ..telemetry import mxblackbox as _bb

        if _bb._ACTIVE:
            # same msg text on every rank saving this step: the
            # postmortem uses matched checkpoint events as cross-rank
            # clock-sync marks (trace_report's collective-end analog)
            _bb.emit("checkpoint", f"save step {snap['step']}",
                     step=snap["step"], sync=sync)
        return final

    @staticmethod
    def _retry_backoff_mark() -> float:
        from ..telemetry import mxgoodput as _goodput

        # THIS thread's total: the blocking save/restore retries run
        # on the calling thread, and a concurrent daemon writer's
        # sleeps must not be deducted from this interval
        return _goodput.retry_backoff_this_thread() \
            if _goodput._ACTIVE else 0.0

    def _record_blocking(self, op: str, dt: float,
                         retry_mark: float) -> None:
        """One blocking checkpoint interval: observe the histogram and
        feed the goodput ledger.  Retry backoff that slept INSIDE this
        interval (checkpoint I/O retries) keeps its own category — it
        is deducted here, and its step-overlap credit is cancelled
        (the sleep was inside a checkpoint, not a step)."""
        from ..telemetry import instruments as _ins
        from ..telemetry import mxgoodput as _goodput

        _ins.ckpt_seconds(op, "sync").observe(dt)
        if not _goodput._ACTIVE:
            return
        backoff = min(max(
            0.0, _goodput.retry_backoff_this_thread()
            - retry_mark), dt)
        if backoff:
            _goodput.consume_overlap(backoff)
        cat = "checkpoint_save" if op == "save" else "checkpoint_restore"
        _goodput.record_badput(cat, max(0.0, dt - backoff))

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every queued async save is on disk."""
        if self._writer is not None:
            self._q.join()
        self._raise_writer_error()

    def _raise_writer_error(self) -> None:
        if self._writer_error:
            e = self._writer_error[0]
            raise MXNetError(
                f"async checkpoint writer failed: {e}") from e

    def _ensure_writer(self) -> None:
        if self._writer is None or not self._writer.is_alive():
            self._writer = threading.Thread(
                target=self._writer_loop, daemon=True,
                name="mx-auto-checkpoint")
            self._writer.start()

    def _writer_loop(self) -> None:
        while True:
            snap = self._q.get()
            t0 = time.monotonic()
            try:
                self._write(snap)
                # daemon disk time: recorded (mode="async") but never
                # badput — it overlapped training
                from ..telemetry import instruments as _ins

                _ins.ckpt_seconds("save", "async").observe(
                    time.monotonic() - t0)
            except BaseException as e:  # surfaced on the next step
                self._writer_error.append(e)
            finally:
                self._q.task_done()

    def _snapshot(self) -> Dict:
        """Host-side copy of everything resume needs — the only work on
        the step path.  Parameters come off replica 0 (sync data-
        parallel replicas are identical; docs/resilience.md)."""
        from ..resource import resource_manager

        tr = self._trainer
        # update_on_kvstore trainers are rejected by _states_payload()
        # below — optimizer state lives server-side there
        params = {}
        for p in tr._params:
            if p._data is None:
                continue
            params[p.name] = np.asarray(p.list_data()[0].asnumpy())
        snap = {
            "step": self.step,
            "params": params,
            "states": tr._states_payload(),
            "rng": resource_manager().rng_state(),
            "position": self._state_provider()
            if self._state_provider is not None else None,
        }
        if self._preempt_info is not None:
            snap["preempt"] = dict(self._preempt_info)
            self._preempt_info = None
        return snap

    def _write(self, snap: Dict) -> None:
        self._retry.call(lambda: self._write_once(snap),
                         site="checkpoint.save", retry_on=(OSError,))

    @staticmethod
    def _write_file(path: str, data, mode: str = "wb") -> None:
        """Write + flush + fsync: the rename below only commits what
        the disk actually has — an os.replace of dirty page cache is
        atomic against a CRASHED PROCESS but not against a crashed
        machine (or a kill -9 racing writeback)."""
        with open(path, mode) as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())

    @staticmethod
    def _fsync_dir(path: str) -> None:
        """fsync a DIRECTORY: the os.replace rename is itself metadata
        that lives in the parent directory — without this, a hard kill
        after the rename can still lose the commit, and resume would
        find neither the .tmp- nor the final dir.  Best-effort on
        filesystems that refuse O_RDONLY dir fsync."""
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(fd)
        except OSError:
            pass  # mxlint: disable=MX007 — fs without dir-fsync support
        finally:
            os.close(fd)

    def _write_once(self, snap: Dict) -> None:
        name = f"{_STEP_PREFIX}{snap['step']:08d}"
        tmp = os.path.join(self._dir, _TMP_PREFIX + name)
        final = os.path.join(self._dir, name)
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        buf = io.BytesIO()
        np.savez(buf, **snap["params"])
        self._write_file(os.path.join(tmp, "params.npz"),
                         buf.getvalue())
        self._write_file(os.path.join(tmp, "trainer.states"),
                         snap["states"])
        meta = {"step": snap["step"], "rng": snap["rng"],
                "position": snap["position"],
                "saved_unix": time.time()}
        if "preempt" in snap:
            # this checkpoint was cut BY a preemption: resume() uses
            # the trigger time to open the goodput recovery window —
            # even in a fresh process, the downtime is measured
            meta["preempt"] = snap["preempt"]
        self._write_file(os.path.join(tmp, "meta.json"),
                         json.dumps(meta, indent=1), mode="w")
        self._fsync_dir(tmp)
        old = None
        if os.path.exists(final):
            # re-save of the same step (the elastic guard re-saving
            # the cadence step is the common case): NEVER rmtree the
            # complete dir before the new one commits — a SIGKILL
            # inside a slow rmtree would destroy the rank's newest
            # checkpoint.  Rename it aside (a `.old-` name resume
            # ignores) so the destruction window shrinks to two
            # renames, and the complete copy survives either crash.
            old = os.path.join(self._dir, f".old-{name}")
            if os.path.exists(old):
                shutil.rmtree(old)
            os.replace(final, old)
        os.replace(tmp, final)
        # crash-consistency for the COMMIT itself: the rename must be
        # durable before this save counts — a kill -9 right after
        # _write_once returns must still find the complete step dir
        self._fsync_dir(self._dir)
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
        self.saves += 1
        self._prune()

    def _prune(self) -> None:
        steps = []
        for name in os.listdir(self._dir):
            if name.startswith(".old-"):
                # aside-rename residue of a crashed re-save (the live
                # _write_once already removed its own): sweep it
                shutil.rmtree(os.path.join(self._dir, name),
                              ignore_errors=True)
                continue
            if name.startswith(_TMP_PREFIX):
                continue  # an in-flight or crashed write; not ours
            if name.startswith(_STEP_PREFIX):
                try:
                    steps.append((int(name[len(_STEP_PREFIX):]), name))
                except ValueError:
                    continue
        steps.sort()
        for _, name in steps[:-self._keep]:
            shutil.rmtree(os.path.join(self._dir, name),
                          ignore_errors=True)

    # ---- resume path ----------------------------------------------------

    def resume(self, path: Optional[str] = None,
               incident: Optional[str] = None) -> Optional[dict]:
        """Restore the newest checkpoint into the attached trainer;
        returns its meta dict ({"step", "position", ...}) or None when
        the directory has no checkpoint (fresh start).  The restore
        re-shards onto the trainer's CURRENT replica layout — resuming
        onto fewer replicas than saved is first-class (the preempted
        slice may come back smaller).

        ``path`` pins an explicit step directory instead of the newest
        one in this checkpointer's own dir — the elastic restart path:
        every rank of a recovered job resumes from the ONE step dir the
        supervisor's commit marker elected, so ranks can never mix
        steps even when their own checkpoint cadences diverged.

        ``incident`` is the mxblackbox incident id the elastic COMMIT
        marker carries: it stamps the goodput recovery window this
        resume opens, tying the measured downtime to its postmortem
        report."""
        from ..ndarray.ndarray import array as nd_array
        from ..resource import resource_manager

        from ..telemetry import mxgoodput as _goodput

        if path is None:
            path = latest_step_dir(self._dir)
        if path is None:
            return None
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        if isinstance(meta.get("preempt"), dict):
            if _goodput._ACTIVE:
                # open the recovery window BEFORE the restore work so
                # the restore seconds (attributed below) are deducted
                # from it rather than double-counted; in-process the
                # trigger already opened it and this is a no-op
                _goodput.on_preemption_resume(
                    meta["preempt"].get("t_unix"),
                    category=self._recovery_category(
                        meta["preempt"].get("kind", "preempt")),
                    incident=incident)
            # the stamp is CONSUMED by this resume: a later resume
            # from the same checkpoint (crash after hours of resumed
            # training) must not re-open a window back to the original
            # SIGTERM and attribute the interim to recovery
            self._consume_preempt_stamp(path, meta)
        retry_mark = self._retry_backoff_mark()
        t0 = time.monotonic()
        tr = self._trainer
        by_name = {p.name: p for p in tr._params}
        with np.load(os.path.join(path, "params.npz")) as blob:
            saved = set(blob.files)
            have = {n for n, p in by_name.items() if p._data is not None}
            if saved != have:
                raise MXNetError(
                    f"checkpoint {path!r} parameter set does not match "
                    f"the model: missing {sorted(have - saved)}, "
                    f"unexpected {sorted(saved - have)}")
            for n in blob.files:
                by_name[n].set_data(nd_array(blob[n]))
        tr.load_states(os.path.join(path, "trainer.states"),
                       allow_resize=True)
        resource_manager().set_rng_state(meta["rng"])
        self.step = int(meta["step"])
        self._record_blocking("restore", time.monotonic() - t0,
                              retry_mark)
        preemption.clear()
        from ..telemetry import mxblackbox as _bb

        if _bb._ACTIVE:
            _bb.emit("checkpoint", f"restore step {meta['step']}",
                     step=int(meta["step"]), path=path,
                     incident=incident)
        return meta

    def _consume_preempt_stamp(self, path: str, meta: Dict) -> None:
        """Rewrite meta.json with the preempt stamp demoted to
        ``preempt_consumed`` (forensics stay; the trigger never
        re-opens a recovery window).  Atomic like every checkpoint
        write; best-effort — a read-only filesystem must not fail the
        resume itself."""
        on_disk = dict(meta)
        on_disk["preempt_consumed"] = on_disk.pop("preempt")
        tmp = os.path.join(path, ".tmp-meta.json")
        try:
            with open(tmp, "w") as f:
                json.dump(on_disk, f, indent=1)
            os.replace(tmp, os.path.join(path, "meta.json"))
        except OSError:
            pass
