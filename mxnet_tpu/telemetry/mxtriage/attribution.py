"""Perf-regression attribution: turn a red gate into ranked suspects.

``tools/perf_compare.py`` knows *that* a lane regressed ("throughput
-12%"); this module reads the mxprof aggregates embedded in the same
bench artifacts (per-phase seconds, collective bytes, data-wait, MFU,
compile counts, HLO fingerprints, the registered-knob fingerprint) on
BOTH sides of the diff and answers *what moved*:

    suspects = rank_suspects(baseline_artifact, fresh_artifact)
    # [{"kind": "phase", "name": "grad-allreduce", "base_s": 0.8,
    #   "fresh_s": 1.1, "change": "+38%", "score": ...}, ...]

Deliberately **stdlib-only with no package-relative imports**:
``perf_compare`` is a dependency-light nightly tool and loads this
file directly (``importlib`` by path) — importing the framework (and
jax) to rank a JSON diff would be absurd.  It also imports normally as
``mxnet_tpu.telemetry.mxtriage.attribution``.

Scoring is deliberately simple and stable: each suspect's score is its
relative change scaled by a kind weight (a phase that grew 200% ranks
above a knob that changed, which ranks above a 12% byte-count drift).
Qualitative findings that cannot regress by themselves (a knob change,
a program-fingerprint change) surface as suspects with flat scores;
stable fingerprints land in ``context`` notes so "the program did NOT
change" is stated, not implied.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

__all__ = ["collect_aggregates", "rank_suspects"]

# dict nodes carrying at least one of these keys are mxprof aggregate
# blocks (a SCALING sweep row, an embedded snapshot summary, ...)
_SIGNAL_KEYS = ("phase_seconds", "collective_bytes",
                "collective_wire_bytes", "data_wait_s",
                "data_wait_s_total", "mfu", "compiles",
                "compile_reasons", "knobs", "knob_fingerprint",
                "hlo_fingerprints", "badput_seconds", "goodput_ratio",
                "schedule_divergences")

# kind weights: how alarming a 1.0 (=100%) relative change of each
# signal is relative to the others
_WEIGHTS = {"phase": 1.0, "data-wait": 1.0, "mfu": 1.0, "badput": 1.0,
            "goodput": 1.0, "compiles": 0.9, "collective-bytes": 0.5}
# flat scores for qualitative suspects (no meaningful magnitude).
# "encoding" is the comm-encoding knob (MXNET_COMM_QUANT...): a flipped
# wire encoding changes numerics AND bytes at once, so it outranks a
# generic knob change; "divergence" (mxrank ScheduleDivergence counts)
# outranks everything qualitative — ranks issuing different collective
# schedules is a correctness bug, not a perf drift
_FLAT = {"knob": 0.75, "program": 0.8, "encoding": 0.85,
         "divergence": 0.95}

# knobs that select the collective wire encoding: their change is an
# "encoding" suspect, not a plain "knob" one
_ENCODING_KNOBS = ("MXNET_COMM_QUANT", "MXNET_COMM_QUANT_EF",
                   "MXNET_COMM_QUANT_MIN_SIZE")

# ignore sub-floor noise: seconds for phases/data-wait, fraction
# for relative changes
_ABS_FLOOR_S = 0.02
_REL_FLOOR = 0.10


def _node_id(node: dict, idx: int) -> str:
    """A stable label for a list element (SCALING sweep rows carry
    path/processes); falls back to the index."""
    bits = [str(node[k]) for k in ("path", "model", "processes", "name")
            if k in node and not isinstance(node[k], (dict, list))]
    return ".".join(bits) if bits else str(idx)


def collect_aggregates(doc) -> Dict[str, dict]:
    """Walk one bench-artifact JSON tree; return {path: node} for every
    dict node that carries mxprof aggregate keys."""
    out: Dict[str, dict] = {}

    def walk(node, path):
        if isinstance(node, dict):
            if any(k in node for k in _SIGNAL_KEYS):
                out[path or "."] = node
            for k, v in node.items():
                walk(v, f"{path}.{k}" if path else k)
        elif isinstance(node, list):
            for i, v in enumerate(node):
                if isinstance(v, dict):
                    walk(v, f"{path}[{_node_id(v, i)}]")
    walk(doc, "")
    return out


def _phase_s(v) -> Optional[float]:
    """phase_seconds values come flat (float) or as
    {"seconds": x, "count": n} (scaling_bench rows)."""
    if isinstance(v, dict):
        v = v.get("seconds")
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def _pct(base: float, fresh: float) -> str:
    if base <= 0:
        return "new"
    d = (fresh / base - 1.0) * 100.0
    return f"{d:+.0f}%"


def _diff_node(where: str, base: dict, fresh: dict,
               suspects: List[dict], context: List[str]) -> None:
    # per-phase seconds: the primary "where did the time go" signal
    bp, fp = base.get("phase_seconds") or {}, \
        fresh.get("phase_seconds") or {}
    for name in sorted(set(bp) | set(fp)):
        b, f = _phase_s(bp.get(name, 0.0)), _phase_s(fp.get(name, 0.0))
        if b is None or f is None:
            continue
        if f > b and (f - b) > _ABS_FLOOR_S and \
                (b == 0 or f / b - 1.0 > _REL_FLOOR):
            rel = (f / b - 1.0) if b > 0 else 1.0
            suspects.append({
                "kind": "phase", "name": name, "where": where,
                "base_s": round(b, 4), "fresh_s": round(f, 4),
                "change": _pct(b, f),
                "score": round(rel * _WEIGHTS["phase"], 4)})
    # data-wait growth: the input pipeline as suspect (scaling rows
    # say data_wait_s; embedded mxprof summaries say data_wait_s_total)
    bw = base.get("data_wait_s", base.get("data_wait_s_total"))
    fw = fresh.get("data_wait_s", fresh.get("data_wait_s_total"))
    if isinstance(bw, (int, float)) and isinstance(fw, (int, float)) \
            and fw > bw and fw - bw > _ABS_FLOOR_S:
        rel = (fw / bw - 1.0) if bw > 0 else 1.0
        suspects.append({
            "kind": "data-wait", "name": "data-wait", "where": where,
            "base_s": round(float(bw), 4), "fresh_s": round(float(fw), 4),
            "change": _pct(float(bw), float(fw)),
            "score": round(rel * _WEIGHTS["data-wait"], 4)})
    # MFU drop (an efficiency collapse with flat wall time)
    bm = (base.get("mfu") or {}).get("mean") \
        if isinstance(base.get("mfu"), dict) else base.get("mfu")
    fm = (fresh.get("mfu") or {}).get("mean") \
        if isinstance(fresh.get("mfu"), dict) else fresh.get("mfu")
    if isinstance(bm, (int, float)) and isinstance(fm, (int, float)) \
            and bm > 0 and fm < bm * (1.0 - _REL_FLOOR):
        rel = 1.0 - fm / bm
        suspects.append({
            "kind": "mfu", "name": "mfu", "where": where,
            "base": round(float(bm), 6), "fresh": round(float(fm), 6),
            "change": _pct(float(bm), float(fm)),
            "score": round(rel * _WEIGHTS["mfu"], 4)})
    # badput-category growth (mxgoodput): a category that grew names
    # where the lost wall-clock went — the same shape as a phase
    # suspect, but at job altitude
    bbp, fbp = base.get("badput_seconds") or {}, \
        fresh.get("badput_seconds") or {}
    for name in sorted(set(bbp) | set(fbp)):
        try:
            b, f = float(bbp.get(name, 0.0)), float(fbp.get(name, 0.0))
        except (TypeError, ValueError):
            continue
        if f > b and (f - b) > _ABS_FLOOR_S and \
                (b == 0 or f / b - 1.0 > _REL_FLOOR):
            rel = (f / b - 1.0) if b > 0 else 1.0
            suspects.append({
                "kind": "badput", "name": name, "where": where,
                "base_s": round(b, 4), "fresh_s": round(f, 4),
                "change": _pct(b, f),
                "score": round(rel * _WEIGHTS["badput"], 4)})
    # goodput-ratio drop (the job-level efficiency collapse; the
    # badput suspects above say WHERE it went)
    bg, fg = base.get("goodput_ratio"), fresh.get("goodput_ratio")
    if isinstance(bg, (int, float)) and isinstance(fg, (int, float)) \
            and bg > 0 and fg < bg * (1.0 - _REL_FLOOR):
        rel = 1.0 - fg / bg
        suspects.append({
            "kind": "goodput", "name": "goodput_ratio", "where": where,
            "base": round(float(bg), 6), "fresh": round(float(fg), 6),
            "change": _pct(float(bg), float(fg)),
            "score": round(rel * _WEIGHTS["goodput"], 4)})
    # collective bytes drift (a bucket-plan / quantization change
    # shows up here before anywhere else); the wire view diffs the
    # same way — its keys carry the encoding ("op@axis:int8"), so a
    # lane that silently fell back to raw names itself
    for sig_key in ("collective_bytes", "collective_wire_bytes"):
        bb, fb = base.get(sig_key) or {}, fresh.get(sig_key) or {}
        for name in sorted(set(bb) | set(fb)):
            b = float(bb.get(name, 0) or 0)
            f = float(fb.get(name, 0) or 0)
            if b <= 0 and f <= 0:
                continue
            rel = abs(f - b) / max(b, f)
            if rel > _REL_FLOOR:
                suspects.append({
                    "kind": "collective-bytes", "name": name,
                    "where": where, "base": int(b), "fresh": int(f),
                    "change": _pct(b, f),
                    "score": round(
                        rel * _WEIGHTS["collective-bytes"], 4)})
    # schedule divergences (mxrank): any growth is a top suspect —
    # a fresh run whose ranks issued different collective schedules
    # has a program bug (MX019/MX020 class), whatever the perf says
    bd, fd = base.get("schedule_divergences"), \
        fresh.get("schedule_divergences")
    if isinstance(fd, (int, float)) and \
            fd > (bd if isinstance(bd, (int, float)) else 0):
        suspects.append({
            "kind": "divergence", "name": "schedule_divergences",
            "where": where,
            "base": int(bd) if isinstance(bd, (int, float)) else 0,
            "fresh": int(fd),
            "change": "collective schedules diverged across ranks "
                      "(see mxlint MX019/MX020)",
            "score": _FLAT["divergence"]})
    # compile-count growth = a recompile storm; name its cause when
    # the provenance aggregates rode along
    bc, fc = base.get("compiles"), fresh.get("compiles")
    if isinstance(bc, (int, float)) and isinstance(fc, (int, float)) \
            and fc > bc:
        rel = (fc / bc - 1.0) if bc > 0 else 1.0
        sus = {"kind": "compiles", "name": "compiles", "where": where,
               "base": int(bc), "fresh": int(fc),
               "change": _pct(float(bc), float(fc)),
               "score": round(min(rel, 4.0) * _WEIGHTS["compiles"], 4)}
        reasons = fresh.get("compile_reasons")
        if isinstance(reasons, dict) and reasons:
            sus["reasons"] = reasons
        suspects.append(sus)
    # registered knobs: a changed value is a first-class suspect; a
    # changed comm-encoding knob is an "encoding" suspect (numerics
    # AND wire bytes move together when one flips)
    bk, fk = base.get("knobs") or {}, fresh.get("knobs") or {}
    for name in sorted(set(bk) | set(fk)):
        if bk.get(name) != fk.get(name):
            kind = "encoding" if name in _ENCODING_KNOBS else "knob"
            suspects.append({
                "kind": kind, "name": name, "where": where,
                "base": bk.get(name), "fresh": fk.get(name),
                "change": f"{bk.get(name)!r} -> {fk.get(name)!r}",
                "score": _FLAT[kind]})
    bkf, fkf = base.get("knob_fingerprint"), \
        fresh.get("knob_fingerprint")
    if bkf and fkf:
        if bkf != fkf and not any(s["kind"] in ("knob", "encoding")
                                  and s["where"] == where
                                  for s in suspects):
            suspects.append({
                "kind": "knob", "name": "knob_fingerprint",
                "where": where, "base": bkf[:12], "fresh": fkf[:12],
                "change": "registered-knob fingerprint changed "
                          "(value-level diff not recorded)",
                "score": _FLAT["knob"]})
        elif bkf == fkf:
            context.append(f"{where}: registered-knob fingerprint "
                           f"stable")
    # HLO program fingerprints: did the compiled program change?
    bf = base.get("hlo_fingerprints")
    ff = fresh.get("hlo_fingerprints")
    if isinstance(bf, list) and isinstance(ff, list) and (bf or ff):
        if set(bf) != set(ff):
            suspects.append({
                "kind": "program", "name": "hlo_fingerprints",
                "where": where,
                "base": sorted(x[:12] for x in bf),
                "fresh": sorted(x[:12] for x in ff),
                "change": "compiled program fingerprints changed",
                "score": _FLAT["program"]})
        else:
            context.append(f"{where}: program fingerprints stable")


def rank_suspects(base_doc, fresh_doc) -> Tuple[List[dict], List[str]]:
    """Diff the mxprof aggregates of two bench artifacts; returns
    ``(suspects, context)`` with suspects ranked most-suspicious
    first.  Aggregate blocks pair by their JSON path; a block present
    on only one side contributes nothing (a renamed lane has no
    baseline to diff)."""
    base_nodes = collect_aggregates(base_doc)
    fresh_nodes = collect_aggregates(fresh_doc)
    suspects: List[dict] = []
    context: List[str] = []
    for path in sorted(set(base_nodes) & set(fresh_nodes)):
        _diff_node(path, base_nodes[path], fresh_nodes[path],
                   suspects, context)
    suspects.sort(key=lambda s: (-s["score"], s["kind"], s["name"]))
    for i, s in enumerate(suspects):
        s["rank"] = i + 1
    return suspects, context
