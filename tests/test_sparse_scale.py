"""Sparse/embedding SCALE story (SURVEY §7 hard part 4, VERDICT r3 #6).

The reference scales big embeddings with PS-sharded row_sparse params
(kvstore_dist_server holding row shards).  The TPU-native counterpart is
GSPMD: the embedding table shards its vocab dim over the mesh ('tp'
rule, or the fsdp fallback), XLA turns the lookup into a collective
gather and the gradient into a scatter onto the owning shard — no
parameter server.  These tests pin that whole path on the 8-virtual-
device CPU mesh:

  * the DEFAULT_RULES map `*embed*weight` onto a vocab-sharded layout,
    so each device holds 1/8 of the table (the memory-scale claim:
    a table 8x one device's dense capacity fits the mesh);
  * a full SPMDTrainer step over the sharded table produces the same
    loss trajectory as the replicated run (gather+scatter correctness).

The dense-backed RowSparseNDArray stays a single-device parity surface
(documented ceiling in docs/sparse.md) — scale goes through this path.
"""
import numpy as np

import jax

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu.gluon import nn, loss as gloss
from mxnet_tpu.gluon.block import HybridBlock
from mxnet_tpu.parallel.sharding import DEFAULT_RULES, PartitionSpec as P

VOCAB, DIM, BS, SEQ = 4096, 16, 8, 12


class TinyLM(HybridBlock):
    def __init__(self):
        super().__init__()
        with self.name_scope():
            self.embed = nn.Embedding(VOCAB, DIM)
            self.head = nn.Dense(4, flatten=False)

    def hybrid_forward(self, F, tokens):
        x = self.embed(tokens)
        return self.head(F.mean(x, axis=1))


def _build():
    np.random.seed(0)
    mx.random.seed(0)
    net = TinyLM()
    net.initialize(mx.initializer.Normal(0.02), ctx=mx.cpu())
    with mx.autograd.pause():
        net(mx.nd.zeros((1, SEQ)))
    return net


def _run_steps(mesh_axes, n_steps=3):
    net = _build()
    rng = np.random.RandomState(1)
    toks = rng.randint(0, VOCAB, (BS, SEQ)).astype(np.int32)
    labels = rng.randint(0, 4, (BS,)).astype(np.int32)
    losses = []
    with parallel.make_mesh(**mesh_axes):
        trainer = parallel.SPMDTrainer(
            net, gloss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.5})
        t, y = trainer._place(toks, None), trainer._place(labels, None)
        for _ in range(n_steps):
            losses.append(float(trainer.step(t, y).asnumpy()))
        emb_name = next(n for n in trainer.params if "embed" in n
                        and n.endswith("weight"))
        emb = trainer.params[emb_name]
    return losses, emb


def test_embed_rule_shards_vocab_dim():
    with parallel.make_mesh(tp=8) as mesh:
        spec = DEFAULT_RULES.spec_for("tinylm0_embedding0_weight",
                                      (VOCAB, DIM), mesh)
    assert spec == P("tp", None)


def test_vocab_sharded_embedding_holds_one_eighth_per_device():
    _, emb = _run_steps({"tp": 8}, n_steps=1)
    shards = emb.addressable_shards
    assert len(shards) == 8
    # each device holds 1/8 of the rows: a table 8x one device's dense
    # capacity fits this mesh — the PS-sharded row_sparse scale story
    assert shards[0].data.shape == (VOCAB // 8, DIM)
    rows = sorted(s.index[0].start or 0 for s in shards)
    assert rows == [i * (VOCAB // 8) for i in range(8)]


def test_size1_axis_rule_is_vacuous_falls_to_fsdp():
    # tp EXISTS but at size 1: the embed->tp rule splits nothing, so the
    # fsdp fallback must still shard the table
    with parallel.make_mesh(tp=1, fsdp=4) as mesh:
        spec = DEFAULT_RULES.spec_for("tinylm0_embedding0_weight",
                                      (VOCAB, DIM), mesh)
    assert spec == P("fsdp", None)


def test_fsdp_fallback_also_shards_the_table():
    _, emb = _run_steps({"dp": 2, "fsdp": 4}, n_steps=1)
    sizes = {s.data.shape for s in emb.addressable_shards}
    assert sizes == {(VOCAB // 4, DIM)}  # largest dim over fsdp=4


def test_sharded_embedding_matches_replicated_training():
    ref, _ = _run_steps({"dp": 1})         # replicated baseline
    tp, _ = _run_steps({"tp": 8})          # vocab-sharded table
    np.testing.assert_allclose(tp, ref, rtol=1e-5, atol=1e-6)
