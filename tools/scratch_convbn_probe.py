"""On-chip Mosaic probe for fused-conv kernel candidates (dev scratch).

Iterates kernel formulations against the real TPU: the round-5 finding
is that Mosaic rejects the im2col jnp.concatenate inside the kernel
(tpu_compile_helper exit 1), so this probes the tap-accumulation form.
Run only when the tunnel is free (single client).
"""
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def candidate_tap(x, w_taps, in_scale, in_bias, shift, *, kernel, stride,
                  pad, act_in, want_stats, nb):
    """Tap-accumulation fused unit: pad OUTSIDE the kernel; inside,
    y = sum_{ky,kx} u[:, ky::sh, kx::sw, :] @ w[ky,kx] (one MXU matmul
    per tap, no concat, no pad)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, h, wd, ci = x.shape
    kh, kw = kernel
    sh_, sw_ = stride
    co = w_taps.shape[-1]
    ho = (h + 2 * pad[0] - kh) // sh_ + 1
    wo = (wd + 2 * pad[1] - kw) // sw_ + 1
    hp, wp = h + 2 * pad[0], wd + 2 * pad[1]
    out_dtype = x.dtype

    def kern(x_ref, w_ref, sc_ref, bi_ref, sh_ref, y_ref, s1_ref, s2_ref):
        xb = x_ref[...]
        if act_in:
            u = xb.astype(jnp.float32) * sc_ref[...] + bi_ref[...]
            u = jnp.maximum(u, 0.0).astype(xb.dtype)
        else:
            u = xb
        # pad AFTER the input affine (padded positions must be exact
        # zeros, not relu(bias)); in-kernel pad, Mosaic permitting
        # pad for the window, plus stride-1 extra rows/cols so every
        # tap's contiguous slice of length s*ho / s*wo stays in bounds
        if pad != (0, 0) or sh_ > 1 or sw_ > 1:
            u = jnp.pad(u, ((0, 0), (pad[0], pad[0] + sh_ - 1),
                            (pad[1], pad[1] + sw_ - 1), (0, 0)))
        acc = jnp.zeros((nb * ho * wo, co), jnp.float32)
        for ky in range(kh):
            for kx in range(kw):
                if sh_ == 1 and sw_ == 1:
                    sl = u[:, ky:ky + ho, kx:kx + wo, :]
                else:
                    # strided slicing lowers to an unsupported gather in
                    # Mosaic; contiguous slice + reshape + unit-index
                    # (a slice of a size-s axis) extracts the same
                    # polyphase plane
                    rows = u[:, ky:ky + sh_ * ho, :, :]
                    rows = rows.reshape(nb, ho, sh_, rows.shape[2], ci)[
                        :, :, 0]
                    cols = rows[:, :, kx:kx + sw_ * wo, :]
                    sl = cols.reshape(nb, ho, wo, sw_, ci)[:, :, :, 0]
                acc = acc + jnp.dot(
                    sl.reshape(nb * ho * wo, ci),
                    w_ref[ky, kx],
                    preferred_element_type=jnp.float32)
        yc = acc.astype(out_dtype)
        y_ref[...] = yc.reshape(nb, ho, wo, co)

        @pl.when(pl.program_id(0) == 0)
        def _():
            s1_ref[...] = jnp.zeros_like(s1_ref)
            s2_ref[...] = jnp.zeros_like(s2_ref)

        if want_stats:
            yf = yc.astype(jnp.float32)
            d = yf - sh_ref[...]
            s1_ref[...] += jnp.sum(yf, axis=0, keepdims=True)
            s2_ref[...] += jnp.sum(d * d, axis=0, keepdims=True)

    grid = (n // nb,)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((nb, h, wd, ci), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kh, kw, ci, co), lambda i: (0, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ci), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ci), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, co), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((nb, ho, wo, co), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, co), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, co), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, ho, wo, co), out_dtype),
            jax.ShapeDtypeStruct((1, co), jnp.float32),
            jax.ShapeDtypeStruct((1, co), jnp.float32),
        ],
    )(x, w_taps, in_scale.reshape(1, ci), in_bias.reshape(1, ci),
      shift.reshape(1, co))


def _time(fn, *args, reps=20):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps * 1e6  # µs


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--time", action="store_true",
                    help="also time fused kernel vs composed XLA on the "
                         "REAL ResNet-50 BS-256 layer shapes")
    args = ap.parse_args()

    print("backend:", jax.default_backend(), jax.devices())
    rng = np.random.RandomState(0)
    cases = [
        # (shape, co, kernel, stride, pad) — the ResNet-50 hot set
        ((4, 16, 16, 128), 128, (3, 3), (1, 1), (1, 1)),
        ((4, 16, 16, 128), 256, (1, 1), (1, 1), (0, 0)),
        ((4, 16, 16, 256), 128, (1, 1), (1, 1), (0, 0)),
        ((4, 16, 16, 128), 128, (3, 3), (2, 2), (1, 1)),
    ]
    if args.time:
        return time_layers(rng)
    for shape, co, kernel, stride, pad in cases:
        n, h, wd, ci = shape
        x = jnp.asarray(rng.randn(*shape).astype("float32") * 0.5,
                        jnp.bfloat16)
        w = jnp.asarray(
            rng.randn(kernel[0], kernel[1], ci, co).astype("float32")
            * 0.05, jnp.bfloat16)
        sc = jnp.asarray(rng.rand(ci).astype("float32") + 0.5)
        bi = jnp.asarray(rng.randn(ci).astype("float32") * 0.1)
        sh = jnp.asarray(rng.randn(co).astype("float32") * 0.1)
        fn = functools.partial(candidate_tap, kernel=kernel, stride=stride,
                               pad=pad, act_in=True, want_stats=True, nb=2)
        t0 = time.time()
        try:
            y, s1, s2 = jax.jit(fn)(x, w, sc, bi, sh)
            jax.block_until_ready(y)
            # oracle
            u = jnp.maximum(x.astype(jnp.float32) * sc + bi, 0.0) \
                .astype(x.dtype)
            yr = jax.lax.conv_general_dilated(
                u, w, stride, [(pad[0], pad[0]), (pad[1], pad[1])],
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            err = float(jnp.max(jnp.abs(y.astype(jnp.float32)
                                        - yr.astype(jnp.float32))))
            print(f"OK   {shape} co={co} k={kernel} s={stride} "
                  f"compile+run {time.time()-t0:.1f}s maxerr={err:.4f}")
        except Exception as e:
            print(f"FAIL {shape} co={co} k={kernel} s={stride}: "
                  f"{type(e).__name__}: {str(e).splitlines()[0][:160]}")
    return 0


def time_layers(rng):
    """Per-shape fused-Pallas vs composed-XLA forward timing on the
    BS-256 ResNet-50 bottleneck shapes (the bench workload).  Uses the
    PRODUCTION kernel via ops.pallas_convbn so probe results transfer."""
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_convbn as pcb

    # (n, h, w, ci) -> co, kernel, stride, pad : stage-representative
    layers = [
        ((256, 56, 56, 64), 64, (3, 3), (1, 1), (1, 1)),
        ((256, 56, 56, 64), 256, (1, 1), (1, 1), (0, 0)),
        ((256, 56, 56, 256), 64, (1, 1), (1, 1), (0, 0)),
        ((256, 28, 28, 128), 128, (3, 3), (1, 1), (1, 1)),
        ((256, 28, 28, 128), 512, (1, 1), (1, 1), (0, 0)),
        ((256, 14, 14, 256), 256, (3, 3), (1, 1), (1, 1)),
        ((256, 14, 14, 1024), 256, (1, 1), (1, 1), (0, 0)),
        ((256, 7, 7, 512), 512, (3, 3), (1, 1), (1, 1)),
        ((256, 7, 7, 512), 2048, (1, 1), (1, 1), (0, 0)),
    ]
    for shape, co, kernel, stride, pad in layers:
        n, h, wd, ci = shape
        x = jnp.asarray(rng.randn(*shape).astype("float32") * 0.5,
                        jnp.bfloat16)
        w = jnp.asarray(rng.randn(co, ci, *kernel).astype("float32")
                        * 0.05, jnp.bfloat16)
        sc = jnp.asarray(rng.rand(ci).astype("float32") + 0.5)
        bi = jnp.asarray(rng.randn(ci).astype("float32") * 0.1)
        sh = jnp.asarray(rng.randn(co).astype("float32") * 0.1)
        kw = dict(kernel=kernel, stride=stride, pad=pad, act_in=True,
                  want_stats=True)
        try:
            pal = jax.jit(functools.partial(pcb._pallas_unit, **kw))
            t_pal = _time(pal, x, w, sc, bi, sh)
        except Exception as e:
            t_pal = None
            err = str(e).splitlines()[0][:100]
        xla = jax.jit(functools.partial(pcb._xla_unit, **kw))
        t_xla = _time(xla, x, w, sc, bi, sh)
        if t_pal is None:
            print(f"{shape} co={co} k={kernel}: pallas FAIL ({err}); "
                  f"xla {t_xla:.0f}us")
        else:
            print(f"{shape} co={co} k={kernel} s={stride}: "
                  f"pallas {t_pal:.0f}us  xla {t_xla:.0f}us  "
                  f"ratio {t_pal / t_xla:.2f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
