#!/usr/bin/env python
"""Collective-bandwidth benchmark (ref role: tools/bandwidth/measure.py).

Measures what the gradient-sync substrate actually delivers:

- **in-graph allreduce over the device mesh** (ICI on real multi-chip
  TPU; host shared-memory on the virtual CPU mesh): a jitted psum over a
  1-D mesh, reported as BUS bandwidth `2*(n-1)/n * bytes / time` (the
  nccl-tests `busbw` convention — hardware-limit comparable).
- **eager DCN allreduce** (`parallel.dist.allreduce_nd`, gloo) when run
  under a multi-process launch (tools/launch.py).

Usage:
    python tools/bandwidth.py                        # single process
    python tools/launch.py -n 2 python tools/bandwidth.py   # adds DCN
    python tools/bandwidth.py --sizes 1,8,64 --devices 8
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mesh_allreduce_bw(sizes_mb, n_devices=None, iters=10):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    n = min(n_devices or len(devs), len(devs))
    if n < 2:
        print(f"[bandwidth] only {n} device(s): skipping mesh allreduce")
        return []
    mesh = Mesh(np.array(devs[:n]), ("x",))
    rows = []
    for mb in sizes_mb:
        # --sizes is the PER-RANK message size (the ring-allreduce
        # convention: every device contributes an mb-MB buffer)
        elems = int(mb * (1 << 20) / 4)
        x = jnp.ones((n, max(elems, 1)), jnp.float32)
        x = jax.device_put(x, NamedSharding(mesh, P("x")))

        @jax.jit
        def allreduce(a):
            return jnp.broadcast_to(jnp.sum(a, axis=0, keepdims=True),
                                    a.shape)

        out = allreduce(x)  # compile + warm
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = allreduce(x)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
        nbytes = elems * 4
        bus_bw = 2 * (n - 1) / n * nbytes / dt / 1e9
        rows.append((f"mesh-psum x{n}", mb, dt * 1e3, bus_bw))
    return rows


def _dcn_allreduce_bw(sizes_mb, iters=5):
    from mxnet_tpu import nd
    from mxnet_tpu.parallel import dist

    dist.init()
    if dist.num_workers() < 2:
        return []
    rows = []
    n = dist.num_workers()
    for mb in sizes_mb:
        elems = int(mb * (1 << 20) / 4)
        v = nd.ones((elems,))
        dist.allreduce_nd(v)  # warm (compile + gloo connect)
        dist.barrier("bw")
        t0 = time.perf_counter()
        for _ in range(iters):
            out = dist.allreduce_nd(v)
        out.wait_to_read()
        dt = (time.perf_counter() - t0) / iters
        bus_bw = 2 * (n - 1) / n * elems * 4 / dt / 1e9
        rows.append((f"dcn-gloo x{n}", mb, dt * 1e3, bus_bw))
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sizes", default="1,16,64",
                    help="comma-separated message sizes in MB")
    ap.add_argument("--devices", type=int, default=None,
                    help="cap the mesh size (default: all local devices)")
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args(argv)
    sizes = [float(s) for s in args.sizes.split(",")]

    rows = _mesh_allreduce_bw(sizes, args.devices, args.iters)
    rows += _dcn_allreduce_bw(sizes)
    if not rows:
        print("nothing measured (1 device, 1 process)")
        return 1
    print(f"{'path':<16}{'MB':>8}{'ms':>10}{'bus GB/s':>12}")
    for path, mb, ms, bw in rows:
        print(f"{path:<16}{mb:>8g}{ms:>10.3f}{bw:>12.2f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
