"""mxblackbox — always-on crash forensics.

mxprof (PR 10) explains a *step*, mxgoodput (PR 13) prices the
*wall-clock*, mxelastic (PR 14) survives the *death* — mxblackbox
answers the question the survivor can't: **why did the job die, and
which rank died first?**

Three pieces:

  * a bounded, lock-cheap per-rank **event journal**
    (:class:`.journal.EventJournal`): ring + append-only spill file,
    unifying the streams that already exist but never meet — alert
    transitions, mxhealth events, chaos fires, retry exhaustions,
    checkpoint save/restore/commit-election, preemption stamps,
    compile-provenance misses, elastic lifecycle — each entry with
    both clocks, rank, step, and category;
  * **crash bundles** (:mod:`.bundle`) on every abnormal-exit path
    (``elastic.guard``'s PeerFailed/Preempted branches, the
    NonFiniteGradient raise, a ``sys.excepthook``/signal last-gasp
    hook, and a supervisor-side scrape for ranks that died too hard
    to write their own): journal tail + mxprof ring + goodput ledger
    + firing alerts + heartbeat ages + knob fingerprint, indexed like
    mxtriage captures;
  * **incident reconstruction** (:mod:`.postmortem`,
    ``tools/postmortem.py``): a generation's bundles merged
    cross-rank with trace_report-style clock alignment into one
    causally-ordered ``INCIDENT.json`` naming the first failing rank,
    category, step, and detection lag.

Enable with ``MXNET_BLACKBOX=1`` or :func:`enable`; the elastic
Supervisor exports both to its workers.  Disabled cost: every seam is
one falsy check on ``_ACTIVE`` (the chaos/mxgoodput precedent, held
to the 3% tier-1 overhead gate).
"""
from __future__ import annotations

import json
import os
import signal as _signal
import sys
import threading
from typing import List, Optional

from ...util import env as _env
from .bundle import (read_index, signal_name, write_bundle,
                     write_supervisor_bundle)
from .journal import EventJournal

__all__ = [
    "enable", "disable", "enabled", "journal", "emit",
    "emit_from_signal", "write_crash_bundle", "install_crash_hooks",
    "base_dir", "recent", "last_bundle", "last_incident",
    "EventJournal", "write_bundle", "write_supervisor_bundle",
    "read_index", "signal_name",
]

#: Fast-path flag: False means every seam (`if _bb._ACTIVE: ...`) is
#: one falsy check and nothing below ever runs.
_ACTIVE = False

_lock = threading.Lock()
_JOURNAL: Optional[EventJournal] = None
_LAST_BUNDLE: Optional[str] = None
_HOOKS = False
_PREV_EXCEPTHOOK = None


def base_dir() -> str:
    return _env.get_str("MXNET_BLACKBOX_DIR") or "mxblackbox"


def _rank() -> Optional[int]:
    from .. import tracing as _tracing

    return _tracing._RANK


def journal() -> EventJournal:
    """The process journal singleton.  Created lazily; recreated ONCE
    if the job rank becomes known after creation (mxtriage lesson:
    the rank qualifies the spill filename, and the supervisor scrape
    looks the dead rank's spill up BY rank)."""
    global _JOURNAL
    rank = _rank()
    j = _JOURNAL
    if j is not None and (j._rank == rank or j._rank is not None):
        return j
    with _lock:
        j = _JOURNAL
        if j is None or (j._rank is None and rank is not None):
            who = f"r{rank}" if rank is not None else f"p{os.getpid()}"
            nj = EventJournal(
                directory=base_dir(), who=who, rank=rank,
                ring=_env.get_int("MXNET_BLACKBOX_RING") or 512,
                spill_max_bytes=(
                    _env.get_int("MXNET_BLACKBOX_SPILL_MB") or 8)
                * 1024 * 1024,
                gen=_env.get_int("MXNET_BLACKBOX_GEN"))
            if j is not None:
                # carry the pre-rank history into the rank journal so
                # a bundle tail still shows startup events
                for e in j.tail(nj._ring.maxlen):
                    nj._ring.append(e)
                j.close()
            _JOURNAL = nj
        return _JOURNAL


def emit(category: str, msg: str = "", step: Optional[int] = None,
         **fields) -> Optional[dict]:
    """Journal one event (no-op unless enabled).  Seam call shape:
    ``if _bb._ACTIVE: _bb.emit("chaos", ...)`` — the flag check stays
    at the call site so the disabled path pays one attribute load."""
    if not _ACTIVE:
        return None
    try:
        entry = journal().emit(category, msg, step=step, **fields)
    except Exception:  # noqa: BLE001 — forensics never break the host path
        return None
    try:
        from .. import instruments as _ins

        _ins.blackbox_events_total(category).inc()
    except Exception:  # noqa: BLE001 — metrics are advisory here
        pass
    return entry


def emit_from_signal(category: str, msg: str = "",
                     step: Optional[int] = None, **fields) -> None:
    """Signal-handler-safe :func:`emit`: enqueue to the journal's
    daemon drainer and return.  No metric bump here — the registry
    lock must not be taken from an interrupted frame."""
    if not _ACTIVE:
        return
    try:
        journal().emit_from_signal(category, msg, step=step, **fields)
    except Exception:  # noqa: BLE001 — never raise out of a handler
        pass


def write_crash_bundle(category: str, reason: str = "",
                       step: Optional[int] = None,
                       exc: Optional[BaseException] = None,
                       exit_record: Optional[dict] = None,
                       extra: Optional[dict] = None) -> Optional[str]:
    """Emit one crash bundle for THIS process (no-op unless enabled).
    Returns the bundle directory."""
    global _LAST_BUNDLE
    if not _ACTIVE:
        return None
    d = write_bundle(category, reason=reason, base_dir=base_dir(),
                     rank=_rank(), step=step, exc=exc,
                     journal=journal(), exit_record=exit_record,
                     extra=extra)
    if d is not None:
        _LAST_BUNDLE = d
    return d


# ---------------------------------------------------------------------------
# last-gasp hooks
# ---------------------------------------------------------------------------

def _excepthook(exc_type, exc, tb):
    if _ACTIVE and not issubclass(exc_type, KeyboardInterrupt):
        try:
            if exc is not None and exc.__traceback__ is None:
                exc = exc.with_traceback(tb)
            write_crash_bundle("crash",
                               reason=f"uncaught {exc_type.__name__}",
                               exc=exc)
        except Exception:  # noqa: BLE001 — the hook must reach the chain
            pass
    hook = _PREV_EXCEPTHOOK or sys.__excepthook__
    hook(exc_type, exc, tb)


def _signal_last_gasp(signum, frame):
    """SIGABRT/SIGQUIT: journal from the handler (queue hand-off —
    the interrupted frame may hold any lock), write the bundle on a
    daemon thread with a bounded join, then die by the default
    disposition so the exit classification stays signal-resolved."""
    name = signal_name(signum)
    emit_from_signal("crash", f"fatal signal {name}", signum=signum)
    done = threading.Event()

    def _write():
        try:
            write_crash_bundle(
                "crash", reason=f"fatal signal {name}",
                exit_record={"signal": signum, "signal_name": name})
        finally:
            done.set()

    threading.Thread(target=_write, daemon=True,
                     name="mx-blackbox-lastgasp").start()
    done.wait(timeout=3.0)
    try:
        _signal.signal(signum, _signal.SIG_DFL)
        os.kill(os.getpid(), signum)
    except (OSError, ValueError):
        os._exit(128 + int(signum))


def install_crash_hooks() -> bool:
    """Chain ``sys.excepthook`` and install the SIGABRT/SIGQUIT
    last-gasp handlers (main thread only — off it, the excepthook
    still chains).  Idempotent."""
    global _HOOKS, _PREV_EXCEPTHOOK
    with _lock:
        if _HOOKS:
            return True
        _PREV_EXCEPTHOOK = sys.excepthook
        sys.excepthook = _excepthook
        for sig in (_signal.SIGABRT, _signal.SIGQUIT):
            try:
                if _signal.getsignal(sig) in (_signal.SIG_DFL, None):
                    _signal.signal(sig, _signal_last_gasp)
            except (ValueError, OSError):
                pass  # mxlint: disable=MX007 — not the main thread
        _HOOKS = True
    return True


# ---------------------------------------------------------------------------
# lifecycle + readers
# ---------------------------------------------------------------------------

def enable(hooks: bool = True) -> EventJournal:
    """Turn the journal seams on (and install the last-gasp hooks).
    Idempotent."""
    global _ACTIVE
    _ACTIVE = True
    j = journal()
    if hooks:
        install_crash_hooks()
    return j


def disable() -> None:
    """Drop the seam flag (journal and hooks stay; re-enable is
    cheap).  The disabled path is back to one falsy check."""
    global _ACTIVE
    _ACTIVE = False


def enabled() -> bool:
    return _ACTIVE


def recent(n: int = 20) -> List[dict]:
    """Newest journal entries (what /statusz shows)."""
    if _JOURNAL is None:
        return []
    return _JOURNAL.tail(n)


def last_bundle() -> Optional[str]:
    return _LAST_BUNDLE


def last_incident() -> Optional[dict]:
    """The newest INCIDENT-*.json under the blackbox dir (the
    supervisor writes them next to the bundles), abbreviated for
    /statusz.  None when there has been no incident."""
    d = base_dir()
    try:
        paths = [os.path.join(d, n) for n in os.listdir(d)
                 if n.startswith("INCIDENT") and n.endswith(".json")]
    except OSError:
        return None
    paths.sort(key=lambda p: (os.path.getmtime(p), p))
    for p in reversed(paths):
        try:
            with open(p) as f:
                rep = json.load(f)
            return {"incident_id": rep.get("incident_id"),
                    "when": rep.get("when"),
                    "first_failure": rep.get("first_failure"),
                    "path": p}
        except (OSError, ValueError):
            continue
    return None


if _env.get_bool("MXNET_BLACKBOX"):
    enable()
