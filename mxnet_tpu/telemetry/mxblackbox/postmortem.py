"""Cross-rank incident reconstruction: N crash bundles -> one
INCIDENT.json.

The supervisor (or ``tools/postmortem.py``) hands this module a
generation's bundles; it aligns the per-rank clocks, merges the
journals into one causally-ordered timeline, and attributes the FIRST
failure — which rank, which category, at which step, and how long
detection lagged behind it.

**Clock alignment** reuses the ``trace_report --merge`` technique: a
blocking collective ends near-simultaneously on every rank, so matched
occurrences of cross-rank-synchronized journal events pin each rank's
offset onto rank 0's clock.  The sync marks here are the journal
categories that record a cross-rank barrier: ``elastic`` lifecycle
events and ``checkpoint`` commit/save events, matched by
``(category, msg, k-th occurrence)`` and averaged exactly like the
trace merger's collective-end marks.  Single-host test jobs share a
wall clock, so offsets degrade gracefully to ~0 when no marks match.

**First-failure attribution** prefers direct evidence over inference,
in order: (1) the earliest *failure-class* journal/bundle event
(``chaos`` fires with a lethal action, ``crash`` bundle emissions that
are not coordinated wind-downs, ``health`` nonfinite raises); (2) a
supervisor exit record with an unreserved rc or a kill signal; (3) the
supervisor's own failed-rank classification.  Ranks that exited the
reserved rcs 43/44 are classified victims/survivors, never the first
failure — a peer observing a death is evidence OF the death, not the
death itself.
"""
from __future__ import annotations

import itertools
import json
import os
import time
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

__all__ = ["load_bundles", "reconstruct", "run_epoch"]

_SEQ = itertools.count(1)

#: journal/bundle categories that can BE a first failure (vs
#: categories that merely observe one)
_FAILURE_CATEGORIES = ("chaos", "health", "crash", "scrape")
#: crash-bundle categories that are coordinated exits, not failures
_COORDINATED = ("peer_failed", "preempted", "winddown")


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------

def load_bundles(base_dir: str,
                 gen: Optional[int] = None,
                 since_unix: Optional[float] = None) -> List[dict]:
    """Every committed bundle (has a readable ``meta.json``) under the
    blackbox dir, optionally filtered to one generation / time window.
    Returns ``[{"meta": ..., "journal": [...]}, ...]``."""
    out: List[dict] = []
    try:
        names = sorted(os.listdir(base_dir))
    except OSError:
        return out
    for name in names:
        d = os.path.join(base_dir, name)
        if not name.startswith("crash-") or not os.path.isdir(d):
            continue
        try:
            with open(os.path.join(d, "meta.json")) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            continue  # interrupted write: never committed
        if gen is not None and meta.get("gen") is not None \
                and int(meta["gen"]) != int(gen):
            continue
        if since_unix is not None and \
                (meta.get("t_unix") or 0) < since_unix:
            continue
        try:
            with open(os.path.join(d, "journal.json")) as f:
                journal = json.load(f)
        except (OSError, ValueError):
            journal = []
        out.append({"meta": meta,
                    "journal": journal if isinstance(journal, list)
                    else []})
    return out


# ---------------------------------------------------------------------------
# clock alignment (the trace_report --merge technique on journal
# events)
# ---------------------------------------------------------------------------

def _sync_marks(events: List[dict]) -> Dict[Tuple, float]:
    """{(category, msg, k): t_unix} for the k-th occurrence of each
    cross-rank-synchronized event — the journal analog of a blocking
    collective's end timestamp."""
    seen: Dict[Tuple, int] = defaultdict(int)
    marks: Dict[Tuple, float] = {}
    for ev in sorted(events, key=lambda e: e.get("t_unix", 0.0)):
        if ev.get("category") not in ("elastic", "checkpoint"):
            continue
        key = (ev.get("category"), ev.get("msg"))
        k = seen[key]
        seen[key] = k + 1
        marks[key + (k,)] = ev.get("t_unix", 0.0)
    return marks


def _offsets(per_rank: List[Tuple[int, List[dict]]]) -> Tuple[dict, dict]:
    """rank -> seconds to ADD to that rank's t_unix to land on the
    reference rank's clock (trace_report: offsets average
    ``ref_marks[c] - marks[c]`` over the common marks)."""
    if not per_rank:
        return {}, {}
    ref_rank, ref_events = per_rank[0]
    ref_marks = _sync_marks(ref_events)
    offsets = {ref_rank: 0.0}
    aligned_on = {ref_rank: None}
    for rank, events in per_rank[1:]:
        marks = _sync_marks(events)
        common = sorted(set(ref_marks) & set(marks))
        if common:
            offsets[rank] = sum(ref_marks[c] - marks[c]
                                for c in common) / len(common)
            aligned_on[rank] = len(common)
        else:
            offsets[rank] = 0.0  # nothing to align on: trust the clock
            aligned_on[rank] = 0
    return offsets, aligned_on


# ---------------------------------------------------------------------------
# reconstruction
# ---------------------------------------------------------------------------

def _failure_candidates(bundles: List[dict],
                        offsets: dict) -> List[dict]:
    """Every event that could BE the first failure, time-aligned."""
    cands: List[dict] = []
    for b in bundles:
        meta = b["meta"]
        rank = meta.get("rank")
        off = offsets.get(rank, 0.0)
        for ev in b["journal"]:
            cat = ev.get("category")
            if cat not in _FAILURE_CATEGORIES:
                continue
            if cat == "chaos" and ev.get("action") not in (
                    "die", "hang", "error"):
                continue
            cands.append({
                "rank": ev.get("rank", rank),
                "category": cat,
                "step": ev.get("step"),
                "t_unix": (ev.get("t_unix") or 0.0) + off,
                "msg": ev.get("msg", ""),
                "source": "journal",
            })
        cat = meta.get("category")
        if cat in _FAILURE_CATEGORIES and cat not in _COORDINATED:
            # a scrape bundle carries the supervisor's exit
            # classification; its own stamp time is DETECTION time,
            # so prefer the last journal entry's time when present
            t = meta.get("t_unix") or 0.0
            if b["journal"]:
                t = (b["journal"][-1].get("t_unix") or t)
            cands.append({
                "rank": meta.get("rank"),
                "category": cat if cat != "scrape" else (
                    (meta.get("exit") or {}).get("classified")
                    or "scrape"),
                "step": meta.get("step"),
                "t_unix": t + offsets.get(meta.get("rank"), 0.0),
                "msg": meta.get("reason", ""),
                "source": "bundle",
                "exit": meta.get("exit"),
            })
    cands.sort(key=lambda c: c["t_unix"])
    return cands


def reconstruct(bundles: List[dict],
                t_detect_unix: Optional[float] = None,
                failed_ranks: Optional[List[int]] = None,
                exits: Optional[dict] = None,
                epoch: Optional[int] = None,
                timeline_max: int = 200) -> dict:
    """Merge one generation's bundles into an incident report dict
    (the INCIDENT.json payload)."""
    per_rank: Dict[int, List[dict]] = {}
    for b in bundles:
        rank = b["meta"].get("rank")
        key = -1 if rank is None else int(rank)
        per_rank.setdefault(key, []).extend(b["journal"])
    ordered = sorted(per_rank.items())
    offsets, aligned_on = _offsets(ordered)

    merged: List[dict] = []
    for rank, events in ordered:
        off = offsets.get(rank, 0.0)
        for ev in events:
            e = dict(ev)
            e["t_aligned"] = (ev.get("t_unix") or 0.0) + off
            merged.append(e)
    # causal order: aligned time; ties break by (rank, mono) so one
    # rank's own events never reorder against each other
    merged.sort(key=lambda e: (e.get("t_aligned", 0.0),
                               e.get("rank") or 0,
                               e.get("t_mono") or 0.0))
    merged = merged[-max(1, int(timeline_max)):]

    cands = _failure_candidates(bundles, offsets)
    first = None
    if cands:
        first = dict(cands[0])
        if first.get("step") is None:
            # the journal fire may predate a step stamp (chaos events
            # carry the call count, not the step) — backfill from the
            # same rank+category's bundle, which does know the step
            for c in cands[1:]:
                if c.get("step") is not None \
                        and c.get("rank") == first.get("rank") \
                        and c.get("category") == first.get("category"):
                    first["step"] = c["step"]
                    break
    elif exits:
        # no direct evidence: fall back to the exit-record
        # classification (unreserved rc / kill signal), then to the
        # supervisor's failed list
        bad = [(r, x) for r, x in sorted(exits.items())
               if x.get("rc") not in (0, 43, 44)
               or x.get("signal") is not None]
        if bad:
            r, x = bad[0]
            first = {"rank": int(r), "category": "exit",
                     "step": None, "t_unix": None,
                     "msg": f"rc {x.get('rc')} signal "
                            f"{x.get('signal')}", "source": "exit"}
    if first is None and failed_ranks:
        first = {"rank": failed_ranks[0], "category": "unknown",
                 "step": None, "t_unix": None,
                 "msg": "supervisor classification only",
                 "source": "supervisor"}

    detection = None
    if t_detect_unix is not None:
        lag = None
        if first is not None and first.get("t_unix"):
            lag = round(t_detect_unix - first["t_unix"], 3)
        # the heartbeat view of the same lag: the failed rank's last
        # stamp age at detection
        hb_lag = None
        for b in bundles:
            hbs = {}
            try:
                with open(os.path.join(b["meta"]["dir"],
                                       "heartbeats.json")) as f:
                    hbs = json.load(f)
            except (OSError, ValueError, KeyError):
                continue
            if first is not None and str(first.get("rank")) in hbs:
                stamp = hbs[str(first["rank"])]
                if isinstance(stamp, dict) and "age_s" in stamp:
                    hb_lag = stamp["age_s"]
                    break
        detection = {"t_detect_unix": t_detect_unix,
                     "lag_s": lag,
                     "heartbeat_age_s": hb_lag}

    stamp = time.strftime("%Y%m%d-%H%M%S")
    fr = first.get("rank") if first else None
    incident_id = f"inc-{stamp}-e{epoch if epoch is not None else 0}" \
                  f"-r{fr if fr is not None else 'x'}-{next(_SEQ)}"
    report = {
        "incident_id": incident_id,
        "epoch": epoch,
        "when": time.strftime("%Y-%m-%d %H:%M:%S"),
        "bundles": len(bundles),
        "ranks": sorted(r for r, _ in ordered),
        "first_failure": first,
        "attributed": bool(first is not None
                           and first.get("category") not in
                           ("unknown",)),
        "detection": detection,
        "failed_ranks": sorted(failed_ranks or []),
        "exits": exits or {},
        "clock": {"offsets_s": {str(r): round(o, 6)
                                for r, o in offsets.items()},
                  "aligned_on": {str(r): a
                                 for r, a in aligned_on.items()}},
        "timeline": merged,
    }
    return report


def run_epoch(base_dir: str, epoch: int,
              gen: Optional[int] = None,
              since_unix: Optional[float] = None,
              t_detect_unix: Optional[float] = None,
              failed_ranks: Optional[List[int]] = None,
              exits: Optional[dict] = None,
              out_path: Optional[str] = None) -> Optional[dict]:
    """The supervisor entry point: reconstruct one failure epoch's
    incident from the shared blackbox dir and write
    ``INCIDENT-epoch<N>.json`` beside the bundles.  Best-effort all
    the way down — forensics must never turn a recoverable failure
    epoch into a supervisor crash."""
    try:
        bundles = load_bundles(base_dir, gen=gen,
                               since_unix=since_unix)
        report = reconstruct(bundles, t_detect_unix=t_detect_unix,
                             failed_ranks=failed_ranks, exits=exits,
                             epoch=epoch)
        path = out_path or os.path.join(base_dir,
                                        f"INCIDENT-epoch{epoch}.json")
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=1, default=repr)
        os.replace(tmp, path)
        report["path"] = path
        try:
            from .. import instruments as _ins

            cat = (report.get("first_failure") or {}).get(
                "category") or "unknown"
            _ins.incident_total(str(cat)).inc()
        except Exception:  # noqa: BLE001 — metrics never block recovery
            pass
        return report
    except Exception:  # noqa: BLE001 — see docstring
        return None
