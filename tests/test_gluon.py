"""Gluon tests (model: tests/python/unittest/test_gluon.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd, gluon
from mxnet_tpu.gluon import nn


def test_parameter_basic():
    p = gluon.Parameter("weight", shape=(3, 4))
    p.initialize(init=mx.initializer.One(), ctx=mx.cpu())
    assert p.data().shape == (3, 4)
    assert p.data().asnumpy().sum() == 12
    assert p.list_ctx() == [mx.cpu()]
    assert p.grad().asnumpy().sum() == 0


def test_parameter_deferred_and_error():
    p = gluon.Parameter("w", shape=(0, 4), allow_deferred_init=True)
    p.initialize(ctx=mx.cpu())
    with pytest.raises(gluon.parameter.DeferredInitializationError):
        p.data()
    p.shape = (2, 4)
    p._finish_deferred_init()
    assert p.data().shape == (2, 4)
    q = gluon.Parameter("q", shape=(3,))
    with pytest.raises(mx.MXNetError):
        q.data()


def test_dense_shapes_and_flatten():
    d = nn.Dense(5, in_units=3)
    d.initialize()
    assert d(nd.ones((2, 3))).shape == (2, 5)
    d2 = nn.Dense(5)  # deferred
    d2.initialize()
    assert d2(nd.ones((4, 2, 3))).shape == (4, 5)  # flatten=True
    d3 = nn.Dense(5, flatten=False)
    d3.initialize()
    assert d3(nd.ones((4, 2, 3))).shape == (4, 2, 5)


def test_sequential_and_children():
    net = nn.Sequential()
    net.add(nn.Dense(4), nn.Dense(2))
    assert len(net) == 2
    net.initialize()
    assert net(nd.ones((3, 7))).shape == (3, 2)


def test_hybrid_matches_eager():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = nd.random.uniform(shape=(5, 8))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-5, atol=1e-6)


def test_hybrid_gradients_match_eager():
    def build():
        net = nn.HybridSequential()
        net.add(nn.Dense(8, activation="tanh"), nn.Dense(1))
        return net

    mx.random.seed(7)
    np.random.seed(7)
    x = nd.random.uniform(shape=(4, 6))
    grads = []
    for hybridize in (False, True):
        np.random.seed(7)
        net = build()
        net.initialize()
        if hybridize:
            net.hybridize()
        with autograd.record():
            y = net(x)
            loss = (y * y).sum()
        loss.backward()
        p = list(net.collect_params().values())[0]
        grads.append(p.grad(p.list_ctx()[0]).asnumpy())
    np.testing.assert_allclose(grads[0], grads[1], rtol=1e-4, atol=1e-5)


def test_batchnorm_running_stats_and_eval():
    bn = nn.BatchNorm(in_channels=3)
    bn.initialize()
    x = nd.random.normal(loc=5, scale=2, shape=(8, 3, 4, 4))
    with autograd.record():
        bn(x)
    rm = bn.running_mean.data().asnumpy()
    assert np.abs(rm).sum() > 0  # stats moved toward batch mean
    out_eval = bn(x)  # eval mode uses running stats
    assert out_eval.shape == x.shape


def test_conv_pool_stack():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, 3, padding=1, in_channels=2), nn.MaxPool2D(),
            nn.GlobalAvgPool2D(), nn.Flatten())
    net.initialize()
    assert net(nd.ones((2, 2, 8, 8))).shape == (2, 4)


def test_embedding():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    out = emb(nd.array([[1, 2], [3, 4]]))
    assert out.shape == (2, 2, 4)


def test_losses():
    l2 = gluon.loss.L2Loss()
    pred = nd.array([[1.0], [2.0]])
    label = nd.array([1.5, 1.0])
    np.testing.assert_allclose(l2(pred, label).asnumpy(),
                               [0.125, 0.5], rtol=1e-5)
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    logits = nd.array([[10.0, 0.0], [0.0, 10.0]])
    labels = nd.array([0.0, 1.0])
    assert ce(logits, labels).asnumpy().max() < 0.01
    l1 = gluon.loss.L1Loss()
    np.testing.assert_allclose(l1(pred, label).asnumpy(), [0.5, 1.0])


def test_trainer_step_sgd():
    net = nn.Dense(1, in_units=2, use_bias=False)
    net.initialize(mx.initializer.One())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = nd.ones((1, 2))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(1)
    # w <- w - 0.1 * 1 = 0.9
    np.testing.assert_allclose(net.weight.data().asnumpy(),
                               [[0.9, 0.9]], rtol=1e-6)


def test_trainer_save_load_states(tmp_path):
    net = nn.Dense(2, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    x = nd.ones((1, 2))
    with autograd.record():
        net(x).sum().backward()
    trainer.step(1)
    f = str(tmp_path / "t.states")
    trainer.save_states(f)
    trainer.load_states(f)


def test_mlp_training_converges():
    np.random.seed(0)
    mx.random.seed(0)
    X = np.random.randn(256, 10).astype("float32")
    w = np.random.randn(10, 3).astype("float32")
    y = (X @ w).argmax(axis=1).astype("float32")
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(3))
    net.initialize(mx.initializer.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    data, label = nd.array(X), nd.array(y)
    for _ in range(60):
        with autograd.record():
            loss = loss_fn(net(data), label)
        loss.backward()
        trainer.step(256)
    acc = float((net(data).argmax(axis=1) == label).mean().asscalar())
    assert acc > 0.9, f"accuracy {acc}"


def test_save_load_parameters_structural(tmp_path):
    f = str(tmp_path / "p.params")
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net.initialize()
    net.save_parameters(f)
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net2.load_parameters(f)
    x = nd.ones((1, 3))
    np.testing.assert_allclose(net(x).asnumpy(), net2(x).asnumpy(), rtol=1e-6)


def test_constant_parameter():
    class Net(nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.const = self.params.get_constant(
                "const", np.array([[1.0, 2.0]]))

        def hybrid_forward(self, F, x, const):
            return x + const

    net = Net()
    net.initialize()
    out = net(nd.zeros((1, 2)))
    np.testing.assert_allclose(out.asnumpy(), [[1, 2]])


def test_lstm_layer_shapes_and_grad():
    lstm = gluon.rnn.LSTM(8, num_layers=2, input_size=4)
    lstm.initialize()
    x = nd.random.uniform(shape=(6, 2, 4))
    out, states = lstm(x, lstm.begin_state(batch_size=2))
    assert out.shape == (6, 2, 8)
    assert states[0].shape == (2, 2, 8) and states[1].shape == (2, 2, 8)
    with autograd.record():
        out, _ = lstm(x, lstm.begin_state(batch_size=2))
        out.sum().backward()
    p = lstm.l0_i2h_weight
    assert float(p.grad(p.list_ctx()[0]).norm().asscalar()) > 0


def test_gru_cell_vs_manual():
    cell = gluon.rnn.GRUCell(4, input_size=4)
    cell.initialize()
    x = nd.random.uniform(shape=(2, 4))
    h = nd.zeros((2, 4))
    out, (h1,) = cell(x, [h])
    assert out.shape == (2, 4)
    np.testing.assert_allclose(out.asnumpy(), h1.asnumpy())


def test_rnn_fused_matches_cell():
    """Fused scan RNN == explicit cell unroll (rnn_relu, 1 layer)."""
    mx.random.seed(3)
    fused = gluon.rnn.RNN(5, num_layers=1, activation="relu", input_size=3)
    fused.initialize()
    x = nd.random.uniform(shape=(4, 2, 3))
    out, _ = fused(x, fused.begin_state(batch_size=2))
    wi = fused.l0_i2h_weight.data().asnumpy()
    wh = fused.l0_h2h_weight.data().asnumpy()
    bi = fused.l0_i2h_bias.data().asnumpy()
    bh = fused.l0_h2h_bias.data().asnumpy()
    h = np.zeros((2, 5), "float32")
    outs = []
    for t in range(4):
        h = np.maximum(x.asnumpy()[t] @ wi.T + bi + h @ wh.T + bh, 0)
        outs.append(h)
    np.testing.assert_allclose(out.asnumpy(), np.stack(outs), rtol=1e-4,
                               atol=1e-5)


def test_split_and_load():
    data = nd.arange(0, 12).reshape((6, 2))
    parts = gluon.utils.split_and_load(data, [mx.cpu(0)])
    assert parts[0].shape == (6, 2)
    with pytest.raises(mx.MXNetError):
        gluon.utils.split_data(nd.ones((5, 2)), 2)


def test_clip_global_norm():
    arrays = [nd.ones((2, 2)) * 3, nd.ones((2,)) * 4]
    norm = gluon.utils.clip_global_norm(arrays, 1.0)
    assert norm > 1.0
    total = sum(float((a * a).sum().asscalar()) for a in arrays)
    assert abs(total - 1.0) < 1e-3


def test_dataloader_and_dataset():
    X = np.arange(20, dtype="float32").reshape(10, 2)
    y = np.arange(10, dtype="float32")
    ds = gluon.data.ArrayDataset(X, y)
    assert len(ds) == 10
    loader = gluon.data.DataLoader(ds, batch_size=4, last_batch="keep")
    batches = list(loader)
    assert len(batches) == 3
    assert batches[0][0].shape == (4, 2)
    assert batches[2][0].shape == (2, 2)
    loader2 = gluon.data.DataLoader(ds, batch_size=4, shuffle=True,
                                    last_batch="discard", num_workers=2)
    assert sum(1 for _ in loader2) == 2


@pytest.mark.slow  # ~37s: 10 model-zoo builds + fwd; nightly integration stage
def test_model_zoo_smoke():
    for name in ("resnet18_v1", "resnet18_v2", "mobilenet0.25",
                 "squeezenet1.1", "vgg11", "alexnet", "densenet121",
                 "inceptionv3", "mobilenetv2_1.0", "vgg11_bn"):
        net = gluon.model_zoo.get_model(name, classes=4)
        net.initialize()
        # fixed global-pool geometries (same as the reference zoo):
        # inception needs 299², densenet 224²; the rest accept 64²
        size = {"inceptionv3": 299, "densenet121": 224}.get(name, 64)
        out = net(nd.random.uniform(shape=(1, 3, size, size)))
        assert out.shape == (1, 4), name


@pytest.mark.slow  # ~31s: bf16 train step across every zoo family; nightly
def test_model_zoo_bf16_train_step():
    """Every family must survive a bf16 hybridized train step (the MXU
    dtype path used by the benchmarks)."""
    from mxnet_tpu import autograd

    for name in ("resnet18_v1", "mobilenet0.25", "squeezenet1.1"):
        net = gluon.model_zoo.get_model(name, classes=4)
        net.initialize()
        net(nd.random.uniform(shape=(1, 3, 64, 64)))
        net.cast("bfloat16")
        net.hybridize()
        x = nd.random.uniform(shape=(2, 3, 64, 64)).astype("bfloat16")
        with autograd.record():
            out = net(x)
            loss = out.astype("float32").sum()
        loss.backward()
        assert np.isfinite(float(loss.asnumpy())), name


# ---------------------------------------------------------------------------
# SymbolBlock (ref: gluon/block.py::SymbolBlock + imports)
# ---------------------------------------------------------------------------

def _sb_symbol():
    data = mx.sym.var("data")
    h = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    h = mx.sym.Activation(h, act_type="relu", name="relu1")
    return mx.sym.FullyConnected(h, num_hidden=3, name="fc2")


def test_symbol_block_forward_and_grad():
    from mxnet_tpu.gluon import SymbolBlock

    sym = _sb_symbol()
    blk = SymbolBlock(sym, [mx.sym.var("data")])
    blk.initialize(mx.initializer.Xavier())
    x = mx.nd.array(np.random.RandomState(0).randn(4, 5).astype("f4"))
    out = blk(x)
    assert out.shape == (4, 3)
    # autograd tapes the imperative evaluation
    with mx.autograd.record():
        loss = (blk(x) ** 2).sum()
    loss.backward()
    g = blk.params.get("fc1_weight").grad().asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0
    # trainable end to end
    from mxnet_tpu.gluon import Trainer

    trainer = Trainer(blk.collect_params(), "sgd",
                      {"learning_rate": 0.1})
    before = float((blk(x) ** 2).sum().asnumpy())
    for _ in range(5):
        with mx.autograd.record():
            loss = (blk(x) ** 2).sum()
        loss.backward()
        trainer.step(4)
    after = float((blk(x) ** 2).sum().asnumpy())
    assert after < before


def test_symbol_block_imports_roundtrip(tmp_path):
    from mxnet_tpu.gluon import SymbolBlock

    sym = _sb_symbol()
    # materialize params by binding once
    rng = np.random.RandomState(1)
    shapes, _, _ = sym.infer_shape(data=(2, 5))
    args = {n: mx.nd.array(rng.randn(*s).astype("f4") * 0.2)
            for n, s in zip(sym.list_arguments(), shapes)
            if n != "data"}
    sym.save(str(tmp_path / "net-symbol.json"))
    mx.nd.save(str(tmp_path / "net.params"),
               {f"arg:{k}": v for k, v in args.items()})
    blk = SymbolBlock.imports(str(tmp_path / "net-symbol.json"), "data",
                              str(tmp_path / "net.params"))
    x = mx.nd.array(rng.randn(2, 5).astype("f4"))
    out = blk(x)
    # matches the raw executor on the same weights
    exe = sym.bind(mx.cpu(), dict(args, data=x), grad_req="null")
    np.testing.assert_allclose(out.asnumpy(),
                               exe.forward()[0].asnumpy(), rtol=1e-5)
    with pytest.warns(UserWarning, match="no effect"):
        blk.hybridize()  # cascaded hybridize must not crash parents


def test_amp_convert_and_loss_scaler():
    """contrib.amp: bf16 conversion keeps norm params fp32; the dynamic
    loss scaler scales/unscales and backs off on overflow."""
    from mxnet_tpu.contrib import amp
    from mxnet_tpu.gluon import Trainer, nn

    amp.init("float16")  # maps to bfloat16, the TPU half type
    net = nn.Sequential()
    net.add(nn.Dense(8, in_units=4))
    net.add(nn.BatchNorm(in_channels=8))
    net.initialize()
    amp.convert_hybrid_block(net)
    assert "bfloat16" in str(net[0].weight.data().dtype)
    assert net[1].gamma.data().dtype == np.float32  # norm stays fp32
    assert net[1].running_mean.data().dtype == np.float32

    # scaler protocol on an fp32 net (explicit fp16-style scaling)
    net2 = nn.Sequential()
    net2.add(nn.Dense(2, in_units=3))
    net2.initialize()
    trainer = Trainer(net2.collect_params(), "sgd",
                      {"learning_rate": 0.0})
    amp.init_trainer(trainer, init_scale=128.0)
    x = mx.nd.array(np.ones((2, 3), np.float32))
    with mx.autograd.record():
        loss = (net2(x) ** 2).sum()
        with amp.scale_loss(loss, trainer) as scaled:
            pass
    scaled.backward()
    g_scaled = net2[0].weight.grad().asnumpy().copy()
    amp.unscale(trainer)
    g = net2[0].weight.grad().asnumpy()
    np.testing.assert_allclose(g, g_scaled / 128.0, rtol=1e-6)
    assert trainer._amp_loss_scaler.loss_scale == 128.0

    # overflow backs the scale off
    net2[0].weight.grad()[:] = np.inf
    amp.unscale(trainer)
    assert trainer._amp_loss_scaler.loss_scale == 64.0


def test_amp_convert_model_symbolic():
    from mxnet_tpu.contrib import amp

    sym = _sb_symbol()
    rng = np.random.RandomState(0)
    shapes, _, _ = sym.infer_shape(data=(2, 5))
    args = {n: mx.nd.array(rng.randn(*s).astype("f4"))
            for n, s in zip(sym.list_arguments(), shapes) if n != "data"}
    _, qargs, _ = amp.convert_model(sym, args, {})
    assert all("bfloat16" in str(v.dtype) for v in qargs.values())


def test_symbol_block_rnn_dropout_live_and_scaler_stays_noop():
    from mxnet_tpu.gluon import SymbolBlock

    data = mx.sym.var("data")
    out = mx.sym.RNN(data, state_size=6, num_layers=2, mode="lstm", p=0.9,
                     state_outputs=False, name="l")
    blk = SymbolBlock(out, [mx.sym.var("data")])
    blk.initialize(mx.initializer.Xavier())
    x = mx.nd.array(np.random.RandomState(0).randn(4, 3, 2).astype("f4"))
    with mx.autograd.record():  # train mode: dropout must fire
        a = blk(x).asnumpy()
    b = blk(x).asnumpy()  # eval mode: deterministic
    c = blk(x).asnumpy()
    assert not np.allclose(a, b)
    np.testing.assert_allclose(b, c)

    # bf16 default scaler must NEVER self-activate
    from mxnet_tpu.contrib.amp import LossScaler

    s = LossScaler()  # init_scale=1 -> disabled
    for _ in range(3000):
        s.update_scale(False)
    assert s.loss_scale == 1.0


def test_nd_kwarg_typo_is_loud():
    x = mx.nd.zeros((2, 2, 3))
    p = mx.nd.zeros((100,))
    with pytest.raises(mx.MXNetError, match="no input or attribute"):
        mx.nd.RNN(x, p, state_cel=mx.nd.zeros((1, 2, 4)), state_size=4)


def test_gradient_mirroring_remat():
    """hybridize(mirror=True) (ref: MXNET_BACKWARD_DO_MIRROR) wraps the
    backward in jax.checkpoint — identical gradients, recomputed
    activations."""
    import os

    from mxnet_tpu.gluon import nn

    def build(mirror):
        np.random.seed(0)
        net = nn.Sequential()
        net.add(nn.Dense(16, activation="relu", in_units=8))
        net.add(nn.Dense(4, in_units=16))
        net.initialize(mx.initializer.Xavier())
        net.hybridize(mirror=mirror)
        return net

    x = mx.nd.array(np.random.RandomState(1).randn(4, 8).astype("f4"))
    grads = []
    for mirror in (False, True):
        net = build(mirror)
        with mx.autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        grads.append(net[0].weight.grad().asnumpy())
    np.testing.assert_allclose(grads[0], grads[1], rtol=1e-5)

    # remat segments really exist: the mirrored pure function's jaxpr
    # contains checkpoint/remat primitives, the plain one does not
    import jax

    net_m = build(True)
    net_m(x)  # builds the CachedOp
    cop = net_m[0]._cached_op
    pure = cop._pure[False]
    import jax.numpy as jnp

    pv = tuple(p.data().data for _, p in cop._param_list())
    jaxpr = str(jax.make_jaxpr(
        lambda p, i, k: pure(p, i, k))(pv, (x.data,), jnp.zeros(
            (2,), jnp.uint32)))
    assert "remat" in jaxpr or "checkpoint" in jaxpr


def test_gradient_mirroring_with_batchnorm_aux():
    """mirror=True with BatchNorm: aux updates cross the checkpoint
    boundary (returned, not leaked) and moving stats still advance."""
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4))
    net.add(nn.BatchNorm(in_channels=8))
    net.add(nn.Dense(2, in_units=8))
    net.initialize(mx.initializer.Xavier())
    net.hybridize(mirror=True)
    x = mx.nd.array(np.random.RandomState(0).randn(16, 4)
                    .astype("f4") * 2 + 1)
    before = net[1].running_mean.data().asnumpy().copy()
    with mx.autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    after = net[1].running_mean.data().asnumpy()
    assert not np.allclose(before, after)  # aux stats advanced
    assert np.isfinite(net[0].weight.grad().asnumpy()).all()


def test_gradient_mirroring_env_route(monkeypatch):
    from mxnet_tpu.gluon import nn

    monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", "1")
    net = nn.Dense(4, in_units=3)
    net.initialize()
    net.hybridize()
    x = mx.nd.array(np.ones((2, 3), np.float32))
    with mx.autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    assert net._cached_op.mirror
    assert np.isfinite(net.weight.grad().asnumpy()).all()
