"""SSD training example (BASELINE config 4: SSD-ResNet50).

Synthetic-data training loop over the full detection stack: SSD model →
SSDTargetGenerator (MultiBoxTarget) → SSDMultiBoxLoss → Trainer, then
MultiBoxDetection decode.  The reference-era equivalent is
example/ssd/train.py.

Usage:
  python examples/ssd_train.py                 # TPU, resnet50 backbone
  python examples/ssd_train.py --cpu --small   # CPU smoke (CI)
"""
from __future__ import annotations

import argparse
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--small", action="store_true",
                    help="mobilenet backbone, 128px, for smoke tests")
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--classes", type=int, default=20)
    ap.add_argument("--no-hybridize", action="store_true")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.gluon import Trainer
    from mxnet_tpu.gluon.model_zoo.detection import (
        SSDMultiBoxLoss, SSDTargetGenerator, get_detection_model)

    ctx = mx.cpu() if args.cpu else mx.tpu(0)
    size = 128 if args.small else 300
    name = "ssd_300_mobilenet1.0" if args.small else "ssd_300_resnet50_v1"
    net = get_detection_model(name, classes=args.classes)
    net.initialize(mx.initializer.Xavier(), ctx=ctx)
    if not args.no_hybridize:
        net.hybridize(static_alloc=True)

    target_gen = SSDTargetGenerator()
    loss_fn = SSDMultiBoxLoss()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 1e-3, "momentum": 0.9, "wd": 5e-4})

    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(args.batch_size, 3, size, size).astype("float32"),
                 ctx=ctx)
    labels = nd.array(
        np.stack([[[rng.randint(args.classes), 0.2, 0.2, 0.7, 0.7]]
                  for _ in range(args.batch_size)]).astype("float32"), ctx=ctx)

    for step in range(args.steps):
        tic = time.time()
        with autograd.record():
            cls_preds, box_preds, anchors = net(x)
            box_t, _box_m, cls_t = target_gen(anchors, labels, cls_preds)
            loss = loss_fn(cls_preds, box_preds, cls_t, box_t)
        loss.backward()
        trainer.step(args.batch_size)
        lval = float(loss.asnumpy().mean())
        print(f"step {step}: loss={lval:.4f} ({time.time() - tic:.2f}s)")

    # decode detections for the final batch
    out = nd.MultiBoxDetection(
        nd.transpose(nd.softmax(cls_preds, axis=-1), axes=(0, 2, 1)),
        nd.reshape(box_preds, shape=(0, -1)), anchors, nms_topk=100)
    kept = (out.asnumpy()[:, :, 0] >= 0).sum()
    print(f"decoded {out.shape} detections, {kept} kept after NMS")


if __name__ == "__main__":
    main()
