"""Symbol + GraphExecutor tests.

Model: tests/python/unittest/test_symbol.py, test_executor.py,
test_infer_shape.py in the reference.
"""
import json

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import symbol as sym
from mxnet_tpu.test_utils import assert_almost_equal


def _mlp():
    data = sym.var("data")
    fc1 = sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(act, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(fc2, name="softmax")


def test_compose_and_list_arguments():
    out = _mlp()
    assert out.list_arguments() == [
        "data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
        "softmax_label"]
    assert out.list_outputs() == ["softmax_output"]
    assert out.list_auxiliary_states() == []


def test_infer_shape_bidirectional():
    out = _mlp()
    arg_shapes, out_shapes, aux_shapes = out.infer_shape(data=(8, 10))
    args = dict(zip(out.list_arguments(), arg_shapes))
    assert args["fc1_weight"] == (16, 10)
    assert args["fc1_bias"] == (16,)
    assert args["fc2_weight"] == (4, 16)
    assert args["softmax_label"] == (8,)
    assert out_shapes == [(8, 4)]


def test_infer_shape_conv():
    data = sym.var("data")
    c1 = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                         name="conv1")
    p = sym.Pooling(c1, kernel=(2, 2), stride=(2, 2), pool_type="max",
                    name="pool1")
    arg_shapes, out_shapes, _ = p.infer_shape(data=(2, 3, 8, 8))
    args = dict(zip(p.list_arguments(), arg_shapes))
    assert args["conv1_weight"] == (8, 3, 3, 3)
    assert args["conv1_bias"] == (8,)
    assert out_shapes == [(2, 8, 4, 4)]


def test_operators_on_symbols():
    a = sym.var("a")
    b = sym.var("b")
    c = (a + b) * 2.0 - b / 2.0
    ex = c.bind(mx.cpu(), {"a": nd.array([1.0, 2.0]),
                           "b": nd.array([3.0, 4.0])})
    out = ex.forward()[0]
    assert_almost_equal(out, np.array([6.5, 10.0], "float32"))


def test_executor_forward_matches_numpy():
    out = _mlp()
    ex = out.simple_bind(mx.cpu(), data=(8, 10))
    rng = np.random.RandomState(0)
    x = rng.randn(8, 10).astype("float32")
    w1 = rng.randn(16, 10).astype("float32") * 0.1
    b1 = np.zeros(16, "float32")
    w2 = rng.randn(4, 16).astype("float32") * 0.1
    b2 = np.zeros(4, "float32")
    ex.copy_params_from({"fc1_weight": w1, "fc1_bias": b1,
                         "fc2_weight": w2, "fc2_bias": b2})
    res = ex.forward(data=x)[0].asnumpy()
    h = np.maximum(x @ w1.T + b1, 0)
    logits = h @ w2.T + b2
    e = np.exp(logits - logits.max(1, keepdims=True))
    assert_almost_equal(res, e / e.sum(1, keepdims=True), rtol=1e-4,
                        atol=1e-5)


def test_executor_backward_softmax_grad():
    out = _mlp()
    ex = out.simple_bind(mx.cpu(), data=(4, 6))
    rng = np.random.RandomState(1)
    params = {"fc1_weight": rng.randn(16, 6).astype("float32") * 0.3,
              "fc1_bias": rng.randn(16).astype("float32") * 0.1,
              "fc2_weight": rng.randn(4, 16).astype("float32") * 0.3,
              "fc2_bias": rng.randn(4).astype("float32") * 0.1}
    ex.copy_params_from(params)
    x = rng.randn(4, 6).astype("float32")
    y = np.array([0, 1, 2, 3], "float32")
    probs = ex.forward(is_train=True, data=x, softmax_label=y)[0].asnumpy()
    ex.backward()
    # SoftmaxOutput gradient wrt logits is (p - onehot)/... — check via the
    # chain into fc2_bias: dL/db2 = sum_b (p - y_onehot)
    onehot = np.eye(4, dtype="float32")[y.astype(int)]
    expect_db2 = (probs - onehot).sum(0)
    assert_almost_equal(ex.grad_dict["fc2_bias"], expect_db2, rtol=1e-3,
                        atol=1e-4)
    # data grad exists and label grad_req is honored
    assert ex.grad_dict["data"] is not None


def test_executor_explicit_out_grads():
    a = sym.var("a")
    b = sym.var("b")
    c = a * b
    ex = c.bind(mx.cpu(), {"a": nd.array([1.0, 2.0]),
                           "b": nd.array([3.0, 4.0])})
    ex.forward(is_train=True)
    ex.backward(out_grads=nd.array([10.0, 10.0]))
    assert_almost_equal(ex.grad_dict["a"], np.array([30.0, 40.0], "float32"))
    assert_almost_equal(ex.grad_dict["b"], np.array([10.0, 20.0], "float32"))


def test_grad_req_add_and_null():
    a = sym.var("a")
    c = a * 2.0
    ex = c.bind(mx.cpu(), {"a": nd.array([1.0])}, grad_req="add")
    ex.forward(is_train=True)
    ex.backward()
    ex.forward(is_train=True)
    ex.backward()
    assert_almost_equal(ex.grad_dict["a"], np.array([4.0], "float32"))
    ex2 = c.bind(mx.cpu(), {"a": nd.array([1.0])}, grad_req="null")
    ex2.forward(is_train=True)
    ex2.backward()
    assert ex2.grad_dict["a"] is None


def test_batchnorm_aux_states_update():
    data = sym.var("data")
    bn = sym.BatchNorm(data, name="bn0")
    assert bn.list_arguments() == ["data", "bn0_gamma", "bn0_beta"]
    assert bn.list_auxiliary_states() == ["bn0_moving_mean", "bn0_moving_var"]
    ex = bn.simple_bind(mx.cpu(), data=(6, 3, 4, 4))
    ex.copy_params_from({"bn0_gamma": np.ones(3, "float32"),
                         "bn0_beta": np.zeros(3, "float32")})
    x = np.random.randn(6, 3, 4, 4).astype("float32") + 2.0
    before = ex.aux_dict["bn0_moving_mean"].asnumpy().copy()
    ex.forward(is_train=True, data=x)
    ex.backward()
    after = ex.aux_dict["bn0_moving_mean"].asnumpy()
    assert not np.allclose(before, after)
    expect = 0.9 * before + 0.1 * x.mean(axis=(0, 2, 3))
    assert_almost_equal(after, expect, rtol=1e-4, atol=1e-5)


def test_dropout_train_vs_inference():
    data = sym.var("data")
    d = sym.Dropout(data, p=0.5, name="drop0")
    ex = d.bind(mx.cpu(), {"data": nd.ones((1000,))})
    out = ex.forward()[0].asnumpy()
    assert_almost_equal(out, np.ones(1000, "float32"))
    out_t = ex.forward(is_train=True)[0].asnumpy()
    kept = out_t > 0
    assert 0.3 < kept.mean() < 0.7


def test_group_and_getitem():
    a = sym.var("a")
    s1 = a * 2.0
    s2 = a + 1.0
    g = sym.Group([s1, s2])
    assert len(g.list_outputs()) == 2
    ex = g.bind(mx.cpu(), {"a": nd.array([1.0, 2.0])})
    o1, o2 = ex.forward()
    assert_almost_equal(o1, np.array([2.0, 4.0], "float32"))
    assert_almost_equal(o2, np.array([2.0, 3.0], "float32"))
    s = g[1]
    assert s.list_outputs() == g.list_outputs()[1:2]


def test_json_roundtrip(tmp_path):
    out = _mlp()
    path = str(tmp_path / "net-symbol.json")
    out.save(path)
    loaded = sym.load(path)
    assert loaded.list_arguments() == out.list_arguments()
    assert loaded.list_outputs() == out.list_outputs()
    # loaded symbol still executes
    ex = loaded.simple_bind(mx.cpu(), data=(2, 10))
    ex.forward(data=np.random.randn(2, 10).astype("float32"))
    assert ex.outputs[0].shape == (2, 4)


def test_get_internals():
    out = _mlp()
    internals = out.get_internals()
    names = internals.list_outputs()
    assert "fc1_output" in names
    fc1 = internals["fc1_output"]
    arg_shapes, out_shapes, _ = fc1.infer_shape(data=(2, 10))
    assert out_shapes == [(2, 16)]


def test_embedding_symbol():
    data = sym.var("data")
    emb = sym.Embedding(data, input_dim=20, output_dim=5, name="embed0")
    assert emb.list_arguments() == ["data", "embed0_weight"]
    arg_shapes, out_shapes, _ = emb.infer_shape(data=(3, 7))
    assert dict(zip(emb.list_arguments(), arg_shapes))["embed0_weight"] == (20, 5)
    assert out_shapes == [(3, 7, 5)]


def test_load_reference_format_json():
    """Regression: a GENUINE reference ``-symbol.json`` carries ONLY
    op/name/attrs/inputs per node (attrs as plain strings, possibly under
    the legacy ``param`` key) — num_outputs / aux-ness / shapes are never
    stored and must be re-derived on load."""
    ref_json = json.dumps({
        "nodes": [
            {"op": "null", "name": "data", "inputs": []},
            {"op": "null", "name": "conv_weight", "inputs": []},
            {"op": "Convolution", "name": "conv",
             "attrs": {"kernel": "(3, 3)", "num_filter": "8",
                       "pad": "(1, 1)", "no_bias": "True"},
             "inputs": [[0, 0, 0], [1, 0, 0]]},
            {"op": "null", "name": "bn_gamma", "inputs": []},
            {"op": "null", "name": "bn_beta", "inputs": []},
            {"op": "null", "name": "bn_moving_mean", "inputs": []},
            {"op": "null", "name": "bn_moving_var", "inputs": []},
            {"op": "BatchNorm", "name": "bn",
             # legacy key + legacy 2-long input entries
             "param": {"eps": "0.001", "momentum": "0.9"},
             "inputs": [[2, 0], [3, 0], [4, 0], [5, 0], [6, 0]]},
            {"op": "relu", "name": "act", "inputs": [[7, 0, 0]]},
        ],
        "arg_nodes": [0, 1, 3, 4, 5, 6],
        "node_row_ptr": list(range(10)),
        "heads": [[8, 0, 0]],
        "attrs": {"mxnet_version": ["int", 10700]},
    })
    loaded = sym.load_json(ref_json)
    # aux-ness re-derived from the BatchNorm schema, not from JSON fields
    assert loaded.list_arguments() == ["data", "conv_weight", "bn_gamma",
                                       "bn_beta"]
    assert loaded.list_auxiliary_states() == ["bn_moving_mean",
                                              "bn_moving_var"]
    # attrs parsed from reference string form ("(3, 3)", "8", "True")
    arg_shapes, out_shapes, aux_shapes = loaded.infer_shape(
        data=(2, 3, 8, 8))
    assert out_shapes == [(2, 8, 8, 8)]
    assert aux_shapes == [(8,), (8,)]
    # and it executes
    ex = loaded.simple_bind(mx.cpu(), data=(2, 3, 8, 8))
    ex.forward(data=np.random.randn(2, 3, 8, 8).astype("float32"))
    assert ex.outputs[0].shape == (2, 8, 8, 8)


def test_tojson_is_reference_format():
    """Our own save must not leak non-reference node fields."""
    out = _mlp()
    data = json.loads(out.tojson())
    for node in data["nodes"]:
        assert set(node) <= {"op", "name", "attrs", "inputs"}
        for v in node.get("attrs", {}).values():
            assert isinstance(v, str)
    assert "node_row_ptr" in data
    # multi-output node round-trips via registry-derived num_outputs
    x = sym.var("x")
    s = sym.SliceChannel(x, num_outputs=3) if hasattr(sym, "SliceChannel") \
        else None
    if s is not None:
        loaded = sym.load_json(s.tojson())
        assert len(loaded.list_outputs()) == 3


def test_load_json_merges_param_and_attr():
    """Legacy nodes split op params ("param") from user attrs ("attr");
    both must survive the load (e.g. __shape__ hints on variables)."""
    ref_json = json.dumps({
        "nodes": [
            {"op": "null", "name": "x", "inputs": [],
             "param": {}, "attr": {"__shape__": "(2, 5)"}},
            {"op": "null", "name": "fc_weight", "inputs": []},
            {"op": "null", "name": "fc_bias", "inputs": []},
            {"op": "FullyConnected", "name": "fc",
             "param": {"num_hidden": "3"}, "attr": {"__lr_mult__": "2.0"},
             "inputs": [[0, 0, 0], [1, 0, 0], [2, 0, 0]]},
        ],
        "arg_nodes": [0, 1, 2],
        "heads": [[3, 0, 0]],
    })
    loaded = sym.load_json(ref_json)
    # __shape__ became the variable's hint; fc num_hidden parsed from param
    arg_shapes, out_shapes, _ = loaded.infer_shape_partial()
    assert out_shapes == [(2, 3)]
    # unknown future op still loads for inspection, fails only at bind
    alien = json.dumps({
        "nodes": [{"op": "null", "name": "d", "inputs": []},
                  {"op": "SomeFutureOp", "name": "f", "attrs": {},
                   "inputs": [[0, 0, 0]]}],
        "arg_nodes": [0], "heads": [[1, 0, 0]],
    })
    s2 = sym.load_json(alien)
    assert s2.list_arguments() == ["d"]


def test_load_json_legacy_encoding():
    """Files saved before the reference-format switch used json.dumps attr
    values ("false", "[3, 3]") and a top-level shape_hint field — they
    must still load correctly."""
    legacy = json.dumps({
        "nodes": [
            {"op": "null", "name": "data", "inputs": [],
             "shape_hint": [2, 3, 8, 8]},
            {"op": "null", "name": "c_weight", "inputs": []},
            {"op": "null", "name": "c_bias", "inputs": []},
            {"op": "Convolution", "name": "c",
             "attrs": {"kernel": "[3, 3]", "num_filter": "4",
                       "pad": "[1, 1]", "no_bias": "false"},
             "inputs": [[0, 0, 0], [1, 0, 0], [2, 0, 0]]},
        ],
        "arg_nodes": [0, 1, 2],
        "heads": [[3, 0, 0]],
    })
    loaded = sym.load_json(legacy)
    # no_bias "false" -> False (bias stays an argument)
    assert "c_bias" in loaded.list_arguments()
    arg_shapes, out_shapes, _ = loaded.infer_shape_partial()
    assert out_shapes == [(2, 4, 8, 8)]



def test_symbolic_rnn_auto_params_and_grad():
    """sym.RNN auto-creates the flat cudnn-style parameter vector
    (schema) and trains through the fused executor."""
    from mxnet_tpu.ops.rnn import rnn_param_size

    data = mx.sym.var("data")
    out = mx.sym.RNN(data, state_size=8, num_layers=2, mode="lstm",
                     state_outputs=False, name="lstm")
    args = out.list_arguments()
    assert "lstm_parameters" in args and "data" in args
    shapes, outs, _ = out.infer_shape(data=(5, 4, 3))
    d = dict(zip(args, shapes))
    assert d["lstm_parameters"] == (rnn_param_size("lstm", 3, 8, 2,
                                                   False),)
    assert outs[0] == (5, 4, 8)
    exe = out.bind(mx.cpu(), {
        "data": mx.nd.array(np.random.RandomState(0)
                            .randn(5, 4, 3).astype("f4")),
        "lstm_parameters": mx.nd.array(
            (np.random.RandomState(1).randn(d["lstm_parameters"][0])
             * 0.1).astype("f4"))},
        args_grad={"lstm_parameters": mx.nd.zeros(d["lstm_parameters"])},
        grad_req={"data": "null", "lstm_parameters": "write"})
    y = exe.forward(is_train=True)[0]
    assert y.shape == (5, 4, 8)
    exe.backward()
    g = exe.grad_dict["lstm_parameters"].asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_infer_shape_more_ops():
    """Shape inference across the auto-param schemas (the reference's
    test_infer_shape.py tier)."""
    d = mx.sym.var("data")
    # Deconvolution: weight is (in, out/g, k, k)
    dc = mx.sym.Deconvolution(d, kernel=(4, 4), stride=(2, 2), pad=(1, 1),
                              num_filter=8, name="up")
    args, outs, _ = dc.infer_shape(data=(2, 16, 7, 7))
    byname = dict(zip(dc.list_arguments(), args))
    assert byname["up_weight"] == (16, 8, 4, 4)
    assert outs[0] == (2, 8, 14, 14)
    # BatchNorm: aux shapes follow channel axis
    bn = mx.sym.BatchNorm(d, name="bn")
    args, outs, aux = bn.infer_shape(data=(4, 6, 5, 5))
    assert dict(zip(bn.list_auxiliary_states(), aux)) == {
        "bn_moving_mean": (6,), "bn_moving_var": (6,)}
    # Pooling 'full' convention: ceil-mode output size
    p = mx.sym.Pooling(d, kernel=(3, 3), stride=(2, 2), pool_type="max",
                       pooling_convention="full")
    _, outs, _ = p.infer_shape(data=(1, 2, 8, 8))
    assert outs[0] == (1, 2, 4, 4)  # ceil((8-3)/2)+1
    # grouped conv divides input channels
    gc = mx.sym.Convolution(d, kernel=(3, 3), num_filter=8, num_group=2,
                            name="gconv")
    args, _, _ = gc.infer_shape(data=(1, 4, 8, 8))
    assert dict(zip(gc.list_arguments(), args))["gconv_weight"] == \
        (8, 2, 3, 3)


def test_rtc_stub_raises_at_use_not_import():
    import mxnet_tpu.rtc as rtc

    with pytest.raises(mx.MXNetError, match="Pallas"):
        rtc.CudaModule("__global__ void k() {}")


def test_sym_module_level_binaries():
    """mx.sym.maximum/power/modulo/logical_* with symbol/scalar operand
    dispatch (ref: python/mxnet/symbol/symbol.py module functions) —
    evaluated through bind to pin numeric semantics incl. the
    non-commutative scalar-LHS cases."""
    import numpy as np

    a = mx.sym.Variable("a")
    av = np.array([[2.0, 3.0]], "f4")

    def ev(s):
        ex = s.bind(mx.cpu(), {"a": mx.nd.array(av)})
        return ex.forward()[0].asnumpy()

    np.testing.assert_allclose(ev(mx.sym.maximum(a, 2.5)), [[2.5, 3.0]])
    np.testing.assert_allclose(ev(mx.sym.power(a, 2)), [[4.0, 9.0]])
    np.testing.assert_allclose(ev(mx.sym.power(2, a)), [[4.0, 8.0]])
    np.testing.assert_allclose(ev(mx.sym.modulo(7, a)), [[1.0, 1.0]])
    b = mx.sym.Variable("a")  # same input, symbol/symbol path
    np.testing.assert_allclose(ev(mx.sym.minimum(a, b)), av)
    t = np.array([1.0, 0.0], "f4")
    s = mx.sym.Variable("a")
    ex = mx.sym.logical_xor(s, 1.0).bind(mx.cpu(), {"a": mx.nd.array(t)})
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), [0.0, 1.0])
