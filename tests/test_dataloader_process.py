"""Spawn-based process-pool DataLoader (worker_pool="process"): strict
sampler order, persistent pool across epochs, error propagation, and the
GIL escape for pure-python __getitem__ (docs/data.md crossover notes).
Spawn (not fork) so no PjRt/TPU client is inherited by workers."""
import numpy as np
import pytest

from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon.data import ArrayDataset, DataLoader


class _PurePython:
    """CPU-bound pure-python __getitem__ (holds the GIL)."""

    def __init__(self, n=24):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        acc = 0
        for k in range(2000):
            acc = (acc + i * k) % 9973
        return np.array([i, acc], np.float32)


class _Failing:
    def __len__(self):
        return 6

    def __getitem__(self, i):
        if i == 3:
            raise ValueError("boom-3")
        return np.zeros(2, np.float32)


def test_process_pool_order_and_reuse():
    x = np.arange(80, dtype=np.float32).reshape(20, 4)
    y = np.arange(20, dtype=np.float32)
    dl = DataLoader(ArrayDataset(x, y), batch_size=4, num_workers=2,
                    worker_pool="process")
    for _epoch in range(2):  # persistent pool: second epoch reuses it
        got = list(dl)
        assert len(got) == 5
        xa, ya = got[0]
        np.testing.assert_array_equal(xa.asnumpy(), x[:4])
        np.testing.assert_array_equal(ya.asnumpy(), y[:4])
        xl, _ = got[-1]
        np.testing.assert_array_equal(xl.asnumpy(), x[16:])


def test_process_pool_propagates_worker_errors():
    dl = DataLoader(_Failing(), batch_size=2, num_workers=2,
                    worker_pool="process")
    with pytest.raises(ValueError, match="boom-3"):
        list(dl)


def test_process_pool_pure_python_dataset():
    dl = DataLoader(_PurePython(), batch_size=6, num_workers=2,
                    worker_pool="process")
    out = list(dl)
    assert len(out) == 4
    first = out[0].asnumpy()
    np.testing.assert_array_equal(first[:, 0], [0, 1, 2, 3, 4, 5])


def test_invalid_worker_pool_rejected():
    with pytest.raises(MXNetError, match="worker_pool"):
        DataLoader(_PurePython(), batch_size=2, worker_pool="greenlet")


def test_process_pool_pipe_transport_matches_shm():
    x = np.arange(48, dtype=np.float32).reshape(12, 4)
    y = np.arange(12, dtype=np.float32)
    for transport in ("shm", "pipe"):
        dl = DataLoader(ArrayDataset(x, y), batch_size=3, num_workers=2,
                        worker_pool="process", worker_transport=transport)
        got = list(dl)
        assert len(got) == 4
        xa, ya = got[1]
        np.testing.assert_array_equal(xa.asnumpy(), x[3:6])
        np.testing.assert_array_equal(ya.asnumpy(), y[3:6])


def test_invalid_worker_transport_rejected():
    with pytest.raises(MXNetError, match="worker_transport"):
        DataLoader(_PurePython(), batch_size=2, worker_transport="rdma")


def test_shm_segments_reclaimed_on_early_break():
    """Abandoning the iterator mid-epoch must not leak /dev/shm
    segments from in-flight prefetched batches."""
    import glob

    def _segs():
        return set(glob.glob("/dev/shm/psm_*"))

    x = np.arange(160, dtype=np.float32).reshape(40, 4)
    dl = DataLoader(ArrayDataset(x, x[:, 0]), batch_size=4,
                    num_workers=2, worker_pool="process")
    before = _segs()
    it = iter(dl)
    next(it)
    it.close()  # generator finally -> _drain_shm
    del it
    import time
    time.sleep(1)
    leaked = _segs() - before
    assert not leaked, leaked
