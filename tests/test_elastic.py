"""mxelastic (ISSUE 15): rank-failure detection, coordinated
shrink/replace recovery, and the job supervisor.

Fast tier-1 coverage: the chaos ``rank=`` selector, heartbeat stamps,
the job-level commit marker (resume can never mix steps across
ranks), the ``PeerFailed`` classification of dist watchdog timeouts /
dead-peer connection errors (non-transient in-process, reserved-rc at
the supervisor boundary), AutoCheckpoint crash-consistency (fsync'd
rename commit), and the mxgoodput ``rank_failure_recovery`` routing.

Slow (nightly elastic stage): the REAL multi-process e2e — a chaos
plan kills exactly one rank mid-training and the supervisor recovers
onto the survivors with loss parity vs an uninterrupted twin — and
the kill-9-mid-async-write crash-consistency proof.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd, resilience
from mxnet_tpu.gluon import nn
from mxnet_tpu.resilience import chaos, elastic, heartbeat, preemption
from mxnet_tpu.resilience.elastic import (RC_PEER_FAILED, RC_WINDDOWN,
                                          PeerFailed)
from mxnet_tpu.resilience.retry import RetryPolicy, is_transient
from mxnet_tpu.telemetry import instruments as _ins

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    chaos.reset_stats()
    chaos.set_rank(None)
    preemption.clear()
    yield
    chaos.set_rank(None)
    preemption.clear()


def _make_net(prefix, seed=3):
    np.random.seed(seed)
    mx.random.seed(seed)
    net = nn.Dense(4, in_units=6, prefix=prefix)
    net.initialize(ctx=mx.cpu())
    return net


def _trainer(net):
    return mx.gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})


def _one_step(net, tr, seed=0):
    rng = np.random.RandomState(seed)
    xb = nd.array(rng.rand(8, 6).astype("f4"), ctx=mx.cpu())
    yb = nd.array(rng.rand(8, 4).astype("f4"), ctx=mx.cpu())
    with autograd.record():
        loss = ((net(xb) - yb) ** 2).sum()
    loss.backward()
    tr.step(8)


# ---------------------------------------------------------------------------
# chaos rank= selector (satellite 1)
# ---------------------------------------------------------------------------

class TestChaosRankSelector:
    def test_rank_selected_plan_fires_only_on_its_rank(self):
        chaos.set_rank(0)
        with chaos.inject("t.rank", at=1, rank=1, action="error") as sc:
            assert chaos.check("t.rank") is None  # rank 0: no fire
            assert sc.fired == 0
        chaos.set_rank(1)
        with chaos.inject("t.rank", at=1, rank=1, action="error") as sc:
            with pytest.raises(chaos.FaultInjected):
                chaos.check("t.rank")
            assert sc.fired == 1

    def test_unresolvable_rank_never_fires(self, monkeypatch):
        for name in ("MXNET_ELASTIC_RANK", "DMLC_WORKER_ID",
                     "PROCESS_ID"):
            monkeypatch.delenv(name, raising=False)
        chaos.set_rank(None)
        with chaos.inject("t.norank", at=1, rank=2):
            assert chaos.check("t.norank") is None

    def test_rank_resolves_from_launcher_env(self, monkeypatch):
        chaos.set_rank(None)
        monkeypatch.setenv("MXNET_ELASTIC_RANK", "2")
        with chaos.inject("t.envrank", at=1, rank=2, action="error"):
            with pytest.raises(chaos.FaultInjected):
                chaos.check("t.envrank")

    def test_spec_grammar_rank_and_hang_duration(self):
        plans = chaos._parse_spec(
            "elastic.worker@4:die:rank=1,dist.collective@x2:hang=3.5",
            seed=0)
        p0, p1 = plans
        assert (p0.kind, p0.at, p0.action, p0.rank) == \
            ("elastic.worker", 4, "die", 1)
        assert (p1.kind, p1.times, p1.action, p1.duration,
                p1.rank) == ("dist.collective", 2, "hang", 3.5, None)

    def test_rank_survives_spawn_transport(self):
        with chaos.inject("t.ship", at=2, rank=3, action="error"):
            specs = chaos.export_plans("t.ship")
        assert specs[0]["rank"] == 3
        chaos.install_plans(specs)
        try:
            chaos.set_rank(3)
            assert chaos.check("t.ship") is None   # call #1
            with pytest.raises(chaos.FaultInjected):
                chaos.check("t.ship")              # call #2
        finally:
            with chaos._LOCK:
                chaos._PLANS.clear()
                chaos._recompute_active_locked()

    def test_default_action_for_elastic_worker_is_die(self):
        plans = chaos._parse_spec("elastic.worker@1:rank=0", seed=0)
        assert plans[0].action == "die"
        assert plans[0].rank == 0


# ---------------------------------------------------------------------------
# heartbeats
# ---------------------------------------------------------------------------

class TestHeartbeat:
    def test_beat_monitor_and_gauge(self, tmp_path):
        w = heartbeat.HeartbeatWriter(str(tmp_path), rank=1)
        w.beat(step=3)
        mon = heartbeat.HeartbeatMonitor(str(tmp_path))
        stamps = mon.read()
        assert stamps[1]["step"] == 3
        assert stamps[1]["pid"] == os.getpid()
        assert stamps[1]["age_s"] < 5.0
        assert _ins.rank_heartbeat_age_seconds("1").value < 5.0
        assert mon.stale(timeout_s=10.0) == []
        assert mon.max_step() == 3

    def test_stale_detection_on_aged_stamp(self, tmp_path):
        heartbeat.HeartbeatWriter(str(tmp_path), rank=0).beat(step=1)
        heartbeat.HeartbeatWriter(str(tmp_path), rank=1).beat(step=1)
        old = time.time() - 120.0
        os.utime(os.path.join(str(tmp_path), heartbeat.stamp_name(1)),
                 (old, old))
        mon = heartbeat.HeartbeatMonitor(str(tmp_path))
        assert mon.stale(timeout_s=30.0) == [1]
        # restricted to a rank subset (the supervisor passes the alive
        # set: an exited rank's stale stamp is not a NEW failure)
        assert mon.stale(timeout_s=30.0, ranks=[0]) == []

    def test_clear_removes_stamps(self, tmp_path):
        heartbeat.HeartbeatWriter(str(tmp_path), rank=0).beat()
        mon = heartbeat.HeartbeatMonitor(str(tmp_path))
        assert mon.read()
        mon.clear()
        assert mon.read() == {}

    def test_background_writer_stamps_and_stops(self, tmp_path):
        w = heartbeat.HeartbeatWriter(str(tmp_path), rank=2,
                                      interval_s=0.05)
        w.start()
        try:
            time.sleep(0.2)
        finally:
            w.stop()
        stamps = heartbeat.HeartbeatMonitor(str(tmp_path)).read()
        assert 2 in stamps and stamps[2]["age_s"] < 5.0


# ---------------------------------------------------------------------------
# the job-level commit marker
# ---------------------------------------------------------------------------

def _fake_ckpt(root, rank, step, complete=True, tmp=False):
    name = f"step-{step:08d}"
    if tmp:
        name = ".tmp-" + name
    d = os.path.join(root, f"rank{rank}", name)
    os.makedirs(d)
    files = ("meta.json", "params.npz", "trainer.states")
    for f in files if complete else files[:1]:
        with open(os.path.join(d, f), "w") as fh:
            fh.write("{}")
    return d


class TestCommitMarker:
    def test_elects_highest_complete_step_across_ranks(self, tmp_path):
        root = str(tmp_path)
        _fake_ckpt(root, 0, 2)
        _fake_ckpt(root, 1, 4)
        _fake_ckpt(root, 1, 6, tmp=True)        # interrupted write
        _fake_ckpt(root, 0, 8, complete=False)  # torn dir
        commit = elastic.elect_commit(root, epoch=1, failed_ranks=[0])
        assert commit["step"] == 4
        assert commit["source_rank"] == 1
        assert commit["failed_ranks"] == [0]
        got = elastic.read_commit(root)
        assert got["step"] == 4 and got["cause"] == "rank_failure"
        path = elastic.committed_resume_path(root)
        assert path and path.endswith(
            os.path.join("rank1", "step-00000004"))

    def test_no_checkpoint_yet_commits_fresh_start(self, tmp_path):
        commit = elastic.elect_commit(str(tmp_path))
        assert commit["step"] == 0 and commit["path"] is None
        assert elastic.committed_resume_path(str(tmp_path)) is None

    def test_commit_marker_write_is_fsynced(self, tmp_path,
                                            monkeypatch):
        """COMMIT.json holds the same crash-consistency bar as the
        checkpoints it elects: payload fsync before the rename,
        parent-dir fsync after it."""
        dir_syncs = []
        real_dir = resilience.AutoCheckpoint._fsync_dir
        monkeypatch.setattr(
            resilience.AutoCheckpoint, "_fsync_dir",
            staticmethod(lambda p: (dir_syncs.append(p),
                                    real_dir(p))[1]))
        synced = []
        real_fsync = os.fsync
        monkeypatch.setattr(os, "fsync",
                            lambda fd: (synced.append(fd),
                                        real_fsync(fd))[1])
        _fake_ckpt(str(tmp_path), 0, 2)
        elastic.elect_commit(str(tmp_path))
        assert synced          # the payload write fsynced
        assert dir_syncs[-1] == str(tmp_path)  # the rename committed

    def test_resume_explicit_path_pins_the_committed_step(self,
                                                          tmp_path):
        """Two ranks with diverged cadences resume from the SAME
        elected step dir — the no-mixed-steps contract."""
        net_a = _make_net("cm_a_")
        tr_a = _trainer(net_a)
        ck_a = resilience.AutoCheckpoint(
            str(tmp_path / "rank0"), tr_a, async_save=False)
        _one_step(net_a, tr_a, seed=0)
        ck_a.step = 2
        ck_a.save(sync=True)
        _one_step(net_a, tr_a, seed=1)
        ck_a.step = 4
        ck_a.save(sync=True)
        commit = elastic.elect_commit(str(tmp_path))
        assert commit["step"] == 4
        # a fresh trainer resumes from the COMMITTED dir even though
        # its own rank dir holds nothing
        net_b = _make_net("cm_a_", seed=99)
        tr_b = _trainer(net_b)
        ck_b = resilience.AutoCheckpoint(
            str(tmp_path / "rank1"), tr_b, async_save=False)
        meta = ck_b.resume(
            path=elastic.committed_resume_path(str(tmp_path)))
        assert meta["step"] == 4 and ck_b.step == 4
        for p_a, p_b in zip(net_a.collect_params().values(),
                            net_b.collect_params().values()):
            np.testing.assert_array_equal(
                p_a.list_data()[0].asnumpy(),
                p_b.list_data()[0].asnumpy())


# ---------------------------------------------------------------------------
# PeerFailed classification (satellite 4): watchdog timeout, poisoned
# sequence, dead-peer connection error — each path non-transient
# in-process, reserved-rc at the supervisor boundary
# ---------------------------------------------------------------------------

class TestPeerFailedClassification:
    def test_watchdog_timeout_raises_peerfailed_nontransient(
            self, monkeypatch):
        from mxnet_tpu.parallel import dist

        monkeypatch.setattr(dist, "_POISONED", None)
        with pytest.raises(PeerFailed, match="timed out") as ei:
            dist._run_with_watchdog(lambda: time.sleep(5.0), 0.2, "t")
        assert ei.value.poisoned is False
        assert not is_transient(ei.value)
        monkeypatch.setattr(dist, "_POISONED", None)

    def test_poisoned_sequence_refusal_is_peerfailed(self, monkeypatch):
        from mxnet_tpu.parallel import dist

        monkeypatch.setattr(dist, "_POISONED", "earlier")
        with pytest.raises(PeerFailed, match="refused") as ei:
            dist._run_with_watchdog(lambda: 1, 0.2, "t2")
        assert ei.value.poisoned is True
        assert not is_transient(ei.value)
        monkeypatch.setattr(dist, "_POISONED", None)

    def test_dead_peer_connection_error_classified_and_poisons(
            self, monkeypatch):
        """gloo raises (not hangs) when the peer socket tears down —
        the same classification must come out of the error path."""
        from mxnet_tpu.parallel import dist

        monkeypatch.setattr(dist, "_POISONED", None)

        def torn():
            raise ValueError(
                "UNKNOWN: Gloo all-reduce failed: Read error "
                "[127.0.0.1]:7575: Connection reset by peer")

        with pytest.raises(PeerFailed, match="peer connection lost"):
            dist._run_with_watchdog(torn, 5.0, "allreduce")
        assert dist._POISONED == "allreduce"
        # and an ordinary error is NOT misclassified
        monkeypatch.setattr(dist, "_POISONED", None)

        def plain():
            raise ValueError("shape mismatch")

        with pytest.raises(ValueError, match="shape mismatch"):
            dist._run_with_watchdog(plain, 5.0, "allreduce")
        assert dist._POISONED is None

    def test_peerfailed_never_retried(self, monkeypatch):
        attempts = []

        def fn():
            attempts.append(1)
            raise PeerFailed("peer gone", what="barrier")

        with pytest.raises(PeerFailed):
            RetryPolicy(max_attempts=5).call(fn, site="t.peer")
        assert len(attempts) == 1  # non-transient: no second attempt

    def test_peerfailed_pickles_with_flags(self):
        import pickle

        e = pickle.loads(pickle.dumps(
            PeerFailed("m", what="allgather", poisoned=True)))
        assert e.what == "allgather" and e.poisoned is True


# ---------------------------------------------------------------------------
# the worker guard: reserved rc contract
# ---------------------------------------------------------------------------

class TestWorkerGuard:
    def test_peerfailed_cuts_checkpoint_and_exits_43(self, tmp_path):
        net = _make_net("gd_a_")
        tr = _trainer(net)
        ck = resilience.AutoCheckpoint(str(tmp_path), tr,
                                       async_save=False)
        _one_step(net, tr)
        ck.step = 3
        codes = []
        with elastic.guard(auto_ckpt=ck, exit_fn=codes.append):
            raise PeerFailed("peer gone", what="allreduce")
        assert codes == [RC_PEER_FAILED]
        path = resilience.latest_step_dir(str(tmp_path))
        assert path.endswith("step-00000003")
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        assert meta["preempt"]["kind"] == "peer_failure"
        assert meta["preempt"]["reason"].startswith("peer-failure")

    def test_preempted_winddown_exits_44(self):
        codes = []
        with elastic.guard(exit_fn=codes.append):
            raise preemption.Preempted("winddown")
        assert codes == [RC_WINDDOWN]

    def test_checkpoint_failure_still_exits_reserved_rc(self, capsys):
        class _Boom:
            def stamp_failure(self, *a, **kw):
                raise OSError("disk gone")

            def save(self, **kw):
                raise OSError("disk gone")

        codes = []
        with elastic.guard(auto_ckpt=_Boom(), exit_fn=codes.append):
            raise PeerFailed("peer gone")
        assert codes == [RC_PEER_FAILED]
        assert "checkpoint failed" in capsys.readouterr().err

    def test_clean_exit_passes_through(self):
        codes = []
        with elastic.guard(exit_fn=codes.append):
            pass
        assert codes == []


# ---------------------------------------------------------------------------
# AutoCheckpoint crash-consistency (satellite 2, fast half)
# ---------------------------------------------------------------------------

class TestCrashConsistency:
    def test_rename_commit_is_fsynced(self, tmp_path, monkeypatch):
        """Every file fsyncs before the rename, and the PARENT DIR
        fsyncs after it — without the latter a kill -9 can lose the
        rename itself."""
        net = _make_net("fs_a_")
        tr = _trainer(net)
        ck = resilience.AutoCheckpoint(str(tmp_path), tr,
                                       async_save=False)
        _one_step(net, tr)
        synced = []
        real_fsync = os.fsync
        monkeypatch.setattr(os, "fsync",
                            lambda fd: (synced.append(fd),
                                        real_fsync(fd))[1])
        dir_syncs = []
        real_dir = resilience.AutoCheckpoint._fsync_dir

        def spy_dir(path):
            dir_syncs.append(path)
            real_dir(path)

        monkeypatch.setattr(resilience.AutoCheckpoint, "_fsync_dir",
                            staticmethod(spy_dir))
        ck.step = 1
        ck.save(sync=True)
        assert len(synced) >= 3  # params.npz, trainer.states, meta.json
        # tmp dir before the rename, parent dir after it
        assert dir_syncs[-1] == str(tmp_path)
        assert dir_syncs[-2].endswith(".tmp-step-00000001")

    def test_resave_never_destroys_the_complete_copy(self, tmp_path,
                                                     monkeypatch):
        """Re-saving an existing step (the elastic guard re-saving the
        cadence step) must keep a COMPLETE copy on disk at every
        instant: the old dir is renamed aside, never rmtree'd before
        the new one commits — a SIGKILL mid-re-save can cost the
        rename, not the checkpoint."""
        net = _make_net("rs_a_")
        tr = _trainer(net)
        ck = resilience.AutoCheckpoint(str(tmp_path), tr,
                                       async_save=False)
        _one_step(net, tr)
        ck.step = 2
        ck.save(sync=True)
        # fail the COMMIT rename persistently (every retry attempt),
        # after the old dir was renamed aside: the complete copy must
        # survive as .old- instead of having been rmtree'd up front
        real_replace = os.replace

        def crashy(src, dst):
            if ".tmp-step-00000002" in src and \
                    dst.endswith("step-00000002"):
                raise OSError("commit rename dies")
            real_replace(src, dst)

        monkeypatch.setattr(os, "replace", crashy)
        ck.step = 2
        with pytest.raises(Exception):
            ck.save(sync=True)
        monkeypatch.setattr(os, "replace", real_replace)
        old = os.path.join(str(tmp_path), ".old-step-00000002")
        assert all(os.path.exists(os.path.join(old, f)) for f in
                   ("meta.json", "params.npz", "trainer.states"))
        # a later healthy save sweeps the residue and recommits
        ck.step = 2
        ck.save(sync=True)
        names = os.listdir(str(tmp_path))
        assert "step-00000002" in names
        assert not any(n.startswith(".old-") for n in names)

    def test_winddown_reason_survives_chained_preemption_handler(
            self, monkeypatch):
        """A worker that installed preemption.install() BEFORE
        elastic.install_winddown(): the chained handler re-triggers
        with 'signal 15', which must NOT overwrite the classified
        peer-failure reason (first trigger wins) — otherwise the
        recovery window routes to the wrong goodput category."""
        prev = signal.getsignal(signal.SIGTERM)
        try:
            # stand-in for a previously installed preemption handler
            signal.signal(signal.SIGTERM,
                          lambda s, f: preemption.trigger(
                              reason=f"signal {s}"))
            elastic.install_winddown()
            handler = signal.getsignal(signal.SIGTERM)
            handler(signal.SIGTERM, None)  # deliver without os.kill
            assert preemption.triggered()
            assert preemption.reason().startswith("peer-failure")
        finally:
            signal.signal(signal.SIGTERM, prev)
            preemption.clear()

    @pytest.mark.slow
    def test_kill9_mid_async_write_falls_back_to_previous_step(
            self, tmp_path):
        """A hard kill (not graceful preemption) mid-async-write must
        leave the previous COMPLETE step dir as the resume point: the
        interrupted write stays a ``.tmp-`` dir resume ignores."""
        child = f"""
import os, sys, time
sys.path.insert(0, {_REPO!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, nd, resilience
from mxnet_tpu.gluon import nn

np.random.seed(3); mx.random.seed(3)
net = nn.Dense(4, in_units=6, prefix="k9_")
net.initialize(ctx=mx.cpu())
tr = mx.gluon.Trainer(net.collect_params(), "sgd",
                      {{"learning_rate": 0.05, "momentum": 0.9}})
rng = np.random.RandomState(0)
xb = nd.array(rng.rand(8, 6).astype("f4")); yb = nd.array(rng.rand(8, 4).astype("f4"))
with autograd.record():
    loss = ((net(xb) - yb) ** 2).sum()
loss.backward(); tr.step(8)
ck = resilience.AutoCheckpoint({str(tmp_path)!r}, tr)
ck.step = 1
ck.save(sync=True)               # the complete fallback checkpoint
real = resilience.AutoCheckpoint._write_file
def slow(path, data, mode="wb"):
    if path.endswith("trainer.states"):
        print("MID_WRITE", flush=True)
        time.sleep(60)           # parent SIGKILLs inside this window
    real(path, data, mode)
resilience.AutoCheckpoint._write_file = staticmethod(slow)
ck.step = 2
ck.save(sync=False)              # async: the daemon writer stalls
print("QUEUED", flush=True)
time.sleep(120)
"""
        p = subprocess.Popen([sys.executable, "-c", child],
                             stdout=subprocess.PIPE, text=True,
                             env=dict(os.environ, JAX_PLATFORMS="cpu"))
        try:
            deadline = time.time() + 120
            saw_mid = False
            while time.time() < deadline:
                line = p.stdout.readline()
                if "MID_WRITE" in line:
                    saw_mid = True
                    break
            assert saw_mid, "writer never reached the params write"
            time.sleep(0.2)  # let it be truly mid-write
            p.send_signal(signal.SIGKILL)
            p.wait(timeout=30)
        finally:
            if p.poll() is None:
                p.kill()
        # the interrupted step-2 write is .tmp- junk; step-1 stands
        names = sorted(os.listdir(str(tmp_path)))
        assert any(n.startswith(".tmp-step-00000002") for n in names)
        assert resilience.latest_step_dir(
            str(tmp_path)).endswith("step-00000001")
        net2 = _make_net("k9_", seed=99)
        tr2 = _trainer(net2)
        ck2 = resilience.AutoCheckpoint(str(tmp_path), tr2)
        meta = ck2.resume()
        assert meta["step"] == 1


# ---------------------------------------------------------------------------
# mxgoodput: rank_failure_recovery routing
# ---------------------------------------------------------------------------

class TestRankFailureGoodput:
    def test_ledger_routes_category(self):
        from mxnet_tpu.telemetry.mxgoodput.ledger import GoodputLedger

        led = GoodputLedger()
        led.open_recovery(category="rank_failure_recovery")
        time.sleep(0.03)
        got = led.close_recovery()
        assert got > 0
        assert led.category_seconds("rank_failure_recovery") \
            == pytest.approx(got)
        assert led.category_seconds("preemption_recovery") == 0.0
        with pytest.raises(ValueError):
            led.open_recovery(category="not_a_category")

    def test_peer_failure_resume_opens_rank_failure_window(
            self, tmp_path):
        """A peer-failure checkpoint (the guard's sync save) resumed
        in a FRESH process opens the recovery window into
        rank_failure_recovery, not preemption_recovery."""
        from mxnet_tpu.telemetry import mxgoodput

        net = _make_net("rf_a_")
        tr = _trainer(net)
        ck = resilience.AutoCheckpoint(str(tmp_path), tr,
                                       async_save=False)
        _one_step(net, tr)
        ck.step = 2
        ck.stamp_failure("peer-failure: collective 'allreduce' timed "
                         "out")
        ck.save(sync=True)
        net2 = _make_net("rf_a_", seed=99)
        tr2 = _trainer(net2)
        ck2 = resilience.AutoCheckpoint(str(tmp_path), tr2)
        mxgoodput.enable(fresh=True)
        try:
            ck2.resume()
            led = mxgoodput.ledger()
            assert led.recovery_open()
            got = led.close_recovery()
            assert got >= 0.0
            assert led.category_seconds("rank_failure_recovery") \
                == pytest.approx(got)
            assert led.category_seconds("preemption_recovery") == 0.0
        finally:
            mxgoodput.disable()

    def test_plain_preemption_still_lands_in_preemption_recovery(
            self, tmp_path):
        from mxnet_tpu.telemetry import mxgoodput

        net = _make_net("pp_a_")
        tr = _trainer(net)
        ck = resilience.AutoCheckpoint(str(tmp_path), tr,
                                       async_save=False)
        _one_step(net, tr)
        ck.step = 2
        ck.stamp_failure("signal 15", kind="preempt")
        ck.save(sync=True)
        net2 = _make_net("pp_a_", seed=98)
        tr2 = _trainer(net2)
        ck2 = resilience.AutoCheckpoint(str(tmp_path), tr2)
        mxgoodput.enable(fresh=True)
        try:
            ck2.resume()
            led = mxgoodput.ledger()
            got = led.close_recovery()
            assert led.category_seconds("preemption_recovery") \
                == pytest.approx(got)
            assert led.category_seconds("rank_failure_recovery") == 0.0
        finally:
            mxgoodput.disable()


# ---------------------------------------------------------------------------
# worker runtime + disabled-path cost
# ---------------------------------------------------------------------------

class TestWorkerRuntime:
    def test_worker_context_beats_and_probes_chaos(self, tmp_path,
                                                   monkeypatch):
        monkeypatch.setenv("MXNET_ELASTIC", "1")
        monkeypatch.setenv("MXNET_ELASTIC_DIR", str(tmp_path))
        monkeypatch.setenv("MXNET_ELASTIC_RANK", "0")
        assert elastic.enabled()
        wc = elastic.WorkerContext()
        wc.on_step(5)
        stamps = heartbeat.HeartbeatMonitor(str(tmp_path)).read()
        assert stamps[0]["step"] == 5
        # the chaos probe is live at the site (error action raises
        # in-place; `die` would hard-exit and is covered by the e2e)
        with chaos.inject("elastic.worker", at=1, action="error"):
            with pytest.raises(chaos.FaultInjected):
                wc.on_step(6)

    def test_worker_context_requires_the_env_contract(self,
                                                      monkeypatch):
        for name in ("MXNET_ELASTIC_DIR", "MXNET_ELASTIC_RANK"):
            monkeypatch.delenv(name, raising=False)
        with pytest.raises(mx.base.MXNetError):
            elastic.WorkerContext()

    def test_startup_wedge_without_first_heartbeat_is_detected(
            self, tmp_path):
        """A rank that hangs BEFORE its first beat has no exit code
        and no stamp to age — the startup-timeout bound must classify
        it hung instead of the supervisor spinning forever."""
        sup = elastic.Supervisor(
            [sys.executable, "-c", "import time; time.sleep(600)"],
            world=1, directory=str(tmp_path), max_restarts=0,
            hb_timeout_s=1.0, startup_timeout_s=1.0, grace_s=0.5,
            poll_s=0.1)
        t0 = time.time()
        report = sup.run()
        assert time.time() - t0 < 30
        assert report["ok"] is False
        assert report["epochs"][0]["failed_ranks"] == [0]
        assert "budget" in report["error"]
        # the private detection stamp never leaks into the report
        assert all("_t_detect" not in e for e in report["epochs"])

    def test_shrink_keeps_world_when_no_failed_rank_identified(
            self, tmp_path, monkeypatch):
        """An epoch where every rank exited a reserved rc (spurious
        watchdog: one rank 43, peers 44, nobody SIGKILLed) names no
        failed rank — shrink mode must restart at FULL size instead of
        discarding a healthy machine."""
        sup = elastic.Supervisor(
            ["true"], world=2, directory=str(tmp_path), mode="shrink",
            max_restarts=2, hb_timeout_s=1.0, grace_s=0.5, poll_s=0.05)
        spawned = []
        monkeypatch.setattr(sup, "_spawn",
                            lambda gen, n: (spawned.append(n), [])[1])
        results = [{"ok": False, "failed": [], "rcs": {0: 43, 1: 44},
                    "t_detect": 0.0, "t_first_step": None, "tails": {}},
                   {"ok": True, "t_first_step": None}]
        monkeypatch.setattr(sup, "_watch",
                            lambda *a, **kw: results.pop(0))
        rep = sup.run()
        assert rep["ok"] and spawned == [2, 2]  # world never shrank
        assert rep["epochs"][0]["world_after"] == 2
        # and a NAMED failure still shrinks
        sup2 = elastic.Supervisor(
            ["true"], world=2, directory=str(tmp_path), mode="shrink",
            max_restarts=2, hb_timeout_s=1.0, grace_s=0.5, poll_s=0.05)
        spawned2 = []
        monkeypatch.setattr(sup2, "_spawn",
                            lambda gen, n: (spawned2.append(n), [])[1])
        results2 = [{"ok": False, "failed": [1], "rcs": {0: 43, 1: 1},
                     "t_detect": 0.0, "t_first_step": None,
                     "tails": {}},
                    {"ok": True, "t_first_step": None}]
        monkeypatch.setattr(sup2, "_watch",
                            lambda *a, **kw: results2.pop(0))
        rep2 = sup2.run()
        assert rep2["ok"] and spawned2 == [2, 1]

    def test_bench_cell_timeout_fails_cell_not_bench(self, monkeypatch,
                                                     tmp_path):
        """A wedged supervised job must fail ITS matrix cell (and
        leave no orphaned process group), never crash the bench before
        RESILIENCE.json is written."""
        import importlib.util
        import subprocess as sp

        spec = importlib.util.spec_from_file_location(
            "bench_resilience_under_test",
            os.path.join(_REPO, "tools", "bench_resilience.py"))
        br = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(br)

        # a real sacrificial process group stands in for the wedged
        # supervisor+workers: the timeout path must kill the GROUP
        sac = sp.Popen([sys.executable, "-c",
                        "import time; time.sleep(300)"],
                       start_new_session=True)
        calls = []

        class _Wedged:
            pid = sac.pid
            returncode = None

            def communicate(self, timeout=None):
                if not calls:
                    calls.append(1)
                    raise sp.TimeoutExpired("elastic_run", timeout)
                return ("", "")

        monkeypatch.setattr(sp, "Popen", lambda *a, **kw: _Wedged())
        try:
            row = br._run_elastic("replace", "", timeout=1.0)
        finally:
            monkeypatch.undo()
        assert row["ok"] is False and "timed out" in row["error"]
        sac.wait(timeout=10)
        assert sac.returncode is not None  # the group was reaped

    def test_supervisor_interrupt_never_orphans_the_generation(
            self, tmp_path, monkeypatch):
        """Ctrl-C (or an outer SIGTERM) mid-watch must kill the live
        workers — N background training processes holding the
        coordinator port is the one thing a dying supervisor may not
        leave behind."""
        sup = elastic.Supervisor(
            [sys.executable, "-c", "import time; time.sleep(600)"],
            world=2, directory=str(tmp_path), hb_timeout_s=1.0,
            grace_s=0.5, poll_s=0.05)
        spawned = []
        real_spawn = sup._spawn

        def spy(gen, n):
            ws = real_spawn(gen, n)
            spawned.extend(ws)
            return ws

        monkeypatch.setattr(sup, "_spawn", spy)

        def interrupted(*a, **kw):
            raise KeyboardInterrupt()

        monkeypatch.setattr(sup, "_watch", interrupted)
        with pytest.raises(KeyboardInterrupt):
            sup.run()
        assert len(spawned) == 2
        for w in spawned:
            assert w["proc"].poll() is not None  # killed, not orphaned

    def test_disabled_path_has_no_elastic_footprint(self, tmp_path):
        """No supervisor => elastic.enabled() is False, no heartbeat
        file appears, no chaos site is consulted — training is the
        plain PR 6 path with zero step cost added."""
        assert not elastic.enabled()
        net = _make_net("off_a_")
        tr = _trainer(net)
        before = set(os.listdir(str(tmp_path)))
        for s in range(3):
            _one_step(net, tr, seed=s)
        assert set(os.listdir(str(tmp_path))) == before
        assert "elastic.worker" not in chaos.stats()


# ---------------------------------------------------------------------------
# the real multi-process e2e (nightly elastic stage)
# ---------------------------------------------------------------------------

def _run_supervised(tmp_path, mode, chaos_spec, workers=2, steps=8):
    out = str(tmp_path / f"report_{mode}.json")
    cmd = [sys.executable, os.path.join(_REPO, "tools",
                                        "elastic_run.py"),
           "--workers", str(workers), "--demo", "--cpu",
           "--mode", mode, "--steps", str(steps), "--ckpt-every", "2",
           "--hb-timeout", "8", "--collective-timeout", "6",
           "--grace", "12", "--out", out]
    if chaos_spec:
        cmd += ["--chaos", chaos_spec]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("MXNET_CHAOS", None)
    env.pop("MXNET_CHAOS_SPEC", None)
    p = subprocess.run(cmd, capture_output=True, text=True,
                       timeout=420, env=env)
    assert p.returncode == 0, p.stdout[-3000:] + p.stderr[-2000:]
    with open(out) as f:
        return json.load(f)


@pytest.mark.slow
def test_e2e_killed_rank_recovers_in_shrink_mode_with_parity(tmp_path):
    """THE ISSUE 15 known-answer: chaos kills exactly rank 1 at its
    4th step of a REAL 2-process job; the supervisor detects it (the
    survivor exits RC_PEER_FAILED off the PeerFailed classification),
    commits the marker, shrinks the world onto the survivor, and the
    recovered loss matches an uninterrupted twin within the
    scaling_bench parity bar — with a measured MTTR."""
    twin = _run_supervised(tmp_path, "replace", "", workers=1)
    assert twin["ok"] and twin["restarts"] == 0
    rep = _run_supervised(tmp_path, "shrink",
                          "elastic.worker@4:die:rank=1")
    assert rep["ok"], rep
    assert rep["restarts"] == 1
    epoch = rep["epochs"][0]
    assert epoch["failed_ranks"] == [1]
    assert epoch["rcs"]["0"] == RC_PEER_FAILED  # survivor classified
    assert epoch["committed_step"] == 4
    assert rep["final_world"] == 1
    assert epoch["mttr_s"] is not None and 0 < epoch["mttr_s"] < 60
    rel = abs(rep["result"]["loss"] - twin["result"]["loss"]) \
        / max(abs(twin["result"]["loss"]), 1e-6)
    assert rel <= 1e-3, (rep["result"], twin["result"])


@pytest.mark.slow
def test_e2e_hung_rank_recovers_in_replace_mode(tmp_path):
    """A HUNG (not dead) rank: chaos sleeps rank 1 inside its step;
    the survivor's collective watchdog fires, the supervisor SIGKILLs
    the hung rank after the wind-down grace and replaces the world at
    full size."""
    rep = _run_supervised(tmp_path, "replace",
                          "elastic.worker@4:hang=600:rank=1")
    assert rep["ok"], rep
    assert rep["restarts"] == 1
    epoch = rep["epochs"][0]
    assert epoch["failed_ranks"] == [1]
    assert rep["final_world"] == 2
    assert epoch["mttr_s"] is not None and 0 < epoch["mttr_s"] < 60
    assert rep["result"]["steps"] == 8


@pytest.mark.slow
def test_e2e_restart_budget_declares_job_dead(tmp_path):
    """A fault that keeps firing past the budget (worker rc != 0 every
    generation via a bad command) must end as a DEAD job with the
    budget recorded, not thrash forever."""
    out = str(tmp_path / "dead.json")
    cmd = [sys.executable, os.path.join(_REPO, "tools",
                                        "elastic_run.py"),
           "--workers", "2", "--mode", "replace",
           "--max-restarts", "1", "--hb-timeout", "5",
           "--grace", "2", "--out", out, "--",
           sys.executable, "-c", "import sys; sys.exit(7)"]
    p = subprocess.run(cmd, capture_output=True, text=True,
                       timeout=300,
                       env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert p.returncode == 1
    with open(out) as f:
        rep = json.load(f)
    assert rep["ok"] is False
    assert rep["restarts"] == 2  # initial + 1 budgeted retry, then dead
    assert "budget" in rep["error"]
    # a resumed generation that dies before its first step leaves
    # mttr_s null — and the private detection stamp must not leak
    # into the persisted report
    assert all("_t_detect" not in e for e in rep["epochs"])
    assert rep["epochs"][1]["mttr_s"] is None
