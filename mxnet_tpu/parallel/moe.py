"""Mixture-of-Experts with expert parallelism over the 'ep' mesh axis.

Beyond-reference capability (SURVEY §2d lists EP as absent upstream; the
mesh has carried the 'ep' axis since round 2 — this gives it a real
consumer).  The formulation is the dense-dispatch one (Mesh-TensorFlow /
GShard): top-1 routing with a fixed per-expert capacity produces
one-hot dispatch/combine tensors, expert inputs form by einsum, the
stacked expert parameters shard their leading dim over 'ep', and each
device runs a vmap over ITS experts inside shard_map.  The dispatch
einsums stay static-shaped (XLA-friendly: no dynamic token counts —
over-capacity tokens are dropped with zero output, the GShard
convention), and GSPMD inserts the all-to-all-equivalent collectives
for the [T,D] -> [E,C,D] resharding.

    y, aux = moe_apply(expert_fn, stacked_params, x, gate_logits)
    # aux: {"gate_probs": [T,E] router probabilities,
    #       "dropped_frac": scalar} for load-balance losses
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..base import MXNetError
from ._compat import shard_map_unchecked
from .mesh import DeviceMesh, current_mesh

__all__ = ["top1_dispatch", "moe_apply"]


def top1_dispatch(gate_logits, capacity):
    """[T, E] logits -> (dispatch [T,E,C] one-hot, combine [T,E,C]
    gate-weighted, dropped_frac scalar, gate probs [T,E] fp32).  Top-1
    routing; each expert accepts its first `capacity` tokens in order,
    later ones drop."""
    t, e = gate_logits.shape
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)                       # [T]
    gate = jnp.max(probs, axis=-1)                            # [T]
    onehot_e = jax.nn.one_hot(expert, e, dtype=jnp.float32)   # [T, E]
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot_e, axis=0) * onehot_e - onehot_e  # [T, E]
    pos_t = jnp.sum(pos, axis=-1)                             # [T]
    keep = pos_t < capacity
    onehot_c = jax.nn.one_hot(pos_t.astype(jnp.int32), capacity,
                              dtype=jnp.float32)              # [T, C]
    dispatch = (onehot_e[:, :, None] * onehot_c[:, None, :]
                * keep[:, None, None].astype(jnp.float32))
    combine = dispatch * gate[:, None, None]
    dropped = 1.0 - jnp.sum(dispatch) / t
    return dispatch, combine, dropped, probs


def moe_apply(expert_fn, stacked_params, x, gate_logits, *,
              capacity_factor: float = 1.25,
              mesh: Optional[DeviceMesh] = None, axis_name: str = "ep"):
    """Apply a top-1 MoE layer.

    expert_fn(params_i, tokens [C, D]) -> [C, D'] — ONE expert's
    computation; stacked_params: pytree with leading expert dim E
    (sharded over 'ep' when present); x [T, D]; gate_logits [T, E].
    Returns (y [T, D'], aux dict with 'gate_probs' [T,E] fp32 and
    'dropped_frac' scalar — feed them to a load-balance loss).
    """
    t, _d = x.shape
    e = gate_logits.shape[-1]
    first = jax.tree_util.tree_leaves(stacked_params)[0]
    if first.shape[0] != e:
        raise MXNetError(
            f"stacked expert dim {first.shape[0]} != gate width {e}")
    capacity = max(1, math.ceil(t / e * capacity_factor))
    dispatch, combine, dropped, probs = top1_dispatch(gate_logits,
                                                      capacity)
    ex_in = jnp.einsum("tec,td->ecd", dispatch,
                       x.astype(jnp.float32)).astype(x.dtype)

    mesh = mesh or current_mesh()

    def run_local(params, xin):
        return jax.vmap(expert_fn)(params, xin)

    if mesh is not None and axis_name in mesh \
            and mesh.size(axis_name) > 1:
        if e % mesh.size(axis_name):
            raise MXNetError(
                f"experts ({e}) must divide over '{axis_name}' "
                f"({mesh.size(axis_name)})")
        p_spec = jax.tree_util.tree_map(
            lambda a: P(axis_name, *([None] * (a.ndim - 1))),
            stacked_params)
        fn = shard_map_unchecked(
            run_local, mesh=mesh.mesh,
            in_specs=(p_spec, P(axis_name, None, None)),
            out_specs=P(axis_name, None, None))
        ex_out = fn(stacked_params, ex_in)
    else:
        ex_out = run_local(stacked_params, ex_in)

    y = jnp.einsum("tec,ecd->td", combine,
                   ex_out.astype(jnp.float32)).astype(x.dtype)
    return y, {"gate_probs": probs, "dropped_frac": dropped}
