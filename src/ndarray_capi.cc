// Minimal NDArray/op C ABI (ref: include/mxnet/c_api.h — the
// MXNDArrayCreate / MXNDArraySyncCopy{From,To}CPU / MXImperativeInvoke
// family), sized for a cpp-package-style consumer: create / free /
// copy-in / copy-out / shape / dtype / invoke-registered-op.
//
// TPU-native inversion of the reference's layering: there the C library
// hosts the runtime and Python wraps it; here the runtime is the Python
// process itself (JAX/PjRt owns device memory), so this layer
// embeds-or-attaches to CPython and marshals into
// mxnet_tpu.capi_bridge.  NDArray handles are opaque PyObject*
// references owned by the caller (release with MXNDArrayFree).
//
// Thread contract: every entry point takes the GIL via PyGILState, so
// any C thread may call in.  Errors: non-zero return; message via
// MXCapiGetLastError() (thread-local, same convention as c_api.cc).
//
// Build: part of libmxnet_tpu_capi.so (lib.py), which links libpython.
// A standalone consumer does:
//   MXCapiInit();                       // starts CPython if needed
//   void* a; MXNDArrayCreate(shape, 2, "float32", &a); ...
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>

namespace {

thread_local std::string g_err;
PyObject* g_bridge = nullptr;  // mxnet_tpu.capi_bridge (owned ref)

void set_err(const std::string& msg) { g_err = msg; }

// capture the pending Python exception into the error ring
void set_err_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) msg = c;
      Py_DECREF(s);
    }
  }
  if (type != nullptr) {
    PyObject* tn = PyObject_GetAttrString(type, "__name__");
    if (tn != nullptr) {
      const char* c = PyUnicode_AsUTF8(tn);
      if (c != nullptr) msg = std::string(c) + ": " + msg;
      Py_DECREF(tn);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  set_err(msg);
}

struct Gil {
  PyGILState_STATE state;
  Gil() : state(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state); }
};

// call bridge.<method>(args...); returns new ref or null (error set)
PyObject* bridge_call(const char* method, PyObject* args) {
  if (g_bridge == nullptr) {
    set_err("MXCapiInit() has not been called");
    return nullptr;
  }
  PyObject* fn = PyObject_GetAttrString(g_bridge, method);
  if (fn == nullptr) {
    set_err_from_python();
    return nullptr;
  }
  PyObject* out = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  if (out == nullptr) set_err_from_python();
  return out;
}

}  // namespace

extern "C" {

const char* MXCapiGetLastError() { return g_err.c_str(); }

// Start (or attach to) the interpreter and import the bridge.  Safe to
// call more than once.  Returns 0 on success.
int MXCapiInit() {
  bool embedded = false;
  if (!Py_IsInitialized()) {
    // standalone C consumer: bring up an embedded interpreter
    Py_InitializeEx(0);
    embedded = true;
  }
  {
    Gil gil;
    if (g_bridge == nullptr) {
      PyObject* mod = PyImport_ImportModule("mxnet_tpu.capi_bridge");
      if (mod == nullptr) {
        set_err_from_python();
        return -1;
      }
      g_bridge = mod;
    }
  }
  if (embedded) {
    // Py_InitializeEx leaves the calling thread owning the GIL; release
    // it so the thread contract ("any C thread may call in" via
    // PyGILState_Ensure) holds — otherwise every OTHER thread deadlocks
    PyEval_SaveThread();
  }
  return 0;
}

int MXNDArrayCreate(const int64_t* shape, int ndim, const char* dtype,
                    void** out) {
  Gil gil;
  PyObject* pshape = PyTuple_New(ndim);
  if (pshape == nullptr) { set_err_from_python(); return -1; }
  for (int i = 0; i < ndim; ++i)
    PyTuple_SET_ITEM(pshape, i, PyLong_FromLongLong(shape[i]));
  PyObject* args = Py_BuildValue("(Os)", pshape, dtype);
  Py_DECREF(pshape);
  if (args == nullptr) { set_err_from_python(); return -1; }
  PyObject* nd = bridge_call("create", args);
  Py_DECREF(args);
  if (nd == nullptr) return -1;
  *out = nd;  // ownership to the caller
  return 0;
}

int MXNDArrayFree(void* handle) {
  Gil gil;
  Py_XDECREF(reinterpret_cast<PyObject*>(handle));
  return 0;
}

int MXNDArraySyncCopyFromCPU(void* handle, const void* data,
                             uint64_t nbytes) {
  Gil gil;
  PyObject* buf = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data),
      static_cast<Py_ssize_t>(nbytes));
  if (buf == nullptr) { set_err_from_python(); return -1; }
  PyObject* args = Py_BuildValue("(OO)",
                                 reinterpret_cast<PyObject*>(handle), buf);
  Py_DECREF(buf);
  if (args == nullptr) { set_err_from_python(); return -1; }
  PyObject* r = bridge_call("copy_from", args);
  Py_DECREF(args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXNDArraySyncCopyToCPU(void* handle, void* data, uint64_t nbytes) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  if (args == nullptr) { set_err_from_python(); return -1; }
  PyObject* bytes = bridge_call("copy_to", args);
  Py_DECREF(args);
  if (bytes == nullptr) return -1;
  char* src = nullptr;
  Py_ssize_t got = 0;
  if (PyBytes_AsStringAndSize(bytes, &src, &got) != 0) {
    Py_DECREF(bytes);
    set_err_from_python();
    return -1;
  }
  if (static_cast<uint64_t>(got) != nbytes) {
    Py_DECREF(bytes);
    set_err("MXNDArraySyncCopyToCPU: buffer is " +
            std::to_string(nbytes) + " bytes, array has " +
            std::to_string(got));
    return -1;
  }
  std::memcpy(data, src, got);
  Py_DECREF(bytes);
  return 0;
}

// shape into caller buffer (up to max_ndim entries); *out_ndim gets the
// true rank even when it exceeds max_ndim (call again with more room)
int MXNDArrayGetShape(void* handle, int* out_ndim, int64_t* out_shape,
                      int max_ndim) {
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  if (args == nullptr) { set_err_from_python(); return -1; }
  PyObject* shp = bridge_call("shape_of", args);
  Py_DECREF(args);
  if (shp == nullptr) return -1;
  Py_ssize_t n = PyTuple_Size(shp);
  *out_ndim = static_cast<int>(n);
  for (Py_ssize_t i = 0; i < n && i < max_ndim; ++i)
    out_shape[i] = PyLong_AsLongLong(PyTuple_GET_ITEM(shp, i));
  Py_DECREF(shp);
  return 0;
}

int MXNDArrayGetDType(void* handle, char* buf, int buflen) {
  if (buf == nullptr || buflen <= 0) {
    set_err("MXNDArrayGetDType: buffer must have room for at least "
            "one byte");
    return -1;
  }
  Gil gil;
  PyObject* args = Py_BuildValue("(O)",
                                 reinterpret_cast<PyObject*>(handle));
  if (args == nullptr) { set_err_from_python(); return -1; }
  PyObject* dt = bridge_call("dtype_of", args);
  Py_DECREF(args);
  if (dt == nullptr) return -1;
  const char* s = PyUnicode_AsUTF8(dt);
  if (s == nullptr) {
    Py_DECREF(dt);
    set_err_from_python();
    return -1;
  }
  size_t need = std::strlen(s);
  if (need >= static_cast<size_t>(buflen)) {
    // a silently truncated dtype name ("flo") is worse than an error
    set_err("MXNDArrayGetDType: dtype name needs " +
            std::to_string(need + 1) + " bytes, buffer has " +
            std::to_string(buflen));
    Py_DECREF(dt);
    return -1;
  }
  std::memcpy(buf, s, need + 1);
  Py_DECREF(dt);
  return 0;
}

}  // extern "C"

namespace {

// shared handle marshalling (refcount discipline lives HERE only)
PyObject* handles_to_pylist(void** handles, int n) {
  PyObject* pin = PyList_New(n);
  if (pin == nullptr) {
    set_err_from_python();
    return nullptr;
  }
  for (int i = 0; i < n; ++i) {
    PyObject* h = reinterpret_cast<PyObject*>(handles[i]);
    Py_INCREF(h);
    PyList_SET_ITEM(pin, i, h);
  }
  return pin;
}

// consumes `outs` (DECREFs it); fills exactly n INCREF'd handles.
// n > max_outputs is an ERROR (no-truncation policy, mirroring
// MXNDArrayGetDType): silently dropping the extra outputs would be
// unrecoverable — re-invoking re-executes the op, with side effects
// such as fresh PRNG draws.  *num_outputs always gets the true count,
// so the caller can retry with a large-enough buffer.
int fill_output_handles(PyObject* outs, void** outputs, int* num_outputs,
                        int max_outputs) {
  Py_ssize_t n = PyList_Size(outs);
  *num_outputs = static_cast<int>(n);
  if (n > max_outputs) {
    set_err("op produced " + std::to_string(n) + " outputs, buffer has "
            "room for " + std::to_string(max_outputs) +
            " — retry with a larger buffer (no outputs were returned)");
    Py_DECREF(outs);
    return -1;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* h = PyList_GET_ITEM(outs, i);
    Py_INCREF(h);
    outputs[i] = h;
  }
  Py_DECREF(outs);
  return 0;
}

}  // namespace

extern "C" {

// Imperative op invoke: attrs as parallel key/value string arrays (the
// reference's MXImperativeInvoke param convention).  *num_outputs gets
// the true count; if it exceeds max_outputs the call FAILS with no
// handles filled (no truncation) — retry with a larger buffer.
int MXImperativeInvoke(const char* op_name, void** inputs, int num_inputs,
                       const char** keys, const char** vals, int num_params,
                       void** outputs, int* num_outputs, int max_outputs) {
  Gil gil;
  PyObject* pin = handles_to_pylist(inputs, num_inputs);
  if (pin == nullptr) return -1;
  PyObject* pattrs = PyDict_New();
  if (pattrs == nullptr) {
    Py_DECREF(pin);
    set_err_from_python();
    return -1;
  }
  for (int i = 0; i < num_params; ++i) {
    PyObject* v = PyUnicode_FromString(vals[i]);
    if (v == nullptr || PyDict_SetItemString(pattrs, keys[i], v) != 0) {
      Py_XDECREF(v);
      Py_DECREF(pin);
      Py_DECREF(pattrs);
      set_err_from_python();
      return -1;
    }
    Py_DECREF(v);
  }
  PyObject* args = Py_BuildValue("(sOO)", op_name, pin, pattrs);
  Py_DECREF(pin);
  Py_DECREF(pattrs);
  if (args == nullptr) { set_err_from_python(); return -1; }
  PyObject* outs = bridge_call("invoke", args);
  Py_DECREF(args);
  if (outs == nullptr) return -1;
  return fill_output_handles(outs, outputs, num_outputs, max_outputs);
}

// ---- deployment artifacts (ref: c_predict_api.h MXPredCreate /
// MXPredForward family): load a contrib.deploy StableHLO artifact and
// serve it — NDArray handles in, NDArray handles out. ----

int MXDeployLoad(const char* path, void** out) {
  Gil gil;
  PyObject* args = Py_BuildValue("(s)", path);
  if (args == nullptr) { set_err_from_python(); return -1; }
  PyObject* served = bridge_call("deploy_load", args);
  Py_DECREF(args);
  if (served == nullptr) return -1;
  *out = served;  // ownership to the caller
  return 0;
}

int MXDeployFree(void* handle) {
  Gil gil;
  Py_XDECREF(reinterpret_cast<PyObject*>(handle));
  return 0;
}

// outputs are FLAT (tree-flatten order); *num_outputs gets the true
// count.  If that count exceeds max_outputs the call FAILS with no
// handles filled (no truncation) — retry with a larger buffer.  `seed`
// feeds the per-call PRNG key (stochastic eval-mode layers draw fresh
// samples).
int MXDeployRun(void* handle, void** inputs, int num_inputs,
                uint64_t seed, void** outputs, int* num_outputs,
                int max_outputs) {
  Gil gil;
  PyObject* pin = handles_to_pylist(inputs, num_inputs);
  if (pin == nullptr) return -1;
  PyObject* args = Py_BuildValue(
      "(OOK)", reinterpret_cast<PyObject*>(handle), pin,
      static_cast<unsigned long long>(seed));
  Py_DECREF(pin);
  if (args == nullptr) { set_err_from_python(); return -1; }
  PyObject* outs = bridge_call("deploy_run", args);
  Py_DECREF(args);
  if (outs == nullptr) return -1;
  return fill_output_handles(outs, outputs, num_outputs, max_outputs);
}

}  // extern "C"
