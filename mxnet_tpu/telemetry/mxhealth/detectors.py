"""mxhealth detectors: rolling median/MAD spikes, ratio drift, and
per-rank straggler detection on merged traces.

All detectors are pure host-side math over already-fetched floats —
they run on the monitor's fetch thread (or inside tools), never on the
step path.  The spike detector is deliberately robust statistics
(median + median-absolute-deviation, not mean + stddev): one diverging
loss sample must not drag the baseline toward itself before the next
sample is judged against it.
"""
from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

__all__ = ["RollingMAD", "ratio_drift", "stragglers_from_merge"]


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    if n % 2:
        return s[mid]
    return 0.5 * (s[mid - 1] + s[mid])


class RollingMAD:
    """Rolling median/MAD spike detector over a bounded window.

    ``update(x)`` returns a spike verdict for ``x`` judged against the
    PRIOR window (x is appended afterwards, so a spike never softens
    its own threshold), or None while the window holds fewer than
    ``min_samples`` points.  The MAD is floored at ``rel_floor`` of the
    median's magnitude so a perfectly flat warmup window (MAD == 0)
    does not turn the first femto-scale wobble into a spike.
    """

    def __init__(self, window: int = 64, k: float = 8.0,
                 min_samples: int = 8, rel_floor: float = 1e-3):
        self._win: "deque[float]" = deque(maxlen=max(2, int(window)))
        self.k = float(k)
        self.min_samples = max(2, int(min_samples))
        self.rel_floor = float(rel_floor)

    def __len__(self) -> int:
        return len(self._win)

    def threshold(self) -> Optional[float]:
        """The current spike boundary (median + k*MAD), or None while
        the window is still warming up."""
        if len(self._win) < self.min_samples:
            return None
        vals = list(self._win)
        med = _median(vals)
        mad = _median([abs(v - med) for v in vals])
        mad = max(mad, abs(med) * self.rel_floor)
        return med + self.k * mad

    def update(self, x: float) -> Optional[dict]:
        """Judge ``x`` against the prior window, then absorb it.
        Returns ``{"value", "median", "threshold"}`` when x spikes,
        None otherwise (including during warmup)."""
        thr = self.threshold()
        verdict = None
        if thr is not None and x > thr:
            verdict = {"value": float(x),
                       "median": _median(list(self._win)),
                       "threshold": float(thr)}
        else:
            # a spike is NOT absorbed: a diverging run keeps being
            # judged against the last healthy window instead of
            # normalizing its own explosion
            self._win.append(float(x))
        return verdict


def ratio_drift(update_norm: float, param_norm: float,
                ratio_max: float) -> Optional[dict]:
    """Update/param-ratio drift: one optimizer step moving parameters
    by more than ``ratio_max`` of their own magnitude.  Returns the
    event payload or None (param_norm == 0 — a fresh zero-initialized
    net — never drifts; ratio_max <= 0 disables)."""
    if ratio_max <= 0 or param_norm <= 0:
        return None
    ratio = update_norm / param_norm
    if ratio > ratio_max:
        return {"ratio": float(ratio), "max": float(ratio_max),
                "update_norm": float(update_norm),
                "param_norm": float(param_norm)}
    return None


def stragglers_from_merge(info: dict, rel_threshold: float = 0.2,
                          min_ms: float = 1.0,
                          phases: Optional[tuple] = None) -> List[dict]:
    """Per-rank straggler detection on ``trace_report --merge`` output.

    ``info`` is the merge info dict (the ``skew`` table: per-phase
    per-rank total milliseconds).  A rank straggles on a phase when its
    time exceeds the median across ranks by more than
    ``rel_threshold`` (and by at least ``min_ms`` absolute, so
    microsecond phases on an idle box do not flag).  ``phases``
    restricts the scan to named (cat-agnostic) phase names; default is
    the training phases, where a straggler means every other rank
    waits at the next collective.
    """
    if phases is None:
        phases = ("forward", "backward", "grad-allreduce", "spmd-step",
                  "reduce-scatter", "shard-update", "all-gather",
                  "fused-update", "optimizer-update", "step")
    out: List[dict] = []
    for row in info.get("skew", []):
        if row.get("name") not in phases:
            continue
        per: Dict[str, float] = row.get("per_rank_ms", {})
        if len(per) < 2:
            continue
        med = _median(list(per.values()))
        for rank, ms in sorted(per.items()):
            if ms - med < min_ms:
                continue
            if med > 0 and (ms - med) / med > rel_threshold:
                out.append({"phase": row["name"],
                            "cat": row.get("cat", ""),
                            "rank": int(rank),
                            "ms": float(ms),
                            "median_ms": float(med),
                            "over": round((ms - med) / med, 4)})
    return out
