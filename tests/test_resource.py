"""N15 resource manager: kRandom / kParallelRandom / kTempSpace
(mxnet_tpu/resource.py; ref role: src/resource.cc ResourceManager)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.resource import resource_manager


def test_per_device_random_streams_deterministic_and_independent():
    rm = resource_manager()
    rm.seed(7)
    k_cpu0 = np.asarray(rm.random(mx.cpu(0)).next_key())
    k_cpu1 = np.asarray(rm.random(mx.cpu(1)).next_key())
    assert not np.array_equal(k_cpu0, k_cpu1)  # independent per device
    rm.seed(7)  # same root seed -> identical streams
    assert np.array_equal(np.asarray(rm.random(mx.cpu(0)).next_key()),
                          k_cpu0)
    assert np.array_equal(np.asarray(rm.random(mx.cpu(1)).next_key()),
                          k_cpu1)
    rm.seed(8)  # new root -> new streams
    assert not np.array_equal(np.asarray(rm.random(mx.cpu(0)).next_key()),
                              k_cpu0)


def test_seed_single_context_only():
    rm = resource_manager()
    rm.seed(7)
    k0 = np.asarray(rm.random(mx.cpu(0)).next_key())
    k1 = np.asarray(rm.random(mx.cpu(1)).next_key())
    rm.seed(99, ctx=mx.cpu(0))  # reseed ONE device (MXRandomSeedContext)
    n0 = np.asarray(rm.random(mx.cpu(0)).next_key())
    n1 = np.asarray(rm.random(mx.cpu(1)).next_key())
    assert not np.array_equal(n0, k0)
    assert not np.array_equal(n1, k1)  # stream advanced...
    rm.seed(7)
    rm.random(mx.cpu(1)).next_key()
    again1 = np.asarray(rm.random(mx.cpu(1)).next_key())
    assert np.array_equal(again1, n1)  # ...but along the same sequence


def test_mx_random_seed_ctx_routes_to_manager():
    mx.random.seed(5, ctx=mx.cpu(2))
    a = np.asarray(resource_manager().random(mx.cpu(2)).next_key())
    mx.random.seed(5, ctx=mx.cpu(2))
    b = np.asarray(resource_manager().random(mx.cpu(2)).next_key())
    assert np.array_equal(a, b)


def test_parallel_random_shape_and_uniqueness():
    rm = resource_manager()
    keys = np.asarray(rm.parallel_random(8, mx.cpu(0)))
    assert keys.shape[0] == 8
    assert len({tuple(k) for k in keys}) == 8  # all lanes distinct


def test_temp_space_grow_only_reuse():
    rm = resource_manager()
    a = rm.temp_space(128, mx.cpu(0))
    assert a.nbytes == 128 and a.dtype == np.uint8
    b = rm.temp_space(64, mx.cpu(0))
    # same backing buffer reused for a smaller request
    assert b.base is a.base or b.base is a or a.base is b.base
    c = rm.temp_space(1024, mx.cpu(0))
    assert c.nbytes == 1024
    # per-device pools are separate
    d = rm.temp_space(1024, mx.cpu(1))
    assert d.ctypes.data != c.ctypes.data


def test_request_front_door_and_unknown_kind():
    rm = resource_manager()
    assert rm.request("temp_space", nbytes=16).nbytes == 16
    assert rm.request("random") is not None
    assert np.asarray(rm.request("parallel_random", n=3)).shape[0] == 3
    with pytest.raises(mx.MXNetError, match="no TPU analogue"):
        rm.request("cudnn_dropout_desc")
    with pytest.raises(mx.MXNetError, match="unknown resource kind"):
        rm.request("warp_drive")
