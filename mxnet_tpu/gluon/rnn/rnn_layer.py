"""Fused RNN layers (ref: python/mxnet/gluon/rnn/rnn_layer.py: _RNNLayer,
RNN, LSTM, GRU) over the fused scan-based RNN op (ops/rnn.py)."""
from __future__ import annotations

from ... import autograd as ag
from ... import random as rnd
from ...base import MXNetError
from ..block import HybridBlock, current_trace

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, mode,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        self._mode = mode
        super().__init__(prefix, params)
        if layout not in ("TNC", "NTC"):
            raise MXNetError(f"invalid layout {layout}; must be TNC or NTC")
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._mode = mode
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        g = self._gates
        with self.name_scope():
            # one Parameter per matrix (gluon layout: {l}{dir}_{i2h,h2h}_*),
            # packed into the fused op's flat vector at forward time
            for layer in range(num_layers):
                for d in range(self._dir):
                    suffix = "l" if d == 0 else "r"
                    in_sz = input_size if layer == 0 else hidden_size * self._dir
                    setattr(self, f"{suffix}{layer}_i2h_weight",
                            self.params.get(
                                f"{suffix}{layer}_i2h_weight",
                                shape=(g * hidden_size, in_sz),
                                init=i2h_weight_initializer,
                                allow_deferred_init=True))
                    setattr(self, f"{suffix}{layer}_h2h_weight",
                            self.params.get(
                                f"{suffix}{layer}_h2h_weight",
                                shape=(g * hidden_size, hidden_size),
                                init=h2h_weight_initializer))
                    setattr(self, f"{suffix}{layer}_i2h_bias",
                            self.params.get(
                                f"{suffix}{layer}_i2h_bias",
                                shape=(g * hidden_size,),
                                init=i2h_bias_initializer))
                    setattr(self, f"{suffix}{layer}_h2h_bias",
                            self.params.get(
                                f"{suffix}{layer}_h2h_bias",
                                shape=(g * hidden_size,),
                                init=h2h_bias_initializer))

    def _alias(self):
        return self._mode

    def state_info(self, batch_size=0):
        if self._mode == "lstm":
            return [
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]

    def begin_state(self, batch_size=0, func=None, ctx=None, **kwargs):
        from ... import ndarray as nd

        if func is None:
            func = nd.zeros
        return [func(tuple(info["shape"]), ctx=ctx, **kwargs)
                for info in self.state_info(batch_size)]

    def _infer_param_shapes(self, x, *args):
        in_sz = int(x.shape[-1])
        g = self._gates
        for d in range(self._dir):
            suffix = "l" if d == 0 else "r"
            getattr(self, f"{suffix}0_i2h_weight").shape = \
                (g * self._hidden_size, in_sz)

    def _ordered_params(self):
        """cudnn packing: all weights (layer-major, dir-minor, i2h then
        h2h), then all biases."""
        names = []
        for layer in range(self._num_layers):
            for d in range(self._dir):
                s = "l" if d == 0 else "r"
                names.append(f"{s}{layer}_i2h_weight")
                names.append(f"{s}{layer}_h2h_weight")
        for layer in range(self._num_layers):
            for d in range(self._dir):
                s = "l" if d == 0 else "r"
                names.append(f"{s}{layer}_i2h_bias")
                names.append(f"{s}{layer}_h2h_bias")
        return names

    def hybrid_forward(self, F, inputs, states=None, **params):
        is_nd = hasattr(inputs, "asnumpy")
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        batch = inputs.shape[1]
        skip_states = states is None
        if skip_states:
            from ... import ndarray as nd

            if is_nd:
                states = self.begin_state(batch, ctx=inputs.ctx)
            else:
                import jax.numpy as jnp

                states = [jnp.zeros(info["shape"])
                          for info in self.state_info(batch)]
        if not isinstance(states, (list, tuple)):
            states = [states]
        flat_names = self._ordered_params()
        parts = [params[n] for n in flat_names]
        if is_nd:
            from ... import ndarray as nd

            packed = nd.concat(*[p.reshape((-1,)) for p in parts], dim=0)
        else:
            import jax.numpy as jnp

            packed = jnp.concatenate([p.reshape(-1) for p in parts])
        ts = current_trace()
        train = ts.train if ts is not None else ag.is_training()
        args = [inputs, packed, states[0]]
        if self._mode == "lstm":
            args.append(states[1])
        else:
            args.append(None)
        key = rnd.next_key() if (self._dropout > 0 and train) else None
        res = F.RNN(*args, key, state_size=self._hidden_size,
                    num_layers=self._num_layers, mode=self._mode,
                    bidirectional=self._dir == 2, p=self._dropout,
                    state_outputs=True, _train=train) \
            if not is_nd else self._nd_rnn(args, key, train)
        if self._mode == "lstm":
            out, h, c = res
            out_states = [h, c]
        else:
            out, h = res
            out_states = [h]
        if self._layout == "NTC":
            out = F.swapaxes(out, dim1=0, dim2=1)
        return out if skip_states else (out, out_states)

    def _nd_rnn(self, args, key, train):
        from ...ops.registry import invoke

        return invoke("RNN", *args, key, state_size=self._hidden_size,
                      num_layers=self._num_layers, mode=self._mode,
                      bidirectional=self._dir == 2, p=self._dropout,
                      state_outputs=True, _train=train)


class RNN(_RNNLayer):
    """ref: rnn_layer.py::RNN (mode rnn_relu|rnn_tanh)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, input_size=0,
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         "rnn_relu" if activation == "relu" else "rnn_tanh",
                         **kwargs)


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "lstm", **kwargs)


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "gru", **kwargs)
