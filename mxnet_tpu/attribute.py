"""AttrScope: scoped symbol attributes (ref: python/mxnet/attribute.py).

``with mx.AttrScope(ctx_group='dev1'):`` attaches attrs to symbols created
inside the scope — the reference's mechanism behind group2ctx model
parallelism (here attrs are carried for parity; device placement is done
with mesh shardings, SURVEY.md §2d).
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["AttrScope", "current"]


class AttrScope:
    _state = threading.local()

    def __init__(self, **kwargs):
        for v in kwargs.values():
            if not isinstance(v, str):
                raise ValueError("attributes must be strings")
        self._attr = dict(kwargs)  # own attrs only; never mutated
        self._old: Optional["AttrScope"] = None
        self._effective: Optional[Dict[str, str]] = None

    def _effective_attrs(self) -> Dict[str, str]:
        """Own attrs merged over the enclosing scope's (computed on enter;
        outside a with-block, just the own attrs)."""
        return self._effective if self._effective is not None else self._attr

    def get(self, attr: Optional[Dict[str, str]]) -> Dict[str, str]:
        ret = dict(self._effective_attrs())
        if attr:
            ret.update(attr)
        return ret

    def __enter__(self):
        self._old = current()
        self._effective = dict(self._old._effective_attrs())
        self._effective.update(self._attr)
        AttrScope._state.scope = self
        return self

    def __exit__(self, *exc):
        AttrScope._state.scope = self._old
        self._effective = None
        return False


def current() -> AttrScope:
    scope = getattr(AttrScope._state, "scope", None)
    if scope is None:
        scope = AttrScope()
        AttrScope._state.scope = scope
    return scope
