"""Content-addressed on-disk entry store for the compile cache.

One entry per digest, one file per entry (``<digest>.mxcc``), flat in
the cache directory.  The format is self-describing::

    b"MXCC1\\n"                      magic (format version 1)
    4-byte big-endian header length
    header JSON                      tier, site, digest, payload sha256,
                                     jax/jaxlib/platform, created
    payload bytes                    tier "exec": pickled serialized
                                     executable; tier "stablehlo": the
                                     lowered module text (utf-8)

Durability rules (the resilience conventions):

  * **Writes are atomic** — ``<digest>.tmp-<pid>-<n>`` then
    ``os.replace``.  Concurrent writers of one digest produce
    equivalent entries (same payload; only the header timestamp
    differs), so the race resolves to either copy and both verify; a
    crash mid-write leaves only a ``.tmp-`` file, which readers never
    open and the next eviction sweep removes.
  * **Loads are digest-verified** — magic, header digest, and a sha256
    over the payload must all match.  Any mismatch (torn write, bit
    rot, truncation) quarantines the file (renamed ``*.corrupt``),
    counts a miss, and the caller compiles fresh: corruption can cost a
    compile, never a failed request.
  * **Transient IO retries** — reads/writes run under the framework
    retry policy with ``OSError`` whitelisted (the checkpoint-IO
    precedent), and ``chaos.check("compile_cache.io")`` sits inside the
    attempt so the chaos suite can prove both properties.

Capacity: :meth:`DiskStore.evict` enforces ``MXNET_COMPILE_CACHE_BYTES``
by removing least-recently-used entries (mtime order; a verified load
touches the file, so hot entries survive).  Eviction runs after each
write — the store can transiently exceed the cap by one entry, never
grow without bound.
"""
from __future__ import annotations

import hashlib
import io
import itertools
import json
import os
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..resilience import chaos as _chaos
from ..resilience import retry as _retry

__all__ = ["DiskStore", "StoreError", "ENTRY_SUFFIX"]

_MAGIC = b"MXCC1\n"
ENTRY_SUFFIX = ".mxcc"
_CORRUPT_SUFFIX = ".corrupt"
_tmp_seq = itertools.count(1)


class StoreError(Exception):
    """An entry failed verification (reported to the caller as a miss;
    the message says what was wrong for the quarantine log)."""


def _io_policy() -> _retry.RetryPolicy:
    # module-level singleton, built lazily so the env knobs are read
    # once but never at import time
    global _POLICY
    if _POLICY is None:
        with _POLICY_LOCK:
            if _POLICY is None:
                _POLICY = _retry.RetryPolicy()
    return _POLICY


_POLICY: Optional[_retry.RetryPolicy] = None
_POLICY_LOCK = threading.Lock()


def encode_entry(header: Dict, payload: bytes) -> bytes:
    """Serialize one entry.  The payload sha256 is stamped here so the
    caller cannot forget it."""
    header = dict(header)
    header["payload_sha256"] = hashlib.sha256(payload).hexdigest()
    hjson = json.dumps(header, sort_keys=True).encode()
    return b"".join([_MAGIC, struct.pack(">I", len(hjson)), hjson,
                     payload])


def decode_entry(blob: bytes, want_digest: str) -> Tuple[Dict, bytes]:
    """Parse + verify one entry; raises :class:`StoreError` on any
    mismatch (the caller quarantines)."""
    if not blob.startswith(_MAGIC):
        raise StoreError("bad magic (not a compile-cache entry)")
    buf = io.BytesIO(blob[len(_MAGIC):])
    raw_len = buf.read(4)
    if len(raw_len) != 4:
        raise StoreError("truncated header length")
    (hlen,) = struct.unpack(">I", raw_len)
    hjson = buf.read(hlen)
    if len(hjson) != hlen:
        raise StoreError("truncated header")
    try:
        header = json.loads(hjson)
    except ValueError as e:
        raise StoreError(f"unparseable header: {e}")
    payload = buf.read()
    if header.get("digest") != want_digest:
        raise StoreError(
            f"digest mismatch: header says {header.get('digest')!r}")
    want_sha = header.get("payload_sha256")
    got_sha = hashlib.sha256(payload).hexdigest()
    if got_sha != want_sha:
        raise StoreError(
            f"payload sha256 mismatch (want {want_sha}, got {got_sha}) "
            "— torn write or bit rot")
    if header.get("tier") not in ("exec", "stablehlo", "alias"):
        raise StoreError(f"unknown tier {header.get('tier')!r}")
    return header, payload


class DiskStore:
    """The directory half of the cache.  Thread-safe; every public
    method tolerates a concurrently-mutated directory (entries appear
    and vanish under readers on a shared cache)."""

    def __init__(self, root: str, cap_bytes: int = 0):
        self.root = root
        #: 0 = unbounded (the operator sized the volume instead)
        self.cap_bytes = int(cap_bytes)
        self._lock = threading.Lock()
        self.evictions = 0
        self.corrupt = 0
        os.makedirs(root, exist_ok=True)

    # ---- paths --------------------------------------------------------

    def path(self, digest: str) -> str:
        return os.path.join(self.root, digest + ENTRY_SUFFIX)

    # ---- read ---------------------------------------------------------

    def load(self, digest: str) -> Optional[Tuple[Dict, bytes]]:
        """(header, payload) for ``digest``, or None on miss.  A failed
        verification quarantines the entry and reports a miss."""
        p = self.path(digest)

        def attempt():
            if _chaos._ACTIVE:
                _chaos.check("compile_cache.io")
            try:
                with open(p, "rb") as f:
                    return f.read()
            except FileNotFoundError:
                return None

        try:
            blob = _io_policy().call(attempt, site="compile_cache.load",
                                     retry_on=(OSError,))
        except (_retry.RetryExhausted, OSError):
            # persistent IO failure reads as a miss: the caller
            # compiles fresh — slow, never broken
            return None
        if blob is None:
            return None
        try:
            header, payload = decode_entry(blob, digest)
        except StoreError:
            self.quarantine(digest)
            return None
        try:
            os.utime(p)  # LRU recency: verified hits stay resident
        except OSError:
            pass  # mxlint: disable=MX007 — recency refresh is advisory
        return header, payload

    def touch(self, digest: str) -> None:
        """Refresh an entry's LRU recency (best-effort; missing entry
        = nothing to refresh)."""
        try:
            os.utime(self.path(digest))
        except OSError:
            return

    def quarantine(self, digest: str) -> None:
        """Move a failed entry aside (``*.corrupt``) so the next lookup
        misses cleanly instead of re-verifying the same bad bytes, and
        so the operator can post-mortem what happened."""
        p = self.path(digest)
        with self._lock:
            self.corrupt += 1
        try:
            os.replace(p, p + _CORRUPT_SUFFIX)
        except OSError:
            # already quarantined/removed by a concurrent reader; the
            # miss still stands
            return

    # ---- write --------------------------------------------------------

    def store(self, digest: str, header: Dict, payload: bytes) -> int:
        """Atomically write one entry; returns the bytes written.  The
        header's ``digest`` field is stamped from the argument."""
        header = dict(header, digest=digest)
        blob = encode_entry(header, payload)
        final = self.path(digest)
        tmp = os.path.join(
            self.root, f".tmp-{os.getpid()}-{next(_tmp_seq)}")

        def attempt():
            if _chaos._ACTIVE:
                _chaos.check("compile_cache.io")
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, final)

        try:
            _io_policy().call(attempt, site="compile_cache.store",
                              retry_on=(OSError,))
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass  # mxlint: disable=MX007 — tmp cleanup is best-effort
        return len(blob)

    # ---- capacity -----------------------------------------------------

    def entries(self) -> List[Tuple[str, int, float]]:
        """(path, bytes, mtime) for every live entry (tmp/corrupt files
        excluded)."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for name in names:
            if not name.endswith(ENTRY_SUFFIX):
                continue
            p = os.path.join(self.root, name)
            try:
                st = os.stat(p)
            except OSError:
                continue  # vanished under us (concurrent eviction)
            out.append((p, st.st_size, st.st_mtime))
        return out

    def bytes_on_disk(self) -> int:
        return sum(size for _, size, _ in self.entries())

    def evict(self) -> Tuple[int, int]:
        """Enforce the byte cap: drop least-recently-used entries until
        under it.  Returns ``(entries_removed, live_bytes_after)`` from
        ONE directory scan — the caller feeds the bytes gauge from it
        instead of paying a second walk per write.

        The same scan is the maintenance sweep: stale ``.tmp-`` litter
        from crashed writers (>1h old) and quarantined ``*.corrupt``
        files past their post-mortem window (>24h) are removed here, so
        neither class accumulates outside the byte cap."""
        now = time.time()
        ents: List[Tuple[str, int, float]] = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return 0, 0
        for name in names:
            p = os.path.join(self.root, name)
            if name.endswith(ENTRY_SUFFIX):
                try:
                    st = os.stat(p)
                except OSError:
                    continue  # vanished under us
                ents.append((p, st.st_size, st.st_mtime))
                continue
            stale_after = 3600.0 if name.startswith(".tmp-") else \
                86400.0 if name.endswith(_CORRUPT_SUFFIX) else None
            if stale_after is not None:
                try:
                    if now - os.stat(p).st_mtime > stale_after:
                        os.remove(p)
                except OSError:
                    continue  # racing cleaner
        total = sum(size for _, size, _ in ents)
        removed = 0
        if self.cap_bytes > 0 and total > self.cap_bytes:
            for p, size, _ in sorted(ents, key=lambda e: e[2]):
                if total <= self.cap_bytes:
                    break
                try:
                    os.remove(p)
                except OSError:
                    continue  # concurrent eviction got it first
                total -= size
                removed += 1
            if removed:
                with self._lock:
                    self.evictions += removed
        return removed, total

    # ---- maintenance --------------------------------------------------

    def sweep_tmp(self, older_than_s: float = 3600.0) -> int:
        """Remove stale ``.tmp-`` files a crashed writer left behind."""
        removed = 0
        now = time.time()
        try:
            names = os.listdir(self.root)
        except OSError:
            return 0
        for name in names:
            if not name.startswith(".tmp-"):
                continue
            p = os.path.join(self.root, name)
            try:
                if now - os.stat(p).st_mtime > older_than_s:
                    os.remove(p)
                    removed += 1
            except OSError:
                continue  # racing writer/cleaner
        return removed
