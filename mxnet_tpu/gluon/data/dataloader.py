"""DataLoader (ref: python/mxnet/gluon/data/dataloader.py).

The reference uses fork()ed worker processes with NDArrays in POSIX shm
(CPUSharedStorage) to parallelise decode/augment.  Forking a process that
holds a PjRt/TPU client is unsafe, so this loader parallelises with a
thread pool + double-buffered prefetch: batchify runs in numpy (releases
the GIL for decode/augment-heavy datasets), and only the assembled batch
is handed to the device.  The C++ RecordIO pipeline (src/io, see native/)
is the high-throughput path for ImageNet-style training.
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Optional

import numpy as np

from ...base import MXNetError
from ...ndarray.ndarray import NDArray, array as nd_array
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn", "default_mp_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (ref: dataloader.py::default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp

        return NDArray(jnp.stack([d.data for d in data]))
    if isinstance(data[0], tuple):
        return tuple(default_batchify_fn(list(d)) for d in zip(*data))
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    if arr.dtype == np.int64:
        arr = arr.astype(np.int32)
    return nd_array(arr)


default_mp_batchify_fn = default_batchify_fn


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False, timeout=120):
        self._dataset = dataset
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError("batch_size is required when batch_sampler "
                                 "is not given")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle must be False with explicit sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError("batch_size/shuffle/sampler/last_batch must not "
                             "be set with explicit batch_sampler")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        yield from self._threaded_iter()

    def _threaded_iter(self):
        """Prefetching iterator with N REAL worker threads (reference
        semantics: num_workers parallel batch producers).  Workers pull
        batch indices from a shared queue and publish into a reorder
        buffer keyed by batch position, so results stream strictly in
        sampler order; numpy/cv2/TF decode inside `__getitem__` releases
        the GIL, which is where the parallelism pays."""
        batches = list(self._batch_sampler)
        n_workers = self._num_workers
        window = max(self._prefetch, n_workers, 2)  # in-flight bound
        task_q: "queue.Queue" = queue.Queue()
        done: dict = {}
        done_cv = threading.Condition()
        stop = threading.Event()

        def worker():
            while True:
                item = task_q.get()
                if item is None or stop.is_set():  # sentinel: shut down
                    return
                pos, indices = item
                try:
                    result = ("ok", self._make_batch(indices))
                except BaseException as e:  # propagate to consumer
                    result = ("err", e)
                with done_cv:
                    done[pos] = result
                    done_cv.notify_all()

        next_submit = min(window, len(batches))
        for pos in range(next_submit):  # seed the prefetch window
            task_q.put((pos, batches[pos]))
        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(n_workers)]
        for t in threads:
            t.start()
        try:
            for pos in range(len(batches)):
                with done_cv:
                    ok = done_cv.wait_for(lambda: pos in done,
                                          timeout=self._timeout)
                    if not ok:
                        raise MXNetError(
                            f"DataLoader worker timed out after "
                            f"{self._timeout}s (batch {pos})")
                    kind, payload = done.pop(pos)
                if kind == "err":
                    raise payload
                if next_submit < len(batches):  # top up the window
                    task_q.put((next_submit, batches[next_submit]))
                    next_submit += 1
                yield payload
        finally:
            stop.set()
            for _ in threads:
                task_q.put(None)

    def __len__(self):
        return len(self._batch_sampler)
