"""mxnet_tpu.telemetry — metrics registry + span tracing (ISSUE 2).

The contract under test:
  * labeled Counter/Gauge/Histogram with fixed exponential buckets and
    bucket-interpolated percentiles (bounded storage);
  * Prometheus text exposition parses (one line per sample, # TYPE
    headers) and the JSON snapshot is json.dumps-able;
  * spans carry trace/span/parent ids into the profiler's chrome-trace
    buffer; a 3-step training loop yields data-wait / forward /
    backward / grad-allreduce / optimizer-update phases and
    tools/trace_report.py renders + validates the dump;
  * profiler satellites: dump(finished=True) clears the buffer, Event
    is an instant marker, set_config rejects typo'd keys;
  * disabled-instrumentation dispatch overhead is a single predicate
    check (micro-benchmark gate vs the seed dispatch section).
"""
import importlib.util
import json
import os
import re
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd, profiler, telemetry
from mxnet_tpu.gluon import Trainer, nn
from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
from mxnet_tpu.telemetry import metrics as tmetrics

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_trace_report():
    spec = importlib.util.spec_from_file_location(
        "trace_report_under_test",
        os.path.join(_REPO, "tools", "trace_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _telemetry_off_and_profiler_clean(tmp_path):
    """Every test starts disabled with an empty capture buffer."""
    telemetry.disable()
    profiler.stop()
    profiler.dump(finished=True, filename=str(tmp_path / "_flush.json"))
    yield
    telemetry.disable()
    profiler.stop()
    profiler.dump(finished=True, filename=str(tmp_path / "_flush2.json"))


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

def test_counter_gauge_basic_and_labels():
    reg = tmetrics.MetricsRegistry()
    c = reg.counter("t_requests_total", "requests", labels=("model",))
    c.labels("a").inc()
    c.labels("a").inc(2)
    c.labels(model="b").inc()
    assert c.labels("a").value == 3
    assert c.labels("b").value == 1
    with pytest.raises(ValueError):
        c.labels("a").inc(-1)  # counters are monotone
    g = reg.gauge("t_depth", "queue depth")
    g.set(5)
    g.inc()
    g.dec(2)
    assert g.value == 4


def test_registry_idempotent_and_kind_clash():
    reg = tmetrics.MetricsRegistry()
    a = reg.counter("t_x_total", labels=("op",))
    b = reg.counter("t_x_total", labels=("op",))
    assert a is b
    with pytest.raises(ValueError):
        reg.gauge("t_x_total")  # kind clash
    with pytest.raises(ValueError):
        reg.counter("t_x_total", labels=("other",))  # label clash
    with pytest.raises(ValueError):
        reg.counter("0bad name")  # invalid prometheus name


def test_histogram_fixed_buckets_and_quantiles():
    reg = tmetrics.MetricsRegistry()
    h = reg.histogram("t_lat_seconds", buckets=[0.001, 0.01, 0.1, 1.0])
    solo = h.labels()
    for _ in range(90):
        solo.observe(0.005)   # lands in (0.001, 0.01]
    for _ in range(10):
        solo.observe(0.5)     # lands in (0.1, 1.0]
    assert solo.count == 100
    assert solo.sum == pytest.approx(90 * 0.005 + 10 * 0.5)
    # p50 interpolates inside the (0.001, 0.01] bucket
    p50 = solo.quantile(0.50)
    assert 0.001 < p50 <= 0.01
    # p99 crosses into the (0.1, 1.0] bucket
    p99 = solo.quantile(0.99)
    assert 0.1 < p99 <= 1.0
    assert solo.quantile(0.5) is not None
    # storage is the fixed ladder, not per-observation
    assert len(solo._counts) == 5  # 4 bounds + overflow
    empty = reg.histogram("t_empty_seconds").labels()
    assert empty.quantile(0.5) is None


def test_prometheus_exposition_parses():
    reg = tmetrics.MetricsRegistry()
    reg.counter("t_reqs_total", "total requests",
                labels=("model",)).labels("m\"x\n").inc(7)
    reg.gauge("t_gauge", "a gauge").set(1.5)
    reg.histogram("t_h_seconds", "hist",
                  buckets=[0.1, 1.0]).labels().observe(0.25)
    text = reg.to_prometheus()
    lines = text.strip().split("\n")
    sample_re = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [0-9eE\.\+\-]+$|'
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? \+Inf$')
    types = set()
    for ln in lines:
        if ln.startswith("# TYPE"):
            types.add(ln.split()[2])
            continue
        if ln.startswith("#"):
            continue
        assert sample_re.match(ln), f"unparseable sample line: {ln!r}"
    for fam in ("t_reqs_total", "t_gauge", "t_h_seconds"):
        assert f"# TYPE {fam} " in text
    # histogram expansion: buckets are cumulative and +Inf == count
    assert 't_h_seconds_bucket{le="0.1"} 0' in text
    assert 't_h_seconds_bucket{le="1"} 1' in text
    assert 't_h_seconds_bucket{le="+Inf"} 1' in text
    assert "t_h_seconds_count 1" in text
    # label values escaped (quote + newline survive on one line)
    assert 't_reqs_total{model="m\\"x\\n"} 7' in text


def test_histogram_bucket_ladder_clash_raises():
    reg = tmetrics.MetricsRegistry()
    # first registration deliberately unsorted: idempotency must be
    # order-insensitive in both directions
    reg.histogram("t_h_seconds", buckets=[1.0, 0.1])
    reg.histogram("t_h_seconds", buckets=[0.1, 1.0])  # same set: fine
    reg.histogram("t_h_seconds", buckets=[1.0, 0.1])
    reg.histogram("t_h_seconds")  # buckets unspecified: fine
    with pytest.raises(ValueError, match="ladder"):
        reg.histogram("t_h_seconds", buckets=[0.5, 1.0])


def test_registry_clear_invalidates_instrument_caches():
    """After clear(), instrument sites must resolve fresh children —
    not keep recording into orphans exposition never sees."""
    from mxnet_tpu.telemetry import instruments as ins

    reg = telemetry.get_registry()
    ins.training_steps_total().inc(5)
    reg.clear()
    ins.training_steps_total().inc()
    fam = reg.get("mx_training_steps_total")
    assert fam is not None and fam.value == 1  # fresh child, visible
    assert "mx_training_steps_total 1" in reg.to_prometheus()


def test_dump_write_failure_preserves_capture(tmp_path):
    profiler.start()
    with profiler.scope("survivor"):
        pass
    profiler.stop()
    n = profiler.num_events()
    assert n >= 1
    with pytest.raises(OSError):
        profiler.dump(finished=True,
                      filename=str(tmp_path / "no" / "dir" / "t.json"))
    assert profiler.num_events() == n  # failed write kept the events
    ok = str(tmp_path / "ok.json")
    profiler.dump(finished=True, filename=ok)
    assert any(e["name"] == "survivor"
               for e in json.load(open(ok))["traceEvents"])
    assert profiler.num_events() == 0


def test_snapshot_is_jsonable():
    reg = tmetrics.MetricsRegistry()
    reg.counter("t_c_total").inc(3)
    reg.histogram("t_lat_seconds").labels().observe(0.02)
    snap = reg.snapshot()
    parsed = json.loads(json.dumps(snap))
    assert parsed["t_c_total"]["samples"][0]["value"] == 3
    h = parsed["t_lat_seconds"]["samples"][0]
    assert h["count"] == 1 and h["p50"] is not None


# ---------------------------------------------------------------------------
# span tracing
# ---------------------------------------------------------------------------

def test_span_noop_when_disabled():
    n0 = profiler.num_events()
    with telemetry.span("nothing") as s:
        assert s is None
    assert profiler.num_events() == n0


def test_spans_nest_with_parent_links(tmp_path):
    profiler.start()
    with telemetry.span("outer", cat="t") as outer:
        with telemetry.span("inner", cat="t") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
    profiler.stop()
    fn = str(tmp_path / "t.json")
    profiler.dump(finished=True, filename=fn)
    evs = json.load(open(fn))["traceEvents"]
    by_name = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert by_name["inner"]["args"]["parent_id"] == \
        by_name["outer"]["args"]["span_id"]
    assert by_name["inner"]["args"]["trace_id"] == \
        by_name["outer"]["args"]["trace_id"]


def test_span_root_breaks_inheritance():
    profiler.start()
    with telemetry.span("ambient") as amb:
        root = telemetry.Span("fresh", root=True)
        assert root.parent_id is None
        assert root.trace_id != amb.trace_id
        root.finish()
    profiler.stop()


# ---------------------------------------------------------------------------
# profiler satellites
# ---------------------------------------------------------------------------

def test_dump_finished_clears_buffer(tmp_path):
    profiler.start()
    with profiler.scope("probe"):
        pass
    profiler.stop()
    assert profiler.num_events() >= 1
    a = str(tmp_path / "a.json")
    profiler.dump(finished=False, filename=a)
    assert profiler.num_events() >= 1  # kept accumulating
    b = str(tmp_path / "b.json")
    profiler.dump(finished=True, filename=b)
    assert profiler.num_events() == 0  # finished: buffer cleared
    assert len(json.load(open(b))["traceEvents"]) >= 1
    c = str(tmp_path / "c.json")
    profiler.dump(finished=True, filename=c)
    assert json.load(open(c))["traceEvents"] == []


def test_event_is_instant_marker(tmp_path):
    profiler.start()
    ev = profiler.Event("epoch-boundary", domain="train")
    ev.mark(epoch=3)
    ev.start()
    ev.stop()
    profiler.stop()
    fn = str(tmp_path / "e.json")
    profiler.dump(finished=True, filename=fn)
    got = [e for e in json.load(open(fn))["traceEvents"]
           if e["name"] == "epoch-boundary"]
    assert len(got) == 3
    assert all(e["ph"] == "i" for e in got), \
        "profiler.Event must emit chrome instant events, not durations"
    assert got[0]["args"] == {"epoch": 3}


def test_set_config_rejects_unknown_keys():
    with pytest.raises(ValueError, match="profile_memroy"):
        profiler.set_config(profile_memroy=True)  # the classic typo
    profiler.set_config(profile_memory=False)  # known key: fine


# ---------------------------------------------------------------------------
# the 3-step training loop trace (tentpole acceptance)
# ---------------------------------------------------------------------------

def _train_three_steps():
    net = nn.Dense(4, in_units=8)
    net.initialize()
    xs = np.random.RandomState(0).rand(12, 8).astype("float32")
    ys = np.random.RandomState(1).rand(12, 4).astype("float32")
    loader = DataLoader(ArrayDataset(nd.array(xs), nd.array(ys)),
                        batch_size=4)
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1})
    for x, y in loader:
        with autograd.record():
            loss = ((net(x) - y) ** 2).sum()
        loss.backward()
        trainer.step(4)
    mx.nd.waitall()


PHASES = ("data-wait", "forward", "backward", "grad-allreduce",
          "optimizer-update")


def test_training_loop_trace_has_per_step_phases(tmp_path):
    telemetry.enable()
    profiler.start()
    try:
        _train_three_steps()
    finally:
        profiler.stop()
        telemetry.disable()
    fn = str(tmp_path / "train.json")
    profiler.dump(finished=True, filename=fn)
    evs = json.load(open(fn))["traceEvents"]
    names = [e["name"] for e in evs if e["ph"] == "X"]
    for phase in PHASES:
        assert names.count(phase) == 3, \
            f"expected 3 {phase!r} spans, got {names.count(phase)}"
    tr = _load_trace_report()
    assert tr.check_events(evs) == []
    table = tr.render_table(evs)
    for phase in PHASES:
        assert phase in table
    assert "training steps: 3" in table
    # step phases also landed in the registry histogram
    fam = telemetry.get_registry().get("mx_training_phase_seconds")
    phases_seen = {v[0] for v, _ in fam.children()}
    assert {"forward", "backward", "grad-allreduce",
            "optimizer-update"} <= phases_seen
    steps = telemetry.get_registry().get("mx_training_steps_total")
    assert steps.value >= 3
    wait = telemetry.get_registry().get("mx_data_wait_seconds")
    assert wait.labels().count >= 3


def test_trace_report_check_flags_corruption(tmp_path):
    tr = _load_trace_report()
    good = {"traceEvents": [
        {"name": "a", "ph": "X", "ts": 0.0, "dur": 5.0, "pid": 1,
         "args": {"trace_id": "t1", "span_id": "s1"}}]}
    assert tr.check_events(good["traceEvents"]) == []
    # missing pid, dangling parent, decreasing cumulative counter,
    # dangling flow id
    bad = [
        {"name": "a", "ph": "X", "ts": 0.0, "dur": 1.0},
        {"name": "b", "ph": "X", "ts": 0.0, "dur": 1.0, "pid": 1,
         "args": {"trace_id": "t1", "span_id": "s2",
                  "parent_id": "nope"}},
        {"name": "c", "ph": "C", "ts": 0.0, "pid": 1,
         "args": {"x_requests": 5}},
        {"name": "c", "ph": "C", "ts": 1.0, "pid": 1,
         "args": {"x_requests": 3}},
        {"name": "request", "ph": "s", "ts": 0.0, "pid": 1,
         "id": "ghost"},
    ]
    errs = tr.check_events(bad)
    assert len(errs) == 4, errs
    # the CLI surfaces the same verdicts
    fn = str(tmp_path / "bad.json")
    json.dump({"traceEvents": bad}, open(fn, "w"))
    assert tr.main([fn, "--check"]) == 1
    ok = str(tmp_path / "ok.json")
    json.dump(good, open(ok, "w"))
    assert tr.main([ok, "--check"]) == 0
    assert tr.main([ok]) == 0  # table mode renders


# ---------------------------------------------------------------------------
# disabled-overhead micro-benchmark (acceptance: <5% vs the seed path)
# ---------------------------------------------------------------------------

def test_disabled_dispatch_overhead_within_5pct_of_seed():
    """With telemetry off and no profiler, the instrumented dispatch
    must cost no more than the SEED's dispatch section (jitted-call
    under a profile_op contextmanager) + 5%.  The new fast path skips
    the contextmanager entirely, so this holds with margin; min-of-N
    timing over 2000-call loops keeps scheduler noise out."""
    from mxnet_tpu.ops import registry

    op = registry.get_op("elemwise_add")
    a = nd.array(np.ones((8, 8), "float32"))._data
    b = nd.array(np.ones((8, 8), "float32"))._data
    attrs_key = registry.freeze_attrs({})
    jit = registry.jitted(op, attrs_key)
    jit(a, b)  # warm the executable cache

    def seed_section():
        with profiler.profile_op(op.name):
            return jit(a, b)

    def new_section():
        return registry.dispatch(op, attrs_key, (a, b), {})

    assert not telemetry.enabled() and not profiler.is_running()

    def best_of(fn, loops=2000, reps=7):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(loops):
                fn()
            best = min(best, time.perf_counter() - t0)
        return best

    seed_section(), new_section()  # warm both paths
    import gc

    gc.disable()  # a collection inside one side skews a 5% gate
    try:
        t_seed = best_of(seed_section)
        t_new = best_of(new_section)
    finally:
        gc.enable()
    assert t_new <= t_seed * 1.05, \
        (f"disabled dispatch {t_new * 1e6 / 2000:.2f}us/call vs seed "
         f"{t_seed * 1e6 / 2000:.2f}us/call — instrumentation is not "
         f"a single predicate check anymore")
    # and truly zero side effects: no events, no dispatch counts
    n0 = profiler.num_events()
    fam = telemetry.get_registry().get("mx_op_dispatch_total")
    c0 = fam.labels(op.name).value if fam is not None else 0
    for _ in range(10):
        new_section()
    assert profiler.num_events() == n0
    fam = telemetry.get_registry().get("mx_op_dispatch_total")
    assert (fam.labels(op.name).value if fam is not None else 0) == c0


def test_enabled_dispatch_counts_ops():
    fam0 = telemetry.get_registry().counter(
        "mx_op_dispatch_total",
        labels=("op",))
    before = fam0.labels("broadcast_add").value
    telemetry.enable()
    try:
        x = nd.array(np.ones((2, 2), "float32"))
        y = x + x
        mx.nd.waitall()
    finally:
        telemetry.disable()
    assert fam0.labels("broadcast_add").value == before + 1
