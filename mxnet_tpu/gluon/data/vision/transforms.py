"""Vision transforms (ref: python/mxnet/gluon/data/vision/transforms.py):
Compose, Cast, ToTensor, Normalize, Resize, CenterCrop, RandomResizedCrop,
RandomFlipLeftRight/TopBottom, RandomBrightness/Contrast/Saturation/Hue,
RandomColorJitter, RandomLighting."""
from __future__ import annotations

import numpy as np

from ....base import MXNetError
from ....ndarray.ndarray import NDArray, array as nd_array
from ...block import Block, HybridBlock
from ...nn import Sequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomBrightness", "RandomContrast", "RandomSaturation",
           "RandomHue", "RandomColorJitter", "RandomLighting"]


class Compose(Sequential):
    """ref: transforms.Compose — a Sequential of transforms."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.cast(x, dtype=self._dtype)


class ToTensor(Block):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (ref: transforms.ToTensor)."""

    def forward(self, x):
        a = x.asnumpy().astype("float32") / 255.0
        if a.ndim == 3:
            a = a.transpose(2, 0, 1)
        elif a.ndim == 4:
            a = a.transpose(0, 3, 1, 2)
        return nd_array(a)


class Normalize(Block):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = np.asarray(mean, dtype="float32")
        self._std = np.asarray(std, dtype="float32")

    def forward(self, x):
        a = x.asnumpy()
        mean = self._mean.reshape(-1, 1, 1) if self._mean.ndim else self._mean
        std = self._std.reshape(-1, 1, 1) if self._std.ndim else self._std
        return nd_array((a - mean) / std)


def _resize_np(a, size):
    """Bilinear resize in numpy (host-side; the native pipeline owns the
    fast path)."""
    h, w = a.shape[:2]
    if isinstance(size, int):
        ow, oh = size, size
    else:
        ow, oh = size
    ys = np.linspace(0, h - 1, oh)
    xs = np.linspace(0, w - 1, ow)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    a = a.astype("float32")
    out = (a[y0][:, x0] * (1 - wy) * (1 - wx) + a[y1][:, x0] * wy * (1 - wx)
           + a[y0][:, x1] * (1 - wy) * wx + a[y1][:, x1] * wy * wx)
    return out


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size

    def forward(self, x):
        return nd_array(_resize_np(x.asnumpy(), self._size))


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        a = x.asnumpy()
        h, w = a.shape[:2]
        cw, ch = self._size
        y0 = max((h - ch) // 2, 0)
        x0 = max((w - cw) // 2, 0)
        out = a[y0:y0 + ch, x0:x0 + cw]
        if out.shape[:2] != (ch, cw):
            out = _resize_np(out, (cw, ch))
        return nd_array(out)


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        a = x.asnumpy()
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target = np.random.uniform(*self._scale) * area
            ratio = np.random.uniform(*self._ratio)
            cw = int(round(np.sqrt(target * ratio)))
            ch = int(round(np.sqrt(target / ratio)))
            if cw <= w and ch <= h:
                x0 = np.random.randint(0, w - cw + 1)
                y0 = np.random.randint(0, h - ch + 1)
                crop = a[y0:y0 + ch, x0:x0 + cw]
                return nd_array(_resize_np(crop, self._size))
        return nd_array(_resize_np(a, self._size))


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            return nd_array(x.asnumpy()[:, ::-1].copy())
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            return nd_array(x.asnumpy()[::-1].copy())
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        alpha = 1.0 + np.random.uniform(-self._b, self._b)
        return nd_array(x.asnumpy().astype("float32") * alpha)


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        a = x.asnumpy().astype("float32")
        alpha = 1.0 + np.random.uniform(-self._c, self._c)
        gray = a.mean()
        return nd_array(gray + alpha * (a - gray))


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        a = x.asnumpy().astype("float32")
        alpha = 1.0 + np.random.uniform(-self._s, self._s)
        if a.ndim == 3 and a.shape[-1] == 3:
            gray = (a * np.array([0.299, 0.587, 0.114])).sum(-1, keepdims=True)
            return nd_array(gray + alpha * (a - gray))
        return x


class RandomHue(Block):
    def __init__(self, hue):
        super().__init__()
        self._h = hue

    def forward(self, x):
        a = x.asnumpy().astype("float32")
        if a.ndim != 3 or a.shape[-1] != 3:
            return x
        alpha = np.random.uniform(-self._h, self._h)
        # cheap hue rotation via YIQ approximation
        u, w = np.cos(alpha * np.pi), np.sin(alpha * np.pi)
        t_yiq = np.array([[0.299, 0.587, 0.114],
                          [0.596, -0.274, -0.321],
                          [0.211, -0.523, 0.311]], dtype="float32")
        t_rgb = np.linalg.inv(t_yiq).astype("float32")
        rot = np.array([[1, 0, 0], [0, u, -w], [0, w, u]], dtype="float32")
        m = t_rgb @ rot @ t_yiq
        return nd_array(a @ m.T)


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        for t in np.random.permutation(self._ts):
            x = t(x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise (ref: transforms.RandomLighting)."""

    _eigval = np.array([55.46, 4.794, 1.148], dtype="float32")
    _eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.814],
                        [-0.5836, -0.6948, 0.4203]], dtype="float32")

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        a = x.asnumpy().astype("float32")
        if a.ndim != 3 or a.shape[-1] != 3:
            return x
        alpha = np.random.normal(0, self._alpha, 3).astype("float32")
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return nd_array(a + rgb)
