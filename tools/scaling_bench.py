"""Data-parallel scaling-efficiency harness (BASELINE scaling target:
>=90% efficiency at 256 v5e chips).

Three step paths share one harness (``--path``):

  * ``replica`` — the per-replica pipeline: eager fwd/bwd (autograd),
    ``KVStore.pushpull_fused`` bucketed gradient sync (DCN/gloo across
    processes), per-replica ``FusedUpdater`` dispatches.
  * ``spmd``    — the unified GSPMD step (ISSUE 9): same eager fwd/bwd,
    but the gradient reduce + optimizer apply run as ONE jit program
    over the cross-process mesh with ZeRO-sharded optimizer states
    (``Trainer(spmd=True)``, optimizer/spmd.py).
  * ``gspmd``   — the whole step (fwd+bwd+reduce+update) as one sharded
    program (``parallel.SPMDTrainer``).

Weak-scaling throughput: the per-device batch is fixed, so perfect
scaling doubles global throughput when the process count doubles.

**Loss parity** (ISSUE 9 satellite): the old sweep let the global batch
grow with the process count, so the reported losses (one overfit run
per count on DIFFERENT data) were incomparable — SCALING.json read
0.035 → 1.26 → 2.40 and looked like a gradient-averaging bug.  The
parity stage pins the GLOBAL batch and seed across process counts
(same data, disjointly sharded by rank, gradients averaged over the
global batch via ``step(global_batch)``) and asserts the loss curves
agree; it runs on a BatchNorm-free MLP by default so the only
tolerated noise is collective summation order.  A real averaging or
sharding bug fails the gate.

On this dev box the transport is the CPU backend + gloo over localhost
(one virtual device per process) — that validates the harness, the
multi-process program, and the efficiency accounting, NOT real ICI/DCN
bandwidth.  The identical command on a v5e pod (one process per host,
libtpu discovers local chips, DCN carries cross-host collectives):

    # on every host i of an N-host v5e pod:
    DMLC_PS_ROOT_URI=<host0-ip> DMLC_PS_ROOT_PORT=9876 \
    DMLC_NUM_WORKER=<N> DMLC_WORKER_ID=<i> \
    python tools/scaling_bench.py --_worker --path spmd \
        --model resnet50 --batch-per-device 256 --image-size 224 \
        --dtype bfloat16 --steps 50

(tools/launch.py -n N --launcher ssh automates exactly this env
contract; see docs/distributed.md.)  Dev-box sweep:

    python tools/scaling_bench.py --procs 1,2 --path spmd --phases
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
_TOOLS = os.path.dirname(os.path.abspath(__file__))
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

_PHASE_NAMES = ("forward", "backward", "grad-allreduce",
                "optimizer-update", "fused-update", "spmd-step",
                "reduce-scatter", "shard-update", "all-gather")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------------
# models
# ---------------------------------------------------------------------------

def _build_model(args, rng, bs_global):
    """-> (net, data tuple, label, loss, opt, opt_args)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import loss as gloss

    if args.model == "mlp":
        # BatchNorm-free: the parity gate's oracle (local BN statistics
        # legitimately differ per process count; dense math does not)
        from mxnet_tpu.gluon import nn

        net = nn.HybridSequential()
        net.add(nn.Dense(64, activation="relu"),
                nn.Dense(32, activation="relu"), nn.Dense(8))
        net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
        with mx.autograd.pause():
            net(mx.nd.zeros((1, 16)))
        data = rng.rand(bs_global, 16).astype(args.dtype)
        label = rng.randint(0, 8, (bs_global,)).astype(np.int32)
        return (net, (data,), label, gloss.SoftmaxCrossEntropyLoss(),
                "sgd", {"learning_rate": 0.05, "momentum": 0.9})
    if args.model.startswith("resnet"):
        from mxnet_tpu.gluon.model_zoo import vision

        net = getattr(vision, args.model + "_v1")(classes=1000,
                                                  layout="NHWC")
        net.initialize(mx.initializer.Xavier(magnitude=2.0), ctx=mx.cpu())
        with mx.autograd.pause():
            net(mx.nd.zeros((1, 32, 32, 3)))
        if args.dtype != "float32":
            net.cast(args.dtype)
        s = args.image_size
        data = rng.rand(bs_global, s, s, 3).astype(args.dtype)
        label = rng.randint(0, 1000, (bs_global,)).astype(np.int32)
        return (net, (data,), label, gloss.SoftmaxCrossEntropyLoss(),
                "sgd", {"learning_rate": 0.1, "momentum": 0.9})
    if args.model == "bert":
        from mxnet_tpu.gluon.model_zoo.bert import get_bert_model

        seq = args.seq_len
        small = args.image_size < 224  # dev-box shapes
        vocab = 1000 if small else 30522
        kw = (dict(num_layers=2, units=64, hidden_size=128, num_heads=4,
                   max_length=seq) if small else dict(max_length=512))
        net = get_bert_model("bert_12_768_12", vocab_size=vocab, **kw)
        net.initialize(mx.initializer.Normal(0.02), ctx=mx.cpu())
        with mx.autograd.pause():
            seq_o, pooled = net(mx.nd.zeros((1, seq)),
                                mx.nd.zeros((1, seq)), mx.nd.array([seq]))
            net.decode_mlm(seq_o)       # resolve the head params too —
            net.classify_nsp(pooled)    # the trainer shards ALL of them
        if args.dtype != "float32":
            net.cast(args.dtype)
        data = (rng.randint(5, vocab, (bs_global, seq)).astype(np.int32),
                np.zeros((bs_global, seq), np.int32),
                np.full((bs_global,), seq, np.float32))
        label = rng.randint(0, 2, (bs_global,)).astype(np.int32)

        class _NSPLoss:
            """CLS-token 2-way loss — enough to drive the full encoder."""

            def __call__(self, out, y):
                import jax as _jax
                import jax.numpy as jnp

                cls = out[:, 0, :2].astype(jnp.float32)
                lsm = _jax.nn.log_softmax(cls, -1)
                return -jnp.take_along_axis(
                    lsm, y[:, None].astype(jnp.int32), -1)[:, 0]

        return (net, data, label, _NSPLoss(), "adam",
                {"learning_rate": 1e-4})
    raise SystemExit(f"unknown model {args.model}")


def _phase_report(trace_path):
    """Per-phase wall seconds from this rank's trace dump — consumed
    through tools/trace_report.py's machine-readable report (the same
    `--json` document, integrity verdict included) instead of a
    parallel metrics-table parse — plus collective bytes, per-step
    MFU, and peak HBM per device from the mxprof flight recorder."""
    import trace_report as tr
    from mxnet_tpu.telemetry import mxprof

    rep = tr.report_json(tr.load_trace(trace_path))
    phases = {}
    for row in rep["phases"]:
        if row["cat"] == "training" and row["name"] in _PHASE_NAMES \
                and row["count"]:
            phases[row["name"]] = {
                "seconds": round(row["total_ms"] / 1e3, 4),
                "count": row["count"]}
    snap = mxprof.snapshot(live_hbm=True)
    recs = snap["records"]
    mfus = [r["mfu"] for r in recs]
    out = {
        "phase_seconds": phases,
        "trace_check_ok": rep["check"]["ok"],
        "collective_bytes": snap["summary"].get("collective_bytes", {}),
        # wire view (keys "op@axis:encoding"): what actually crossed
        # the interconnect — under MXNET_COMM_QUANT this diverges from
        # the model-sized logical bytes above, and the nightly's
        # <=0.30x gate reads THIS
        "collective_wire_bytes": snap["summary"].get(
            "collective_wire_bytes", {}),
        "mfu": {
            "per_step": mfus,
            "mean": snap["summary"].get("mfu_mean"),
            "peak_flops": snap["peak_flops"],
        },
        "hbm_peak_bytes": {dev: row["peak_bytes"]
                           for dev, row in snap["hbm"].items()},
        "verdicts": snap["summary"].get("verdicts", {}),
        # attribution lane for perf_compare: input-pipeline stall
        # seconds over the measured steps (lower is better; gates
        # independently of throughput)
        "data_wait_s": snap["summary"].get("data_wait_s_total", 0.0),
        # mxtriage regression-attribution lanes: compile counts (with
        # provenance reasons when any miss was diffed), the compiled
        # programs' identities, and the registered-knob surface — so a
        # failing nightly can name its suspect instead of a bare %
        "compiles": snap["summary"].get("compiles", 0),
        "hlo_fingerprints": sorted({
            row["hlo_fingerprint"]
            for per in snap.get("executable_costs", {}).values()
            for row in per.values() if row.get("hlo_fingerprint")}),
        "knobs": snap.get("knobs", {}),
        "knob_fingerprint": snap.get("knob_fingerprint"),
    }
    reasons = snap["summary"].get("compile_reasons")
    if reasons:
        out["compile_reasons"] = reasons
    state = snap.get("optimizer_state_bytes_per_device")
    if state:
        out["optimizer_state_bytes_per_device"] = state
    # goodput lane: the ledger rode the snapshot (mxgoodput was
    # enabled for the attribution steps) — rows carry the ratio and
    # the badput decomposition so mxtriage attribution can rank a
    # badput-category shift as a suspect
    good = snap.get("goodput")
    if isinstance(good, dict):
        out["goodput_ratio"] = good.get("goodput_ratio")
        out["badput_seconds"] = good.get("badput_s", {})
        # the comm-stall lane the overlap gate reads: EXPOSED
        # communication seconds (overlap hides comm inside the update
        # dispatch, so this drops when MXNET_COMM_OVERLAP earns it)
        out["comm_stall_s"] = round(float(
            good.get("badput_s", {}).get("comm_stall", 0.0)), 6)
    return out


# ---------------------------------------------------------------------------
# worker (one process of the job)
# ---------------------------------------------------------------------------

def worker(args):
    import numpy as np

    if args.path == "spmd":
        os.environ.setdefault("MXNET_SPMD", "1")
    else:
        # pin the baseline: an MXNET_SPMD=1 inherited from the
        # operator's shell must not turn the per-replica measurement
        # into a second SPMD run (the nightly gate compares the two)
        os.environ["MXNET_SPMD"] = "0"
    # pin the comm lane the same way: the quantized/overlapped rows and
    # the raw baseline must not bleed into each other via the shell
    os.environ["MXNET_COMM_QUANT"] = args.quant
    os.environ["MXNET_COMM_OVERLAP"] = "1" if args.overlap else "0"
    import mxnet_tpu as mx
    from mxnet_tpu import parallel
    from mxnet_tpu.parallel import dist

    dist.init()
    import jax

    n_dev = jax.device_count()
    n_proc = jax.process_count()
    rank = jax.process_index()
    n_local = jax.local_device_count()
    bs_global = args.global_batch or args.batch_per_device * n_dev
    if bs_global % n_dev:
        raise SystemExit(f"global batch {bs_global} not divisible by "
                         f"{n_dev} devices")

    # THE loss-parity fix (ISSUE 9 satellite): every rank must
    # initialize the SAME model.  The parameter init draws from the
    # framework RNG, which seeds nondeterministically per process —
    # unseeded, each rank trains a DIFFERENT model whose replicated
    # params only pretend to agree, and the sweep's losses drift with
    # the process count (SCALING.json 0.035 -> 1.26 -> 2.40).  Data
    # stays rank-identical too (the launcher contract: every process
    # generates the global batch, then shards it disjointly).
    mx.random.seed(args.seed)
    np.random.seed(args.seed)  # initializers draw from global numpy too
    rng = np.random.RandomState(args.seed)
    net, data, label, loss, opt, opt_args = _build_model(args, rng,
                                                         bs_global)

    if args.path == "gspmd":
        lval, dt, trace = _run_gspmd(args, mx, parallel, net, data,
                                     label, loss, opt, opt_args, n_dev,
                                     rank)
    else:
        lval, dt, trace = _run_trainer(args, mx, net, data, label,
                                       loss, opt, opt_args, bs_global,
                                       n_proc, rank, n_local)

    tp = bs_global * args.steps / dt
    # only rank 0 reports; the live-array HBM scan + trace parse in
    # _phase_report is pure waste on the other ranks
    phase_rep = _phase_report(trace) if trace and rank == 0 else None
    if rank == 0:
        # the quantized lane is its OWN path label ("spmd-int8"): its
        # rows sit beside the raw rows in SCALING.json and diff/gate
        # against them instead of silently replacing them
        path_label = args.path if args.quant == "none" \
            else f"{args.path}-{args.quant}"
        row = {
            "model": args.model, "path": path_label,
            "processes": n_proc, "devices": n_dev,
            "batch_per_device": bs_global // n_dev,
            "global_batch": bs_global,
            "global_throughput": round(tp, 2),
            "per_device_throughput": round(tp / n_dev, 2),
            "unit": "samples/s", "loss": round(lval, 4),
        }
        if phase_rep:
            row.update(phase_rep)
        print(json.dumps(row), flush=True)
    return 0


def _attribution_steps(args, one_step, rank):
    """--phases: run a couple of EXTRA traced+profiled steps AFTER the
    timed window — the phased SPMD variant and the span bookkeeping
    must never distort the throughput/efficiency numbers the sweep
    gates on (tracing serializes the step into per-phase dispatches).
    Every rank dumps its own trace (for the parent's multi-rank merge)
    and keeps the mxprof flight recorder attached for the MFU/HBM
    numbers the row reports.  Returns this rank's trace path."""
    if not args.phases:
        return None
    import tempfile

    from mxnet_tpu import profiler, telemetry
    from mxnet_tpu.telemetry import mxgoodput, mxprof

    telemetry.enable()  # span tracing + metrics + the mxprof recorder
    mxprof.clear()      # attribute ONLY the steps below
    mxgoodput.enable(fresh=True)  # goodput lane over the same window
    profiler.start()
    try:
        for _ in range(2):
            one_step()
    finally:
        profiler.stop()
        telemetry.disable()
    if args.trace_dir:
        path = os.path.join(args.trace_dir, f"trace_rank{rank}.json")
    else:
        fd, path = tempfile.mkstemp(prefix="mx_scaling_trace_",
                                    suffix=".json")
        os.close(fd)
    profiler.dump(finished=True, filename=path)
    return path


def _run_gspmd(args, mx, parallel, net, data, label, loss, opt,
               opt_args, n_dev, rank):
    import time as _t

    mesh = parallel.make_mesh(dp=n_dev)
    with mesh:
        trainer = parallel.SPMDTrainer(net, loss, opt, dict(opt_args))
        placed = [trainer._place(a, None) for a in data + (label,)]
        # >=1 unmeasured call: keeps compilation out of the timed window
        # and binds `lv` even for --warmup 0
        for _ in range(max(args.warmup, 1)):
            lv = trainer.step(*placed)
        lv.asnumpy()
        t0 = _t.perf_counter()
        for _ in range(args.steps):
            lv = trainer.step(*placed)
        lval = float(lv.asnumpy())
        dt = _t.perf_counter() - t0
        trace = _attribution_steps(
            args, lambda: trainer.step(*placed).asnumpy(), rank)
    return lval, dt, trace


def _run_trainer(args, mx, net, data, label, loss_fn, opt, opt_args,
                 bs_global, n_proc, rank, n_local):
    """The gluon Trainer paths (per-replica and unified SPMD): eager
    fwd/bwd on this process's disjoint shard of the global batch, then
    Trainer.step.  The loss reported is the GLOBAL batch mean (local
    sums allreduced), so it is comparable across process counts."""
    import time as _t

    import numpy as np
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon.trainer import Trainer
    from mxnet_tpu.parallel import dist

    # disjoint shard: rank r owns rows [r*per_proc, (r+1)*per_proc)
    per_proc = bs_global // n_proc
    sl = slice(rank * per_proc, (rank + 1) * per_proc)
    local = [mx.nd.array(a[sl]) for a in data] + [mx.nd.array(label[sl])]
    *xs, y = local

    kv = "dist_sync" if n_proc > 1 else "device"
    trainer = Trainer(net.collect_params(), opt, dict(opt_args),
                      kvstore=kv, update_on_kvstore=False,
                      spmd=(args.path == "spmd"))

    def one_step():
        with autograd.record():
            out = net(*xs)
            outs = out if isinstance(out, (list, tuple)) else (out,)
            l = loss_fn(outs[0], y)
        l.backward()
        # sum-loss backward + step(global) = mean over the GLOBAL batch
        trainer.step(bs_global)
        return l

    for _ in range(max(args.warmup, 1)):
        l = one_step()
    l.asnumpy()
    t0 = _t.perf_counter()
    for _ in range(args.steps):
        l = one_step()
    local_sum = float(l.asnumpy().sum())
    dt = _t.perf_counter() - t0
    gsum = float(dist.allgather_np(np.asarray(local_sum)).sum())
    trace = _attribution_steps(args, lambda: one_step().asnumpy(), rank)
    return gsum / bs_global, dt, trace


# ---------------------------------------------------------------------------
# parent: localhost sweep over process counts
# ---------------------------------------------------------------------------

def _spawn_sweep(args, n):
    import shutil
    import tempfile

    port = str(_free_port())
    trace_dir = tempfile.mkdtemp(prefix="mx_scaling_traces_") \
        if args.phases else None
    procs = []
    for i in range(n):
        env = dict(os.environ)
        env["PALLAS_AXON_POOL_IPS"] = ""   # detach the single-client chip
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env.update({"DMLC_ROLE": "worker", "DMLC_PS_ROOT_URI": "127.0.0.1",
                    "DMLC_PS_ROOT_PORT": port, "DMLC_NUM_WORKER": str(n),
                    "DMLC_WORKER_ID": str(i)})
        if args.phases:
            # dev-box MFU denominator: without a real accelerator the
            # peak is unknowable — a nominal 1e12 keeps the MFU
            # plumbing exercised; the row records the source as "env"
            # so nobody mistakes it for hardware utilization
            env.setdefault("MXNET_PEAK_FLOPS", "1e12")
        cmd = [sys.executable, os.path.abspath(__file__), "--_worker",
               "--model", args.model, "--path", args.path,
               "--steps", str(args.steps),
               "--warmup", str(args.warmup),
               "--batch-per-device", str(args.batch_per_device),
               "--image-size", str(args.image_size),
               "--seq-len", str(args.seq_len), "--dtype", args.dtype,
               "--seed", str(args.seed),
               "--global-batch", str(args.global_batch),
               "--quant", args.quant]
        if args.overlap:
            cmd.append("--overlap")
        if args.phases:
            cmd.append("--phases")
        if trace_dir:
            cmd += ["--trace-dir", trace_dir]
        procs.append(subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                      stderr=subprocess.STDOUT, text=True))
    line = None
    try:
        for p in procs:
            out, _ = p.communicate(timeout=args.proc_timeout)
            if p.returncode != 0:
                tail = "\n".join(out.splitlines()[-12:])
                raise RuntimeError(f"worker rc={p.returncode}:\n{tail}")
            for ln in out.splitlines():
                if ln.startswith("{"):
                    line = ln
    finally:
        # a dead rank leaves the siblings blocked in a collective; never
        # leak them (they'd also hold the coordinator port)
        for p in procs:
            if p.poll() is None:
                p.kill()
    row = json.loads(line)
    if trace_dir:
        try:
            row.update(_merge_rank_traces(args, trace_dir, n))
        finally:
            if args.keep_traces:
                print(f"rank traces kept in {trace_dir}",
                      file=sys.stderr)
            else:
                shutil.rmtree(trace_dir, ignore_errors=True)
    return row


def _merge_rank_traces(args, trace_dir, n):
    """Clock-align + merge every rank's attribution trace and run the
    integrity gate on the result (trace_report --merge --check
    semantics, via the shared merge_loaded pipeline).  The merged
    trace lands next to --out for multi-rank runs so a regression can
    be inspected in Perfetto."""
    import glob

    import trace_report as tr

    paths = sorted(glob.glob(os.path.join(trace_dir, "trace_rank*.json")))
    if not paths:
        return {}
    loaded = [tr.load_trace(p) for p in paths]
    dst = os.path.splitext(args.out)[0] + f"_trace_{n}proc.json" \
        if len(loaded) > 1 and args.out else None
    merged, info, errs = tr.merge_loaded(loaded, out=dst)
    out = {"merged_trace": {
        "ranks": len(loaded), "events": len(merged),
        "check_ok": not errs,
        "violations": errs[:5],
        "offsets_us": info["offsets_us"],
        "skew_top": info["skew"][:5],
    }}
    if dst:
        out["merged_trace"]["path"] = os.path.basename(dst)
    return out


def _parity_stage(args, counts):
    """Same seed + same GLOBAL batch across process counts => the loss
    curves must agree (the gradients are averaged over the same data,
    only the sharding differs).  Returns the report dict; 'ok' is the
    gate."""
    gb = args.batch_per_device * max(counts)
    rows = []
    pa = argparse.Namespace(**vars(args))
    pa.model = args.parity_model
    pa.global_batch = gb
    for n in counts:
        rows.append(_spawn_sweep(pa, n))
    losses = [r["loss"] for r in rows]
    spread = max(losses) - min(losses)
    ref = max(abs(losses[0]), 1e-6)
    ok = spread / ref <= args.parity_tol
    return {"model": pa.model, "global_batch": gb,
            "steps": args.steps, "losses": losses,
            "rel_spread": round(spread / ref, 6),
            "tol": args.parity_tol, "ok": ok}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet18",
                    choices=["mlp", "resnet18", "resnet50", "bert"])
    ap.add_argument("--path", default="replica",
                    choices=["replica", "spmd", "gspmd"])
    ap.add_argument("--spmd", action="store_true",
                    help="shorthand for --path spmd")
    ap.add_argument("--quant", default="none",
                    choices=["none", "int8", "fp8"],
                    help="collective wire encoding for the run "
                         "(MXNET_COMM_QUANT); the row's path label "
                         "becomes e.g. 'spmd-int8'")
    ap.add_argument("--overlap", action="store_true",
                    help="launch bucket collectives in gradient-ready "
                         "order (MXNET_COMM_OVERLAP=1)")
    ap.add_argument("--procs", default="1,2,4",
                    help="comma-separated process counts for the sweep")
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--batch-per-device", type=int, default=8)
    ap.add_argument("--global-batch", type=int, default=0,
                    help="pin the GLOBAL batch (loss parity across "
                         "process counts); 0 = batch-per-device * n "
                         "(weak scaling)")
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--seed", type=int, default=0,
                    help="framework + data RNG seed (every rank MUST "
                         "agree — see the parity note in worker())")
    ap.add_argument("--phases", action="store_true",
                    help="report per-phase step-time attribution + "
                         "collective bytes, collected from 2 extra "
                         "traced steps AFTER the timed window (the "
                         "phased SPMD variant serializes dispatches; "
                         "it must not distort the gated efficiency)")
    ap.add_argument("--no-parity", action="store_true",
                    help="skip the fixed-global-batch loss-parity gate")
    ap.add_argument("--parity-model", default="mlp",
                    help="model for the parity stage (default: the "
                         "BatchNorm-free mlp — BN batch statistics "
                         "legitimately vary with the local batch)")
    ap.add_argument("--parity-tol", type=float, default=1e-3,
                    help="max relative spread of final losses across "
                         "process counts")
    ap.add_argument("--proc-timeout", type=float, default=900.0)
    ap.add_argument("--out", default=os.path.join(_REPO, "SCALING.json"))
    ap.add_argument("--keep-traces", action="store_true",
                    help="with --phases: keep each run's per-rank "
                         "trace dir instead of deleting it after the "
                         "merge")
    ap.add_argument("--trace-dir", default="", help=argparse.SUPPRESS)
    ap.add_argument("--_worker", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.spmd:
        args.path = "spmd"

    if args._worker:
        return worker(args)

    results = []
    counts = sorted({int(x) for x in args.procs.split(",")})
    base = base_n = None
    for n in counts:
        res = _spawn_sweep(args, n)
        if base is None:  # smallest count is the efficiency reference
            base, base_n = res["per_device_throughput"], n
        res[f"efficiency_vs_{base_n}proc"] = round(
            res["per_device_throughput"] / base, 4)
        results.append(res)
        print(json.dumps(res))

    report = {"when": time.strftime("%Y-%m-%d %H:%M:%S"),
              "backend": "cpu+gloo localhost (dev box)",
              "path": args.path,
              "quant": args.quant, "overlap": bool(args.overlap),
              "note": "validates harness+program, not ICI/DCN "
                      "bandwidth; see docstring for the pod command",
              "sweep": results}
    rc = 0
    if not args.no_parity and len(counts) > 1:
        parity = _parity_stage(args, counts)
        report["parity"] = parity
        print(json.dumps({"parity": parity}))
        if not parity["ok"]:
            print("PARITY GATE FAILED: loss curves diverge across "
                  "process counts", file=sys.stderr)
            rc = 1
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    return rc


if __name__ == "__main__":
    sys.exit(main())
