"""Serving throughput gate (ref: SERVING_BENCH.json — ISSUE 1).

A strict perf assertion — batched throughput must beat unbatched at
concurrency >= 8 — belongs in the nightly perf-gate lane, not tier-1:
on a loaded shared CPU the margin is real but the wall-clock is not
deterministic.  Tier-1 still exercises the whole serving stack
in-process via tests/test_serving.py.
"""
import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _run(cmd, timeout=420):
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    p = subprocess.run(cmd, capture_output=True, text=True, cwd=_REPO,
                       timeout=timeout, env=env)
    assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
    lines = [ln for ln in p.stdout.splitlines() if ln.startswith("{")]
    assert lines, p.stdout[-2000:]
    return [json.loads(ln) for ln in lines]


def test_bench_serving_batched_beats_unbatched(tmp_path):
    """ISSUE 1 gate: at concurrency >= 8, server-side batching must
    yield strictly higher throughput than one-launch-per-request, and
    the report must carry QPS, p50/p99, and batch occupancy."""
    out = tmp_path / "SERVING_BENCH.json"
    rows = _run([sys.executable, "tools/bench_serving.py",
                 "--duration", "2.5", "--out", str(out)], timeout=420)
    report = rows[-1]
    assert report["batched_over_unbatched"] > 1.0
    assert report["batched"]["concurrency"] >= 8
    for mode in ("unbatched", "batched"):
        r = report[mode]
        assert r["qps"] > 0 and r["p50_latency_ms"] > 0
        assert r["p99_latency_ms"] >= r["p50_latency_ms"]
        assert 0 < r["batch_occupancy"] <= 1.0
    assert report["batched"]["mean_batch_rows"] > 1.0
    assert json.loads(out.read_text()) == report
