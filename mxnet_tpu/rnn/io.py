"""Legacy RNN data helpers (ref: python/mxnet/rnn/io.py):
BucketSentenceIter + encode_sentences — the input side of the
reference's bucketing language-model recipe."""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..base import MXNetError
from ..io.io import DataBatch, DataDesc, DataIter
from ..ndarray import array as nd_array


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0, unknown_token=None):
    """Encode tokenized sentences into id lists, building/extending the
    vocab (ref: io.py::encode_sentences)."""
    idx = start_label
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        new_vocab = True
    else:
        new_vocab = False
        idx = max(vocab.values()) + 1
    res = []
    for sent in sentences:
        coded = []
        for word in sent:
            if word not in vocab:
                if not new_vocab:
                    if unknown_token is None:
                        raise MXNetError(f"unknown token {word!r} with a "
                                         "frozen vocab and no unknown_token")
                    word = unknown_token
                    if word not in vocab:
                        # a frozen vocab must already contain its
                        # unknown_token; inserting it would silently
                        # mutate a vocab the caller declared fixed
                        raise MXNetError(
                            f"unknown_token {unknown_token!r} is not in "
                            "the provided (frozen) vocab")
                else:
                    if idx == invalid_label:
                        idx += 1
                    vocab[word] = idx
                    idx += 1
            coded.append(vocab[word])
        res.append(coded)
    return res, vocab


class BucketSentenceIter(DataIter):
    """Pads encoded sentences into per-bucket batches
    (ref: io.py::BucketSentenceIter).  provide_data/label follow the
    current bucket; `bucket_key` of each batch selects the
    BucketingModule executor."""

    def __init__(self, sentences: List[List[int]], batch_size: int,
                 buckets: Optional[List[int]] = None, invalid_label=-1,
                 data_name="data", label_name="softmax_label",
                 dtype="float32", layout="NT"):
        super().__init__(batch_size)
        if buckets is None:
            lens = np.bincount([len(s) for s in sentences])
            buckets = [i for i, n in enumerate(lens)
                       if n >= batch_size] or [max(len(s)
                                                   for s in sentences)]
        buckets = sorted(buckets)
        self.data = [[] for _ in buckets]
        ndiscard = 0
        for sent in sentences:
            buck = next((i for i, b in enumerate(buckets)
                         if b >= len(sent)), None)
            if buck is None:
                ndiscard += 1
                continue
            buf = np.full((buckets[buck],), invalid_label, dtype=dtype)
            buf[:len(sent)] = sent
            self.data[buck].append(buf)
        self.data = [np.asarray(x, dtype=dtype) for x in self.data]
        if ndiscard:
            import logging

            logging.info("BucketSentenceIter: discarded %d sentences "
                         "longer than the largest bucket", ndiscard)
        self.batch_size = batch_size
        self.buckets = buckets
        self.invalid_label = invalid_label
        self.dtype = dtype
        self.data_name, self.label_name = data_name, label_name
        self.major_axis = 0 if layout.find("N") == 0 else 1
        self.default_bucket_key = max(buckets)
        self._rng = np.random.RandomState(1)
        self.reset()

    @property
    def provide_data(self):
        shape = ((self.batch_size, self.default_bucket_key)
                 if self.major_axis == 0
                 else (self.default_bucket_key, self.batch_size))
        return [DataDesc(self.data_name, shape, self.dtype)]

    @property
    def provide_label(self):
        shape = ((self.batch_size, self.default_bucket_key)
                 if self.major_axis == 0
                 else (self.default_bucket_key, self.batch_size))
        return [DataDesc(self.label_name, shape, self.dtype)]

    def reset(self):
        self.curr_idx = 0
        self.idx = []
        for i, buck in enumerate(self.data):
            self._rng.shuffle(buck)
            for j in range(0, len(buck) - self.batch_size + 1,
                           self.batch_size):
                self.idx.append((i, j))
        self._rng.shuffle(self.idx)

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        buck = self.data[i][j:j + self.batch_size]
        label = np.full_like(buck, self.invalid_label)
        label[:, :-1] = buck[:, 1:]
        if self.major_axis == 1:
            buck, label = buck.T, label.T
        shape = buck.shape
        return DataBatch(
            data=[nd_array(buck)], label=[nd_array(label)],
            bucket_key=self.buckets[i],
            provide_data=[DataDesc(self.data_name, shape, self.dtype)],
            provide_label=[DataDesc(self.label_name, shape, self.dtype)])
