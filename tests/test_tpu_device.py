"""Opt-in REAL-DEVICE test suite: op consistency cpu vs tpu + model
forward/backward + a converging train step on the actual chip.

Counterpart of the reference's tests/python/gpu/test_operator_gpu.py
(same-computation-two-devices consistency via check_consistency).

Run via:  python tools/run_tpu_tests.py
(sets MXNET_TEST_PLATFORM=tpu so conftest keeps the accelerator visible,
executes this module on the chip, and writes the TPU_TESTS_r*.json
artifact with pass counts).  Skipped in the normal CPU suite.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal, check_consistency

pytestmark = pytest.mark.skipif(
    os.environ.get("MXNET_TEST_PLATFORM") != "tpu",
    reason="on-device suite; run via tools/run_tpu_tests.py")


def _ctxs():
    return [mx.cpu(0), mx.tpu(0)]


def _r(*shape):
    return np.random.RandomState(0).randn(*shape).astype("float32")


# matmul-family ops run on the MXU in bf16 by default (jax 'default'
# precision — the perf-correct choice this framework makes, like the
# reference's TensorCore fp16 lane); consistency vs fp32 CPU uses the
# correspondingly looser tolerance, exactly as the reference's fp16 GPU
# tests do (ref: test_operator_gpu.py check_consistency tol tables).
MXU_CASES = [
    ("FullyConnected",
     lambda x, w, b: nd.FullyConnected(x, w, b, num_hidden=8),
     [_r(4, 16), _r(8, 16), _r(8)]),
    ("Convolution",
     lambda x, w, b: nd.Convolution(x, w, b, kernel=(3, 3), num_filter=8,
                                    pad=(1, 1)),
     [_r(2, 3, 8, 8), _r(8, 3, 3, 3), _r(8)]),
    ("dot", lambda a, b: nd.dot(a, b), [_r(4, 8), _r(8, 6)]),
    ("linalg_gemm2", lambda a, b: nd.linalg_gemm2(a, b),
     [_r(3, 4), _r(4, 5)]),
]


@pytest.mark.parametrize("name,fn,args", MXU_CASES,
                         ids=[c[0] for c in MXU_CASES])
def test_op_consistency_mxu(name, fn, args):
    check_consistency(fn, _ctxs(), args, rtol=3e-2, atol=3e-2)


OP_CASES = [
    ("Pooling",
     lambda x: nd.Pooling(x, kernel=(2, 2), stride=(2, 2),
                          pool_type="max"),
     [_r(2, 3, 8, 8)]),
    ("Activation-relu", lambda x: nd.Activation(x, act_type="relu"),
     [_r(4, 32)]),
    ("softmax", lambda x: nd.softmax(x), [_r(4, 10)]),
    ("LayerNorm",
     lambda x, g, b: nd.LayerNorm(x, g, b), [_r(4, 16), _r(16), _r(16)]),
    ("broadcast_add", lambda a, b: a + b, [_r(4, 8), _r(1, 8)]),
    ("sum", lambda x: nd.sum(x, axis=1), [_r(4, 9)]),
    ("mean", lambda x: nd.mean(x, axis=0), [_r(5, 7)]),
    ("exp-log", lambda x: nd.log(nd.exp(x) + 1.0), [_r(4, 6)]),
    ("transpose-reshape",
     lambda x: nd.reshape(nd.transpose(x, axes=(0, 2, 1)), shape=(2, -1)),
     [_r(2, 3, 4)]),
    ("concat", lambda a, b: nd.concat(a, b, dim=1), [_r(3, 4), _r(3, 5)]),
    ("take",
     lambda x: nd.take(x, nd.array(np.array([0, 2], "f4"), ctx=x.ctx),
                       axis=0),
     [_r(4, 5)]),
    ("sigmoid-tanh", lambda x: nd.sigmoid(x) * nd.tanh(x), [_r(4, 4)]),
    ("L2Normalization", lambda x: nd.L2Normalization(x), [_r(4, 8)]),
    ("smooth_l1", lambda x: nd.smooth_l1(x, scalar=1.0), [_r(4, 8)]),
]


@pytest.mark.parametrize("name,fn,args", OP_CASES,
                         ids=[c[0] for c in OP_CASES])
def test_op_consistency_cpu_tpu(name, fn, args):
    check_consistency(fn, _ctxs(), args, rtol=2e-3, atol=2e-3)


NOGRAD_CASES = [
    ("topk", lambda x: nd.topk(x, k=3, ret_typ="value"), [_r(4, 10)]),
    ("argmax", lambda x: nd.argmax(x, axis=1), [_r(4, 10)]),
    ("MultiBoxPrior",
     lambda x: nd.MultiBoxPrior(x, sizes=(0.5, 0.2), ratios=(1, 2)),
     [_r(1, 3, 4, 4)]),
    ("box_nms",
     lambda x: nd.box_nms(x, overlap_thresh=0.5, force_suppress=True),
     [np.abs(_r(12, 6))]),
    ("quantize-dequantize",
     lambda x: nd.dequantize(*nd.quantize_v2(x, out_type="int8")),
     [_r(6, 6)]),
]


@pytest.mark.parametrize("name,fn,args", NOGRAD_CASES,
                         ids=[c[0] for c in NOGRAD_CASES])
def test_op_consistency_nograd(name, fn, args):
    check_consistency(fn, _ctxs(), args, rtol=2e-3, atol=2e-3, grad=False)


def test_batchnorm_train_consistency():
    def f(x, g, b):
        mm = nd.zeros(5, ctx=x.ctx)
        mv = nd.ones(5, ctx=x.ctx)
        return nd.BatchNorm(x, g, b, mm, mv)

    check_consistency(f, _ctxs(), [_r(4, 5, 6, 6), _r(5), _r(5)],
                      rtol=5e-3, atol=5e-3)


def test_resnet_block_fwd_bwd_on_chip():
    """A residual conv block end-to-end on the TPU: finite loss + grads."""
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.resnet18_v1(classes=10)
    net.initialize(mx.initializer.Xavier(), ctx=mx.tpu(0))
    x = nd.array(_r(2, 3, 32, 32), ctx=mx.tpu(0))
    y = nd.array(np.array([1, 3], "f4"), ctx=mx.tpu(0))
    from mxnet_tpu.gluon import loss as gloss

    params = [p for _, p in sorted(net.collect_params().items())]
    with mx.autograd.record():
        out = net(x)
        loss = gloss.SoftmaxCrossEntropyLoss()(out, y).mean()
    loss.backward()
    assert np.isfinite(float(loss.asnumpy()))
    gnorm = sum(float((p.grad().asnumpy() ** 2).sum()) for p in params
                if p.grad_req != "null")
    assert np.isfinite(gnorm) and gnorm > 0


def test_train_step_converges_on_chip():
    """SPMD train step on the real chip drives the loss down."""
    from mxnet_tpu import parallel
    from mxnet_tpu.gluon import loss as gloss

    net = mx.gluon.nn.HybridSequential()
    net.add(mx.gluon.nn.Dense(32, activation="relu"),
            mx.gluon.nn.Dense(4))
    net.initialize(ctx=mx.cpu())
    net(nd.zeros((2, 8), ctx=mx.cpu()))
    rng = np.random.RandomState(0)
    x = rng.randn(64, 8).astype("f4")
    y = (rng.rand(64) * 4).astype(np.int32)
    with parallel.make_mesh(dp=1):
        tr = parallel.SPMDTrainer(net, gloss.SoftmaxCrossEntropyLoss(),
                                  "sgd", {"learning_rate": 0.5})
        losses = [float(tr.step(x, y).asnumpy()) for _ in range(60)]
    assert losses[-1] < losses[0] * 0.7, losses[::8]


def test_fused_conv_bwd_pallas_vs_xla_on_chip():
    """The single-pass fused BACKWARD kernel (MXNET_FUSED_CONVBN_BWD)
    vs the XLA linear_transpose backward on the real chip — every
    gradient, Mosaic-compiled (the CPU suite covers interpret only)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_convbn as pcb

    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(4, 16, 16, 128).astype("float32") * 0.5,
                    jnp.bfloat16)
    w = jnp.asarray(rng.randn(128, 128, 3, 3).astype("float32") * 0.05,
                    jnp.bfloat16)
    sc = jnp.asarray(rng.rand(128).astype("float32") + 0.5)
    bi = jnp.asarray(rng.randn(128).astype("float32") * 0.1)
    sh = jnp.asarray(rng.randn(128).astype("float32") * 0.1)
    y = jnp.asarray(rng.randn(4, 16, 16, 128).astype("float32") * 0.5,
                    jnp.bfloat16)
    gy = jnp.asarray(rng.randn(4, 16, 16, 128).astype("float32") * 0.1,
                     jnp.bfloat16)
    gs1 = jnp.asarray(rng.randn(128).astype("float32") * 1e-3)
    gs2 = jnp.asarray(rng.rand(128).astype("float32") * 1e-3)
    kw = dict(kernel=(3, 3), stride=(1, 1), pad=(1, 1), act_in=True,
              want_stats=True)
    gx_p, dw_p, gsc_p, gbi_p = pcb._pallas_unit_bwd(
        x, w, sc, bi, sh, y, gy, gs1, gs2, **kw)
    # XLA oracle: same math through the fallback backward (knob forced
    # off so the oracle cannot itself take the Pallas path)
    res = (x, w, sc, bi, sh, y)
    old = os.environ.pop("MXNET_FUSED_CONVBN_BWD", None)
    try:
        gx_x, dw_x, gsc_x, gbi_x, _ = pcb._unit_bwd(
            (3, 3), (1, 1), (1, 1), True, True, res, (gy, gs1, gs2))
    finally:
        if old is not None:
            os.environ["MXNET_FUSED_CONVBN_BWD"] = old
    assert_almost_equal(np.asarray(gx_p, np.float32),
                        np.asarray(gx_x, np.float32), rtol=3e-2,
                        atol=3e-2)
    assert_almost_equal(np.asarray(dw_p, np.float32),
                        np.asarray(dw_x, np.float32), rtol=3e-2,
                        atol=3e-2)
    assert_almost_equal(np.asarray(gsc_p), np.asarray(gsc_x), rtol=3e-2,
                        atol=3e-1)
    assert_almost_equal(np.asarray(gbi_p), np.asarray(gbi_x), rtol=3e-2,
                        atol=3e-1)


def test_fused_conv_unit_pallas_vs_xla_on_chip():
    """The fused Conv+BN+ReLU unit's PALLAS kernel vs its XLA fallback
    on the real chip: same outputs and statistics (the CPU suite can
    only check interpret mode — this is the Mosaic-compiled kernel)."""
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_convbn as pcb

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 16, 16, 128).astype("float32") * 0.5,
                    jnp.bfloat16)
    w = jnp.asarray(rng.randn(128, 128, 3, 3).astype("float32") * 0.05,
                    jnp.bfloat16)
    sc = jnp.asarray(rng.rand(128).astype("float32") + 0.5)
    bi = jnp.asarray(rng.randn(128).astype("float32") * 0.1)
    sh = jnp.asarray(rng.randn(128).astype("float32") * 0.1)
    kw = dict(kernel=(3, 3), stride=(1, 1), pad=(1, 1), act_in=True,
              want_stats=True)
    y_p, s1_p, s2_p = pcb._pallas_unit(x, w, sc, bi, sh, **kw)
    y_x, s1_x, s2_x = pcb._xla_unit(x, w, sc, bi, sh, **kw)
    assert_almost_equal(np.asarray(y_p, np.float32),
                        np.asarray(y_x, np.float32), rtol=2e-2, atol=2e-2)
    n = y_p.size // y_p.shape[-1]
    assert_almost_equal(np.asarray(s1_p) / n, np.asarray(s1_x) / n,
                        rtol=2e-2, atol=2e-2)
    assert_almost_equal(np.asarray(s2_p) / n, np.asarray(s2_x) / n,
                        rtol=3e-2, atol=3e-2)


def test_fused_resnet_block_matches_on_chip():
    """Whole fused bottleneck (Pallas path live) vs the op-granular
    block on the chip: train-mode forward + every gradient."""
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon.model_zoo.vision.resnet import BottleneckV1

    rng = np.random.RandomState(1)
    xnp = rng.randn(2, 8, 8, 16).astype("float32")
    block = BottleneckV1(16, 1, downsample=False, in_channels=16,
                         layout="NHWC")
    # params on the CHIP: eager inputs default to tpu(0) on this host,
    # and cpu-resident params would raise a ctx mismatch (and the test
    # exists to run the Pallas path on the device anyway)
    block.initialize(mx.initializer.Xavier(), ctx=mx.tpu(0))
    block(mx.nd.array(xnp))
    snap = {n_: p.data().asnumpy().copy()
            for n_, p in block.collect_params().items()}

    def run(fused):
        for n_, p in block.collect_params().items():
            p.set_data(mx.nd.array(snap[n_]))
        block.hybridize()
        if fused:
            os.environ["MXNET_FUSED_CONVBN"] = "1"
        try:
            with autograd.record():
                out = block(mx.nd.array(xnp))
                loss = (out * out).sum()
            loss.backward()
        finally:
            os.environ.pop("MXNET_FUSED_CONVBN", None)
        grads = {n_: p.grad().asnumpy().copy()
                 for n_, p in block.collect_params().items()
                 if p.grad_req != "null"}
        return out.asnumpy(), grads

    out_r, g_r = run(False)
    out_f, g_f = run(True)
    assert_almost_equal(out_f, out_r, rtol=1e-3, atol=1e-3)
    for n_ in g_r:
        assert_almost_equal(g_f[n_], g_r[n_], rtol=5e-3, atol=5e-3)


def test_pallas_attention_vs_xla_on_chip():
    """Flash-attention Pallas kernel vs the XLA fallback on-chip (the
    committed delta VERDICT asked for lives in BENCH_ALL's bert
    variants; this is the correctness side)."""
    from mxnet_tpu.ops import pallas_attention as pa
    from mxnet_tpu.ops import registry as reg

    rng = np.random.RandomState(2)
    b, s, d = 2, 128, 64
    q = nd.array(rng.randn(b, s, d).astype("float32") * 0.2)
    k = nd.array(rng.randn(b, s, d).astype("float32") * 0.2)
    v = nd.array(rng.randn(b, s, d).astype("float32") * 0.2)
    mask = nd.array(np.ones((b, s), "float32"))
    out_p = nd.dot_product_attention(q, k, v, mask, num_heads=1)
    # Force the XLA path for the second call: flipping the state alone
    # is NOT enough — the first call jit-compiled the op with the
    # Pallas branch baked in, and an identical-shape call would hit the
    # registry's jit cache without re-consulting _pallas_wanted().  A
    # subprocess is off the table (the tunnel is single-client), so
    # clear the op-level jit caches to force a retrace.
    old = pa._PALLAS_STATE["enabled"]
    pa._PALLAS_STATE["enabled"] = False
    saved_jit = dict(reg._jit_cache)
    saved_grad = dict(reg._grad_cache)
    reg._jit_cache.clear()
    reg._grad_cache.clear()
    try:
        out_x = nd.dot_product_attention(q, k, v, mask, num_heads=1)
    finally:
        pa._PALLAS_STATE["enabled"] = old
        reg._jit_cache.update(saved_jit)
        reg._grad_cache.update(saved_grad)
    assert_almost_equal(out_p.asnumpy(), out_x.asnumpy(), rtol=2e-2,
                        atol=2e-2)


def test_deploy_artifact_serves_on_chip(tmp_path):
    """The multi-platform deployment promise on real hardware: export a
    model (lowered for cpu AND tpu), serve it on the TPU backend, and
    match a float32 numpy oracle computed from the same weights."""
    from mxnet_tpu.contrib import deploy
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8))
        net.add(nn.Dense(4, in_units=16))
    net.initialize(mx.initializer.Xavier(), ctx=mx.tpu(0))
    x_np = np.random.RandomState(0).rand(4, 8).astype("float32")
    deploy.export_model(net, str(tmp_path), [mx.nd.array(x_np)])
    served = deploy.import_model(str(tmp_path))
    got = served(mx.nd.array(x_np))
    assert got.ctx.device_type == "tpu"
    # numpy oracle from the exported weights
    p = {n_: v.asnumpy() for n_, v in
         ((n_, pp.data()) for n_, pp in net.collect_params().items())}
    names = sorted(p)
    w0 = p[[n_ for n_ in names if n_.endswith("dense0_weight")][0]]
    b0 = p[[n_ for n_ in names if n_.endswith("dense0_bias")][0]]
    w1 = p[[n_ for n_ in names if n_.endswith("dense1_weight")][0]]
    b1 = p[[n_ for n_ in names if n_.endswith("dense1_bias")][0]]
    h = np.maximum(x_np @ w0.T + b0, 0.0)
    ref = h @ w1.T + b1
    assert_almost_equal(got.asnumpy(), ref, rtol=1e-4, atol=1e-5)
