"""Multi-host (DCN) bootstrap and collectives.

TPU-native counterpart of the reference's ps-lite layer (SURVEY.md N11,
CS5): instead of a ZMQ parameter server with scheduler/server/worker roles,
multi-host jobs run one process per host, bootstrapped by jax.distributed's
coordination service; gradient sync is collective (allreduce over DCN
between slices, ICI within), which is the `dist_sync` semantics.  The
`dist_async` mode of the reference is served by the same path (documented
emulation — SURVEY.md §7 hard part 6).

The launcher env contract is kept bilingual:
  reference (tools/launch.py / dmlc tracker):
      DMLC_ROLE=worker DMLC_PS_ROOT_URI=<ip> DMLC_PS_ROOT_PORT=<port>
      DMLC_NUM_WORKER=<n> DMLC_WORKER_ID=<i>
  jax-native:
      COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID
Either set initializes the same way.
"""
from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

from ..base import MXNetError

__all__ = ["init", "initialized", "rank", "num_workers", "barrier",
           "allreduce_nd", "allgather_np"]

_INITIALIZED = False


def _env(*names, default=None):
    for n in names:
        v = os.environ.get(n)
        if v is not None:
            return v
    return default


def init(coordinator_address: Optional[str] = None,
         num_processes: Optional[int] = None,
         process_id: Optional[int] = None) -> None:
    """Initialize the DCN coordination service (idempotent).

    Reads the DMLC_* contract of the reference's launcher when explicit
    args are absent.  Single-process (no env, no args) is a no-op so the
    same training script runs unmodified on one host.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    if coordinator_address is None:
        uri = _env("DMLC_PS_ROOT_URI")
        port = _env("DMLC_PS_ROOT_PORT", default="9091")
        if uri is not None:
            coordinator_address = f"{uri}:{port}"
        else:
            coordinator_address = _env("COORDINATOR_ADDRESS")
    if num_processes is None:
        v = _env("DMLC_NUM_WORKER", "NUM_PROCESSES")
        num_processes = int(v) if v is not None else None
    if process_id is None:
        # scheduler-provided ranks for the mpi/slurm launchers
        # (tools/launch.py delegates placement to mpirun/srun)
        v = _env("DMLC_WORKER_ID", "PROCESS_ID", "OMPI_COMM_WORLD_RANK",
                 "PMI_RANK", "SLURM_PROCID")
        process_id = int(v) if v is not None else None
    if coordinator_address is None:
        # mpi/slurm launchers delegate placement to mpirun/srun: the
        # coordinator (rank 0's node) is unknowable at launch time, so
        # jax's cluster auto-detection resolves it at runtime here
        if _env("SLURM_JOB_ID", "OMPI_COMM_WORLD_SIZE",
                "PMI_SIZE") is not None:
            jax.distributed.initialize()
            _INITIALIZED = True
            return
        _INITIALIZED = True  # single-process
        return
    role = _env("DMLC_ROLE", default="worker")
    if role in ("scheduler", "server"):
        # The jax coordination service (hosted by worker 0) subsumes the
        # scheduler, and collectives subsume the parameter server.  These
        # roles exist only so reference launchers (tools/launch.py spawning
        # scheduler + servers + workers) run unmodified: they must NOT join
        # the device cluster — worker 0 already owns process_id 0.
        _INITIALIZED = True
        return
    try:
        # CPU cross-process collectives need an explicit implementation
        # (gloo ships in jaxlib); harmless for TPU where ICI/DCN transport
        # is native (ref role: ps-lite ZMQVan -> gloo/ICI substrate)
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _INITIALIZED = True


def initialized() -> bool:
    return _INITIALIZED


def rank() -> int:
    return jax.process_index()


def num_workers() -> int:
    return jax.process_count()


def barrier(name: str = "mxnet_tpu_barrier") -> None:
    """Block until every worker arrives (ref: Postoffice::Barrier)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def allgather_np(value: np.ndarray) -> np.ndarray:
    """Gather a host numpy value from every process -> stacked [n, ...]."""
    if jax.process_count() == 1:
        return np.asarray(value)[None]
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(value))


def allreduce_nd(val):
    """Sum an NDArray across processes over DCN (eager path used by
    KVStore('dist_*'); the SPMD path does this in-graph instead).

    row_sparse inputs stay row_sparse: the dense backing is summed and the
    stored-row sets are unioned (via a fixed-size row mask, so workers may
    hold different nnz)."""
    from ..ndarray.ndarray import NDArray
    from ..ndarray.sparse import RowSparseNDArray

    if jax.process_count() == 1:
        return val
    summed = allgather_np(np.asarray(val._data)).sum(axis=0)
    out = jax.numpy.asarray(summed)
    if isinstance(val, RowSparseNDArray):
        mask = np.zeros((val.shape[0],), np.int32)
        mask[np.asarray(val._aux["indices"])] = 1
        union = allgather_np(mask).max(axis=0)
        idx = jax.numpy.asarray(np.flatnonzero(union).astype(np.int32))
        return RowSparseNDArray(out, {"indices": idx}, ctx=val.ctx)
    if val.stype == "csr":
        from ..ndarray.sparse import cast_storage

        return cast_storage(NDArray(out, ctx=val.ctx), "csr")
    return NDArray(out, ctx=val.ctx)
