"""Vision datasets (ref: python/mxnet/gluon/data/vision/datasets.py:
MNIST, FashionMNIST, CIFAR10/100, ImageRecordDataset, ImageFolderDataset).

Offline container: datasets load from a local `root` in the standard
formats (MNIST idx files, CIFAR binary batches). If the files are absent,
a deterministic synthetic sample set is generated instead so examples,
tests and benchmarks run hermetically — clearly flagged via `.synthetic`.
"""
from __future__ import annotations

import gzip
import os
import struct
from typing import Optional

import numpy as np

from ....base import MXNetError
from ..dataset import ArrayDataset, Dataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


def _synthetic_images(n, shape, num_classes, template_seed, sample_seed):
    """Deterministic class-separable synthetic data: each class gets a
    fixed random template (shared by train AND test via template_seed);
    samples are noisy templates (sample_seed differs per split).  Converges
    like a toy dataset, so training-loop smoke tests are meaningful."""
    t_rng = np.random.RandomState(template_seed)
    templates = t_rng.uniform(0, 255, (num_classes,) + shape).astype("float32")
    s_rng = np.random.RandomState(sample_seed)
    labels = s_rng.randint(0, num_classes, n).astype("int32")
    noise = s_rng.normal(0, 32, (n,) + shape).astype("float32")
    images = np.clip(templates[labels] + noise, 0, 255).astype("uint8")
    return images, labels


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self.synthetic = False
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        from ....ndarray.ndarray import array as nd_array

        img = nd_array(self._data[idx])
        label = self._label[idx]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self._label)


class MNIST(_DownloadedDataset):
    """ref: datasets.py::MNIST — idx-format files in root."""

    _files = {
        True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }
    _shape = (28, 28, 1)
    _classes = 10
    _seed = 42

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        super().__init__(root, train, transform)

    def _read_idx(self, img_path, lbl_path):
        def opener(p):
            return gzip.open(p, "rb") if p.endswith(".gz") else open(p, "rb")

        with opener(lbl_path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), dtype=np.uint8)\
                .astype(np.int32)
        with opener(img_path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), dtype=np.uint8)\
                .reshape(n, rows, cols, 1)
        return images, labels

    def _get_data(self):
        img_name, lbl_name = self._files[self._train]
        for suffix in ("", ".gz"):
            ip = os.path.join(self._root, img_name + suffix)
            lp = os.path.join(self._root, lbl_name + suffix)
            if os.path.exists(ip) and os.path.exists(lp):
                self._data, self._label = self._read_idx(ip, lp)
                return
        self.synthetic = True
        n = 60000 if self._train else 10000
        # cap synthetic size to keep hermetic runs fast
        n = min(n, 8192 if self._train else 2048)
        self._data, self._label = _synthetic_images(
            n, self._shape, self._classes, self._seed,
            self._seed + 1000 + int(self._train))


class FashionMNIST(MNIST):
    _seed = 43

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """ref: datasets.py::CIFAR10 — binary batch files in root."""

    _shape = (32, 32, 3)
    _classes = 10
    _seed = 44

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        super().__init__(root, train, transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as f:
            raw = np.frombuffer(f.read(), dtype=np.uint8)
        rec = raw.reshape(-1, 3072 + 1)
        return rec[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            rec[:, 0].astype(np.int32)

    def _get_data(self):
        if self._train:
            names = [f"data_batch_{i}.bin" for i in range(1, 6)]
        else:
            names = ["test_batch.bin"]
        paths = [os.path.join(self._root, n) for n in names]
        if all(os.path.exists(p) for p in paths):
            data, labels = zip(*[self._read_batch(p) for p in paths])
            self._data = np.concatenate(data)
            self._label = np.concatenate(labels)
            return
        self.synthetic = True
        n = 4096 if self._train else 1024
        self._data, self._label = _synthetic_images(
            n, self._shape, self._classes, self._seed,
            self._seed + 1000 + int(self._train))


class CIFAR100(CIFAR10):
    _classes = 100
    _seed = 45

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar100"),
                 fine_label=False, train=True, transform=None):
        self._fine = fine_label
        super(CIFAR10, self).__init__(root, train, transform)

    def _get_data(self):
        name = "train.bin" if self._train else "test.bin"
        p = os.path.join(self._root, name)
        if os.path.exists(p):
            with open(p, "rb") as f:
                raw = np.frombuffer(f.read(), dtype=np.uint8)
            rec = raw.reshape(-1, 3072 + 2)
            self._data = rec[:, 2:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            self._label = rec[:, 1 if self._fine else 0].astype(np.int32)
            return
        self.synthetic = True
        n = 4096 if self._train else 1024
        self._data, self._label = _synthetic_images(
            n, self._shape, self._classes, self._seed,
            self._seed + 1000 + int(self._train))


class ImageRecordDataset(Dataset):
    """Dataset over an image RecordIO file (ref: ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        from ....recordio import MXIndexedRecordIO, unpack_img

        idx_file = filename[:filename.rfind(".")] + ".idx"
        self._record = MXIndexedRecordIO(idx_file, filename, "r")
        self._flag = flag
        self._transform = transform
        self._unpack = unpack_img

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        from ....ndarray.ndarray import array as nd_array

        record = self._record.read_idx(self._record.keys[idx])
        header, img = self._unpack(record, self._flag)
        label = header.label
        if hasattr(label, "__len__") and len(label) == 1:
            label = float(label[0])
        img = nd_array(img)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(Dataset):
    """class-per-subfolder layout (ref: ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.synsets = []
        self.items = []
        self._list_images(self._root)

    def _list_images(self, root):
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if fname.lower().endswith((".jpg", ".jpeg", ".png", ".npy")):
                    self.items.append((os.path.join(path, fname), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        from ....image import imread
        from ....ndarray.ndarray import array as nd_array

        path, label = self.items[idx]
        if path.endswith(".npy"):
            img = nd_array(np.load(path))
        else:
            img = imread(path, self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label
