// Dependency engine: versioned variables + async op scheduling.
//
// TPU-native counterpart of the reference's engine
// (ref: src/engine/threaded_engine.cc ThreadedVar/ThreadedOpr/OprBlock,
// include/mxnet/engine.h Engine::PushAsync/WaitForVar/WaitForAll;
// naive_engine.cc for the synchronous debug mode).
//
// Role in this framework (SURVEY.md §7): device compute is scheduled by
// PjRt's async streams, so this engine schedules HOST-side work — data
// pipeline stages, decode workers, checkpoint IO, and any user task
// pushed from Python — with the same read/write-variable hazard
// semantics the reference guarantees (WAR/RAW/WAW serialization per
// variable, concurrent reads, FIFO write order).
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include "base.h"

namespace mxt {

typedef void (*EngineFn)(void* arg);

class Engine;

struct Opr {
  EngineFn fn;
  void* arg;
  std::vector<struct Var*> reads;
  std::vector<struct Var*> writes;
  std::atomic<int> wait{0};
  int priority{0};
  bool delete_writes{false};  // final op of DeleteVariable: frees the Var
};

// Versioned variable with the reference's ThreadedVar grant rules:
// reads run concurrently; writes are exclusive and FIFO; reads queued
// behind a write wait for it (ref: threaded_engine.h ThreadedVar).
struct Var {
  std::mutex m;
  struct Entry {
    Opr* opr;
    bool is_write;
  };
  std::deque<Entry> queue;  // not-yet-granted ops, FIFO
  int running_reads = 0;
  bool running_write = false;
  uint64_t version = 0;  // bumped per completed write
};

class Engine {
 public:
  explicit Engine(int num_workers);
  ~Engine();

  int64_t NewVariable();
  void DeleteVariable(int64_t handle);
  void PushAsync(EngineFn fn, void* arg, const int64_t* read_vars,
                 int n_read, const int64_t* write_vars, int n_write,
                 int priority);
  void WaitForVar(int64_t handle);
  void WaitForAll();
  int NumPending();
  uint64_t VarVersion(int64_t handle);
  bool is_naive() const { return workers_.empty(); }

 private:
  Var* GetVar(int64_t handle);
  void GrantLocked(Var* v);           // caller holds v->m
  void DecWait(Opr* opr);
  void PushAsyncVars(EngineFn fn, void* arg, std::vector<Var*> reads,
                     std::vector<Var*> writes, int priority,
                     bool delete_writes);
  void DrainReady();
  void Execute(Opr* opr);
  void CompleteDeps(Opr* opr);
  void WorkerLoop();

  std::mutex vars_m_;
  std::unordered_map<int64_t, Var*> vars_;
  std::atomic<int64_t> next_var_{1};

  std::mutex ready_m_;
  std::condition_variable ready_cv_;
  std::deque<Opr*> ready_hi_, ready_lo_;
  bool shutdown_ = false;

  std::mutex pending_m_;
  std::condition_variable pending_cv_;
  int pending_ = 0;

  std::vector<std::thread> workers_;
};

}  // namespace mxt
