"""Autograd tests (model: tests/python/unittest/test_autograd.py +
check_numeric_gradient from python/mxnet/test_utils.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import autograd as ag


def numeric_grad(f, x, eps=1e-3):
    """Central finite differences of scalar f at numpy x."""
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        i = it.multi_index
        xp, xm = x.copy(), x.copy()
        xp[i] += eps
        xm[i] -= eps
        g[i] = (f(xp) - f(xm)) / (2 * eps)
        it.iternext()
    return g


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with ag.record():
        y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2, 4, 6])


def test_write_vs_add_semantics():
    x = nd.array([1.0, 2.0])
    x.attach_grad()  # write
    for _ in range(2):
        with ag.record():
            (x * x).sum().backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2, 4])
    y = nd.array([1.0, 2.0])
    y.attach_grad(grad_req="add")
    for _ in range(2):
        with ag.record():
            (y * y).sum().backward()
    np.testing.assert_allclose(y.grad.asnumpy(), [4, 8])


def test_multi_path_accumulation():
    x = nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = x * 3 + x * x   # dy/dx = 3 + 2x = 7
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [7.0])


def test_chain_and_branching():
    a = np.random.rand(4, 3).astype("float32") + 0.5
    x = nd.array(a)
    x.attach_grad()
    with ag.record():
        y = (nd.exp(x.log() * 2) + nd.sqrt(x)).sum()
    y.backward()
    expect = 2 * a + 0.5 / np.sqrt(a)
    np.testing.assert_allclose(x.grad.asnumpy(), expect, rtol=1e-4)


def test_numeric_gradient_softmax_ce():
    logits = np.random.randn(4, 5).astype("float32")
    label = np.array([0, 2, 1, 4])

    def f(lg):
        e = np.exp(lg - lg.max(axis=1, keepdims=True))
        p = e / e.sum(axis=1, keepdims=True)
        return -np.log(p[np.arange(4), label]).sum()

    x = nd.array(logits)
    x.attach_grad()
    with ag.record():
        lp = nd.log_softmax(x)
        loss = -nd.pick(lp, nd.array(label.astype("float32")), axis=1).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), numeric_grad(f, logits),
                               rtol=1e-2, atol=1e-3)


def test_detach_and_stop_gradient():
    x = nd.array([3.0])
    x.attach_grad()
    with ag.record():
        y = x * 2
        z = y.detach() * x
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [6.0])
    x.zero_grad()
    with ag.record():
        w = nd.stop_gradient(x * 2) * x
    w.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [6.0])


def test_pause_and_modes():
    x = nd.array([1.0])
    x.attach_grad()
    with ag.record():
        assert ag.is_recording() and ag.is_training()
        with ag.pause():
            assert not ag.is_recording()
            y = x * 2
        assert y._ag_node is None
        with ag.predict_mode():
            assert not ag.is_training()


def test_grad_function():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = (x ** 3).sum()
    (g,) = ag.grad([y], [x])
    np.testing.assert_allclose(g.asnumpy(), [3, 12])
    assert x.grad.asnumpy().tolist() == [0, 0]  # .grad untouched by ag.grad


def test_head_gradient():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = x * 2
    y.backward(nd.array([10.0, 100.0]))
    np.testing.assert_allclose(x.grad.asnumpy(), [20, 200])


def test_custom_function():
    class Square(ag.Function):
        def forward(self, x):
            self.save_for_backward(x)
            return x * x

        def backward(self, dy):
            (x,) = self.saved_tensors
            return 2 * x * dy

    x = nd.array([2.0, 3.0])
    x.attach_grad()
    sq = Square()
    with ag.record():
        y = sq(x)
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4, 6])


def test_deep_chain_no_recursion_error():
    x = nd.ones((2,))
    x.attach_grad()
    with ag.record():
        t = x
        for _ in range(1200):
            t = t + 1
        t.sum().backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [1, 1])


def test_inplace_op_gradient():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = x * 2
        y += x
        y.sum().backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [3, 3])


def test_softmax_output_legacy_grad():
    data = nd.array(np.random.randn(3, 4).astype("float32"))
    label = nd.array([0.0, 1.0, 2.0])
    data.attach_grad()
    with ag.record():
        out = nd.SoftmaxOutput(data, label)
    out.backward()
    sm = out.asnumpy()
    onehot = np.eye(4)[[0, 1, 2]]
    # normalization='null' (default): no batch division, scale 1
    np.testing.assert_allclose(data.grad.asnumpy(), sm - onehot,
                               rtol=1e-5, atol=1e-6)
