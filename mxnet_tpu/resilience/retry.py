"""Retry policy: jittered exponential backoff with a hard budget.

One policy object serves every retryable call site in the framework —
collectives (``parallel/dist.py``), the bucketed gradient allreduce
(``kvstore.pushpull_fused``), checkpoint I/O (``resilience.autockpt``),
and serving execute (``serving/batcher.py``).  The contract:

  * only TRANSIENT errors retry.  An error is transient when its class
    carries ``transient = True`` (:class:`chaos.FaultInjected`, and any
    infra error a site marks), or when the site lists its class in
    ``retry_on``.  Everything else — trace errors, shape mismatches, a
    poisoned collective sequence — re-raises immediately: retrying a
    deterministic bug just triples its latency.
  * each retry bumps ``mx_retry_total{site}`` so a dashboard sees retry
    pressure per site before it becomes an outage, and every backoff
    sleep bumps ``mx_retry_backoff_seconds_total{site}`` — the sleeps
    were invisible wall-clock before; now they are measured whether or
    not the mxgoodput ledger is enabled (when it is, they also land in
    the ``retry_backoff`` badput category).
  * the budget is HARD.  After ``max_attempts`` attempts or once the
    next backoff would overrun ``budget_s`` (or the caller's deadline),
    :class:`RetryExhausted` is raised chained to the last error, with
    every attempt's error in the message — the "retried, then failed
    loudly with the evidence" semantics the chaos suite asserts.

Defaults come from the ``MXNET_RETRY_*`` knobs (util/env.py); call
sites may construct stricter policies.  Jitter seeding: under an
active chaos plan it is deterministic per site (site-name seed) so
chaos experiments replay bit-identically; in production the pid is
mixed in, so a fleet of workers hitting the same fault does NOT retry
in lockstep — which is the point of jitter.
"""
from __future__ import annotations

import os
import random as _random
import time
import zlib
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple

from ..base import MXNetError

__all__ = ["RetryPolicy", "RetryExhausted", "default_policy",
           "is_transient"]


class RetryExhausted(MXNetError):
    """All attempts failed; carries the per-attempt error trail."""

    def __init__(self, site: str, errors):
        trail = "; ".join(f"attempt {i + 1}: {type(e).__name__}: {e}"
                          for i, e in enumerate(errors))
        super().__init__(
            f"retry budget exhausted at site '{site}' after "
            f"{len(errors)} attempt(s): {trail}")
        self.site = site
        self.attempts = len(errors)
        self.errors = list(errors)

    def __reduce__(self):
        # custom-arg __init__ needs an explicit recipe or unpickling
        # (e.g. out of a process-pool worker) raises TypeError
        return (RetryExhausted, (self.site, self.errors))


def is_transient(exc: BaseException,
                 retry_on: Tuple[type, ...] = ()) -> bool:
    """A site may retry `exc`: its class opted in (``transient=True``)
    or the site whitelisted the class."""
    return bool(getattr(exc, "transient", False)) or \
        (bool(retry_on) and isinstance(exc, retry_on))


@dataclass
class RetryPolicy:
    """max_attempts — total tries (1 = no retry).
    base_s/max_s/multiplier — exponential delay ladder, capped.
    jitter — ± fraction of the delay (0.5 = 50%), decorrelates a fleet
    retrying in lockstep.
    budget_s — wall-clock ceiling across ALL attempts incl. sleeps."""

    max_attempts: int = field(default=None)
    base_s: float = field(default=None)
    max_s: float = field(default=None)
    multiplier: float = 2.0
    jitter: float = 0.5
    budget_s: float = field(default=None)

    def __post_init__(self):
        from ..util import env

        if self.max_attempts is None:
            self.max_attempts = env.get_int("MXNET_RETRY_MAX_ATTEMPTS")
        if self.base_s is None:
            self.base_s = env.get_float("MXNET_RETRY_BASE_MS") / 1e3
        if self.max_s is None:
            self.max_s = env.get_float("MXNET_RETRY_MAX_MS") / 1e3
        if self.budget_s is None:
            self.budget_s = env.get_float("MXNET_RETRY_BUDGET_MS") / 1e3

    def delay_s(self, attempt: int, rng=None) -> float:
        """Backoff before attempt `attempt+1` (attempt is 1-based count
        of failures so far), jittered."""
        d = min(self.max_s,
                self.base_s * (self.multiplier ** (attempt - 1)))
        if self.jitter and rng is not None:
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(d, 0.0)

    def call(self, fn: Callable, site: str,
             deadline: Optional[float] = None,
             retry_on: Tuple[type, ...] = (),
             on_failure: Optional[Callable] = None):
        """Run ``fn()`` under this policy.  `deadline` is an absolute
        ``time.monotonic()`` instant no attempt may start after.
        `on_failure(exc)` runs on every failed attempt (circuit-breaker
        feedback) before the retry decision."""
        from ..telemetry import instruments as _ins
        from . import chaos as _chaos

        seed = zlib.crc32(site.encode())
        if not _chaos._ACTIVE:
            # decorrelate the fleet: without this every process would
            # sleep the identical "jittered" ladder.  Chaos runs keep
            # the pure site seed for bit-identical replay.
            seed ^= os.getpid()
        rng = _random.Random(seed)
        start = time.monotonic()
        errors = []
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn()
            except BaseException as e:  # noqa: BLE001 — classified below
                if on_failure is not None:
                    on_failure(e)
                errors.append(e)
                if not is_transient(e, retry_on):
                    raise
                if attempt >= self.max_attempts:
                    self._journal_exhausted(site, "max_attempts",
                                            attempt, e)
                    raise RetryExhausted(site, errors) from e
                delay = self.delay_s(attempt, rng)
                now = time.monotonic()
                over_budget = (now - start) + delay > self.budget_s
                past_deadline = deadline is not None and \
                    now + delay >= deadline
                if over_budget or past_deadline:
                    self._journal_exhausted(
                        site, "budget" if over_budget else "deadline",
                        attempt, e)
                    raise RetryExhausted(site, errors) from e
                _ins.retry_total(site).inc()
                # the sleep is real wall-clock the job is NOT training:
                # measure it always (the counter is free), attribute it
                # when the goodput ledger is on.  overlaps_step=True:
                # a retry under a collective sleeps INSIDE the step's
                # wall, and the ledger must not count those seconds
                # again as comm-stall/productive.
                t_sleep = time.monotonic()
                time.sleep(delay)
                slept = time.monotonic() - t_sleep
                _ins.retry_backoff_seconds_total(site).inc(slept)
                from ..telemetry import mxgoodput as _goodput

                if _goodput._ACTIVE:
                    _goodput.record_badput("retry_backoff", slept,
                                           site=site,
                                           overlaps_step=True)

    @staticmethod
    def _journal_exhausted(site: str, why: str, attempts: int,
                           exc: BaseException) -> None:
        """Blackbox feed: an exhaustion is the moment a transient
        fault became a real failure — exactly what a postmortem needs
        on the timeline."""
        from ..telemetry import mxblackbox as _bb

        if _bb._ACTIVE:
            _bb.emit("retry", f"retry exhausted at '{site}' ({why})",
                     site=site, why=why, attempts=attempts,
                     error=repr(exc))


_DEFAULT = None


def default_policy() -> RetryPolicy:
    """The process-wide env-configured policy (constructed lazily so
    the knobs are read once)."""
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = RetryPolicy()
    return _DEFAULT
