"""mxelastic — multi-host rank-failure detection and coordinated
shrink/replace recovery.

The preemption seam (PR 6) survives a *single-process* SIGTERM:
checkpoint at the step boundary, resume bit-consistent.  The GSPMD
spine (PR 9) made training *multi-process* — and a multi-process job
has a failure mode no single process can recover from alone: one dead
or hung rank wedges every survivor inside a blocking collective until
the watchdog poisons the sequence, and the job is over unless someone
restarts it.  This module is that someone.

Three cooperating pieces (docs/resilience.md, "Elastic recovery"):

  * **worker runtime** — ranks stamp heartbeats (:mod:`.heartbeat`),
    classify a dist-collective watchdog timeout as :class:`PeerFailed`
    (the peer is gone; this process is fine), cut a sync checkpoint at
    the last completed step boundary through the existing preemption
    seam, and exit with a *reserved* rc the supervisor understands:
    ``RC_PEER_FAILED`` (43, "I observed a peer die") or
    ``RC_WINDDOWN`` (44, "the supervisor asked me to stop; my
    checkpoint is on disk").
  * **supervisor** (:class:`Supervisor`, CLI ``tools/elastic_run.py``)
    — launches N workers, watches exit codes + heartbeat ages, and on
    a failure epoch coordinates recovery: wind down survivors, elect a
    job-level **commit marker** (the newest *complete* checkpoint any
    rank holds — every restarted rank resumes from that ONE step
    directory, so resume can never mix steps across ranks), then
    restart in **replace** mode (same world size) or **shrink** mode
    (resume onto the survivors — ``Trainer.load_states(
    allow_resize=True)`` re-shards the state), bounded by a restart
    budget before declaring the job dead.
  * **accounting** — restarts bump ``mx_elastic_restarts_total{mode}``,
    heartbeat ages ride ``mx_rank_heartbeat_age_seconds{rank}``, and a
    peer-failure resume opens the mxgoodput ``rank_failure_recovery``
    badput window (closed at the first post-resume step) so recovery
    is *measured*, never mystery badput.

Disabled path: nothing here runs without the supervisor.  A job
launched plainly has no heartbeat writer, no extra step hooks, and
``elastic.enabled()`` is one env read — zero step cost.
"""
from __future__ import annotations

import contextlib
import json
import os
import signal as _signal
import sys
import time
from typing import Dict, List, Optional

from ..base import MXNetError
from . import preemption
from .preemption import Preempted

__all__ = [
    "PeerFailed", "ScheduleDivergence", "RC_PEER_FAILED",
    "RC_WINDDOWN", "RC_DIVERGENCE", "RESERVED_RCS",
    "enabled", "rank", "world", "shared_dir", "install_winddown",
    "guard", "WorkerContext", "elect_commit", "read_commit",
    "committed_resume_path", "scan_rank_checkpoints", "Supervisor",
]

#: Reserved worker exit codes (the worker<->supervisor rc contract).
#: 43 — this rank OBSERVED a peer failure (watchdog timeout / poisoned
#: sequence), checkpointed where possible, and got out of the way.
#: 44 — supervisor-initiated wind-down (SIGTERM observed at a step
#: boundary, sync checkpoint cut by the preemption seam).
#: 45 — the watchdog-timeout schedule compare proved the ranks issued
#: DIFFERENT collective schedules (mxrank): a deterministic program
#: bug the supervisor must not burn restart budget replaying.
RC_PEER_FAILED = 43
RC_WINDDOWN = 44
RC_DIVERGENCE = 45
RESERVED_RCS = (RC_PEER_FAILED, RC_WINDDOWN, RC_DIVERGENCE)

_COMMIT_NAME = "COMMIT.json"
_RANK_DIR_PREFIX = "rank"


class PeerFailed(MXNetError):
    """A blocking collective gave up on an unreachable peer: either the
    watchdog timed out (``poisoned=False`` on the first fire) or a
    later collective refused because the sequence is already poisoned
    (``poisoned=True``).  NOT transient — this process is out of step
    with its peers and no in-process retry can fix that; the recovery
    is coordinated (checkpoint, exit ``RC_PEER_FAILED``, let the
    supervisor restart the job)."""

    transient = False

    def __init__(self, msg: str, what: str = "", poisoned: bool = False):
        super().__init__(msg)
        self.what = what
        self.poisoned = poisoned

    def __reduce__(self):
        return (PeerFailed, (str(self), self.what, self.poisoned))


class ScheduleDivergence(MXNetError):
    """The watchdog fired AND the cross-rank schedule compare proved
    the ranks issued *different* collectives at the same sequence
    index — the deterministic rank-/data-divergent control-flow bug
    class the static MX019/MX020 rules flag at lint time
    (``parallel/schedule.py`` is the runtime ledger behind the
    compare).  NOT transient, and unlike :class:`PeerFailed` a
    restart cannot help: every generation replays the same divergent
    schedule, so the supervisor treats this as job-fatal without
    consuming restart budget.  Deliberately a SIBLING of PeerFailed,
    not a subclass — ``except PeerFailed`` recovery paths must never
    swallow a program bug as a dead peer."""

    transient = False

    def __init__(self, msg: str, what: str = "",
                 seq: Optional[int] = None, mine=None, theirs=None,
                 peer: Optional[int] = None):
        super().__init__(msg)
        self.what = what
        self.seq = seq          # first divergent seq index
        self.mine = list(mine or ())    # this rank's site trail
        self.theirs = list(theirs or ())  # the peer's site trail
        self.peer = peer

    def __reduce__(self):
        return (ScheduleDivergence,
                (str(self), self.what, self.seq, self.mine,
                 self.theirs, self.peer))


# ---------------------------------------------------------------------------
# worker-side runtime
# ---------------------------------------------------------------------------

def enabled() -> bool:
    """True when this process runs under the elastic supervisor
    (``MXNET_ELASTIC=1`` in the env the supervisor exports)."""
    from ..util import env

    return bool(env.get_bool("MXNET_ELASTIC"))


def rank() -> Optional[int]:
    from ..util import env

    return env.get_int("MXNET_ELASTIC_RANK")


def world() -> Optional[int]:
    from ..util import env

    return env.get_int("MXNET_ELASTIC_WORLD")


def shared_dir() -> Optional[str]:
    from ..util import env

    d = env.get_str("MXNET_ELASTIC_DIR")
    return d or None


def install_winddown() -> None:
    """Install the elastic SIGTERM handler: under the supervisor a
    worker SIGTERM means "a peer failed; wind down" — the trigger
    reason is marked ``peer-failure`` so the checkpoint meta (and the
    goodput recovery window a resume opens) lands in
    ``rank_failure_recovery``, not ``preemption_recovery``.  The
    previous handler is chained."""
    prev = _signal.getsignal(_signal.SIGTERM)

    def _handler(signum, frame, _prev=prev):
        preemption.trigger(
            reason="peer-failure: supervisor wind-down (SIGTERM)")
        if callable(_prev):
            _prev(signum, frame)

    _signal.signal(_signal.SIGTERM, _handler)


def _hard_exit(code: int) -> None:
    """Exit without the interpreter teardown: the jax coordination
    service's shutdown barrier would block ~100s waiting for the dead
    peer (the same rationale as ``dist.abort``)."""
    sys.stderr.flush()
    sys.stdout.flush()
    os._exit(code)


@contextlib.contextmanager
def guard(auto_ckpt=None, exit_fn=None):
    """Wrap a training loop with the worker side of the rc contract:

      * :class:`PeerFailed` (watchdog timeout / poisoned sequence) —
        cut a best-effort sync checkpoint at the last completed step
        boundary (the failed collective never wrote back, so the
        parameters ARE the last boundary), stamped ``peer_failure``,
        then exit ``RC_PEER_FAILED``;
      * :class:`ScheduleDivergence` (the timeout's schedule compare
        proved a program bug, not a dead peer) — same best-effort
        checkpoint, then exit ``RC_DIVERGENCE`` so the supervisor
        aborts the job instead of burning restarts replaying it;
      * :class:`Preempted` (supervisor wind-down observed at a step
        boundary; the seam already saved synchronously) — exit
        ``RC_WINDDOWN``.

    ``exit_fn`` is injectable for tests; the default is a hard
    ``os._exit`` (see :func:`_hard_exit`)."""
    from ..telemetry import mxblackbox as _bb

    ex = exit_fn or _hard_exit
    try:
        yield
    except ScheduleDivergence as e:
        if _bb._ACTIVE:
            _bb.emit("elastic",
                     f"schedule divergence: {e.what or 'collective'}",
                     seq=e.seq, peer=e.peer)
        if auto_ckpt is not None:
            try:
                auto_ckpt.stamp_failure(f"schedule-divergence: {e}")
                auto_ckpt.save(sync=True)
            except BaseException as save_err:  # noqa: BLE001
                print(f"[mxelastic] divergence checkpoint failed: "
                      f"{save_err}", file=sys.stderr, flush=True)
        if _bb._ACTIVE:
            _bb.write_crash_bundle(
                "schedule_divergence", reason=str(e), exc=e,
                exit_record={"rc": RC_DIVERGENCE, "seq": e.seq,
                             "mine": e.mine, "theirs": e.theirs})
        ex(RC_DIVERGENCE)
    except PeerFailed as e:
        if _bb._ACTIVE:
            _bb.emit("elastic",
                     f"peer failure observed: {e.what or 'collective'}",
                     poisoned=e.poisoned)
        if auto_ckpt is not None:
            try:
                auto_ckpt.stamp_failure(f"peer-failure: {e}")
                auto_ckpt.save(sync=True)
            except BaseException as save_err:  # noqa: BLE001
                # the checkpoint is best-effort — an older complete one
                # (or another rank's) still commits; exiting with the
                # reserved rc is what recovery actually depends on
                print(f"[mxelastic] peer-failure checkpoint failed: "
                      f"{save_err}", file=sys.stderr, flush=True)
        if _bb._ACTIVE:
            # bundle AFTER the save so the journal tail shows the
            # stamp+checkpoint this exit cut; category 'peer_failed'
            # is a coordinated exit — postmortem never attributes the
            # first failure to the rank that merely OBSERVED it
            _bb.write_crash_bundle(
                "peer_failed", reason=str(e), exc=e,
                exit_record={"rc": RC_PEER_FAILED})
        ex(RC_PEER_FAILED)
    except Preempted as e:
        if _bb._ACTIVE:
            _bb.write_crash_bundle(
                "preempted", reason=str(e),
                exit_record={"rc": RC_WINDDOWN})
        ex(RC_WINDDOWN)


class WorkerContext:
    """The worker-side per-step runtime under the supervisor: stamps
    the rank's heartbeat and probes the ``elastic.worker`` chaos site
    (default action ``die`` — the deterministic one-rank kill/hang the
    chaos e2e injects via ``elastic.worker@N:die:rank=K``).  Construct
    only when :func:`enabled`; a plain job never pays for it."""

    def __init__(self, directory: Optional[str] = None,
                 worker_rank: Optional[int] = None):
        from .heartbeat import HeartbeatWriter

        d = directory or shared_dir()
        r = worker_rank if worker_rank is not None else rank()
        if d is None or r is None:
            raise MXNetError(
                "WorkerContext needs the elastic env contract "
                "(MXNET_ELASTIC_DIR + MXNET_ELASTIC_RANK) or explicit "
                "directory/worker_rank")
        self.rank = int(r)
        self.heartbeat = HeartbeatWriter(d, self.rank)
        from ..telemetry import mxblackbox as _bb

        if _bb._ACTIVE:
            # identical msg on every rank of a generation: postmortem
            # uses matched elastic events as clock-sync marks
            _bb.emit("elastic", "generation start",
                     rank=self.rank, world=world())

    def on_step(self, step: int) -> None:
        """Call once per training step: chaos probe first (a ``die``
        plan kills THIS step, before the beat, so the stamp's age
        reflects the last completed step), then the heartbeat."""
        from . import chaos as _chaos

        if _chaos._ACTIVE:
            if _chaos.check("elastic.worker") == "die":
                from ..telemetry import mxblackbox as _bb

                if _bb._ACTIVE:
                    # the dying rank's own flight record — the known-
                    # answer source postmortem attributes first
                    # failure from (category, rank, kill step)
                    _bb.write_crash_bundle(
                        "chaos",
                        reason="chaos die at elastic.worker",
                        step=step,
                        exit_record={"rc": 1, "cause": "chaos-die"})
                _hard_exit(1)  # an unreserved rc: this rank IS the failure
        self.heartbeat.beat(step=step)


# ---------------------------------------------------------------------------
# the job-level commit marker
# ---------------------------------------------------------------------------

def _complete_step_dirs(rank_dir: str) -> Dict[int, str]:
    """step -> path of every COMPLETE checkpoint under one rank dir
    (all three files present; ``.tmp-`` write residue ignored)."""
    out: Dict[int, str] = {}
    try:
        names = os.listdir(rank_dir)
    except OSError:
        return out
    for name in names:
        if not name.startswith("step-"):
            continue
        try:
            step = int(name[len("step-"):])
        except ValueError:
            continue
        path = os.path.join(rank_dir, name)
        if all(os.path.exists(os.path.join(path, f))
               for f in ("meta.json", "params.npz", "trainer.states")):
            out[step] = path
    return out


def scan_rank_checkpoints(directory: str) -> Dict[int, Dict[int, str]]:
    """``{rank: {step: path}}`` over every ``rank<k>/`` checkpoint
    subdirectory of the shared elastic dir."""
    out: Dict[int, Dict[int, str]] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        if not name.startswith(_RANK_DIR_PREFIX):
            continue
        try:
            r = int(name[len(_RANK_DIR_PREFIX):])
        except ValueError:
            continue
        steps = _complete_step_dirs(os.path.join(directory, name))
        if steps:
            out[r] = steps
    return out


def elect_commit(directory: str, cause: str = "rank_failure",
                 epoch: int = 0,
                 failed_ranks: Optional[List[int]] = None,
                 incident_id: Optional[str] = None) -> dict:
    """Pick the job-level resume point and write ``COMMIT.json``
    (atomically): the HIGHEST step for which any rank holds a complete
    checkpoint (ties go to the lowest rank — deterministic).  Every
    restarted rank resumes from that ONE step directory, which is what
    makes "resume can never mix steps across ranks" structural rather
    than hoped-for.  Sync data-parallel training keeps parameters and
    optimizer state identical across ranks, so any rank's complete
    checkpoint serves the whole job (and ``load_states(
    allow_resize=True)`` re-shards it onto a different world size in
    shrink mode).  ``step`` 0 with no path = no checkpoint yet; the
    restarted job starts fresh.

    ``incident_id`` is the mxblackbox postmortem id of the failure
    epoch this commit recovers from: restarted ranks read it off the
    marker and stamp it into the goodput recovery window
    (``AutoCheckpoint.resume(incident=...)``)."""
    ckpts = scan_rank_checkpoints(directory)
    best_step, best_rank, best_path = 0, None, None
    for r in sorted(ckpts):
        for step, path in ckpts[r].items():
            if step > best_step:
                best_step, best_rank, best_path = step, r, path
    commit = {
        "step": best_step,
        "source_rank": best_rank,
        "path": os.path.relpath(best_path, directory)
        if best_path else None,
        "cause": cause,
        "epoch": int(epoch),
        "failed_ranks": sorted(failed_ranks or []),
        "incident": incident_id,
        "t_unix": time.time(),
    }
    # same crash-consistency bar as the checkpoints it elects: fsync
    # the payload before the rename and the parent dir after it — a
    # machine crash racing writeback must not lose the marker and
    # silently restart the whole job from step 0
    from .autockpt import AutoCheckpoint

    tmp = os.path.join(directory, f".tmp-{_COMMIT_NAME}")
    AutoCheckpoint._write_file(tmp, json.dumps(commit, indent=1),
                               mode="w")
    os.replace(tmp, os.path.join(directory, _COMMIT_NAME))
    AutoCheckpoint._fsync_dir(directory)
    return commit


def read_commit(directory: str) -> Optional[dict]:
    try:
        with open(os.path.join(directory, _COMMIT_NAME)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def committed_resume_path(directory: str) -> Optional[str]:
    """Absolute step-dir path of the committed resume point (None when
    there is no commit marker or it names no checkpoint)."""
    commit = read_commit(directory)
    if not commit or not commit.get("path"):
        return None
    return os.path.join(os.path.abspath(directory), commit["path"])


# ---------------------------------------------------------------------------
# the supervisor
# ---------------------------------------------------------------------------

class Supervisor:
    """Launch N copies of one worker command, watch them, recover.

    The worker command is rank-agnostic; each rank gets the elastic env
    contract (``MXNET_ELASTIC=1``, ``MXNET_ELASTIC_DIR/RANK/WORLD``)
    plus the dmlc launcher contract (``DMLC_*`` with a fresh
    coordinator port per generation — a restarted jax coordination
    service must not collide with the dying one's socket).  Chaos env
    (``MXNET_CHAOS*``) is forwarded ONLY to generation 0: an injected
    fault describes the first life of the job, not an affliction every
    recovery must re-suffer (``at=N`` schedules would otherwise re-kill
    the respawned rank at its Nth call, forever).

    One failure epoch = detect -> wind down survivors (SIGTERM; they
    checkpoint via the preemption seam and exit a reserved rc; anything
    still alive after the grace window is SIGKILLed and classified
    failed/hung) -> elect the commit marker -> restart (``replace``
    keeps the world size, ``shrink`` drops the failed ranks) -> watch
    heartbeats until a rank reports a step past the committed one (the
    MTTR end mark).  ``max_restarts`` epochs and the job is dead."""

    def __init__(self, worker_cmd: List[str], world: int,
                 directory: str, mode: str = "replace",
                 max_restarts: Optional[int] = None,
                 hb_timeout_s: Optional[float] = None,
                 grace_s: Optional[float] = None,
                 collective_timeout_s: Optional[float] = None,
                 poll_s: float = 0.25,
                 startup_timeout_s: Optional[float] = None,
                 coordinator_host: str = "127.0.0.1",
                 base_env: Optional[dict] = None):
        from ..util import env

        if mode not in ("replace", "shrink"):
            raise MXNetError(f"elastic mode {mode!r}: expected "
                             "'replace' or 'shrink'")
        self.worker_cmd = list(worker_cmd)
        self.world = int(world)
        self.dir = os.path.abspath(directory)
        os.makedirs(self.dir, exist_ok=True)
        self.mode = mode
        self.max_restarts = max_restarts if max_restarts is not None \
            else env.get_int("MXNET_ELASTIC_MAX_RESTARTS")
        self.hb_timeout = hb_timeout_s if hb_timeout_s is not None \
            else env.get_float("MXNET_ELASTIC_HEARTBEAT_TIMEOUT_S")
        self.collective_timeout = collective_timeout_s \
            if collective_timeout_s is not None else self.hb_timeout
        self.grace = grace_s if grace_s is not None else max(
            env.get_float("MXNET_ELASTIC_GRACE_S"),
            self.collective_timeout + 5.0)
        self.poll_s = float(poll_s)
        # liveness bound for ranks that never produce a FIRST stamp: a
        # worker wedged before its first beat (stuck import, a hang
        # before WorkerContext) has no exit code and no stamp to age,
        # so without this the supervisor would spin forever — the
        # exact wedge it exists to prevent, one level up.  None (the
        # default) = AUTO: the bound (max(60, 4x hb timeout)) arms
        # only once some rank of this job has actually stamped — a
        # supervised command that never integrates heartbeats is
        # watched by exit codes alone instead of being declared hung
        # at 60s while healthy.  Explicit seconds force it on; 0
        # forces it off.
        self.startup_timeout = startup_timeout_s
        self._saw_stamps = False
        self.host = coordinator_host
        self.base_env = dict(base_env if base_env is not None
                             else os.environ)
        self.log_dir = os.path.join(self.dir, "logs")
        os.makedirs(self.log_dir, exist_ok=True)
        # crash forensics: workers journal/bundle here, the supervisor
        # scrapes SIGKILLed ranks into it, postmortem merges it
        self.blackbox_dir = os.path.join(self.dir, "blackbox")
        os.makedirs(self.blackbox_dir, exist_ok=True)

    # -- spawning ---------------------------------------------------------

    @staticmethod
    def _free_port() -> int:
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def _worker_env(self, gen: int, i: int, n: int, port: int) -> dict:
        env = dict(self.base_env)
        if gen > 0:
            # chaos describes generation 0 only (see class docstring)
            env.pop("MXNET_CHAOS", None)
            env.pop("MXNET_CHAOS_SPEC", None)
        env.update({
            "MXNET_ELASTIC": "1",
            "MXNET_ELASTIC_DIR": self.dir,
            "MXNET_ELASTIC_RANK": str(i),
            "MXNET_ELASTIC_WORLD": str(n),
            "DMLC_ROLE": "worker",
            "DMLC_PS_ROOT_URI": self.host,
            "DMLC_PS_ROOT_PORT": str(port),
            "DMLC_NUM_WORKER": str(n),
            "DMLC_WORKER_ID": str(i),
        })
        # the watchdog IS the in-collective failure detector: without
        # it a dead peer means an infinite hang no supervisor can
        # distinguish from slow compile.  An operator override stands.
        env.setdefault("MXNET_KVSTORE_TIMEOUT",
                       str(self.collective_timeout))
        # crash forensics ride every supervised worker (an operator's
        # explicit MXNET_BLACKBOX=0 / custom dir stands); the
        # generation stamp is per-spawn, never inherited
        env.setdefault("MXNET_BLACKBOX", "1")
        env.setdefault("MXNET_BLACKBOX_DIR", self.blackbox_dir)
        env["MXNET_BLACKBOX_GEN"] = str(gen)
        return env

    def _spawn(self, gen: int, n: int) -> List[dict]:
        import subprocess

        port = self._free_port()
        workers = []
        for i in range(n):
            log_path = os.path.join(self.log_dir,
                                    f"gen{gen}-rank{i}.log")
            # stderr gets its OWN per-rank per-generation file (not
            # merged into stdout): the crash bundle attaches its tail,
            # and a traceback must not interleave with step output
            err_path = os.path.join(self.log_dir,
                                    f"gen{gen}-rank{i}.stderr")
            log = open(log_path, "w")
            err = open(err_path, "w")
            p = subprocess.Popen(self.worker_cmd,
                                 env=self._worker_env(gen, i, n, port),
                                 stdout=log, stderr=err)
            workers.append({"rank": i, "proc": p, "log": log,
                            "log_path": log_path, "err": err,
                            "err_path": err_path})
        return workers

    @staticmethod
    def _close_logs(workers: List[dict]) -> None:
        for w in workers:
            for key in ("log", "err"):
                try:
                    w[key].close()
                except (OSError, KeyError):
                    pass  # mxlint: disable=MX007 — log fd teardown only

    @staticmethod
    def _teardown(workers: List[dict]) -> None:
        """Kill whatever is still alive of one generation — the
        supervisor dying (Ctrl-C, an outer timeout's SIGTERM) must
        never orphan N training processes still holding the
        coordinator port and writing into the shared dir."""
        for w in workers:
            if w["proc"].poll() is None:
                try:
                    w["proc"].kill()
                except OSError:
                    pass  # mxlint: disable=MX007 — exited under us
        import subprocess

        for w in workers:
            if w["proc"].poll() is None:
                try:
                    w["proc"].wait(timeout=10)
                except (subprocess.TimeoutExpired, OSError):
                    pass  # mxlint: disable=MX007 — unwaitable zombie;
                    # the kill above was delivered, nothing more to do

    def _tails(self, workers: List[dict], lines: int = 12) -> dict:
        out = {}
        for w in workers:
            try:
                with open(w["log_path"]) as f:
                    out[str(w["rank"])] = "\n".join(
                        f.read().splitlines()[-lines:])
            except OSError:
                out[str(w["rank"])] = "(log unreadable)"
            err = self._stderr_tail(w, lines * 400)
            if err:
                out[str(w["rank"])] += "\n--- stderr ---\n" + err
        return out

    @staticmethod
    def _stderr_tail(w: dict, max_bytes: Optional[int] = None) -> str:
        """Bounded tail of one worker's stderr file (what the scrape
        bundle attaches)."""
        from ..util import env

        if max_bytes is None:
            max_bytes = (env.get_int("MXNET_BLACKBOX_STDERR_TAIL_KB")
                         or 64) * 1024
        path = w.get("err_path")
        if not path:
            return ""
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - max_bytes))
                return f.read().decode("utf-8", "replace")
        except OSError:
            return ""

    # -- one generation ---------------------------------------------------

    def _watch(self, workers: List[dict], mon, committed_step: int,
               watch_first_step: bool, gen: int = 0) -> dict:
        """Watch one generation to completion or failure epoch.
        Returns {"ok": True} or {"ok": False, "failed": [...],
        "t_detect": mono, "t_first_step": mono|None, ...}."""
        t_first_step = None
        gen_t0 = time.monotonic()
        while True:
            time.sleep(self.poll_s)
            # ONE heartbeat-directory scan per poll feeds every
            # consumer below (shared checkpoint filesystems are slow;
            # stale() + max_step() would double the I/O)
            stamps = mon.read()
            if stamps:
                self._saw_stamps = True  # this job DOES heartbeat
            if watch_first_step and t_first_step is None:
                steps = [s["step"] for s in stamps.values()
                         if s.get("step") is not None]
                if steps and max(steps) > committed_step:
                    t_first_step = time.monotonic()
            rcs = {w["rank"]: w["proc"].poll() for w in workers}
            bad = [r for r, rc in rcs.items()
                   if rc is not None and rc != 0]
            if not bad:
                alive = [r for r, rc in rcs.items() if rc is None]
                if not alive:
                    return {"ok": True, "t_first_step": t_first_step}
                hung = [r for r in alive if r in stamps
                        and stamps[r]["age_s"] > self.hb_timeout]
                startup = self.startup_timeout \
                    if self.startup_timeout is not None else (
                        max(60.0, 4.0 * self.hb_timeout)
                        if self._saw_stamps else 0.0)
                if not hung and startup and \
                        time.monotonic() - gen_t0 > startup:
                    # a heartbeating job's rank never produced a FIRST
                    # stamp inside the startup window: wedged before
                    # its first beat
                    hung = [r for r in alive if r not in stamps]
                if not hung:
                    continue
            # --- failure epoch: wind down, classify ---
            t_detect = time.monotonic()
            t_detect_unix = time.time()
            for w in workers:
                if w["proc"].poll() is None:
                    try:
                        w["proc"].send_signal(_signal.SIGTERM)
                    except OSError:
                        pass  # mxlint: disable=MX007 — exited under us
            deadline = time.monotonic() + self.grace
            while time.monotonic() < deadline and \
                    any(w["proc"].poll() is None for w in workers):
                time.sleep(self.poll_s)
            killed = []
            for w in workers:
                if w["proc"].poll() is None:
                    killed.append(w["rank"])
                    try:
                        w["proc"].kill()
                    except OSError:
                        pass  # mxlint: disable=MX007 — exited under us
                    w["proc"].wait()
            rcs = {w["rank"]: w["proc"].returncode for w in workers}
            # failed = died with an unreserved rc, or hung past grace;
            # reserved rcs are survivors doing the coordinated exit
            failed = sorted(set(killed) | {
                r for r, rc in rcs.items()
                if rc not in (0,) + RESERVED_RCS})
            exits = self._exit_records(workers, killed)
            self._scrape_failed(workers, failed, exits, gen, stamps)
            return {"ok": False, "failed": failed, "rcs": rcs,
                    "exits": exits,
                    "t_detect": t_detect,
                    "t_detect_unix": t_detect_unix,
                    "t_first_step": t_first_step,
                    "tails": self._tails(workers)}

    @staticmethod
    def _exit_records(workers: List[dict], killed: List[int]) -> dict:
        """Per-rank exit classification that keeps the SIGNAL, not
        just the rc: Popen returncode < 0 is death-by-signal
        (``WTERMSIG``), and whether the SIGKILL was the supervisor's
        own grace-expiry kill or came from outside (the OOM killer)
        changes the incident's meaning entirely — a chaos ``die``
        (plain rc 1) must never read like either."""
        from ..telemetry.mxblackbox import signal_name

        out = {}
        for w in workers:
            rc = w["proc"].returncode
            sig = -rc if rc is not None and rc < 0 else None
            if w["rank"] in killed:
                classified = "hung"  # supervisor SIGKILL at grace end
            elif rc == 0:
                classified = "clean"
            elif rc == RC_PEER_FAILED:
                classified = "peer_failed"
            elif rc == RC_WINDDOWN:
                classified = "winddown"
            elif rc == RC_DIVERGENCE:
                classified = "divergence"
            elif sig is not None:
                # killed from OUTSIDE the supervisor: OOM killer,
                # operator kill, a segfault's SIGSEGV
                classified = f"killed:{signal_name(sig)}"
            else:
                classified = "died"
            out[str(w["rank"])] = {
                "rc": rc,
                "signal": sig,
                "signal_name": signal_name(sig),
                "supervisor_sigkill": w["rank"] in killed,
                "classified": classified,
            }
        return out

    def _scrape_failed(self, workers: List[dict], failed: List[int],
                       exits: dict, gen: int, stamps: dict) -> None:
        """Supervisor-side crash bundles for the ranks that could not
        write their own (SIGKILLed / hung / died hard): scrape the
        rank's journal spill + stderr tail + last heartbeat.  Best-
        effort — forensics never block recovery."""
        from ..telemetry.mxblackbox import write_supervisor_bundle

        for w in workers:
            r = w["rank"]
            if r not in failed:
                continue
            try:
                hb = stamps.get(r)
                write_supervisor_bundle(
                    self.blackbox_dir, r, exits[str(r)], gen=gen,
                    stderr_path=w.get("err_path"),
                    stderr_tail=self._stderr_tail(w),
                    heartbeat=dict(hb) if isinstance(hb, dict)
                    else None)
            except Exception:  # noqa: BLE001  # mxlint: disable=MX007 — forensics never block recovery
                pass

    # -- the job ----------------------------------------------------------

    def run(self) -> dict:
        """Supervise until success, or until the restart budget is
        spent.  Returns the job report (also what
        ``tools/elastic_run.py`` prints as JSON)."""
        from .heartbeat import HeartbeatMonitor

        mon = HeartbeatMonitor(self.dir)
        report = {"ok": False, "mode": self.mode,
                  "world_start": self.world, "restarts": 0,
                  "epochs": []}
        n = self.world
        gen = 0
        pending = None  # the epoch awaiting its first-post-resume step
        current: List[dict] = []
        try:
            return self._run_loop(mon, report, n, gen, pending,
                                  current)
        finally:
            # an interrupt/crash anywhere above (Ctrl-C in a poll
            # sleep, an outer SIGTERM converted to SystemExit) must
            # not orphan the live generation
            self._teardown(current)

    def _postmortem(self, epoch: int, gen: int,
                    res: dict) -> Optional[dict]:
        """Reconstruct one failure epoch's incident from the blackbox
        dir (merged cross-rank journals, first-failure attribution)
        into ``blackbox/INCIDENT-epoch<N>.json``.  Best-effort."""
        from ..telemetry.mxblackbox import postmortem as _pm

        return _pm.run_epoch(
            self.blackbox_dir, epoch, gen=gen,
            t_detect_unix=res.get("t_detect_unix"),
            failed_ranks=res.get("failed"),
            exits=res.get("exits"))

    def _run_loop(self, mon, report, n, gen, pending,
                  current: List[dict]) -> dict:
        from ..telemetry import instruments as _ins

        while True:
            mon.clear()
            committed = read_commit(self.dir) if gen > 0 else None
            committed_step = committed["step"] if committed else 0
            workers = self._spawn(gen, n)
            current[:] = workers
            try:
                res = self._watch(workers, mon, committed_step,
                                  watch_first_step=pending is not None,
                                  gen=gen)
            finally:
                self._close_logs(workers)
            current[:] = []  # _watch returns only after every exit
            if pending is not None:
                # MTTR = detection -> first post-resume step (restart
                # time is inside it; the step is the proof training
                # actually recovered, not just that processes exist).
                # The private monotonic stamp is popped UNCONDITIONALLY
                # — it must never leak into the persisted report when
                # the resumed generation dies before its first step.
                t_det = pending.pop("_t_detect")
                t1 = res.get("t_first_step")
                pending["mttr_s"] = round(t1 - t_det, 3) \
                    if t1 is not None else None
                pending = None
            if res["ok"]:
                report["ok"] = True
                report["final_world"] = n
                return report
            diverged = sorted(
                int(r) for r, e in res.get("exits", {}).items()
                if e.get("classified") == "divergence")
            if diverged:
                # a schedule divergence is a deterministic program
                # bug: every restart replays the identical divergent
                # collective sequence, so the job is fatal NOW — zero
                # restarts consumed, budget untouched
                incident = self._postmortem(
                    report["restarts"] + 1, gen, res)
                report["epochs"].append({
                    "failed_ranks": res["failed"],
                    "rcs": {str(k): v for k, v in res["rcs"].items()},
                    "exits": res.get("exits", {}),
                    "incident_id": incident.get("incident_id")
                    if incident else None,
                    "world_before": n,
                    "mttr_s": None,
                    "schedule_divergence": True,
                    "diverged_ranks": diverged,
                    "log_tails": res["tails"],
                })
                report["final_world"] = n
                report["error"] = (
                    f"schedule divergence on rank(s) {diverged}: the "
                    "ranks issued different collective sequences — a "
                    "deterministic program bug (see MX019/MX020); "
                    "restarting would replay it, job aborted with 0 "
                    "restarts consumed")
                _ins.elastic_restarts_total("aborted").inc()
                return report
            report["restarts"] += 1
            # incident reconstruction BEFORE the commit election so
            # the marker (and through it every restarted rank's
            # recovery window) carries the incident id
            incident = self._postmortem(report["restarts"], gen, res)
            epoch = {
                "failed_ranks": res["failed"],
                "rcs": {str(k): v for k, v in res["rcs"].items()},
                "exits": res.get("exits", {}),
                "incident_id": incident.get("incident_id")
                if incident else None,
                "world_before": n,
                "_t_detect": res["t_detect"],
                "mttr_s": None,
            }
            if report["restarts"] > self.max_restarts:
                epoch.pop("_t_detect")
                epoch["budget_exhausted"] = True
                epoch["log_tails"] = res["tails"]
                report["epochs"].append(epoch)
                report["final_world"] = n
                report["error"] = (
                    f"restart budget ({self.max_restarts}) exhausted; "
                    f"job dead")
                # job-fatal outcomes get their own counter label —
                # reusing the recovery mode here would read as one
                # more measured recovery when the job in fact died
                _ins.elastic_restarts_total("aborted").inc()
                return report
            if self.mode == "shrink":
                # shrink by the ranks actually IDENTIFIED as failed;
                # an epoch where every rank exited a reserved rc (e.g.
                # a spurious watchdog fire) names nobody — restarting
                # at full size is right, discarding a healthy machine
                # is not
                n = max(1, n - len(res["failed"]))
            commit = elect_commit(self.dir, cause="rank_failure",
                                  epoch=report["restarts"],
                                  failed_ranks=res["failed"],
                                  incident_id=epoch["incident_id"])
            epoch["committed_step"] = commit["step"]
            epoch["committed_source_rank"] = commit["source_rank"]
            epoch["world_after"] = n
            report["epochs"].append(epoch)
            _ins.elastic_restarts_total(self.mode).inc()
            pending = epoch
            gen += 1
