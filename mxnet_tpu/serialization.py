"""NDArray binary serialization — the `.params` format.

Counterpart of the reference's NDArray::Save/Load
(ref: src/ndarray/ndarray.cc, magic-tagged dmlc::Stream binary holding
{names -> arrays}; python surface mx.nd.save/load and
Block.save_parameters).  Layout implemented here (little-endian):

  file:   u64 list_magic (0x112)   # kMXAPINDArrayListMagic
          u64 reserved (0)
          u64 n_arrays, n_arrays * ndarray_record
          u64 n_names,  n_names  * (u64 len, utf-8 bytes)
  record: u32 NDARRAY_V2_MAGIC (0xF993FAC9)
          u32 stype (0 = default dense)
          u32 ndim, ndim * i64 dims
          i32 dev_type, i32 dev_id
          i32 type_flag (MXNet dtype code)
          raw data bytes (C order)

The list/array magics follow the reference's published constants so files
round-trip with MXNet-1.x-lineage tooling; bfloat16 uses type_flag 12 and
is stored as raw uint16 words.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Sequence, Union

import numpy as np

from .base import MXNetError
from .context import cpu
from .ndarray.ndarray import NDArray, array as nd_array

LIST_MAGIC = 0x112
NDARRAY_V2_MAGIC = 0xF993FAC9

# MXNet type_flag codes (ref: include/mxnet/base.h mshadow type enum)
_TYPE_FLAG = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3,
              "int32": 4, "int8": 5, "int64": 6, "bool": 7, "bfloat16": 12}
_FLAG_TYPE = {v: k for k, v in _TYPE_FLAG.items()}


def _dtype_name(nd: NDArray) -> str:
    return str(nd.data.dtype)


_STYPE_ID = {"default": 0, "row_sparse": 1, "csr": 2}


def _raw_bytes(a, name):
    if name == "bfloat16":
        import ml_dtypes

        return a.astype(np.float32).astype(ml_dtypes.bfloat16).tobytes(), \
            _TYPE_FLAG["bfloat16"]
    flag = _TYPE_FLAG.get(name)
    if flag is None:
        raise MXNetError(f"cannot serialize dtype {name}")
    return np.ascontiguousarray(a).tobytes(), flag


def _write_one(f, nd: NDArray):
    from .ndarray.sparse import BaseSparseNDArray, CSRNDArray

    stype = _STYPE_ID[nd.stype]
    f.write(struct.pack("<II", NDARRAY_V2_MAGIC, stype))
    if stype == 0:
        a = nd.asnumpy()
        data, flag = _raw_bytes(a, _dtype_name(nd))
        f.write(struct.pack("<I", a.ndim))
        f.write(struct.pack(f"<{a.ndim}q", *a.shape))
        f.write(struct.pack("<ii", 1, 0))  # saved ctx: cpu(0), like reference
        f.write(struct.pack("<i", flag))
        f.write(data)
        return
    # sparse record (ref: NDArray::Save sparse branch): full shape + ctx +
    # dtype, then aux arrays (csr: [indptr, indices]; row_sparse:
    # [indices]), then the stored-values block (NOT the dense backing).
    f.write(struct.pack("<I", nd.ndim))
    f.write(struct.pack(f"<{nd.ndim}q", *nd.shape))
    f.write(struct.pack("<ii", 1, 0))
    values = nd.data.asnumpy()
    data, flag = _raw_bytes(values, str(nd.data.data.dtype))
    f.write(struct.pack("<i", flag))
    auxes = ([nd.indptr, nd.indices] if isinstance(nd, CSRNDArray)
             else [nd.indices])
    f.write(struct.pack("<I", len(auxes)))
    for aux in auxes:
        a = aux.asnumpy().astype(np.int64)
        f.write(struct.pack("<I", a.ndim))
        f.write(struct.pack(f"<{a.ndim}q", *a.shape))
        f.write(a.tobytes())
    f.write(struct.pack("<I", values.ndim))
    f.write(struct.pack(f"<{values.ndim}q", *values.shape))
    f.write(data)


def _np_dtype_of_flag(flag):
    dtname = _FLAG_TYPE.get(flag)
    if dtname is None:
        raise MXNetError(f"unknown type flag {flag}")
    if dtname == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(dtname)


def _read_shape(f):
    (ndim,) = struct.unpack("<I", f.read(4))
    return struct.unpack(f"<{ndim}q", f.read(8 * ndim)) if ndim else ()


def _read_raw(f, shape, npdt):
    n = int(np.prod(shape)) if shape else 1
    buf = f.read(n * npdt.itemsize)
    return np.frombuffer(buf, dtype=npdt).reshape(shape)


def _read_one(f) -> NDArray:
    magic, stype = struct.unpack("<II", f.read(8))
    if magic != NDARRAY_V2_MAGIC:
        raise MXNetError(f"bad ndarray magic {magic:#x}")
    if stype == 0:
        shape = _read_shape(f)
        struct.unpack("<ii", f.read(8))
        (flag,) = struct.unpack("<i", f.read(4))
        npdt = _np_dtype_of_flag(flag)
        return nd_array(_read_raw(f, shape, npdt), ctx=cpu(), dtype=npdt)
    from .ndarray.sparse import csr_matrix, row_sparse_array

    shape = _read_shape(f)
    struct.unpack("<ii", f.read(8))
    (flag,) = struct.unpack("<i", f.read(4))
    npdt = _np_dtype_of_flag(flag)
    (num_aux,) = struct.unpack("<I", f.read(4))
    auxes = []
    for _ in range(num_aux):
        ashape = _read_shape(f)
        auxes.append(_read_raw(f, ashape, np.dtype(np.int64)))
    vshape = _read_shape(f)
    values = _read_raw(f, vshape, npdt)
    if stype == 1:
        return row_sparse_array((values, auxes[0]), shape=shape, ctx=cpu(),
                                dtype=npdt)
    if stype == 2:
        indptr, indices = auxes
        return csr_matrix((values, indices, indptr), shape=shape, ctx=cpu(),
                          dtype=npdt)
    raise MXNetError(f"unknown storage type id {stype}")


def save_ndarrays(fname: str, data) -> None:
    """mx.nd.save: accepts NDArray, list of NDArray, or dict name->NDArray."""
    if isinstance(data, NDArray):
        arrays, names = [data], []
    elif isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        arrays, names = list(data), []
    with open(fname, "wb") as f:
        f.write(struct.pack("<QQ", LIST_MAGIC, 0))
        f.write(struct.pack("<Q", len(arrays)))
        for a in arrays:
            _write_one(f, a)
        f.write(struct.pack("<Q", len(names)))
        for nm in names:
            b = nm.encode("utf-8")
            f.write(struct.pack("<Q", len(b)))
            f.write(b)


def load_ndarrays(fname: str) -> Union[List[NDArray], Dict[str, NDArray]]:
    with open(fname, "rb") as f:
        magic, _ = struct.unpack("<QQ", f.read(16))
        if magic != LIST_MAGIC:
            raise MXNetError(f"invalid NDArray file {fname}: magic {magic:#x}")
        (n,) = struct.unpack("<Q", f.read(8))
        arrays = [_read_one(f) for _ in range(n)]
        (nn,) = struct.unpack("<Q", f.read(8))
        names = []
        for _ in range(nn):
            (ln,) = struct.unpack("<Q", f.read(8))
            names.append(f.read(ln).decode("utf-8"))
    if names:
        return dict(zip(names, arrays))
    return arrays
