"""pytest plugin: fail any test during which mxsan records a violation.

Registered by ``tests/conftest.py`` when ``MXNET_SAN`` is set (the same
knob that makes ``import mxnet_tpu`` enable the sanitizer before the
framework's locks and caches are built).  Behaviour:

* per test: the SESSION sanitizer's violation count is snapshotted at
  setup; new violations at teardown raise ``MxsanViolationError`` with
  the full formatted reports (stacks for both lock orders, the racing
  access, or the recompiling site).  Tests that seed violations on
  purpose use ``mxsan.scope()`` — scoped findings never touch the
  session instance, so they do not trip this hook.
* per session: the JSON report is written to ``MXNET_SAN_OUT``
  (default ``MXSAN.json``) — the artifact ``tools/run_nightly.py``
  archives for the violation trajectory across PRs.
"""
from __future__ import annotations

import os

import pytest

__all__ = ["MxsanPlugin", "MxsanViolationError"]


class MxsanViolationError(AssertionError):
    """Raised in teardown so the violation fails the test it happened
    under (closest attribution the plugin can give)."""


def _sanitizer():
    # lazy: importing the sanitizer package pulls mxnet_tpu, which the
    # test process imports anyway — but never at plugin-import time
    from mxnet_tpu.analysis import sanitizer

    return sanitizer


class MxsanPlugin:
    name = "mxsan"

    def __init__(self):
        self._before = 0

    def _session_violations(self):
        # the SESSION instance, never a test's scoped one (a test that
        # forgot to exit a scope must not swap the ledger out)
        san = _sanitizer().default()
        return san.violations() if san is not None else []

    def pytest_runtest_setup(self, item):
        self._before = len(self._session_violations())

    @pytest.hookimpl(trylast=True)
    def pytest_runtest_teardown(self, item):
        # trylast: AFTER the runner's teardown has finalized fixtures —
        # mxsan.scope() fixtures must exit before the ledger is read,
        # and raising here must not preempt fixture finalization
        vs = self._session_violations()
        new = vs[self._before:]
        if new:
            self._before = len(vs)  # attribute each finding once
            raise MxsanViolationError(
                f"{len(new)} mxsan violation(s) during {item.nodeid}:\n"
                + "\n".join(v.format() for v in new))

    def pytest_sessionfinish(self, session, exitstatus):
        sanitizer = _sanitizer()
        san = sanitizer.default()
        if san is None:
            return
        from mxnet_tpu.util import env

        out = env.get_str("MXNET_SAN_OUT") or "MXSAN.json"
        if not os.path.isabs(out):
            out = os.path.join(os.getcwd(), out)
        sanitizer.write_report(out, san)
        n = len(san.violations())
        tr = session.config.pluginmanager.get_plugin("terminalreporter")
        if tr is not None:
            tr.write_line(
                f"mxsan: {n} violation(s), report written to {out}")
        if n and exitstatus == 0:
            # violations recorded OUTSIDE any test window (import/
            # collection time, or a daemon thread after the last
            # teardown) never raised in a teardown hook — a green exit
            # would bury them.  session.exitstatus is read after the
            # sessionfinish hooks run, so this flips the process rc.
            session.exitstatus = 1
            if tr is not None:
                tr.write_line(
                    "mxsan: failing the session — violation(s) were "
                    "recorded outside any test window (see the report)")
