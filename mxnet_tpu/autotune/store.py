"""Persistent tuned-config store (mxtune winners).

Winning knob configs live beside the compile cache as content-addressed
JSON entries: the filename is the sha256 of the canonical entry KEY
(scenario/model fingerprint, mesh shape, device kind, framework
version), so a process boots tuned by hashing its own identity and
looking the digest up — no index file, no scan-order races.  The entry
body carries its own payload digest; a load that fails to verify
quarantines the file (rename to ``*.corrupt``) and reports a miss, never
an error — a truncated write from a crashed tuner must not take down
every process that shares the store volume (compile_cache/store.py
precedent).

Everything here is stdlib + the knob registry only: the store is
consulted during ``import mxnet_tpu``, before any heavyweight subsystem
exists.
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence

from ..util import env

__all__ = ["ConfigStore", "config_fingerprint", "entry_key",
           "default_dir"]

_MAGIC = "mxtc1"
_SUFFIX = ".mxtc"


def config_fingerprint(config: Dict[str, Any]) -> str:
    """sha256 over the sorted config items — the identity mxprof dumps
    stamp as ``tuned_config.fingerprint`` so perf_compare/mxtriage can
    tell two runs apart by WHICH tuned config they booted with.
    Deliberately mirrors env.fingerprint()'s serialization."""
    h = hashlib.sha256()
    for name, value in sorted(config.items()):
        h.update(f"{name}={value!r}\x1f".encode())
    return h.hexdigest()


def entry_key(scenario: str, mesh: Sequence[int] = (),
              device_kind: str = "", framework_version: str = "",
              platform: str = "") -> Dict[str, Any]:
    """The store key: what must match for a stored winner to apply.
    ``platform`` (JAX_PLATFORMS at tune time) rides along because
    device_kind needs an initialized backend to resolve — startup
    matching falls back to it rather than initializing devices as an
    import side effect."""
    return {
        "scenario": scenario,
        "mesh": list(mesh),
        "device_kind": device_kind,
        "framework_version": framework_version,
        "platform": platform,
    }


def _key_digest(key: Dict[str, Any]) -> str:
    blob = json.dumps(key, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def default_dir() -> str:
    """Where the store lives: MXNET_AUTOTUNE_DIR, else an ``autotune/``
    subdirectory of the compile cache when one is configured, else empty
    (store off)."""
    d = env.get_str("MXNET_AUTOTUNE_DIR") or ""
    if d:
        return d
    cc = env.get_str("MXNET_COMPILE_CACHE_DIR") or ""
    return os.path.join(cc, "autotune") if cc else ""


class ConfigStore:
    """Directory of verified tuned-config entries.

    ``put`` is atomic (temp file + ``os.replace``); ``get`` verifies the
    payload digest and quarantines anything unreadable.  Counters mirror
    the compile-cache store so goodput dashboards can watch hit rate.
    """

    def __init__(self, root: str):
        self.root = root
        self._seq = 0
        self.stats: Dict[str, int] = {
            "hits": 0, "misses": 0, "puts": 0, "corrupt": 0}

    # -- encode / decode ----------------------------------------------------

    @staticmethod
    def _encode(key: Dict[str, Any], config: Dict[str, Any],
                objective: float, meta: Optional[Dict[str, Any]]) -> bytes:
        entry = {
            "magic": _MAGIC,
            "key": key,
            "config": config,
            "config_fingerprint": config_fingerprint(config),
            "objective": objective,
            "meta": meta or {},
            "created": time.time(),
        }
        return json.dumps(entry, sort_keys=True, indent=1).encode()

    @staticmethod
    def _decode(blob: bytes) -> Dict[str, Any]:
        entry = json.loads(blob.decode())
        if entry.get("magic") != _MAGIC:
            raise ValueError(f"bad magic {entry.get('magic')!r}")
        config = entry["config"]
        if not isinstance(config, dict):
            raise ValueError("config is not an object")
        if entry.get("config_fingerprint") != config_fingerprint(config):
            raise ValueError("config fingerprint mismatch")
        float(entry["objective"])  # must be numeric
        return entry

    # -- store ops ----------------------------------------------------------

    def _path(self, key: Dict[str, Any]) -> str:
        return os.path.join(self.root, _key_digest(key) + _SUFFIX)

    def put(self, key: Dict[str, Any], config: Dict[str, Any],
            objective: float, meta: Optional[Dict[str, Any]] = None) -> str:
        os.makedirs(self.root, exist_ok=True)
        path = self._path(key)
        self._seq += 1
        tmp = f"{path}.tmp-{os.getpid()}-{self._seq}"
        with open(tmp, "wb") as f:
            f.write(self._encode(key, config, objective, meta))
        os.replace(tmp, path)  # concurrent tuners: last writer wins, whole
        self.stats["puts"] += 1  # #                 entries only
        return path

    def _quarantine(self, path: str) -> None:
        self.stats["corrupt"] += 1
        try:
            os.replace(path, path + ".corrupt")
        except OSError:
            pass  # already quarantined/removed by a peer — still a miss

    def _load(self, path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            return None
        try:
            entry = self._decode(blob)
        except Exception:  # noqa: BLE001 — ANY decode failure is a miss
            self._quarantine(path)
            return None
        entry["path"] = path
        return entry

    def get(self, key: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        entry = self._load(self._path(key))
        self.stats["hits" if entry is not None else "misses"] += 1
        return entry

    def entries(self) -> List[Dict[str, Any]]:
        """Every verified entry (corrupt files quarantined on the way)."""
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return []
        out = []
        for name in names:
            if not name.endswith(_SUFFIX):
                continue
            entry = self._load(os.path.join(self.root, name))
            if entry is not None:
                out.append(entry)
        return out

    def best_for_startup(self, scenario: str = "",
                         framework_version: str = "",
                         platform: str = "") -> Optional[Dict[str, Any]]:
        """The entry a fresh process should boot with.

        Matching is conservative: the framework version must match
        exactly (a winner tuned against other code is stale by
        definition), the scenario must match when the caller pins one
        (MXNET_AUTOTUNE_SCENARIO), and among the remainder entries for
        this platform beat platform-less ones, newest ``created`` wins.
        Returns None rather than guessing when nothing survives.
        """
        best = None
        best_rank = None
        for e in self.entries():
            k = e.get("key", {})
            if framework_version and \
                    k.get("framework_version") != framework_version:
                continue
            if scenario and k.get("scenario") != scenario:
                continue
            rank = (1 if platform and k.get("platform") == platform else 0,
                    e.get("created", 0.0))
            if best_rank is None or rank > best_rank:
                best, best_rank = e, rank
        return best
