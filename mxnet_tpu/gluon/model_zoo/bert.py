"""BERT models (BASELINE config 3: BERT-base pretraining).

Counterpart of the GluonNLP BERT stack the reference ecosystem provides
(ref: gluonnlp model/bert.py — BERTModel/BERTEncoder; the fused attention
ops in src/operator/contrib/transformer.cc).

TPU-first design: the encoder is a plain HybridBlock stack → hybridize
compiles the whole network (embeddings → N layers → heads) into ONE XLA
program in bf16-friendly form; attention goes through the registered
`dot_product_attention` op (Pallas kernel on TPU, XLA fallback elsewhere
— ops/pallas_attention.py); the MLM decoder ties the word-embedding
matrix (shared Parameter), matching BERT's weight tying.
"""
from __future__ import annotations

from typing import Optional

from ...base import MXNetError
from .. import nn
from ..block import HybridBlock

__all__ = ["BERTModel", "BERTEncoder", "BERTEncoderCell",
           "MultiHeadAttention", "bert_12_768_12", "bert_24_1024_16",
           "get_bert_model"]


class MultiHeadAttention(HybridBlock):
    """Multi-head attention over the fused attention op; query and
    key/value sources may differ (decoder cross-attention).  Dropout on
    the attention probabilities is threaded through the keyed frontend."""

    def __init__(self, units, num_heads, dropout=0.0, causal=False,
                 out_dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise MXNetError(f"units {units} not divisible by heads {num_heads}")
        self._units = units
        self._num_heads = num_heads
        self._dropout = dropout
        self._causal = causal
        with self.name_scope():
            self.query = nn.Dense(units, flatten=False, prefix="query_")
            self.key = nn.Dense(units, flatten=False, prefix="key_")
            self.value = nn.Dense(units, flatten=False, prefix="value_")
            self.proj = nn.Dense(units, flatten=False, prefix="proj_")
            self.dropout = nn.Dropout(out_dropout) if out_dropout else None

    def hybrid_forward(self, F, x, mem, mem_mask):
        q = self.query(x)
        k = self.key(mem)
        v = self.value(mem)
        out = F.dot_product_attention(q, k, v, mem_mask,
                                      num_heads=self._num_heads,
                                      dropout=self._dropout,
                                      causal=self._causal)
        out = self.proj(out)
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class BERTSelfAttention(MultiHeadAttention):
    """Self-attention with BERT's output dropout."""

    def __init__(self, units, num_heads, dropout=0.0, **kwargs):
        super().__init__(units, num_heads, dropout=dropout,
                         out_dropout=dropout, **kwargs)

    def hybrid_forward(self, F, x, mask):
        return super().hybrid_forward(F, x, x, mask)


class BERTPositionwiseFFN(HybridBlock):
    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu",
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ffn_1 = nn.Dense(hidden_size, flatten=False,
                                  activation=activation, prefix="ffn1_")
            self.ffn_2 = nn.Dense(units, flatten=False, prefix="ffn2_")
            self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        out = self.ffn_2(self.ffn_1(x))
        if self.dropout is not None:
            out = self.dropout(out)
        return out


class BERTEncoderCell(HybridBlock):
    """Post-LN transformer encoder layer (BERT convention)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attention = BERTSelfAttention(units, num_heads, dropout,
                                               prefix="attn_")
            self.ln1 = nn.LayerNorm(epsilon=1e-12, prefix="ln1_")
            self.ffn = BERTPositionwiseFFN(units, hidden_size, dropout,
                                           prefix="ffn_")
            self.ln2 = nn.LayerNorm(epsilon=1e-12, prefix="ln2_")

    def hybrid_forward(self, F, x, mask):
        x = self.ln1(x + self.attention(x, mask))
        x = self.ln2(x + self.ffn(x))
        return x


class BERTEncoder(HybridBlock):
    """N-layer transformer encoder (ref: gluonnlp BERTEncoder)."""

    def __init__(self, num_layers=12, units=768, hidden_size=3072,
                 num_heads=12, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        with self.name_scope():
            self.layers = nn.HybridSequential(prefix="layers_")
            for i in range(num_layers):
                self.layers.add(BERTEncoderCell(units, hidden_size, num_heads,
                                                dropout, prefix=f"layer{i}_"))

    def hybrid_forward(self, F, x, mask):
        for cell in self.layers._children.values():
            x = cell(x, mask)
        return x


class _MLMDecoder(HybridBlock):
    """MLM head: transform + LN + vocab projection with the TIED
    word-embedding matrix.  A HybridBlock, so `net.mlm_decoder.hybridize()`
    compiles the head into one XLA program too."""

    def __init__(self, units, vocab_size, embed_weight, **kwargs):
        super().__init__(**kwargs)
        self._vocab_size = vocab_size
        with self.name_scope():
            self.transform = nn.Dense(units, flatten=False,
                                      activation="gelu", prefix="transform_")
            self.ln = nn.LayerNorm(epsilon=1e-12, prefix="ln_")
            self.bias = self.params.get("bias", shape=(vocab_size,),
                                        init="zeros")
        # shared Parameter (weight tying): registering the embedding's own
        # Parameter here makes it flow into hybrid_forward and the trace
        self.embed_weight = embed_weight

    def hybrid_forward(self, F, x, embed_weight, bias):
        h = self.ln(self.transform(x))
        return F.FullyConnected(h, embed_weight, bias,
                                num_hidden=self._vocab_size, flatten=False)


class BERTModel(HybridBlock):
    """BERT with pooler, tied MLM decoder, and NSP classifier.

    forward(inputs, token_types, valid_length) ->
        (sequence_output (B, S, U), pooled_output (B, U))
    `decode_mlm(sequence_output)` -> (B, S, vocab) scores (tied weights);
    `classify_nsp(pooled_output)` -> (B, 2).  The heads are HybridBlocks —
    hybridize() covers the encoder program; the heads compile as their own
    programs when invoked (they run outside the encoder's forward).
    """

    def __init__(self, vocab_size=30522, token_type_vocab_size=2,
                 units=768, hidden_size=3072, max_length=512,
                 num_layers=12, num_heads=12, dropout=0.1,
                 use_pooler=True, use_decoder=True, use_classifier=True,
                 **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._vocab_size = vocab_size
        self._use_pooler = use_pooler
        self._use_decoder = use_decoder
        self._use_classifier = use_classifier
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units,
                                           prefix="word_embed_")
            self.token_type_embed = nn.Embedding(token_type_vocab_size, units,
                                                 prefix="token_type_embed_")
            self.position_weight = self.params.get(
                "position_embed_weight", shape=(max_length, units),
                init="normal")
            self.embed_ln = nn.LayerNorm(epsilon=1e-12, prefix="embed_ln_")
            self.embed_dropout = nn.Dropout(dropout) if dropout else None
            self.encoder = BERTEncoder(num_layers, units, hidden_size,
                                       num_heads, dropout, prefix="encoder_")
            if use_pooler:
                self.pooler = nn.Dense(units, flatten=False,
                                       activation="tanh", prefix="pooler_")
            if use_decoder:
                self.mlm_decoder = _MLMDecoder(units, vocab_size,
                                               self.word_embed.weight,
                                               prefix="mlm_")
            if use_classifier:
                self.classifier = nn.Dense(2, flatten=False,
                                           prefix="nsp_classifier_")

    def hybrid_forward(self, F, inputs, token_types, valid_length,
                       position_weight):
        x = self.word_embed(inputs) + self.token_type_embed(token_types)
        seq_len = inputs.shape[1]
        pos = F.slice_axis(position_weight, axis=0, begin=0, end=seq_len)
        x = F.broadcast_add(x, F.expand_dims(pos, axis=0))
        x = self.embed_ln(x)
        if self.embed_dropout is not None:
            x = self.embed_dropout(x)
        # key-validity mask (B, S) from valid_length
        steps = F._arange_like(inputs, axis=1)
        mask = F.cast(F.broadcast_lesser(
            F.expand_dims(steps, axis=0),
            F.expand_dims(valid_length, axis=-1)), dtype="float32")
        seq = self.encoder(x, mask)
        outputs = [seq]
        if self._use_pooler:
            cls_tok = F.squeeze(F.slice_axis(seq, axis=1, begin=0, end=1),
                                axis=1)
            outputs.append(self.pooler(cls_tok))
        return tuple(outputs) if len(outputs) > 1 else outputs[0]

    # ---- heads (hybridizable sub-programs) -------------------------------
    def decode_mlm(self, sequence_output):
        """MLM scores over every position with tied embedding weights."""
        if not self._use_decoder:
            raise MXNetError("model built with use_decoder=False")
        return self.mlm_decoder(sequence_output)

    def classify_nsp(self, pooled_output):
        if not self._use_classifier:
            raise MXNetError("model built with use_classifier=False")
        return self.classifier(pooled_output)


_BERT_SPECS = {
    "bert_12_768_12": dict(num_layers=12, units=768, hidden_size=3072,
                           num_heads=12),
    "bert_24_1024_16": dict(num_layers=24, units=1024, hidden_size=4096,
                            num_heads=16),
}


def get_bert_model(model_name="bert_12_768_12", vocab_size=30522,
                   dropout=0.1, max_length=512, **kwargs):
    if model_name not in _BERT_SPECS:
        raise MXNetError(f"unknown BERT model {model_name}; have "
                         f"{sorted(_BERT_SPECS)}")
    spec = dict(_BERT_SPECS[model_name])
    spec.update(kwargs)
    return BERTModel(vocab_size=vocab_size, dropout=dropout,
                     max_length=max_length, **spec)


def bert_12_768_12(**kwargs):
    """BERT-base (ref: gluonnlp bert_12_768_12)."""
    return get_bert_model("bert_12_768_12", **kwargs)


def bert_24_1024_16(**kwargs):
    """BERT-large (ref: gluonnlp bert_24_1024_16)."""
    return get_bert_model("bert_24_1024_16", **kwargs)
