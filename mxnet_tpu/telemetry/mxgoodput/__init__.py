"""mxgoodput — job-level goodput/badput accounting.

mxprof (PR 10) explains a *step*, mxhealth (PR 11) judges the *math*,
mxtriage (PR 12) explains a *regression* — mxgoodput answers the
fleet-operator question none of them can: **what fraction of this
job's wall-clock was productive training, and where did the rest go?**

One ledger (:mod:`.ledger`) decomposes elapsed time into ``productive``
versus named badput categories — ``compile``, ``data_wait``,
``checkpoint_save`` (blocking portion only), ``checkpoint_restore``,
``preemption_recovery``, ``retry_backoff{site}``, ``comm_stall`` —
plus a computed ``unattributed`` remainder, under the closure
invariant *everything sums to wall-clock; nothing silently vanishes*.

Feeds are the existing seams, not new timers:

  * a flight-recorder **step listener** (``mxprof.add_step_listener``)
    consumes per-step records: step wall becomes productive after
    compile / comm-stall are peeled off, data-wait rides beside it;
  * ``RetryPolicy`` reports every backoff sleep (site-labeled);
  * ``AutoCheckpoint`` reports blocking save and restore seconds and
    stamps preemption saves so a resume can open the recovery window;
  * the Trainer / SPMD step entry closes the recovery window at the
    first post-resume step.

Enable with ``MXNET_GOODPUT=1`` or :func:`enable` (rides along with
the mxprof recorder); read back via :func:`snapshot`, the ``goodput``
block of ``mxprof.dump()`` / SIGUSR2 dumps, ``/statusz``, or the
``mx_goodput_ratio`` / ``mx_badput_seconds_total{category}`` /
``mx_job_wall_seconds`` families on ``/metrics``.
``tools/goodput_report.py`` rolls rank-qualified dumps into one
job-level GOODPUT.json and runs the chaos known-answer gate.

Disabled cost: every hook is a single falsy check on ``_ACTIVE`` (the
chaos/mxhealth precedent); no listener is registered, so the step path
pays nothing (tier-1 overhead gate).
"""
from __future__ import annotations

import threading
from typing import Optional

from ...util import env as _env
from . import ledger as _ledger_mod
from .ledger import CATEGORIES, GoodputLedger

__all__ = [
    "enable", "disable", "enabled", "ledger", "snapshot",
    "record_badput", "category_seconds", "retry_backoff_this_thread",
    "consume_overlap", "on_step_entry",
    "on_preemption_trigger", "on_preemption_resume",
    "CATEGORIES", "GoodputLedger",
]

#: Fast-path flag: False means every hook site is one falsy check and
#: the mxprof step listener is not registered.
_ACTIVE = False

_lock = threading.Lock()
_LEDGER: Optional[GoodputLedger] = None


def ledger() -> GoodputLedger:
    """The process ledger (created on first use; :func:`enable` is
    what starts the clock/feed)."""
    global _LEDGER
    with _lock:
        if _LEDGER is None:
            _LEDGER = GoodputLedger()
        return _LEDGER


def _on_step(step: int) -> None:
    """The flight-recorder step listener: fold newly closed records
    into the ledger.  Registered through the module-level mxprof
    helpers so an ``enable(ring=N)`` recorder swap carries it (and a
    later :func:`disable` removes it from the LIVE recorder)."""
    led = _LEDGER
    if led is None or not _ACTIVE:
        return
    from .. import mxprof as _mxprof

    try:
        led.consume(_mxprof.recorder())
    except Exception:  # noqa: BLE001 — accounting never breaks a step
        pass


def enable(fresh: bool = False) -> GoodputLedger:
    """Start (or resume) goodput accounting: attach the mxprof flight
    recorder (the span feed the ledger consumes) and register the step
    listener.  ``fresh=True`` starts a new ledger — a new job's wall
    clock must not inherit the previous one's.  Idempotent."""
    global _LEDGER, _ACTIVE
    from .. import mxprof as _mxprof

    rec = _mxprof.recorder()
    with _lock:
        if _LEDGER is None or fresh:
            # a fresh ledger accounts from NOW: records the recorder
            # closed before this instant belong to wall-clock the
            # ledger never saw — consuming them would over-attribute
            # (closure error).  The high-water mark is set BEFORE the
            # ledger is published: with a listener already live, a
            # step closing concurrently must never see the fresh
            # ledger at mark 0 and back-attribute the whole ring.
            led = GoodputLedger()
            led.set_record_high_water(rec.current_step())
            _LEDGER = led
        led = _LEDGER
        _ACTIVE = True
    _mxprof.enable()
    _mxprof.add_step_listener(_on_step)
    return led


def disable() -> None:
    """Stop accounting: deregister the step listener from the live
    recorder (module-level remove — a held recorder reference would
    miss an ``enable(ring=N)`` swap) and drop the hook flag.  The
    ledger stays readable; the mxprof recorder is left as it was."""
    global _ACTIVE
    _ACTIVE = False
    from .. import mxprof as _mxprof

    _mxprof.remove_step_listener(_on_step)


def enabled() -> bool:
    return _ACTIVE


def snapshot() -> dict:
    """The ledger snapshot (consumes any records the listener has not
    seen yet first, so a dump taken mid-step-stream is current)."""
    led = ledger()
    if _ACTIVE:
        from .. import mxprof as _mxprof

        try:
            led.consume(_mxprof.recorder())
        except Exception:  # noqa: BLE001 — a dump never fails on the feed
            pass
    return led.snapshot()


def record_badput(category: str, seconds: float,
                  site: Optional[str] = None,
                  overlaps_step: bool = False) -> None:
    """Interval feed for the attribution hooks (retry / autockpt);
    a no-op while mxgoodput is disabled."""
    if _ACTIVE:
        ledger().record_badput(category, seconds, site=site,
                               overlaps_step=overlaps_step)


def category_seconds(category: str) -> float:
    """Cumulative seconds attributed to one category (0.0 while
    disabled with no ledger)."""
    led = _LEDGER
    return led.category_seconds(category) if led is not None else 0.0


def retry_backoff_this_thread() -> float:
    """Retry-backoff seconds slept on the calling thread — the mark
    autockpt brackets a blocking save/restore with (a concurrent
    daemon writer's sleeps must not be deducted from it)."""
    led = _LEDGER
    return led.retry_backoff_this_thread() if led is not None else 0.0


def consume_overlap(seconds: float) -> None:
    if _ACTIVE:
        ledger().consume_overlap(seconds)


def on_step_entry() -> None:
    """Hook at Trainer/SPMD step entry: the FIRST step after a resume
    stamps the recovery window with 'training resumed HERE'.  The
    window closes when that step's record is consumed, at
    min(this stamp, the record's start) — the stamp alone would
    overlap the record (gluon's forward/backward siblings run before
    Trainer.step), the record start alone could drift on the gspmd
    next-boundary close; together they pin the end mark."""
    led = _LEDGER
    if led is not None and _ACTIVE:
        led.mark_step_entry()


def on_preemption_trigger(
        category: str = "preemption_recovery") -> None:
    """Hook where the step boundary OBSERVES the preemption flag
    (AutoCheckpoint.on_step), before the sync save: opens the recovery
    window at the trigger instant.  ``category`` routes the window —
    ``rank_failure_recovery`` when the trigger was an elastic
    peer-failure wind-down rather than a genuine preemption.  Never
    called from a signal handler."""
    if not _ACTIVE:
        return
    from ...resilience import preemption as _preemption

    t = _preemption.trigger_time()
    ledger().open_recovery(t0_mono=t[1] if t else None,
                           category=category)


def on_preemption_resume(t_unix: Optional[float] = None,
                         category: str = "preemption_recovery",
                         incident: Optional[str] = None) -> None:
    """Hook in ``AutoCheckpoint.resume`` when the restored checkpoint
    was a preemption (or elastic peer-failure) save: opens the
    recovery window (idempotent when the trigger already opened it
    in-process).  ``t_unix`` is the trigger time persisted in the
    checkpoint meta — a fresh process extends its wall back to it so
    the downtime is measured, not forgotten.  ``incident`` stamps the
    window with the mxblackbox incident id (elastic restart: the
    supervisor's COMMIT marker carries it)."""
    if _ACTIVE:
        ledger().open_recovery(t0_unix=t_unix, category=category,
                               incident=incident)


if _env.get_bool("MXNET_GOODPUT"):
    enable()
