"""Legacy mx.rnn symbolic cell API (ref: python/mxnet/rnn/) —
parameter-level parity with gluon.rnn cells (identical gate layouts) and
the end-to-end BucketingModule language-model recipe it exists for."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def _bind_run(out_sym, feed):
    ex = out_sym.bind(mx.cpu(), {k: nd.array(v) for k, v in feed.items()})
    return [o.asnumpy() for o in ex.forward()]


def _gluon_unroll(cell_cls, kwargs, params_np, x):
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon import rnn as grnn

    cell = cell_cls(**kwargs)
    cell.initialize(ctx=mx.cpu())
    outs, _ = cell.unroll(x.shape[1], nd.array(x), layout="NTC",
                          merge_outputs=True)
    for name, p in cell.collect_params().items():
        short = name.split("_", 1)[1] if "_" in name else name
        for k, v in params_np.items():
            if name.endswith(k):
                p.set_data(nd.array(v))
    outs, _ = cell.unroll(x.shape[1], nd.array(x), layout="NTC",
                          merge_outputs=True)
    return outs.asnumpy()


@pytest.mark.parametrize("kind", ["rnn", "lstm", "gru"])
def test_legacy_cell_matches_gluon(kind):
    rng = np.random.RandomState(0)
    N, T, C, H = 2, 5, 4, 6
    x = rng.randn(N, T, C).astype("float32") * 0.5
    mult = {"rnn": 1, "lstm": 4, "gru": 3}[kind]
    params = {
        "i2h_weight": rng.randn(mult * H, C).astype("float32") * 0.3,
        "h2h_weight": rng.randn(mult * H, H).astype("float32") * 0.3,
        "i2h_bias": rng.randn(mult * H).astype("float32") * 0.1,
        "h2h_bias": rng.randn(mult * H).astype("float32") * 0.1,
    }

    from mxnet_tpu import rnn as legacy
    from mxnet_tpu.gluon import rnn as grnn

    cell = {"rnn": legacy.RNNCell, "lstm": legacy.LSTMCell,
            "gru": legacy.GRUCell}[kind](H, prefix=f"{kind}0_")
    data = mx.sym.Variable("data")
    merged, _states = cell.unroll(T, data, layout="NTC")
    feed = {"data": x}
    feed.update({f"{kind}0_{k}": v for k, v in params.items()})
    got = _bind_run(merged, feed)[0]

    gcell_cls = {"rnn": grnn.RNNCell, "lstm": grnn.LSTMCell,
                 "gru": grnn.GRUCell}[kind]
    ref = _gluon_unroll(gcell_cls, {"hidden_size": H}, params, x)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_sequential_and_residual_and_dropout_stack():
    from mxnet_tpu import rnn as legacy

    rng = np.random.RandomState(1)
    N, T, H = 2, 4, 6
    x = rng.randn(N, T, H).astype("float32") * 0.5
    stack = legacy.SequentialRNNCell()
    stack.add(legacy.LSTMCell(H, prefix="l0_"))
    stack.add(legacy.DropoutCell(0.0))
    stack.add(legacy.ResidualCell(legacy.GRUCell(H, prefix="l1_")))
    data = mx.sym.Variable("data")
    merged, states = stack.unroll(T, data, layout="NTC")
    assert len(states) == 3  # lstm h,c + gru h
    feed = {"data": x}
    for name in stack.params:
        mult = 4 if name.startswith("l0_") else 3
        cols = H if "h2h" in name or name.startswith("l1_") else H
        shape = (mult * H, H) if "weight" in name else (mult * H,)
        feed[name] = rng.randn(*shape).astype("float32") * 0.2
    out = _bind_run(merged, feed)[0]
    assert out.shape == (N, T, H)
    assert np.isfinite(out).all()


def test_bidirectional_unroll():
    from mxnet_tpu import rnn as legacy

    rng = np.random.RandomState(2)
    N, T, C, H = 2, 4, 3, 5
    x = rng.randn(N, T, C).astype("float32")
    bi = legacy.BidirectionalCell(legacy.LSTMCell(H, prefix="fw_"),
                                  legacy.LSTMCell(H, prefix="bw_"))
    data = mx.sym.Variable("data")
    merged, _ = bi.unroll(T, data, layout="NTC")
    feed = {"data": x}
    for name in bi.params:
        shape = ((4 * H, C) if name.endswith("i2h_weight")
                 else (4 * H, H) if name.endswith("h2h_weight")
                 else (4 * H,))
        feed[name] = rng.randn(*shape).astype("float32") * 0.2
    out = _bind_run(merged, feed)[0]
    assert out.shape == (N, T, 2 * H)


def test_encode_sentences_and_bucket_iter():
    from mxnet_tpu import rnn as legacy

    sents = [["a", "b", "c"], ["a", "c"], ["b", "c", "a", "b"],
             ["c", "a"], ["a", "b"], ["b", "c", "a"]]
    coded, vocab = legacy.encode_sentences(sents, invalid_label=0,
                                           start_label=1)
    assert set(vocab.values()) >= {1, 2, 3}
    it = legacy.BucketSentenceIter(coded, batch_size=2, buckets=[2, 4],
                                   invalid_label=0)
    n = 0
    for batch in it:
        n += 1
        assert batch.bucket_key in (2, 4)
        d = batch.data[0].asnumpy()
        l = batch.label[0].asnumpy()
        assert d.shape == (2, batch.bucket_key)
        # label = data shifted left one step
        np.testing.assert_array_equal(l[:, :-1], d[:, 1:])
    assert n >= 2


def test_fused_cell_unroll_runs():
    from mxnet_tpu import rnn as legacy

    rng = np.random.RandomState(3)
    N, T, C, H = 2, 4, 3, 5
    x = rng.randn(N, T, C).astype("float32")
    cell = legacy.FusedRNNCell(H, num_layers=2, mode="lstm",
                               prefix="f_")
    data = mx.sym.Variable("data")
    merged, _ = cell.unroll(T, data, layout="NTC")
    # the flat weight blob carries the reference's name (checkpoints map)
    assert cell.params == ["f_parameters"]
    ex = merged.simple_bind(mx.cpu(), data=(N, T, C))
    out = ex.forward(data=nd.array(x))[0]
    assert out.shape == (N, T, H)


def test_legacy_lstm_begin_state_passthrough():
    """A non-zero begin_state must actually flow into the unroll (a
    silently-ignored begin_state was a review finding)."""
    from mxnet_tpu import rnn as legacy

    rng = np.random.RandomState(4)
    N, T, C, H = 2, 3, 4, 5
    x = rng.randn(N, T, C).astype("float32") * 0.3
    h0 = rng.randn(N, H).astype("float32")
    c0 = rng.randn(N, H).astype("float32")
    cell = legacy.LSTMCell(H, prefix="s_")
    data = mx.sym.Variable("data")
    bh = mx.sym.Variable("h0")
    bc = mx.sym.Variable("c0")
    merged, _ = cell.unroll(T, data, begin_state=[bh, bc], layout="NTC")
    params = {
        "s_i2h_weight": rng.randn(4 * H, C).astype("float32") * 0.3,
        "s_h2h_weight": rng.randn(4 * H, H).astype("float32") * 0.3,
        "s_i2h_bias": np.zeros(4 * H, "float32"),
        "s_h2h_bias": np.zeros(4 * H, "float32"),
    }
    out1 = _bind_run(merged, {"data": x, "h0": h0, "c0": c0,
                              **params})[0]
    out2 = _bind_run(merged, {"data": x, "h0": h0 * 0, "c0": c0 * 0,
                              **params})[0]
    assert np.abs(out1 - out2).max() > 1e-4  # states mattered
