"""User-defined operators: CustomOp / CustomOpProp / register.

Counterpart of the reference's custom-op machinery
(ref: src/operator/custom/custom.cc + python/mxnet/operator.py).  The
reference runs user Python forward/backward on the CPU via engine
callbacks; the TPU-native equivalent is a host callback: the user code
runs through ``jax.pure_callback`` under ``jax.custom_vjp``, so a Custom
op works BOTH eagerly and inside traced/jitted programs (hybridize,
Symbol bind, SPMDTrainer) — XLA treats it as an opaque host call, and
gradients route back through the user's ``backward``.

API parity with the reference::

    class Sigmoid(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            y = 1.0 / (1.0 + mx.nd.exp(-in_data[0]))
            self.assign(out_data[0], req[0], y)

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            y = out_data[0]
            self.assign(in_grad[0], req[0], out_grad[0] * y * (1 - y))

    @mx.operator.register("sigmoid")
    class SigmoidProp(mx.operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=True)

        def list_arguments(self):
            return ["data"]

        def list_outputs(self):
            return ["output"]

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, shapes, dtypes):
            return Sigmoid()

    y = mx.nd.Custom(x, op_type="sigmoid")
"""
from __future__ import annotations

import functools
from typing import Dict, List, Type

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError
from .ops.registry import register_op

__all__ = ["CustomOp", "CustomOpProp", "register", "get_prop"]


class CustomOp:
    """Base class for user forward/backward (ref: operator.py::CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError(
            "this CustomOp does not implement backward")

    @staticmethod
    def assign(dst, req, src):
        """Honor the write/add/null request (ref: CustomOp.assign)."""
        if req in ("null", None):
            return
        if req == "add":
            dst += src
        else:  # write / inplace
            dst[:] = src


class CustomOpProp:
    """Shape/type/operator factory (ref: operator.py::CustomOpProp)."""

    def __init__(self, need_top_grad: bool = True, **kwargs):
        self.need_top_grad_ = need_top_grad
        self.kwargs = kwargs

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        return list(out_grad) + list(in_data) + list(out_data)

    def create_operator(self, ctx, shapes, dtypes):
        raise NotImplementedError


_PROPS: Dict[str, Type[CustomOpProp]] = {}


def register(op_type: str):
    """Decorator registering a CustomOpProp under ``op_type``
    (ref: mx.operator.register)."""

    def _wrap(cls: Type[CustomOpProp]) -> Type[CustomOpProp]:
        if not issubclass(cls, CustomOpProp):
            raise MXNetError(
                f"@operator.register expects a CustomOpProp subclass, "
                f"got {cls!r}")
        _PROPS[op_type] = cls
        return cls

    return _wrap


def get_prop(op_type: str) -> Type[CustomOpProp]:
    if op_type not in _PROPS:
        raise MXNetError(
            f"unknown custom op_type {op_type!r}; registered: "
            f"{sorted(_PROPS)}")
    return _PROPS[op_type]


# ---------------------------------------------------------------------------
# the 'Custom' registry op — host-callback execution with custom_vjp
# ---------------------------------------------------------------------------

class _HostArray(np.ndarray):
    """numpy view with the small NDArray-ish surface CustomOp code uses
    (asnumpy; the arithmetic comes from ndarray itself)."""

    def asnumpy(self):
        return np.asarray(self)


def _wrap_host(a: np.ndarray) -> _HostArray:
    return np.ascontiguousarray(a).view(_HostArray)


def _custom_nout(attrs) -> int:
    prop_cls = get_prop(attrs["op_type"])
    kw = {k: v for k, v in attrs.items() if k != "op_type"}
    return len(prop_cls(**kw).list_outputs())


def make_operator(prop: CustomOpProp, np_ins) -> CustomOp:
    return prop.create_operator(None, [list(a.shape) for a in np_ins],
                                [a.dtype for a in np_ins])


# live operator instances awaiting their backward, keyed by token.
# Bounded: primal-only executions never pop theirs (no backward runs),
# so the oldest entries are evicted instead of leaking.
import collections
import itertools
import threading

_LIVE_LOCK = threading.Lock()
_LIVE_OPS: "collections.OrderedDict[int, CustomOp]" = \
    collections.OrderedDict()
_LIVE_CAP = 4096
# ops whose backward already ran once: kept only so a REPEATED vjp
# application (grad-of-grad, re-applied cached vjp) still finds them,
# in a much smaller cache — steady-state training (one backward per
# forward) therefore retains ~_DONE_CAP instances, not _LIVE_CAP,
# even when user code stashes activation-sized state on self
_DONE_OPS: "collections.OrderedDict[int, CustomOp]" = \
    collections.OrderedDict()
_DONE_CAP = 64
_NEXT_TOKEN = itertools.count(1)


def _stash_op(op: CustomOp) -> int:
    with _LIVE_LOCK:
        tok = next(_NEXT_TOKEN) % (2 ** 31 - 1)
        _LIVE_OPS[tok] = op
        while len(_LIVE_OPS) > _LIVE_CAP:
            _LIVE_OPS.popitem(last=False)
    return tok


def _get_op(tok: int, mark_done: bool = False):
    """Fetch WITHOUT popping (a vjp may be applied repeatedly).
    `mark_done=True` (the backward path) demotes the entry to the small
    done-cache; never-backwarded entries age out of the big LRU."""
    with _LIVE_LOCK:
        op = _LIVE_OPS.get(tok)
        if op is not None:
            if mark_done:
                del _LIVE_OPS[tok]
                _DONE_OPS[tok] = op
                while len(_DONE_OPS) > _DONE_CAP:
                    _DONE_OPS.popitem(last=False)
            else:
                _LIVE_OPS.move_to_end(tok)
            return op
        op = _DONE_OPS.get(tok)
        if op is not None:
            _DONE_OPS.move_to_end(tok)
        return op


def run_forward_host(op: CustomOp, np_ins, out_structs,
                     is_train: bool = True):
    """Execute the user forward on host numpy arrays.  The SAME op
    instance must be passed to run_backward_host — the reference creates
    one CustomOp per graph node and reuses it, so user code may stash
    forward state on self for backward (mask patterns etc)."""
    n_out = len(out_structs)
    in_data = [_wrap_host(a) for a in np_ins]
    outs = [np.zeros(s.shape, s.dtype).view(_HostArray)
            for s in out_structs]
    op.forward(is_train=is_train, req=["write"] * n_out,
               in_data=in_data, out_data=outs, aux=[])
    return tuple(np.asarray(o) for o in outs)


def run_backward_host(op: CustomOp, np_ins, np_outs, np_cts):
    """Execute the user backward on host numpy arrays (same op instance
    as the forward — see run_forward_host)."""
    in_grad = [np.zeros(a.shape, a.dtype).view(_HostArray) for a in np_ins]
    op.backward(req=["write"] * len(np_ins),
                out_grad=[_wrap_host(c) for c in np_cts],
                in_data=[_wrap_host(a) for a in np_ins],
                out_data=[_wrap_host(o) for o in np_outs],
                in_grad=in_grad, aux=[])
    return tuple(np.asarray(g) for g in in_grad)


def out_structs_for(prop: CustomOpProp, in_shapes, in_dtypes):
    ishapes, oshapes, _aux = prop.infer_shape([list(s) for s in in_shapes])
    itypes, otypes, _ = prop.infer_type(list(in_dtypes))
    return tuple(jax.ShapeDtypeStruct(tuple(s), np.dtype(t))
                 for s, t in zip(oshapes, otypes))


@functools.lru_cache(maxsize=None)
def _build_custom(op_type: str, kw_items: tuple, in_shapes: tuple,
                  in_dtypes: tuple, is_train: bool = True):
    """One custom_vjp-wrapped host callback per (op_type, attrs,
    signature, train-mode) — cached like any other compiled executable."""
    kw = dict(kw_items)
    prop = get_prop(op_type)(**kw)
    n_in = len(in_shapes)
    out_structs = out_structs_for(prop, in_shapes, in_dtypes)
    n_out = len(out_structs)
    # Each forward execution creates ONE operator instance whose id rides
    # the custom_vjp residuals as a token, so the matching backward — and
    # only it — gets that exact instance back (reference custom.cc
    # lifetime: per-node state like forward-stashed masks stays paired
    # even when the same compiled op runs many times before backprop).
    out_structs_tok = out_structs + (
        jax.ShapeDtypeStruct((), np.int32),)  # x64 is disabled

    def fwd_host_plain(*ins):
        # primal-only path: no backward will come, so nothing is stashed
        # (stashing here would flood the LRU and evict grad-pending ops)
        op = make_operator(prop, ins)
        return run_forward_host(op, ins, out_structs, is_train=is_train)

    def fwd_host(*ins):
        op = make_operator(prop, ins)
        outs = run_forward_host(op, ins, out_structs, is_train=is_train)
        return outs + (np.int32(_stash_op(op)),)

    def bwd_host(tok, *args):
        ins = args[:n_in]
        outs = args[n_in:n_in + n_out]
        cts = args[n_in + n_out:]
        # demoted to the done-cache, NOT popped: repeated vjp application
        op = _get_op(int(tok), mark_done=True)
        if op is None:
            raise MXNetError(
                f"Custom op {op_type!r}: the operator instance for this "
                "backward was evicted (more than "
                f"{_LIVE_CAP} grad-pending Custom forwards in flight, or "
                f"more than {_DONE_CAP} completed backwards since this "
                "one first ran) — cannot silently rebuild stateful "
                "backward")
        return run_backward_host(op, ins, outs, cts)

    @jax.custom_vjp
    def run(*ins):
        out = jax.pure_callback(fwd_host_plain, out_structs, *ins)
        return out if n_out > 1 else out[0]

    def run_fwd(*ins):
        *outs, tok = jax.pure_callback(fwd_host, out_structs_tok, *ins)
        primal = tuple(outs) if n_out > 1 else outs[0]
        return primal, (ins, tuple(outs), tok)

    def run_bwd(res, cts):
        ins, outs, tok = res
        cts = cts if isinstance(cts, tuple) else (cts,)
        grad_structs = tuple(
            jax.ShapeDtypeStruct(a.shape, a.dtype) for a in ins)
        grads = jax.pure_callback(bwd_host, grad_structs, tok,
                                  *ins, *outs, *cts)
        return tuple(grads)

    run.defvjp(run_fwd, run_bwd)
    return run


@register_op("Custom", num_outputs=_custom_nout)
def _custom(*arrays, op_type=None, _train=None, **kwargs):
    """Dispatch to the registered CustomOpProp (ref: custom.cc Custom).
    ``_train`` follows the OpContext convention: defaults to the global
    autograd train mode at trace time."""
    if op_type is None:
        raise MXNetError("nd.Custom requires op_type=")
    get_prop(op_type)  # loud unknown-type error before tracing
    if _train is None:
        from . import autograd as _ag

        _train = _ag.is_training()
    kw_items = tuple(sorted(kwargs.items()))
    in_shapes = tuple(tuple(a.shape) for a in arrays)
    in_dtypes = tuple(np.dtype(a.dtype) for a in arrays)
    return _build_custom(op_type, kw_items, in_shapes, in_dtypes,
                         bool(_train))(*arrays)
