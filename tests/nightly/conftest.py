"""Nightly tier gate (ref: tests/nightly/ — large arrays, model
backwards compatibility).  Slow and memory-hungry by design: skipped
unless MXNET_NIGHTLY=1.  Run via `python tools/run_nightly.py`."""
import os

import pytest


def pytest_collection_modifyitems(config, items):
    if os.environ.get("MXNET_NIGHTLY") == "1":
        return
    skip = pytest.mark.skip(reason="nightly tier: set MXNET_NIGHTLY=1 "
                                   "(tools/run_nightly.py)")
    here = os.path.dirname(os.path.abspath(__file__))
    for item in items:
        # this hook receives EVERY collected item, not just this
        # directory's — scope the gate to tests/nightly or a full-suite
        # `pytest tests/` run would skip the entire suite
        if str(item.fspath).startswith(here + os.sep):
            item.add_marker(skip)
