"""mxlint output: human text + machine JSON (the MXLINT.json artifact)."""
from __future__ import annotations

from typing import Dict, List, Sequence

from .engine import RULE_REGISTRY, Violation

__all__ = ["render_text", "render_json"]


def _per_rule_counts(violations: Sequence[Violation]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for v in violations:
        counts[v.rule] = counts.get(v.rule, 0) + 1
    return counts


def render_text(new: Sequence[Violation],
                suppressed: Sequence[Violation] = (),
                stale: Sequence[dict] = (),
                errors: Sequence[str] = ()) -> str:
    lines: List[str] = []
    for v in new:
        lines.append(v.format())
    for e in errors:
        lines.append(f"{e} (file skipped)")
    if stale:
        lines.append("")
        lines.append(f"{len(stale)} stale baseline entr"
                     f"{'y' if len(stale) == 1 else 'ies'} (violation "
                     "fixed — delete from MXLINT_BASELINE.json):")
        for e in stale:
            lines.append(f"  {e['path']} {e['rule']} [{e['symbol']}] "
                         f"{e['src'][:60]}")
    lines.append("")
    verdict = "FAIL" if new else "OK"
    lines.append(f"mxlint: {verdict} — {len(new)} new violation(s), "
                 f"{len(suppressed)} baselined, {len(stale)} stale "
                 f"baseline entr{'y' if len(stale) == 1 else 'ies'}, "
                 f"{len(errors)} unparsable file(s)")
    return "\n".join(lines)


def render_json(new: Sequence[Violation],
                suppressed: Sequence[Violation] = (),
                stale: Sequence[dict] = (),
                errors: Sequence[str] = ()) -> dict:
    """The MXLINT.json shape: per-rule counts first (the trajectory the
    nightly tracks across PRs), then the full finding list."""
    return {
        "ok": not new,
        "counts": {
            "new": len(new),
            "baselined": len(suppressed),
            "stale_baseline": len(stale),
            "errors": len(errors),
        },
        "new_per_rule": _per_rule_counts(new),
        "baselined_per_rule": _per_rule_counts(suppressed),
        "rules": {rid: {"name": cls.name, "description": cls.description}
                  for rid, cls in sorted(RULE_REGISTRY.items())},
        "new": [{
            "rule": v.rule, "path": v.path, "line": v.line, "col": v.col,
            "symbol": v.symbol, "message": v.message,
            "fingerprint": v.fingerprint,
        } for v in new],
        "stale_baseline": list(stale),
        "errors": list(errors),
    }
