"""Quantization operators.

TPU-native counterpart of src/operator/quantization/** (quantize.cc,
quantize_v2.cc, dequantize.cc, requantize.cc, quantized_conv/fc/pool).

The numeric core — quantize / quantize_v2 / dequantize / requantize —
is implemented for real with the reference's affine int8/uint8 scheme
(min/max calibration ranges carried alongside the payload).  The
quantized COMPUTE kernels (quantized_conv, quantized_fully_connected,
...) raise informatively: on TPU the MXU's native low-precision path is
bfloat16/int8-with-fp32-accumulate chosen by XLA, and int8 inference
graphs should be expressed through normal ops + these converters; there
is no cuDNN-int8 analogue worth emulating op-by-op.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import MXNetError
from .registry import register_op

__all__ = []


def _qrange(out_type: str):
    if out_type == "uint8":
        return 0.0, 255.0, jnp.uint8
    if out_type == "int8":
        return -127.0, 127.0, jnp.int8
    raise MXNetError(f"unsupported quantized type {out_type!r} "
                     "(uint8/int8)")


@register_op("_contrib_quantize", aliases=("quantize",), num_outputs=3,
             differentiable=False)
def _quantize(data, min_range, max_range, out_type="uint8"):
    """Affine-quantize fp32 into uint8/int8 given calibration ranges;
    returns (q, out_min, out_max) (ref: quantization/quantize.cc)."""
    qmin, qmax, qdt = _qrange(out_type)
    rmin = jnp.minimum(min_range, 0.0).reshape(())
    rmax = jnp.maximum(max_range, 0.0).reshape(())
    if out_type == "int8":
        # symmetric: scale by max |range| (ref quantize.cc int8 branch)
        absmax = jnp.maximum(jnp.abs(rmin), jnp.abs(rmax))
        scale = qmax / jnp.maximum(absmax, 1e-20)
        q = jnp.clip(jnp.round(data * scale), qmin, qmax).astype(qdt)
        return q, -absmax, absmax
    scale = (qmax - qmin) / jnp.maximum(rmax - rmin, 1e-20)
    q = jnp.clip(jnp.round((data - rmin) * scale) + qmin, qmin,
                 qmax).astype(qdt)
    return q, rmin, rmax


@register_op("_contrib_quantize_v2", aliases=("quantize_v2",),
             num_outputs=3, differentiable=False)
def _quantize_v2(data, out_type="int8", min_calib_range=None,
                 max_calib_range=None):
    """Quantize with self-calibration when no ranges are given
    (ref: quantize_v2.cc)."""
    if min_calib_range is None or max_calib_range is None:
        rmin = jnp.min(data)
        rmax = jnp.max(data)
    else:
        rmin = jnp.asarray(min_calib_range, jnp.float32)
        rmax = jnp.asarray(max_calib_range, jnp.float32)
    return _quantize(data, rmin, rmax, out_type=out_type)


@register_op("_contrib_dequantize", aliases=("dequantize",),
             differentiable=False)
def _dequantize(data, min_range, max_range, out_type="float32"):
    """Invert the affine quantization (ref: dequantize.cc)."""
    rmin = min_range.reshape(())
    rmax = max_range.reshape(())
    if data.dtype == jnp.int8:
        absmax = jnp.maximum(jnp.abs(rmin), jnp.abs(rmax))
        return data.astype(jnp.float32) * (absmax / 127.0)
    scale = (rmax - rmin) / 255.0
    return data.astype(jnp.float32) * scale + rmin


@register_op("_contrib_requantize", aliases=("requantize",), num_outputs=3,
             differentiable=False)
def _requantize(data, min_range, max_range, out_type="int8",
                min_calib_range=None, max_calib_range=None):
    """int32 accumulator -> int8 with recalibrated ranges
    (ref: requantize.cc)."""
    if data.dtype != jnp.int32:
        raise MXNetError("requantize expects int32 input")
    f = _dequantize_int32(data, min_range, max_range)
    if min_calib_range is not None and max_calib_range is not None:
        rmin = jnp.asarray(min_calib_range, jnp.float32)
        rmax = jnp.asarray(max_calib_range, jnp.float32)
    else:
        rmin = jnp.min(f)
        rmax = jnp.max(f)
    return _quantize(f, rmin, rmax, out_type=out_type)


def _dequantize_int32(data, min_range, max_range):
    absmax = jnp.maximum(jnp.abs(min_range.reshape(())),
                         jnp.abs(max_range.reshape(())))
    return data.astype(jnp.float32) * (absmax / float(2 ** 31 - 1))


def _register_quantized_stub(name: str):
    def stub(*args, **kwargs):
        raise MXNetError(
            f"{name} is not provided as a standalone kernel on TPU: the "
            "MXU's low-precision path is bf16 (or XLA-chosen int8 with "
            "fp32 accumulate).  Express int8 inference as "
            "quantize_v2 -> normal ops -> dequantize, or train/serve in "
            "bfloat16 (net.cast('bfloat16')) for the native fast path.")

    stub.__name__ = name
    register_op(name, differentiable=False, no_jit=True)(stub)


for _name in ("_contrib_quantized_conv", "_contrib_quantized_fully_connected",
              "_contrib_quantized_pooling", "_contrib_quantized_flatten",
              "_contrib_quantized_act", "_contrib_quantized_concat",
              "_contrib_quantized_elemwise_add"):
    _register_quantized_stub(_name)
