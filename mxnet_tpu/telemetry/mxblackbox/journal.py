"""The per-rank event journal: a bounded in-memory ring plus an
append-only on-disk spill file.

Design constraints, in order:

  * **lock-cheap** — one plain (non-reentrant, leaf) ``threading.Lock``
    around the ring append and the spill write; no other lock is taken
    while it is held, so it can never participate in a lock-order
    cycle (the mxsan bar).
  * **signal-safe** — :meth:`EventJournal.emit_from_signal` NEVER takes
    the journal lock inline: the interrupted frame may BE mid-``emit``
    holding that very lock (the PR 10 SIGUSR2 self-deadlock lesson,
    see mxtriage's ``_on_sigusr1``).  The signal path enqueues onto a
    ``queue.SimpleQueue`` (reentrant-safe C implementation) and a
    daemon thread performs the real emit once the interrupted frame
    releases the lock.
  * **crash-durable** — each spill record is one JSON line written by a
    single ``os.write`` on an ``O_APPEND`` fd: the append-only analog
    of the heartbeat stamp's tmp+``os.replace`` (a torn line at a hard
    kill can only be the LAST line, and the reader skips unparsable
    tails).  The spill rotates once (``.1`` suffix) past the size
    bound, so disk use is bounded at ~2x the cap.

Every entry carries both clocks (``t_unix`` for cross-rank merge,
``t_mono`` for in-process intervals), the rank, the training step
(caller-provided, or the mxprof step counter when one is live), the
category, and free-form fields.
"""
from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import List, Optional

from ...analysis import sanitizer as _mxsan

__all__ = ["EventJournal"]


def _mxprof_step() -> Optional[int]:
    """The flight recorder's current step, best-effort (None when
    mxprof is not imported/enabled — the journal must not drag the
    recorder in)."""
    try:
        import sys

        mxprof = sys.modules.get("mxnet_tpu.telemetry.mxprof")
        if mxprof is None:
            return None
        return mxprof.recorder()._step or None
    except Exception:  # noqa: BLE001 — a step stamp is advisory
        return None


class EventJournal:
    """One process's event journal (module-level singleton lives in
    the package ``__init__``; tests build private ones)."""

    def __init__(self, directory: Optional[str] = None,
                 who: str = "p0", ring: int = 512,
                 spill_max_bytes: int = 8 * 1024 * 1024,
                 rank: Optional[int] = None,
                 gen: Optional[int] = None):
        from collections import deque

        self._dir = directory
        self._who = who
        self._rank = rank
        self._gen = gen
        # mxsan: every post-publish access holds self._lock (emit,
        # tail, __len__); the pre-publish carry-over appends in the
        # package __init__ run while the journal is still exclusive
        self._ring: "deque[dict]" = _mxsan.track(
            deque(maxlen=max(16, int(ring))),
            "telemetry.mxblackbox.journal._ring")
        self._spill_max = max(64 * 1024, int(spill_max_bytes))
        # a LEAF lock, deliberately non-reentrant: nothing called under
        # it may emit (the signal-safety test pins this type)
        self._lock = threading.Lock()
        self._fd: Optional[int] = None
        self._spilled_bytes = 0
        self.dropped = 0  # spill write failures (ring still has them)
        # the signal hand-off: SimpleQueue.put is reentrant-safe; the
        # daemon drains it OUTSIDE any interrupted frame's locks
        self._sigq: "queue.SimpleQueue" = queue.SimpleQueue()
        self._drainer: Optional[threading.Thread] = None

    # ---- paths -------------------------------------------------------

    def spill_path(self) -> Optional[str]:
        if self._dir is None:
            return None
        return os.path.join(self._dir, f"journal-{self._who}.jsonl")

    # ---- the emit path ----------------------------------------------

    def emit(self, category: str, msg: str = "",
             step: Optional[int] = None, **fields) -> dict:
        """Append one entry (ring + spill).  Safe from any thread;
        NEVER call from a signal handler — use
        :meth:`emit_from_signal`."""
        entry = {
            "t_unix": time.time(),
            "t_mono": time.monotonic(),
            "rank": self._rank,
            "step": step if step is not None else _mxprof_step(),
            "category": category,
            "msg": msg,
        }
        if self._gen is not None:
            entry["gen"] = self._gen
        if fields:
            entry.update(fields)
        line = None
        if self._dir is not None:
            try:
                line = (json.dumps(entry, default=repr) + "\n").encode()
            except (TypeError, ValueError):
                line = None
        with self._lock:
            self._ring.append(entry)
            if line is not None:
                # deliberately under the lock: one O_APPEND write per
                # entry keeps the spill in ring order, and the ~µs
                # append is the whole cost of a rare forensic event —
                # not a hot path worth a publish-outside dance
                self._spill_locked(line)  # mxlint: disable=MX008
        return entry

    def emit_from_signal(self, category: str, msg: str = "",
                         step: Optional[int] = None, **fields) -> None:
        """Signal-handler-safe emit: enqueue and return.  The daemon
        drainer performs the real :meth:`emit` once the interrupted
        frame (which may hold the journal lock) resumes and releases
        it.  The clocks are stamped HERE so the entry records when the
        signal fired, not when the drainer got scheduled."""
        self._sigq.put((category, msg, step,
                        dict(fields, t_unix=time.time(),
                             t_mono=time.monotonic())))
        self._ensure_drainer()

    def _ensure_drainer(self) -> None:
        t = self._drainer
        if t is not None and t.is_alive():
            return
        # benign race: two starters create two drainers; SimpleQueue
        # hands each item to exactly one of them
        t = threading.Thread(target=self._drain_loop, daemon=True,
                             name="mx-blackbox-journal")
        self._drainer = t
        t.start()

    def _drain_loop(self) -> None:
        while True:
            category, msg, step, fields = self._sigq.get()
            t_unix = fields.pop("t_unix", None)
            t_mono = fields.pop("t_mono", None)
            try:
                entry = self.emit(category, msg, step=step, **fields)
                if t_unix is not None:
                    entry["t_unix"] = t_unix
                if t_mono is not None:
                    entry["t_mono"] = t_mono
            except Exception:  # noqa: BLE001 — forensics never kill the host
                pass

    # ---- spill file --------------------------------------------------

    def _spill_locked(self, line: bytes) -> None:
        """One O_APPEND write per entry; rotate past the size bound.
        Failures count in ``dropped`` — the ring keeps the entry, and
        journaling must never raise into the instrumented seam."""
        try:
            if self._fd is None:
                os.makedirs(self._dir, exist_ok=True)
                self._fd = os.open(
                    self.spill_path(),
                    os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
                try:
                    self._spilled_bytes = os.fstat(self._fd).st_size
                except OSError:
                    self._spilled_bytes = 0
            os.write(self._fd, line)
            self._spilled_bytes += len(line)
            if self._spilled_bytes >= self._spill_max:
                self._rotate_locked()
        except OSError:
            self.dropped += 1

    def _rotate_locked(self) -> None:
        path = self.spill_path()
        try:
            if self._fd is not None:
                os.close(self._fd)
        except OSError:
            pass  # mxlint: disable=MX007 — fd teardown only
        self._fd = None
        self._spilled_bytes = 0
        try:
            os.replace(path, path + ".1")
        except OSError:
            pass  # mxlint: disable=MX007 — rotation is best-effort

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass  # mxlint: disable=MX007 — fd teardown only
                self._fd = None

    # ---- readers -----------------------------------------------------

    def tail(self, n: int = 200) -> List[dict]:
        """Last ``n`` ring entries, newest last (what a crash bundle
        embeds)."""
        with self._lock:
            entries = list(self._ring)
        return [dict(e) for e in entries[-max(0, int(n)):]]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @staticmethod
    def read_spill(path: str, tail: Optional[int] = None) -> List[dict]:
        """Parse a spill file (skipping a torn final line — the only
        kind a single-write append can leave).  ``tail`` bounds the
        result to the newest N entries.  The supervisor uses this to
        scrape a SIGKILLed rank's journal, so it must tolerate any
        on-disk state."""
        out: List[dict] = []
        try:
            with open(path, "rb") as f:
                for raw in f:
                    try:
                        out.append(json.loads(raw.decode("utf-8")))
                    except (ValueError, UnicodeDecodeError):
                        continue  # torn/garbled line: skip
        except OSError:
            return []
        if tail is not None:
            out = out[-max(0, int(tail)):]
        return out
