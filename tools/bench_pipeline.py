#!/usr/bin/env python
"""Standalone data-pipeline benchmark: decode+augment img/s on synthetic
recordio shards (VERDICT r2 ask #3: 'benchmark the pipeline alone').

Generates a shard of random JPEGs (default 256x256 q95, ImageNet-ish
entropy), then measures:
  * native C++ pipeline (src/image_pipeline.cc) at each thread count
  * the pure-Python ImageRecordIter decode path, for reference

NOTE on absolute numbers: JPEG decode is CPU-bound; this container has
`nproc`=1, so the native pipeline cannot reach the TPU bench's img/s here
— the design scales with cores (each decode worker is independent), the
box does not.  Run with --threads matching the host's cores in production.
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def make_shard(path_prefix: str, n: int, size: int, quality: int) -> str:
    import cv2

    from mxnet_tpu import recordio

    rng = np.random.RandomState(0)
    rec = recordio.MXIndexedRecordIO(
        path_prefix + ".idx", path_prefix + ".rec", "w")
    # natural-image-ish entropy: smoothed noise compresses like photos
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3), np.uint8)
        img = cv2.GaussianBlur(img, (7, 7), 3)
        ok, buf = cv2.imencode(".jpg", img,
                               [cv2.IMWRITE_JPEG_QUALITY, quality])
        assert ok
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i % 1000), i, 0), buf.tobytes()))
    rec.close()
    return path_prefix + ".rec"


def bench_native(rec, idx, batch, hw, threads, epochs=1):
    from mxnet_tpu import lib

    pipe = lib.NativeImagePipeline(
        rec, idx, batch=batch, channels=3, height=hw, width=hw,
        label_width=1, threads=threads, rand_crop=True, rand_mirror=True,
        resize_short=hw + 32)
    n = 0
    t0 = time.perf_counter()
    for _ in range(epochs):
        while True:
            res = pipe.next()
            if res is None:
                break
            n += batch - res[2]
        pipe.reset()
    dt = time.perf_counter() - t0
    pipe.close()
    return n / dt


def bench_python(rec, batch, hw):
    # subprocess: MXNET_USE_NATIVE is latched at first native-lib touch
    import subprocess

    code = f"""
import time
from mxnet_tpu.io import ImageRecordIter
it = ImageRecordIter(path_imgrec={rec!r}, data_shape=(3, {hw}, {hw}),
                     batch_size={batch}, resize={hw + 32}, rand_crop=True)
assert it._pipe is None
n = 0
t0 = time.perf_counter()
for b in it:
    n += {batch} - b.pad
print("PYRATE", n / (time.perf_counter() - t0))
"""
    env = dict(os.environ, MXNET_USE_NATIVE="0",
               PYTHONPATH=os.pathsep.join(sys.path))
    p = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env)
    for ln in p.stdout.splitlines():
        if ln.startswith("PYRATE"):
            return float(ln.split()[1])
    return None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=512)
    ap.add_argument("--size", type=int, default=256)
    ap.add_argument("--crop", type=int, default=224)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--quality", type=int, default=90)
    ap.add_argument("--threads", type=int, nargs="+",
                    default=[1, 2, 4, os.cpu_count() or 1])
    ap.add_argument("--python-baseline", action="store_true")
    args = ap.parse_args()

    tmp = tempfile.mkdtemp()
    prefix = os.path.join(tmp, "bench")
    print(f"generating {args.images} synthetic JPEGs ({args.size}px "
          f"q{args.quality})...")
    rec = make_shard(prefix, args.images, args.size, args.quality)
    idx = prefix + ".idx"

    results = {}
    for t in sorted(set(args.threads)):
        r = bench_native(rec, idx, args.batch, args.crop, t)
        results[f"native_t{t}"] = round(r, 1)
        print(f"native pipeline, {t:2d} threads: {r:8.1f} img/s")
    if args.python_baseline:
        r = bench_python(rec, args.batch, args.crop)
        if r is not None:
            results["python"] = round(r, 1)
            print(f"python ImageRecordIter:      {r:8.1f} img/s")
    import json

    print(json.dumps({"metric": "image_pipeline_decode_throughput",
                      "unit": "img/s", "nproc": os.cpu_count(),
                      "results": results}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
