"""mxsan — runtime concurrency & dispatch sanitizer for mxnet_tpu.

The static side (mxlint, this package's sibling) can pattern-match lock
*syntax*; mxsan verifies lock *behaviour* at runtime.  Three detectors:

* **lock-order graph** — every instrumented ``threading.Lock`` /
  ``RLock`` / ``Condition`` acquire is recorded with thread id and call
  site; a cycle in the acquisition-order graph (including the classic
  2-lock inversion) is deadlock potential, reported with both stacks.
* **Eraser-style lockset races** — ``mxsan.track(obj, name)``
  annotations on module-level caches check every read/write against
  the intersection of held locks; an empty candidate set after
  cross-thread access means no lock consistently guards the data.
* **recompile-storm detector** — the ops-registry jit cache, the
  FusedUpdater AOT cache, and the serving bucket cache report each
  executable build; a rebuilt signature or a per-site compile count
  past warmup is the runtime ground truth static rule MX001 can only
  guess at.

Enablement (opt-in; zero overhead when off):

* ``MXNET_SAN=1`` — ``mxnet_tpu`` enables the sanitizer at import,
  before the framework's module-level locks and caches are built, so
  everything first-party is instrumented.  The pytest plugin
  (``tools/mxsan_pytest.py``, auto-registered by ``tests/conftest.py``)
  then turns violations into test failures and writes ``MXSAN.json``.
* ``mxsan.enable()`` — programmatic, same effect from that point on
  (locks/caches created earlier stay uninstrumented).
* ``mxsan.scope()`` — context manager giving a PRIVATE sanitizer
  instance for tests: seeded violations land in the scoped instance,
  not the session report.

Stdlib-only, like the rest of ``mxnet_tpu.analysis``.  See
docs/static_analysis.md ("Dynamic analysis") for the detector
semantics and annotation how-to.
"""
from __future__ import annotations

import contextlib
from typing import Any, List, Optional

from . import core, locks, lockset, report
from .core import Sanitizer, SanViolation, get_active
from .lockset import track, is_tracked
from .report import render_json, render_text, write_report

__all__ = [
    "Sanitizer", "SanViolation",
    "enable", "disable", "enabled", "scope", "get_active",
    "track", "is_tracked", "record_compile",
    "violations", "clear_violations",
    "render_json", "render_text", "write_report",
]

_default: Optional[Sanitizer] = None


def enable(**config: Any) -> Sanitizer:
    """Patch lock construction and activate the process-wide sanitizer
    instance (created on first call; ``config`` forwards to
    :class:`Sanitizer`).  Idempotent."""
    global _default
    if _default is None:
        _default = Sanitizer(**config)
    if core.get_active() is not _default:
        locks.patch()
        core.activate(_default)
    return _default


def disable() -> None:
    """Deactivate and unpatch.  Locks already wrapped keep working as
    plain locks (bookkeeping stops recording)."""
    if core.get_active() is None:
        return
    core.activate(None)
    locks.unpatch()


def enabled() -> bool:
    return core.get_active() is not None


def default() -> Optional[Sanitizer]:
    """The process-wide instance ``enable()`` manages (None before the
    first enable).  Session accounting (the pytest plugin) reads THIS
    — never the momentarily-active scoped instance of a test."""
    return _default


@contextlib.contextmanager
def scope(**config: Any):
    """A private sanitizer for one test: patches lock construction,
    activates a fresh instance, and restores the previous activation
    (session instance or none) on exit — seeded violations never leak
    into the session report.

    Known tradeoff: activation is process-global, so while a scope is
    open, events from UNRELATED background threads also land in the
    scoped instance and are discarded with it.  The detectors are
    cumulative over the whole session and scope windows are short, so
    a real defect re-fires outside them — but a scope is a detection
    blind spot for exactly its duration.  Keep scopes tight."""
    prev = core.get_active()
    san = Sanitizer(**config)
    locks.patch()
    core.activate(san)
    try:
        yield san
    finally:
        core.activate(prev)
        locks.unpatch()


def record_compile(site: str, key: Any = None,
                   seconds: float = 0.0,
                   provenance: str = "build") -> None:
    """Hook for executable-cache miss paths (ops registry, fused
    updater, serving buckets).  No-op unless a sanitizer is active.

    ``provenance="cache"`` records a persistent-compile-cache load:
    tallied, but never counted toward the duplicate-key or storm
    detectors (a warm restart is not a recompile storm)."""
    san = core.get_active()
    if san is not None:
        san.record_compile(site, key, seconds, provenance=provenance)


def violations() -> List[SanViolation]:
    san = core.get_active()
    return san.violations() if san is not None else []


def clear_violations() -> None:
    san = core.get_active()
    if san is not None:
        san.clear_violations()
