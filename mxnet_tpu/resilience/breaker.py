"""Per-model circuit breaker: degrade, don't die.

A model whose executor keeps failing (poisoned artifact, OOM loop,
driver wedge) must cost its own 503s — not take the process, and with
it every healthy model, down with it.  Standard three-state breaker:

    CLOSED     normal; consecutive failures are counted, any success
               resets the count.
    OPEN       after `threshold` consecutive failures; `allow()` is
               False (submit answers 503 ModelUnavailable without
               touching the executor) until `cooldown_s` elapses.
    HALF_OPEN  one probe request is let through after the cooldown;
               success closes the breaker, failure re-opens it (fresh
               cooldown).

Feedback comes from the batcher's launch path (`record_success` /
`record_failure` around the executor), the gate from the server's
submit path (`allow()`), so queued requests behind a trip still fail
fast.  State transitions bump ``mx_breaker_state{model,version}``
(0 closed / 1 half-open / 2 open) and ``mx_breaker_open_total``.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

__all__ = ["CircuitBreaker", "CLOSED", "OPEN", "HALF_OPEN"]

CLOSED, HALF_OPEN, OPEN = "closed", "half-open", "open"
_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    def __init__(self, name: str = "", version=0,
                 threshold: Optional[int] = None,
                 cooldown_s: Optional[float] = None):
        from ..util import env

        self._name, self._version = name, version
        self._threshold = threshold if threshold is not None \
            else env.get_int("MXNET_BREAKER_THRESHOLD")
        self._cooldown_s = cooldown_s if cooldown_s is not None \
            else env.get_float("MXNET_BREAKER_COOLDOWN_MS") / 1e3
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0          # consecutive, in CLOSED
        self._opened_at = 0.0
        self._probe_out = False     # a HALF_OPEN probe is in flight
        self._probe_at = 0.0        # when it was granted (staleness)

    def configure(self, threshold: Optional[int] = None,
                  cooldown_s: Optional[float] = None) -> None:
        """Late override (ServingConfig beats the env default)."""
        with self._lock:
            if threshold is not None:
                self._threshold = int(threshold)
            if cooldown_s is not None:
                self._cooldown_s = float(cooldown_s)

    # ---- gate ----------------------------------------------------------

    def allow(self) -> bool:
        """May a request proceed right now?  OPEN past its cooldown
        transitions to HALF_OPEN and admits exactly ONE probe; further
        requests stay rejected until the probe resolves.  CONSUMES the
        probe slot — the authoritative submit-path gate.  A probe that
        never resolves (its request died before the executor) goes
        stale after cooldown+30s so the breaker cannot wedge."""
        with self._lock:
            if self._state == CLOSED:
                return True
            now = time.monotonic()
            if self._state == OPEN:
                if now - self._opened_at < self._cooldown_s:
                    return False
                self._set_state_locked(HALF_OPEN)
                self._probe_out = True
                self._probe_at = now
                return True
            # HALF_OPEN: one probe at a time
            if self._probe_out and \
                    now - self._probe_at < self._cooldown_s + 30.0:
                return False
            self._probe_out = True
            self._probe_at = now
            return True

    def would_allow(self) -> bool:
        """Advisory, NON-consuming twin of :meth:`allow` (front-end
        fail-fast checks must not burn the half-open probe slot)."""
        with self._lock:
            if self._state == CLOSED:
                return True
            now = time.monotonic()
            if self._state == OPEN:
                return now - self._opened_at >= self._cooldown_s
            return not self._probe_out or \
                now - self._probe_at >= self._cooldown_s + 30.0

    def abandon_probe(self) -> None:
        """The granted probe request died before reaching the executor
        (admission raced shutdown, artifact import failed, client
        cancelled): free the slot so the next request can probe."""
        with self._lock:
            if self._state == HALF_OPEN:
                self._probe_out = False

    # ---- feedback ------------------------------------------------------

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_out = False
            if self._state != CLOSED:
                self._set_state_locked(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                # the probe failed: back to OPEN, fresh cooldown
                self._probe_out = False
                self._trip_locked()
                return
            if self._state == OPEN:
                return  # in-flight stragglers from before the trip
            self._failures += 1
            if self._failures >= self._threshold:
                self._trip_locked()

    # ---- introspection -------------------------------------------------

    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self._state,
                    "consecutive_failures": self._failures,
                    "threshold": self._threshold,
                    "cooldown_s": self._cooldown_s}

    # ---- internals (caller holds self._lock) ---------------------------

    def _trip_locked(self) -> None:
        self._failures = 0
        self._opened_at = time.monotonic()
        self._set_state_locked(OPEN)
        from ..telemetry import instruments as _ins

        _ins.breaker_open_total(self._name, self._version).inc()

    def _set_state_locked(self, state: str) -> None:
        self._state = state
        from ..telemetry import instruments as _ins

        _ins.breaker_state(self._name, self._version).set(
            _STATE_CODE[state])
