"""Engine frontend knobs (ref: python/mxnet/engine.py).

The reference exposes `bulk` (batch many engine pushes into one segment)
and engine-type introspection.  In the TPU runtime, op-level bulking is
XLA's job (everything under one jit is one program), so `bulk` here
controls the *dispatch* layer: inside a bulk scope the imperative invoke
path skips per-op synchronization entirely (it already does by default —
PjRt async dispatch), and NaiveEngine-mode block_until_ready is deferred
to scope exit.  The API contract (context manager, set_bulk_size) matches
the reference.
"""
from __future__ import annotations

import contextlib
import threading
from typing import List

import jax

from .util import env

__all__ = ["bulk", "set_bulk_size", "current_engine_type"]

_STATE = threading.local()


def _bulk_depth() -> int:
    return getattr(_STATE, "depth", 0)


def _track(arrays) -> None:
    pend = getattr(_STATE, "pending", None)
    if pend is not None:
        pend.extend(arrays)


def in_bulk() -> bool:
    return _bulk_depth() > 0


def current_engine_type() -> str:
    """MXNET_ENGINE_TYPE compat: 'ThreadedEnginePerDevice' (async PjRt
    dispatch, the default) or 'NaiveEngine' (synchronous)."""
    return env.get_str("MXNET_ENGINE_TYPE")


_bulk_size = 15  # parity default (MXNET_ENGINE bulking size)


def set_bulk_size(size: int) -> int:
    """ref: engine.set_bulk_size — returns the previous size."""
    global _bulk_size
    prev = _bulk_size
    _bulk_size = int(size)
    return prev


@contextlib.contextmanager
def bulk(size: int = 15):
    """Bulking scope (ref: engine.bulk).  Defers NaiveEngine's synchronous
    waits until scope exit; under the default async engine it is the
    identity (PjRt already pipelines dispatches)."""
    prev_depth = _bulk_depth()
    prev_pending = getattr(_STATE, "pending", None)
    _STATE.depth = prev_depth + 1
    _STATE.pending = []
    try:
        yield
    finally:
        pending: List = _STATE.pending
        _STATE.depth = prev_depth
        _STATE.pending = prev_pending
        if pending and current_engine_type() == "NaiveEngine":
            jax.block_until_ready(pending)
