"""Portable model export via StableHLO — the TPU-native deployment path.

Counterpart of the reference's deploy story (ref: save -symbol.json +
.params, reload in the C++ predictor / another language via the C API,
docs/faq/smart_device.md "deploy without Python").  On this stack the
compiler IR *is* the portable artifact: `export_model` traces the
block's eval-mode forward once and serializes it as versioned StableHLO
(jax.export), which any later jax release — or any StableHLO-speaking
runtime — can execute WITHOUT the model's Python class.  Weights ride
alongside in the standard reference `.params` byte format
(serialization.py), so they stay interchangeable with every other tool
in this framework.

The traced program is CachedOp's pure eval-mode function (the same
functionalization hybridize() compiles), with the PRNG key as a real
argument — stochastic eval-mode layers draw from the key you serve
with instead of replaying a baked-in constant.

Artifact layout (a directory):
    model.stablehlo   versioned StableHLO bytes (jax.export.serialize)
    model.params      the block's parameters, reference .params format
    meta.json         input shapes/dtypes + param order + output arity

    from mxnet_tpu.contrib import deploy
    deploy.export_model(net, "deploy_dir", [nd.zeros((1, 3, 224, 224))])
    ...
    served = deploy.import_model("deploy_dir")   # no model code needed
    y = served(x_nd)                             # NDArray in/out
"""
from __future__ import annotations

import json
import os
from typing import List, Sequence

from ..base import MXNetError
from ..context import current_context
from ..ndarray.ndarray import NDArray

__all__ = ["export_model", "import_model", "ServedModel"]


def export_model(block, path: str, example_inputs: Sequence) -> str:
    """Trace `block` (initialized; deferred shapes are resolved with
    one eager pass on `example_inputs` if needed) and write the
    portable artifact directory.  Returns `path`."""
    import jax
    import jax.numpy as jnp

    from jax import export as jexport

    from .. import autograd
    from ..gluon.block import CachedOp
    from ..gluon.parameter import DeferredInitializationError

    xs = [x.data if isinstance(x, NDArray) else jnp.asarray(x)
          for x in example_inputs]
    op = CachedOp(block)
    plist = op._param_list()
    if not plist:
        raise MXNetError("export_model: block has no parameters; "
                         "initialize it first")
    try:
        pvals = tuple(p.data().data for _, p in plist)
    except DeferredInitializationError:
        # we hold exactly the inputs needed to resolve deferred shapes
        # (the CachedOp.__call__ resolve-and-retry pattern)
        with autograd.pause():
            block(*[NDArray(x) for x in xs])
        op._pstruct = None
        plist = op._param_list()
        pvals = tuple(p.data().data for _, p in plist)

    pure = op._make_pure(train=False)

    def serve_fn(params, key, *inputs):
        flat, _aux = pure(params, inputs, key)
        return flat

    structs = tuple(jax.ShapeDtypeStruct(v.shape, v.dtype) for v in pvals)
    key_struct = jax.ShapeDtypeStruct((2,), jnp.uint32)
    in_structs = tuple(jax.ShapeDtypeStruct(x.shape, x.dtype) for x in xs)
    exp = jexport.export(jax.jit(serve_fn))(structs, key_struct,
                                            *in_structs)
    blob = exp.serialize()

    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "model.stablehlo"), "wb") as f:
        f.write(blob)
    from ..serialization import save_ndarrays as nd_save

    nd_save(os.path.join(path, "model.params"),
            {name: p.data() for name, p in plist})
    meta = {
        "format": "mxnet_tpu.deploy/1",
        "param_order": [name for name, _ in plist],
        "param_shapes": {name: list(p.data().shape) for name, p in plist},
        "param_dtypes": {name: str(p.data().dtype) for name, p in plist},
        "inputs": [{"shape": list(x.shape), "dtype": str(x.dtype)}
                   for x in xs],
        "n_outputs": len(exp.out_avals),
    }
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    return path


class ServedModel:
    """A reloaded artifact: callable NDArray-in/NDArray-out.

    `params` may be swapped wholesale (same names/shapes/dtypes) with
    `set_params`, e.g. after further training — the compiled program is
    weight-agnostic because parameters are arguments, not constants.
    Stochastic eval-mode layers draw from the per-call `seed`."""

    def __init__(self, exported, params: dict, meta: dict):
        self._exported = exported
        self._meta = meta
        self._order: List[str] = meta["param_order"]
        self.set_params(params)

    def set_params(self, params: dict) -> None:
        """Validated atomically: a bad set leaves the old weights."""
        missing = [n for n in self._order if n not in params]
        if missing:
            raise MXNetError(f"artifact params missing {missing[:5]}")
        new = []
        for n in self._order:
            v = params[n].data if isinstance(params[n], NDArray) \
                else params[n]
            want_s = self._meta.get("param_shapes", {}).get(n)
            want_d = self._meta.get("param_dtypes", {}).get(n)
            if want_s is not None and list(v.shape) != want_s:
                raise MXNetError(
                    f"param {n}: shape {list(v.shape)} != exported "
                    f"{want_s}")
            if want_d is not None and str(v.dtype) != want_d:
                raise MXNetError(
                    f"param {n}: dtype {v.dtype} != exported {want_d}")
            new.append(v)
        self._pvals = tuple(new)

    def __call__(self, *inputs, seed: int = 0):
        import jax
        import jax.numpy as jnp

        want = self._meta["inputs"]
        if len(inputs) != len(want):
            raise MXNetError(
                f"artifact takes {len(want)} inputs, got {len(inputs)}")
        ctx = next((x.ctx for x in inputs if isinstance(x, NDArray)),
                   None) or current_context()
        xs = []
        for x, w in zip(inputs, want):
            v = x.data if isinstance(x, NDArray) else jnp.asarray(x)
            if list(v.shape) != w["shape"]:
                raise MXNetError(
                    f"input shape {list(v.shape)} != exported "
                    f"{w['shape']} (StableHLO artifacts are fixed-shape)")
            if str(v.dtype) != w["dtype"]:
                raise MXNetError(
                    f"input dtype {v.dtype} != exported {w['dtype']}")
            xs.append(v)
        key = jax.random.PRNGKey(seed)
        outs = self._exported.call(self._pvals, key, *xs)
        nds = [NDArray(o, ctx=ctx) for o in outs]
        return nds[0] if len(nds) == 1 else nds


def import_model(path: str) -> ServedModel:
    """Reload an artifact directory — no model code, no block class."""
    from jax import export as jexport

    from ..serialization import load_ndarrays as nd_load

    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    if meta.get("format") != "mxnet_tpu.deploy/1":
        raise MXNetError(f"not a deploy artifact: {path}")
    with open(os.path.join(path, "model.stablehlo"), "rb") as f:
        exported = jexport.deserialize(f.read())
    params = nd_load(os.path.join(path, "model.params"))
    return ServedModel(exported, params, meta)
