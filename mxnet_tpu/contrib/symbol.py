"""mx.contrib.symbol — symbolic contrib op wrappers
(ref: python/mxnet/symbol/contrib.py generated namespace)."""
from __future__ import annotations

import threading

from ..analysis import sanitizer as _mxsan
from ..ops.registry import OP_REGISTRY
from ..symbol.symbol import make_symbol_function

# mxsan: lock-free __getattr__ fast path; writes hold _CACHE_LOCK
_CACHE = _mxsan.track({}, "contrib.symbol._CACHE",
                      reads="unlocked-ok")
_CACHE_LOCK = threading.Lock()  # module attrs resolve from any thread


def __getattr__(name):
    # '_contrib_' registry alias FIRST, bare name as fallback — the ONE
    # lookup rule for every contrib namespace spelling (sym.contrib.X,
    # mx.contrib.symbol.X); contrib-first so a name shared between a
    # plain op and a distinct contrib op resolves to the contrib one
    if name in _CACHE:
        return _CACHE[name]
    for cand in (f"_contrib_{name}", name):
        if cand in OP_REGISTRY:
            fn = make_symbol_function(cand)
            with _CACHE_LOCK:
                fn = _CACHE.setdefault(name, fn)
            return fn
    raise AttributeError(
        f"no contrib symbol op {name!r} (tried '_contrib_{name}' too)")
