"""Fused training step (ISSUE 3): single-dispatch donated optimizer
update + bucketed gradient allreduce.

The fused path is a pure optimization — every test here pins it against
the eager per-parameter loop: identical weights AND identical optimizer
states, for every registered optimizer, multi-precision included, with
no recompile on schedule changes (asserted through the
``mx_fused_compile_seconds`` histogram, which counts executable builds).
"""
import pickle

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, optimizer
from mxnet_tpu.gluon.parameter import Parameter
from mxnet_tpu.gluon.trainer import Trainer
from mxnet_tpu.ndarray.ndarray import NDArray, array as nd_array
from mxnet_tpu.optimizer import fused as fused_mod
from mxnet_tpu.telemetry import instruments as _ins

SHAPES = [(4, 3), (7,), (2, 3, 2), (1,)]

# (name, kwargs) — one spec per registered optimizer, plus variants
# that flip a state-structure branch (momentum on/off, centered).
CASES = [
    ("sgd", {"momentum": 0.9, "wd": 0.01}),
    ("sgd", {}),
    ("nag", {"momentum": 0.9}),
    ("adam", {}),
    ("adagrad", {}),
    ("adadelta", {}),
    ("adamax", {}),
    ("nadam", {}),
    ("rmsprop", {}),
    ("rmsprop", {"centered": True}),
    ("ftrl", {}),
    ("signum", {"momentum": 0.9}),
    ("signsgd", {}),
    ("lamb", {}),
    ("test", {}),
]


def _make_params(ctx=None, dtype="float32", seed=0):
    rng = np.random.RandomState(seed)
    params = []
    for i, shp in enumerate(SHAPES):
        p = Parameter(f"w{i}", shape=shp, dtype=dtype)
        p.initialize(ctx=ctx or [mx.cpu()])
        p.set_data(nd_array(rng.randn(*shp).astype("float32")))
        params.append(p)
    return params


def _set_grads(params, step, replica_scale=False):
    rng = np.random.RandomState(1000 + step)
    for p in params:
        g = rng.randn(*p.shape).astype("float32")
        for r, gnd in enumerate(p.list_grad()):
            scaled = g * (r + 1) if replica_scale else g
            gnd._data = nd_array(scaled, ctx=gnd.ctx,
                                 dtype=str(gnd.data.dtype)).data


def _assert_state_close(a, b, **tol):
    if a is None:
        assert b is None
        return
    if isinstance(a, NDArray):
        np.testing.assert_allclose(a.asnumpy().astype("f8"),
                                   b.asnumpy().astype("f8"), **tol)
        return
    assert len(a) == len(b)
    for x, y in zip(a, b):
        _assert_state_close(x, y, **tol)


def _run_pair(name, kwargs, steps=5, dtype="float32", ctx=None,
              opt_extra=None):
    """Two identical trainers, one fused one eager, fed identical
    gradients; returns them plus their parameter lists."""
    opt_kw = dict(kwargs, **(opt_extra or {}))
    pf = _make_params(ctx=ctx, dtype=dtype)
    pe = _make_params(ctx=ctx, dtype=dtype)
    kv = "device" if ctx and len(ctx) > 1 else None
    tf = Trainer(pf, name, dict(opt_kw), kvstore=kv, fuse_step=True)
    te = Trainer(pe, name, dict(opt_kw), kvstore=kv, fuse_step=False)
    for step in range(steps):
        _set_grads(pf, step, replica_scale=ctx is not None)
        _set_grads(pe, step, replica_scale=ctx is not None)
        tf.step(2)
        te.step(2)
    return tf, te, pf, pe


def test_every_registered_optimizer_has_a_parity_case():
    """New optimizers must be added to CASES (and grow a fused path or
    an explicit eager-only marker) — the registry is the checklist."""
    from mxnet_tpu.optimizer.optimizer import _REG

    assert {n for n, _ in CASES} >= set(_REG.list())


@pytest.mark.parametrize("name,kwargs",
                         CASES, ids=[f"{n}-{i}" for i, (n, _)
                                     in enumerate(CASES)])
def test_fused_eager_parity(name, kwargs):
    tf, te, pf, pe = _run_pair(name, kwargs)
    for p_f, p_e in zip(pf, pe):
        np.testing.assert_allclose(p_f.data().asnumpy(),
                                   p_e.data().asnumpy(),
                                   rtol=2e-5, atol=1e-6)
    for k, s_e in te._updaters[0].states.items():
        _assert_state_close(tf._updaters[0].states[k], s_e,
                            rtol=2e-5, atol=1e-6)


@pytest.mark.parametrize("name,kwargs",
                         [("sgd", {"momentum": 0.9}), ("sgd", {}),
                          ("adam", {})])
def test_fused_eager_parity_multi_precision(name, kwargs):
    tf, te, pf, pe = _run_pair(name, kwargs, dtype="float16",
                               opt_extra={"multi_precision": True})
    for p_f, p_e in zip(pf, pe):
        assert str(p_f.data().data.dtype) == "float16"
        np.testing.assert_allclose(p_f.data().asnumpy().astype("f4"),
                                   p_e.data().asnumpy().astype("f4"),
                                   rtol=1e-3, atol=1e-3)
    for k, s_e in te._updaters[0].states.items():
        _assert_state_close(tf._updaters[0].states[k], s_e,
                            rtol=1e-3, atol=1e-3)


def test_set_learning_rate_changes_behavior_without_recompile():
    """The acceptance gate: exactly ONE executable build across 5 steps
    that include an lr change and a batch-size (rescale_grad) change —
    asserted via mx_fused_compile_seconds — while the lr change still
    takes effect (parity with an eager run doing the same schedule)."""
    hist = _ins.fused_compile_seconds()

    # unique shapes: the executable cache is process-wide, so reusing a
    # signature another test already compiled would undercount
    def make():
        rng = np.random.RandomState(0)
        ps = []
        for i, shp in enumerate([(3, 5), (11,), (2, 2, 3)]):
            p = Parameter(f"lr{i}", shape=shp)
            p.initialize(ctx=[mx.cpu()])
            p.set_data(nd_array(rng.standard_normal(shp).astype("f4")))
            ps.append(p)
        return ps

    pf = make()
    pe = make()
    tf = Trainer(pf, "sgd", {"learning_rate": 0.1, "momentum": 0.9},
                 kvstore=None, fuse_step=True)
    te = Trainer(pe, "sgd", {"learning_rate": 0.1, "momentum": 0.9},
                 kvstore=None, fuse_step=False)
    c0 = hist.count
    s0 = fused_mod.compile_stats()["count"]
    for step in range(5):
        if step == 2:
            tf.set_learning_rate(0.03)
            te.set_learning_rate(0.03)
        _set_grads(pf, step)
        _set_grads(pe, step)
        bs = 2 if step < 3 else 4  # rescale_grad change, also traced
        tf.step(bs)
        te.step(bs)
    assert hist.count - c0 == 1
    assert fused_mod.compile_stats()["count"] - s0 == 1
    for p_f, p_e in zip(pf, pe):
        np.testing.assert_allclose(p_f.data().asnumpy(),
                                   p_e.data().asnumpy(),
                                   rtol=2e-5, atol=1e-6)


def test_fused_multi_replica_parity():
    """Two device replicas with DIFFERENT per-replica gradients: the
    bucketed allreduce + per-replica fused update must match the eager
    push/pull + per-parameter loop, and replicas must stay in sync."""
    ctx = [mx.cpu(0), mx.cpu(1)]
    tf, te, pf, pe = _run_pair("sgd", {"momentum": 0.9}, steps=3, ctx=ctx)
    assert len(tf._updaters) == 2
    for p_f, p_e in zip(pf, pe):
        for d_f, d_e in zip(p_f.list_data(), p_e.list_data()):
            np.testing.assert_allclose(d_f.asnumpy(), d_e.asnumpy(),
                                       rtol=2e-5, atol=1e-6)
        r0, r1 = (d.asnumpy() for d in p_f.list_data())
        np.testing.assert_allclose(r0, r1, rtol=1e-6)


def test_trainer_save_load_states_all_replicas(tmp_path):
    """Regression (ISSUE 3 satellite): with N replicas the trainer owns
    N updaters, but save_states used to persist only _updaters[0] —
    every replica's state must round-trip."""
    ctx = [mx.cpu(0), mx.cpu(1)]
    params = _make_params(ctx=ctx)
    trainer = Trainer(params, "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9},
                      kvstore="device")
    for step in range(2):
        _set_grads(params, step, replica_scale=True)
        trainer.step(2)
    assert len(trainer._updaters) == 2
    fname = str(tmp_path / "t.states")
    trainer.save_states(fname)

    params2 = _make_params(ctx=ctx)
    for p2, p in zip(params2, params):  # same weights as the original
        p2.set_data(p.data())
    restored = Trainer(params2, "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9},
                       kvstore="device")
    restored.load_states(fname)
    restored._init_kvstore()
    assert len(restored._updaters) == 2
    for r in range(2):
        src = trainer._updaters[r].states
        dst = restored._updaters[r].states
        assert set(src) == set(dst)
        for k in src:
            _assert_state_close(dst[k], src[k], rtol=1e-6, atol=1e-7)
    # the round-trip must actually RESUME: stepping the restored
    # trainer (fused path, states must sit on each replica's device)
    # matches the original trainer continuing
    _set_grads(params, 7, replica_scale=True)
    _set_grads(params2, 7, replica_scale=True)
    trainer.step(2)
    restored.step(2)
    for p, p2 in zip(params, params2):
        for d, d2 in zip(p.list_data(), p2.list_data()):
            np.testing.assert_allclose(d2.asnumpy(), d.asnumpy(),
                                       rtol=2e-5, atol=1e-6)


def test_load_states_legacy_single_payload_broadcasts(tmp_path):
    """A pre-fix checkpoint (one raw Updater payload) must still load —
    and now lands on EVERY replica instead of only replica 0."""
    ctx = [mx.cpu(0), mx.cpu(1)]
    params = _make_params(ctx=ctx)
    trainer = Trainer(params, "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9},
                      kvstore="device")
    _set_grads(params, 0, replica_scale=True)
    trainer.step(2)
    legacy = trainer._updaters[0].get_states(dump_optimizer=False)
    fname = str(tmp_path / "legacy.states")
    with open(fname, "wb") as f:
        f.write(legacy)
    params2 = _make_params(ctx=ctx)
    for p2, p in zip(params2, params):
        p2.set_data(p.data())
    restored = Trainer(params2, "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9},
                       kvstore="device")
    restored._init_kvstore()
    restored.load_states(fname)
    # a FRESH trainer must size by the replica count, not by the (zero)
    # updaters it happens to have — both replicas restored
    assert len(restored._updaters) == 2
    ref = pickle.loads(legacy)
    for u in restored._updaters:
        for k in ref:
            _assert_state_close(u.states[k],
                                trainer._updaters[0].states[k],
                                rtol=1e-6, atol=1e-7)
    # resuming keeps the replicas in lockstep (in-sync training has
    # identical state on every replica, so broadcast is exact)
    _set_grads(params, 5, replica_scale=True)
    _set_grads(params2, 5, replica_scale=True)
    trainer.step(2)
    restored.step(2)
    for p, p2 in zip(params, params2):
        r0, r1 = (d.asnumpy() for d in p2.list_data())
        np.testing.assert_allclose(r0, r1, rtol=1e-6)
        np.testing.assert_allclose(r0, p.list_data()[0].asnumpy(),
                                   rtol=2e-5, atol=1e-6)


@pytest.mark.parametrize("name", ["nadam", "adamax"])
def test_half_precision_t_hyper_falls_back_to_eager(name):
    """Optimizers whose kernels consume the raw step count t cannot
    trace it in half precision (bf16 cannot represent t > 256): without
    a multi-precision master copy the fused path must hand the step to
    the eager loop — same weights, zero fused compiles."""
    from mxnet_tpu.optimizer import fused as fused_mod

    c0 = fused_mod.compile_stats()["count"]
    tf, te, pf, pe = _run_pair(name, {}, steps=3, dtype="float16")
    assert fused_mod.compile_stats()["count"] == c0
    # the incompatibility is static for the run — the UPDATE half is
    # latched to eager (no per-step probe, no phantom fused-update
    # span) while the bucketed allreduce stays engaged
    assert tf._fuse_update_ok is False
    assert tf._fuse_active is True
    for p_f, p_e in zip(pf, pe):
        assert str(p_f.data().data.dtype) == "float16"
        np.testing.assert_allclose(p_f.data().asnumpy().astype("f4"),
                                   p_e.data().asnumpy().astype("f4"),
                                   rtol=1e-3, atol=1e-3)
    # with the fp32 master copy the same optimizer fuses fine
    tf2, te2, pf2, pe2 = _run_pair(name, {}, steps=3, dtype="float16",
                                   opt_extra={"multi_precision": True})
    assert fused_mod.compile_stats()["count"] > c0
    for p_f, p_e in zip(pf2, pe2):
        np.testing.assert_allclose(p_f.data().asnumpy().astype("f4"),
                                   p_e.data().asnumpy().astype("f4"),
                                   rtol=1e-3, atol=1e-3)


def test_load_states_replica_count_mismatch_is_loud(tmp_path):
    """A checkpoint with FEWER replica payloads than the trainer's live
    updaters must raise — restoring a subset would silently leave the
    remaining replicas' momentum stale."""
    from mxnet_tpu.base import MXNetError

    ctx = [mx.cpu(0), mx.cpu(1)]
    params = _make_params(ctx=ctx)
    trainer = Trainer(params, "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9},
                      kvstore="device")
    _set_grads(params, 0, replica_scale=True)
    trainer.step(2)
    one_blob = pickle.dumps({"__mx_replica_states__": [
        trainer._updaters[0].get_states(dump_optimizer=False)]})
    fname = str(tmp_path / "one.states")
    with open(fname, "wb") as f:
        f.write(one_blob)
    with pytest.raises(MXNetError, match="replica"):
        trainer.load_states(fname)


def test_sparse_grad_step_falls_back_to_eager():
    """A row-sparse gradient appearing mid-run must take the eager
    (lazy-update) path for that step — same result as fuse_step=False —
    then return to the fused path on the next dense step."""
    from mxnet_tpu.ndarray import sparse as sp

    results = {}
    for fuse in (True, False):
        params = _make_params(seed=3)
        emb = Parameter("emb", shape=(6, 3))
        emb.initialize(ctx=[mx.cpu()])
        emb.set_data(nd_array(
            np.random.RandomState(5).randn(6, 3).astype("f4")))
        trainer = Trainer(params + [emb], "sgd",
                          {"learning_rate": 0.1, "momentum": 0.9},
                          kvstore=None, fuse_step=fuse)
        for step in range(3):
            _set_grads(params, step)
            if step == 1:  # sparse grad only on the middle step
                emb.data()._ag_grad = sp.row_sparse_array(
                    (np.ones((2, 3), "f4"), [1, 4]), shape=(6, 3))
            else:
                emb.data()._ag_grad = nd_array(
                    np.zeros((6, 3), "f4"))
            trainer.step(2)
        results[fuse] = [p.data().asnumpy() for p in params + [emb]]
    for wf, we in zip(results[True], results[False]):
        np.testing.assert_allclose(wf, we, rtol=2e-5, atol=1e-6)


def test_empty_compression_params_keep_the_fused_path():
    """compression_params={} configures nothing (_init_kvstore skips
    it), so it must not disengage the fused path either."""
    params = _make_params()
    trainer = Trainer(params, "sgd", {"learning_rate": 0.1},
                      kvstore=None, compression_params={})
    _set_grads(params, 0)
    trainer.step(2)
    assert trainer._fuse_active is True


def test_ragged_replica_layout_save_load_round_trips(tmp_path):
    """Mixed replica counts (param0 on one ctx, param1 on two) run the
    eager loop but still own per-replica updaters — save/load must size
    by the LONGEST ctx list and resume cleanly."""
    p0 = Parameter("rag0", shape=(4, 3))
    p0.initialize(ctx=[mx.cpu(0)])
    p1 = Parameter("rag1", shape=(5,))
    p1.initialize(ctx=[mx.cpu(0), mx.cpu(1)])

    def set_grads(ps, step):
        rng = np.random.RandomState(50 + step)
        for p in ps:
            g = rng.standard_normal(p.shape).astype("f4")
            for r, gnd in enumerate(p.list_grad()):
                gnd._data = nd_array(g * (r + 1), ctx=gnd.ctx).data

    trainer = Trainer([p0, p1], "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9},
                      kvstore=None)
    set_grads([p0, p1], 0)
    trainer.step(2)
    assert len(trainer._updaters) == 2
    fname = str(tmp_path / "ragged.states")
    trainer.save_states(fname)

    q0 = Parameter("rag0", shape=(4, 3))
    q0.initialize(ctx=[mx.cpu(0)])
    q1 = Parameter("rag1", shape=(5,))
    q1.initialize(ctx=[mx.cpu(0), mx.cpu(1)])
    # kvstore=None means the replicas legitimately diverged — copy each
    # replica's weights individually, not a replica-0 broadcast
    for src, dst in ((p0, q0), (p1, q1)):
        for s, d in zip(src.list_data(), dst.list_data()):
            d._data = nd_array(s.asnumpy(), ctx=d.ctx).data
    restored = Trainer([q0, q1], "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9},
                       kvstore=None)
    restored._init_kvstore()
    restored.load_states(fname)
    assert len(restored._updaters) == 2
    set_grads([p0, p1], 1)
    set_grads([q0, q1], 1)
    trainer.step(2)
    restored.step(2)
    for a, b in ((p0, q0), (p1, q1)):
        for d, d2 in zip(a.list_data(), b.list_data()):
            np.testing.assert_allclose(d2.asnumpy(), d.asnumpy(),
                                       rtol=2e-5, atol=1e-6)


def test_fuse_step_true_with_compression_warns_and_falls_back():
    params = _make_params()
    trainer = Trainer(params, "sgd", {"learning_rate": 0.1},
                      kvstore="device",
                      compression_params={"type": "2bit"},
                      fuse_step=True)
    _set_grads(params, 0)
    with pytest.warns(UserWarning, match="fuse_step"):
        trainer.step(2)
    assert trainer._fuse_active is False
    # and the step still happened
    assert not np.allclose(params[0].data().asnumpy(),
                           _make_params()[0].data().asnumpy())


def test_fused_step_telemetry_counter_and_span():
    from mxnet_tpu import telemetry

    telemetry.enable()
    try:
        params = _make_params()
        trainer = Trainer(params, "sgd", {"learning_rate": 0.1},
                          kvstore=None, fuse_step=True)
        reg = telemetry.get_registry()
        for step in range(3):
            _set_grads(params, step)
            trainer.step(2)
        assert reg.get("mx_fused_step_total").value >= 3
        phases = {v[0] for v, _ in
                  reg.get("mx_training_phase_seconds").children()}
        assert "fused-update" in phases
    finally:
        telemetry.disable()


# ---- kvstore bucketing ------------------------------------------------


def test_pushpull_fused_matches_per_key_pushpull():
    rng = np.random.RandomState(0)
    shapes = [(4, 3), (7,), (2, 3, 2), (5,), (3, 3), ()]
    keys = list(range(len(shapes)))
    vals = [[nd_array(rng.standard_normal(s).astype("f4"))
             for _ in range(2)] for s in shapes]
    expect = [sum(v.asnumpy() for v in vs) for vs in vals]

    kv = mx.kvstore.create("device")
    outs = [[nd.zeros(s), nd.zeros(s)] for s in shapes]
    # tiny bucket_bytes forces multiple buckets; correctness must not
    # depend on the packing
    kv.pushpull_fused(keys, vals, out=outs, bucket_bytes=64)
    for exp, os_ in zip(expect, outs):
        for o in os_:
            np.testing.assert_allclose(o.asnumpy(), exp, rtol=1e-6)

    # the reduced value is PUBLISHED to the store (push contract, same
    # as the eager Trainer's push+pull) — a later pull must see it
    kv.init(0, nd.zeros(shapes[0]))
    kv.pushpull_fused(keys, vals, out=outs, bucket_bytes=64)
    pulled = nd.zeros(shapes[0])
    kv.pull(0, out=pulled)
    np.testing.assert_allclose(pulled.asnumpy(), expect[0], rtol=1e-6)

    # default (one big bucket) agrees with per-key pushpull
    kv2 = mx.kvstore.create("device")
    outs2 = [[nd.zeros(s), nd.zeros(s)] for s in shapes]
    kv2.pushpull_fused(keys, vals, out=outs2)
    ref = [[nd.zeros(s), nd.zeros(s)] for s in shapes]
    kv3 = mx.kvstore.create("device")
    for k, v, o in zip(keys, vals, ref):
        kv3.pushpull(k, v, out=o)
    for os_, rs_ in zip(outs2, ref):
        for o, r in zip(os_, rs_):
            np.testing.assert_allclose(o.asnumpy(), r.asnumpy(),
                                       rtol=1e-6)


def test_pushpull_fused_mixed_dtypes_bucket_homogeneous():
    rng = np.random.RandomState(1)
    v32 = [nd_array(rng.randn(4, 3).astype("f4")) for _ in range(2)]
    v16 = [nd_array(rng.randn(5).astype("f4")).astype("float16")
           for _ in range(2)]
    kv = mx.kvstore.create("device")
    outs = [[nd.zeros((4, 3)), nd.zeros((4, 3))],
            [nd.zeros((5,)).astype("float16"),
             nd.zeros((5,)).astype("float16")]]
    kv.pushpull_fused([0, 1], [v32, v16], out=outs)
    np.testing.assert_allclose(outs[0][0].asnumpy(),
                               v32[0].asnumpy() + v32[1].asnumpy(),
                               rtol=1e-6)
    assert str(outs[1][0].data.dtype) == "float16"
    np.testing.assert_allclose(
        outs[1][0].asnumpy().astype("f4"),
        (v16[0].asnumpy() + v16[1].asnumpy()).astype("f4"),
        rtol=1e-2, atol=1e-2)


def test_pushpull_fused_falls_back_to_per_key_with_updater():
    """A server-side updater needs key-level treatment: the fused call
    must produce exactly what per-key pushpull produces."""
    rng = np.random.RandomState(2)
    init = [rng.randn(4, 3).astype("f4"), rng.randn(5).astype("f4")]
    grads = [rng.randn(4, 3).astype("f4"), rng.randn(5).astype("f4")]

    def run(fusedcall):
        kv = mx.kvstore.create("device")
        kv.set_optimizer(optimizer.create("sgd", learning_rate=0.1))
        for k, w in enumerate(init):
            kv.init(k, nd_array(w))
        outs = [nd.zeros(w.shape) for w in init]
        vals = [nd_array(g) for g in grads]
        if fusedcall:
            kv.pushpull_fused([0, 1], vals, out=outs)
        else:
            for k, (v, o) in enumerate(zip(vals, outs)):
                kv.pushpull(k, v, out=o)
        return [o.asnumpy() for o in outs]

    for a, b in zip(run(True), run(False)):
        np.testing.assert_allclose(a, b, rtol=1e-6)
