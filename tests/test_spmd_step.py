"""Unified SPMD training step (ISSUE 9): one donated jit program over
the replica mesh — gradient reduce + ZeRO-sharded optimizer apply.

The SPMD path is a pure optimization over the per-replica fused path:
every test here pins it against that path (which PR 3 already pinned
against the eager loop), across every registered optimizer, plus the
ISSUE-9 acceptance assertions: per-device optimizer-state memory
shrinks ~1/N, exactly ONE executable per (mesh, layout), states
round-trip through save/load including onto a different mesh shape,
and the documented fallbacks hand states off losslessly.

The conftest pins an 8-virtual-device CPU backend, so the >=2-device
harness runs in-process.  MXNET_ZERO_MIN_SIZE is dropped to 1 in most
tests: the suite's parameters are tiny and would otherwise (correctly)
stay replicated.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer
from mxnet_tpu.gluon.parameter import Parameter
from mxnet_tpu.gluon.trainer import Trainer
from mxnet_tpu.ndarray.ndarray import NDArray, array as nd_array
from mxnet_tpu.optimizer import spmd as spmd_mod
from mxnet_tpu.telemetry import instruments as _ins

SHAPES = [(4, 3), (7,), (2, 3, 2), (1,)]

CASES = [
    ("sgd", {"momentum": 0.9, "wd": 0.01}),
    ("sgd", {}),
    ("nag", {"momentum": 0.9}),
    ("adam", {}),
    ("adagrad", {}),
    ("adadelta", {}),
    ("adamax", {}),
    ("nadam", {}),
    ("rmsprop", {}),
    ("rmsprop", {"centered": True}),
    ("ftrl", {}),
    ("signum", {"momentum": 0.9}),
    ("signsgd", {}),
    ("lamb", {}),
    ("test", {}),
]


@pytest.fixture(autouse=True)
def _small_zero_min(monkeypatch):
    """Test params are tiny; shard them anyway so the ZeRO layout is
    what every test exercises."""
    monkeypatch.setenv("MXNET_ZERO_MIN_SIZE", "1")


def _make_params(ctx=None, dtype="float32", seed=0, shapes=SHAPES):
    rng = np.random.RandomState(seed)
    params = []
    for i, shp in enumerate(shapes):
        p = Parameter(f"w{i}", shape=shp, dtype=dtype)
        p.initialize(ctx=ctx or [mx.cpu()])
        p.set_data(nd_array(rng.randn(*shp).astype("float32")))
        params.append(p)
    return params


def _set_grads(params, step, replica_scale=True):
    rng = np.random.RandomState(1000 + step)
    for p in params:
        g = rng.randn(*p.shape).astype("float32")
        for r, gnd in enumerate(p.list_grad()):
            scaled = g * (r + 1) if replica_scale else g
            gnd._data = nd_array(scaled, ctx=gnd.ctx,
                                 dtype=str(gnd.data.dtype)).data


def _assert_state_close(a, b, **tol):
    if a is None:
        assert b is None
        return
    if isinstance(a, (NDArray, np.ndarray)):
        an = a.asnumpy() if isinstance(a, NDArray) else a
        bn = b.asnumpy() if isinstance(b, NDArray) else b
        np.testing.assert_allclose(np.asarray(an, "f8"),
                                   np.asarray(bn, "f8"), **tol)
        return
    assert len(a) == len(b)
    for x, y in zip(a, b):
        _assert_state_close(x, y, **tol)


def _run_pair(name, kwargs, steps=3, ctx=None, shapes=SHAPES):
    """Two identical trainers, SPMD vs per-replica fused, fed identical
    per-replica gradients."""
    ctx = ctx or [mx.cpu(0), mx.cpu(1)]
    ps = _make_params(ctx=ctx, shapes=shapes)
    pf = _make_params(ctx=ctx, shapes=shapes)
    ts = Trainer(ps, name, dict(kwargs), kvstore="device", spmd=True)
    tf = Trainer(pf, name, dict(kwargs), kvstore="device",
                 fuse_step=True)
    for step in range(steps):
        _set_grads(ps, step)
        _set_grads(pf, step)
        ts.step(2)
        tf.step(2)
    return ts, tf, ps, pf


def test_every_registered_optimizer_has_a_spmd_case():
    from mxnet_tpu.optimizer.optimizer import _REG

    assert {n for n, _ in CASES} >= set(_REG.list())


@pytest.mark.parametrize("name,kwargs", CASES,
                         ids=[f"{n}-{i}" for i, (n, _)
                              in enumerate(CASES)])
def test_spmd_matches_per_replica_fused(name, kwargs):
    """Params AND states match the per-replica path's replica 0 (the
    documented trajectory for t-optimizers; exact for the rest), and
    the SPMD replicas stay bit-identical to each other."""
    ts, tf, ps, pf = _run_pair(name, kwargs)
    assert ts._spmd_active and ts._spmd_updater is not None
    for p_s, p_f in zip(ps, pf):
        np.testing.assert_allclose(p_s.list_data()[0].asnumpy(),
                                   p_f.list_data()[0].asnumpy(),
                                   rtol=2e-5, atol=1e-6)
        r0, r1 = (d.asnumpy() for d in p_s.list_data())
        np.testing.assert_allclose(r0, r1, rtol=0, atol=0)
    import pickle

    spmd_states = pickle.loads(
        ts._spmd_updater.get_states(dump_optimizer=False))
    for k, s_f in tf._updaters[0].states.items():
        _assert_state_close(spmd_states[k], s_f, rtol=2e-5, atol=1e-6)


def test_states_shard_one_over_n_per_device():
    """ISSUE 9 acceptance: optimizer-state memory per device shrinks
    ~1/N vs replicated."""
    n = 4
    ctx = [mx.cpu(i) for i in range(n)]
    shapes = [(64, 8), (128,), (16, 16)]
    ts, _, ps, _ = _run_pair("adam", {}, ctx=ctx, shapes=shapes)
    u = ts._spmd_updater
    assert u.shard_factor() == n
    total = per_dev = 0
    leaves = []

    def walk(t):
        if t is None:
            return
        if isinstance(t, tuple):
            for x in t:
                walk(x)
            return
        leaves.append(t)

    for tree in list(u._bstate.values()) + list(u._pstate.values()):
        walk(tree)
    assert leaves
    for leaf in leaves:
        total += leaf.size
        shard = leaf.sharding.shard_shape(leaf.shape)
        per_dev += int(np.prod(shard))
    assert per_dev == total // n  # exactly 1/N (padding already inside)


def test_one_executable_per_mesh_layout():
    """ISSUE 9 acceptance: two trainers with the same (mesh, layout)
    share ONE compiled step; a different layout compiles a second."""
    c0 = spmd_mod.compile_stats()["count"]
    _run_pair("sgd", {"momentum": 0.5}, steps=2)
    built = spmd_mod.compile_stats()["count"] - c0
    assert built == 1
    _run_pair("sgd", {"momentum": 0.5}, steps=2)  # same layout: cached
    assert spmd_mod.compile_stats()["count"] - c0 == built
    _run_pair("sgd", {"momentum": 0.5}, steps=2,
              ctx=[mx.cpu(i) for i in range(4)])  # new mesh: one more
    assert spmd_mod.compile_stats()["count"] - c0 == built + 1


def test_no_recompile_on_lr_change():
    ctx = [mx.cpu(0), mx.cpu(1)]
    ps = _make_params(ctx=ctx)
    ts = Trainer(ps, "sgd", {"momentum": 0.9}, kvstore="device",
                 spmd=True)
    _set_grads(ps, 0)
    ts.step(2)
    c0 = spmd_mod.compile_stats()["count"]
    before = ps[0].list_data()[0].asnumpy().copy()
    ts.set_learning_rate(0.5)
    _set_grads(ps, 1)
    ts.step(2)
    assert spmd_mod.compile_stats()["count"] == c0
    assert not np.allclose(before, ps[0].list_data()[0].asnumpy())


def test_save_load_roundtrip_onto_different_mesh(tmp_path):
    """Gather-on-save / reshard-on-load: resume a 4-replica SPMD run
    onto a 2-replica mesh and onto the per-replica fused path — both
    continue exactly."""
    ctx4 = [mx.cpu(i) for i in range(4)]
    ps = _make_params(ctx=ctx4)
    ts = Trainer(ps, "sgd", {"momentum": 0.9, "learning_rate": 0.1},
                 kvstore="device", spmd=True)
    for step in range(2):
        _set_grads(ps, step)
        ts.step(2)
    fname = str(tmp_path / "spmd.states")
    ts.save_states(fname)

    # resume on a 2-replica SPMD mesh
    ctx2 = [mx.cpu(0), mx.cpu(1)]
    p2 = _make_params(ctx=ctx2)
    for pa, pb in zip(p2, ps):
        pa.set_data(pb.list_data()[0])
    t2 = Trainer(p2, "sgd", {"momentum": 0.9, "learning_rate": 0.1},
                 kvstore="device", spmd=True)
    t2.load_states(fname)
    # resume on the per-replica fused path
    p3 = _make_params(ctx=ctx2)
    for pa, pb in zip(p3, ps):
        pa.set_data(pb.list_data()[0])
    t3 = Trainer(p3, "sgd", {"momentum": 0.9, "learning_rate": 0.1},
                 kvstore="device", fuse_step=True)
    t3.load_states(fname)

    for tr, pp in ((t2, p2), (t3, p3)):
        _set_grads(pp, 9)
        tr.step(2)
    for pa, pb in zip(p2, p3):
        np.testing.assert_allclose(pa.list_data()[0].asnumpy(),
                                   pb.list_data()[0].asnumpy(),
                                   rtol=2e-5, atol=1e-6)


def test_sparse_grad_disengages_and_hands_states_off():
    """A sparse gradient after the mesh engaged disengages the SPMD
    path permanently, handing the accumulated (sharded) momentum off
    to the per-replica updaters — the whole run matches a pure
    per-replica twin."""
    from mxnet_tpu.ndarray import sparse as sp

    results = {}
    for use_spmd in (True, False):
        params = _make_params(seed=3)
        emb = Parameter("emb", shape=(6, 3))
        emb.initialize(ctx=[mx.cpu()])
        emb.set_data(nd_array(
            np.random.RandomState(5).randn(6, 3).astype("f4")))
        trainer = Trainer(params + [emb], "sgd",
                          {"learning_rate": 0.1, "momentum": 0.9},
                          kvstore=None, spmd=use_spmd,
                          fuse_step=not use_spmd)
        for step in range(4):
            _set_grads(params, step)
            if step == 2:  # sparse grad after 2 SPMD steps
                emb.data()._ag_grad = sp.row_sparse_array(
                    (np.ones((2, 3), "f4"), [1, 4]), shape=(6, 3))
            else:
                emb.data()._ag_grad = nd_array(
                    np.zeros((6, 3), "f4"))
            trainer.step(2)
        if use_spmd:
            assert trainer._spmd_active is False  # disengaged
            assert trainer._spmd_updater is None
            assert trainer._updaters[0].states  # states handed off
        results[use_spmd] = [p.data().asnumpy()
                             for p in params + [emb]]
    for ws, we in zip(results[True], results[False]):
        np.testing.assert_allclose(ws, we, rtol=2e-5, atol=1e-6)


def test_manual_update_flow_hands_states_off():
    """The documented manual flow — allreduce_grads() + update() —
    after the mesh engaged must NOT run the per-replica updaters on
    fresh zero states: update() disengages first, handing the sharded
    momentum off, so the whole run matches a per-replica twin."""
    results = {}
    for use_spmd in (True, False):
        ps = _make_params(ctx=[mx.cpu(0), mx.cpu(1)], seed=4)
        t = Trainer(ps, "sgd", {"learning_rate": 0.1, "momentum": 0.9},
                    kvstore="device", spmd=use_spmd,
                    fuse_step=not use_spmd)
        for step in range(2):
            _set_grads(ps, step)
            t.step(2)
        if use_spmd:
            assert t._spmd_updater is not None  # engaged, states live
        _set_grads(ps, 2)
        t.allreduce_grads()
        t.update(2)
        if use_spmd:
            assert t._spmd_active is False
            assert t._spmd_updater is None
            assert t._updaters[0].states  # momentum handed off
        results[use_spmd] = [p.list_data()[0].asnumpy() for p in ps]
    for ws, we in zip(results[True], results[False]):
        np.testing.assert_allclose(ws, we, rtol=2e-5, atol=1e-6)


def test_kvstore_spmd_reduces_off_device_buffer(monkeypatch):
    """A gradient buffer that drifted off its ctx device reduces fine
    under MXNET_SPMD=1 (same device_put normalization as the classic
    bucket path) instead of crashing the mesh-array assembly."""
    import jax as _jax

    from mxnet_tpu import kvstore as kvs

    rng = np.random.RandomState(9)
    raw = [rng.randn(4, 3).astype("f4") for _ in range(2)]
    expected = raw[0] + raw[1]
    monkeypatch.setenv("MXNET_SPMD", "1")
    kv = kvs.create("device")
    reps = [nd_array(v, ctx=mx.cpu(r)) for r, v in enumerate(raw)]
    kv.init(0, reps[0])
    # simulate drift: replica 0's buffer lives on replica 1's device
    reps[0]._data = _jax.device_put(reps[0].data,
                                    mx.cpu(1).jax_device)
    kv.pushpull_fused([0], [reps], out=[reps])
    for r in reps:
        np.testing.assert_allclose(r.asnumpy(), expected, rtol=1e-6)


def test_spmd_false_env_off_keeps_per_replica_path(monkeypatch):
    monkeypatch.delenv("MXNET_SPMD", raising=False)
    ctx = [mx.cpu(0), mx.cpu(1)]
    ps = _make_params(ctx=ctx)
    t = Trainer(ps, "sgd", {}, kvstore="device")
    _set_grads(ps, 0)
    t.step(2)
    assert t._spmd_active is False
    assert t._spmd_updater is None


def test_spmd_env_engages(monkeypatch):
    monkeypatch.setenv("MXNET_SPMD", "1")
    ctx = [mx.cpu(0), mx.cpu(1)]
    ps = _make_params(ctx=ctx)
    t = Trainer(ps, "sgd", {}, kvstore="device")
    _set_grads(ps, 0)
    t.step(2)
    assert t._spmd_active is True
    assert t._spmd_updater is not None


def test_spmd_true_with_compression_warns_and_falls_back():
    ctx = [mx.cpu(0), mx.cpu(1)]
    ps = _make_params(ctx=ctx)
    with pytest.warns(UserWarning, match="spmd=True"):
        t = Trainer(ps, "sgd", {}, kvstore="device", spmd=True,
                    compression_params={"type": "2bit"})
        _set_grads(ps, 0)
        t.step(2)
    assert t._spmd_active is False


def test_zero_states_off_keeps_states_replicated(monkeypatch):
    monkeypatch.setenv("MXNET_ZERO_STATES", "0")
    ts, tf, ps, pf = _run_pair("sgd", {"momentum": 0.9})
    u = ts._spmd_updater
    assert u.shard_factor() == 1
    for p_s, p_f in zip(ps, pf):
        np.testing.assert_allclose(p_s.list_data()[0].asnumpy(),
                                   p_f.list_data()[0].asnumpy(),
                                   rtol=2e-5, atol=1e-6)


def test_zero_min_size_keeps_small_params_replicated(monkeypatch):
    """Params below MXNET_ZERO_MIN_SIZE skip the flat-shard layout
    (collective latency would beat the memory win) — the plan puts
    them in the small group."""
    monkeypatch.setenv("MXNET_ZERO_MIN_SIZE", "64")
    shapes = [(64, 8), (7,)]  # 512 sharded, 7 replicated
    ts, tf, ps, pf = _run_pair("sgd", {"momentum": 0.9}, shapes=shapes)
    plan = ts._spmd_updater._plan
    assert len(plan.buckets) == 1 and plan.buckets[0].pos == (0,)
    assert plan.smalls and plan.smalls[0].pos == (1,)
    for p_s, p_f in zip(ps, pf):
        np.testing.assert_allclose(p_s.list_data()[0].asnumpy(),
                                   p_f.list_data()[0].asnumpy(),
                                   rtol=2e-5, atol=1e-6)


def test_lamb_takes_per_param_singles():
    """Norm-based optimizers cannot concatenate (per-tensor trust
    ratio) — the plan routes them through singles, still sharded."""
    ts, tf, ps, pf = _run_pair("lamb", {})
    plan = ts._spmd_updater._plan
    assert not plan.buckets and len(plan.singles) == len(SHAPES)
    for p_s, p_f in zip(ps, pf):
        np.testing.assert_allclose(p_s.list_data()[0].asnumpy(),
                                   p_f.list_data()[0].asnumpy(),
                                   rtol=2e-5, atol=1e-6)


def test_half_precision_t_hyper_disengages_cleanly():
    """Adamax (t-hyper) on bf16 weights without multi_precision cannot
    take the mesh program — the trainer falls back without touching
    state."""
    ctx = [mx.cpu(0), mx.cpu(1)]
    ps = _make_params(ctx=ctx, dtype="bfloat16")
    pf = _make_params(ctx=ctx, dtype="bfloat16")
    ts = Trainer(ps, "adamax", {}, kvstore="device", spmd=True)
    tf = Trainer(pf, "adamax", {}, kvstore="device", fuse_step=False)
    for step in range(2):
        _set_grads(ps, step)
        _set_grads(pf, step)
        ts.step(2)
        tf.step(2)
    assert ts._spmd_active is False  # disengaged on first step
    for p_s, p_f in zip(ps, pf):
        np.testing.assert_allclose(
            p_s.list_data()[0].asnumpy().astype("f4"),
            p_f.list_data()[0].asnumpy().astype("f4"),
            rtol=2e-2, atol=1e-2)


def test_multi_precision_bf16_master_weights():
    ctx = [mx.cpu(0), mx.cpu(1)]
    ps = _make_params(ctx=ctx, dtype="bfloat16")
    pf = _make_params(ctx=ctx, dtype="bfloat16")
    ts = Trainer(ps, "sgd", {"momentum": 0.9, "multi_precision": True},
                 kvstore="device", spmd=True)
    tf = Trainer(pf, "sgd", {"momentum": 0.9, "multi_precision": True},
                 kvstore="device", fuse_step=True)
    for step in range(3):
        _set_grads(ps, step)
        _set_grads(pf, step)
        ts.step(2)
        tf.step(2)
    assert ts._spmd_active is True
    for p_s, p_f in zip(ps, pf):
        np.testing.assert_allclose(
            p_s.list_data()[0].asnumpy().astype("f4"),
            p_f.list_data()[0].asnumpy().astype("f4"),
            rtol=2e-2, atol=1e-2)


def test_kvstore_pushpull_fused_spmd_parity(monkeypatch):
    """MXNET_SPMD=1 routes pushpull_fused's buckets through one mesh
    program per bucket — same values, store still published."""
    from mxnet_tpu import kvstore as kvs

    rng = np.random.RandomState(3)
    keys = [0, 1, 2]
    shapes = [(4, 3), (16,), (2, 2)]

    def build():
        kv = kvs.create("device")
        vals = []
        for k, s in zip(keys, shapes):
            reps = [nd_array(rng.randn(*s).astype("f4"), ctx=mx.cpu(r))
                    for r in range(2)]
            kv.init(k, reps[0])
            vals.append(reps)
        return kv, vals

    rng = np.random.RandomState(3)
    monkeypatch.setenv("MXNET_SPMD", "0")
    kv_a, vals_a = build()
    rng = np.random.RandomState(3)
    monkeypatch.setenv("MXNET_SPMD", "1")
    kv_b, vals_b = build()
    kv_a.pushpull_fused(keys, vals_a, out=vals_a)
    kv_b.pushpull_fused(keys, vals_b, out=vals_b)
    for ra, rb in zip(vals_a, vals_b):
        for a, b in zip(ra, rb):
            np.testing.assert_allclose(a.asnumpy(), b.asnumpy(),
                                       rtol=1e-6)
    for k in keys:
        np.testing.assert_allclose(kv_a._store[k].asnumpy(),
                                   kv_b._store[k].asnumpy(), rtol=1e-6)


def test_phased_spans_and_collective_bytes():
    """Tracing on: the step runs the phased variant with
    reduce-scatter/shard-update/all-gather spans, layout gauges, and
    the collective-bytes counters move."""
    from mxnet_tpu.telemetry import tracing

    ctx = [mx.cpu(0), mx.cpu(1)]
    ps = _make_params(ctx=ctx)
    pf = _make_params(ctx=ctx)
    ts = Trainer(ps, "sgd", {"momentum": 0.9}, kvstore="device",
                 spmd=True)
    tf = Trainer(pf, "sgd", {"momentum": 0.9}, kvstore="device",
                 spmd=True)
    _set_grads(ps, 0)
    ts.step(2)  # untraced warmup engages the mesh
    tracing.enable()
    try:
        b0 = _ins.collective_bytes_total("reduce-scatter", "dp").value
        s0 = _ins.training_phase_seconds("shard-update").count
        for step in range(2):
            _set_grads(ps, step + 1)
            ts.step(2)
        assert _ins.collective_bytes_total(
            "reduce-scatter", "dp").value > b0
        assert _ins.training_phase_seconds("shard-update").count >= s0 + 2
        assert _ins.step_layout_axis_size("dp").value == 2
        assert _ins.step_state_shard_factor().value == 2
        # phased result == fused-program result (same stages, split)
        _set_grads(pf, 0)
        tf.step(2)
    finally:
        tracing.disable()
    for step in range(2):
        _set_grads(pf, step + 1)
        tf.step(2)
    for p_s, p_f in zip(ps, pf):
        np.testing.assert_allclose(p_s.list_data()[0].asnumpy(),
                                   p_f.list_data()[0].asnumpy(),
                                   rtol=2e-5, atol=1e-6)


@pytest.mark.slow
def test_cross_process_mesh_warm_starts_from_shared_cache(tmp_path):
    """ISSUE 9 acceptance, cross-process half: a 2-process job runs ONE
    mesh program spanning both workers' devices (states sharded 4-way,
    replicas bit-identical job-wide), and a SECOND job over the same
    shared compile-cache dir warm-starts the executable from disk —
    zero XLA builds (PR-7 store)."""
    import json as _json
    import os
    import socket
    import subprocess
    import sys

    worker = os.path.join(os.path.dirname(__file__), "dist_worker.py")

    def spawn(cache_dir):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = str(s.getsockname()[1])
        procs = []
        for i in range(2):
            env = dict(os.environ)
            env["PALLAS_AXON_POOL_IPS"] = ""
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("XLA_FLAGS", None)
            env["MXNET_COMPILE_CACHE_DIR"] = cache_dir
            env.update({"DMLC_ROLE": "worker",
                        "DMLC_PS_ROOT_URI": "127.0.0.1",
                        "DMLC_PS_ROOT_PORT": port,
                        "DMLC_NUM_WORKER": "2",
                        "DMLC_WORKER_ID": str(i)})
            procs.append(subprocess.Popen(
                [sys.executable, worker, "spmd"], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        stats = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=300)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            assert p.returncode == 0, out[-2000:]
            assert "DIST_OK" in out
            line = [ln for ln in out.splitlines()
                    if ln.startswith("SPMD_STATS ")][0]
            stats.append(_json.loads(line.split(" ", 1)[1]))
        return stats

    cache = str(tmp_path / "cc")
    cold = spawn(cache)
    assert {s["params_sha"] for s in cold} == {cold[0]["params_sha"]}
    for s in cold:  # exactly ONE executable built per (mesh, layout)
        assert s["compiles"] == 1, s
    warm = spawn(cache)
    for s in warm:  # fresh processes warm-start from the shared store
        assert s["compiles"] == 0, s
        assert s["cache_loads"] >= 1, s
    assert warm[0]["params_sha"] == cold[0]["params_sha"]


def test_single_replica_single_device_degenerate_case():
    """dp=1: same code path, no collectives, parity with fused."""
    ctx = [mx.cpu(0)]
    ts, tf, ps, pf = _run_pair("adam", {}, ctx=ctx)
    assert ts._spmd_active
    for p_s, p_f in zip(ps, pf):
        np.testing.assert_allclose(p_s.list_data()[0].asnumpy(),
                                   p_f.list_data()[0].asnumpy(),
                                   rtol=2e-5, atol=1e-6)
