"""Persistent compile cache: cross-process AOT executable store.

Every executable the framework builds — serving's per-bucket
executors, the fused optimizer step, the ops-registry jit/grad
programs (opt-in) — can be persisted to a content-addressed on-disk
store and reloaded by a *different process*, so a deploy, preemption
restart, or autoscale-up serves its first request and takes its first
training step without an XLA compile storm.

One verb::

    from mxnet_tpu import compile_cache as cc
    key = cc.cache_key("serving.bucket", parts=(...),
                       program_text=lowered.as_text())
    exe, origin = cc.get_or_compile("serving:mlp", key, lowered.compile)

Enable by setting ``MXNET_COMPILE_CACHE_DIR`` (optionally capped by
``MXNET_COMPILE_CACHE_BYTES``; ``MXNET_COMPILE_CACHE_DISABLE=1`` is
the kill switch).  Populate offline with ``tools/warm_cache.py``;
measure with ``tools/bench_compile_cache.py``.  See
docs/compile_cache.md for keying, tiers, invalidation, and the warmup
workflow.
"""
from __future__ import annotations

from .core import (CompileCache, enabled, get_cache, get_or_compile,
                   reset, stats)
from .key import CacheKey, cache_key, env_fingerprint, first_party
from .store import DiskStore, StoreError

__all__ = [
    "CompileCache", "CacheKey", "DiskStore", "StoreError",
    "cache_key", "env_fingerprint", "first_party",
    "get_or_compile", "get_cache", "stats", "reset", "enabled",
]
