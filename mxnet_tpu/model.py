"""Checkpoint helpers + legacy FeedForward model
(ref: python/mxnet/model.py).

`save_checkpoint`/`load_checkpoint` write the reference's two-file format:
``prefix-symbol.json`` (graph) + ``prefix-%04d.params`` (arrays tagged
``arg:``/``aux:``, via the .params-compatible serializer).
"""
from __future__ import annotations

import logging
from typing import Dict, Optional, Tuple

from . import serialization
from .base import MXNetError
from .ndarray import NDArray

__all__ = ["save_checkpoint", "load_checkpoint", "load_params",
           "BatchEndParam", "FeedForward"]

from .callback import BatchEndParam  # re-export (reference keeps it here)


def save_checkpoint(prefix: str, epoch: int, symbol, arg_params: Dict[str, NDArray],
                    aux_params: Dict[str, NDArray], remove_amp_cast=True):
    """ref: model.save_checkpoint."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in (arg_params or {}).items()}
    save_dict.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    param_name = f"{prefix}-{epoch:04d}.params"
    serialization.save_ndarrays(param_name, save_dict)
    logging.info('Saved checkpoint to "%s"', param_name)


def load_params(prefix: str, epoch: int):
    """ref: model.load_params — split arg:/aux: tagged dict."""
    loaded = serialization.load_ndarrays(f"{prefix}-{epoch:04d}.params")
    if not isinstance(loaded, dict):
        raise MXNetError("checkpoint params file must be a named dict")
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        tag, name = k.split(":", 1)
        if tag == "arg":
            arg_params[name] = v
        elif tag == "aux":
            aux_params[name] = v
    return arg_params, aux_params


def load_checkpoint(prefix: str, epoch: int):
    """ref: model.load_checkpoint → (symbol, arg_params, aux_params)."""
    from . import symbol as sym

    symbol = sym.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params


class FeedForward:
    """Deprecated legacy trainer (ref: model.FeedForward). Thin adapter
    over Module, kept for API parity; use Module or Gluon."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, begin_epoch=0, **kwargs):
        from .context import current_context
        from . import initializer as init_mod

        self.symbol = symbol
        self.ctx = ctx or current_context()
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer or init_mod.Uniform(0.01)
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.begin_epoch = begin_epoch
        self.numpy_batch_size = numpy_batch_size
        self._kwargs = kwargs
        self._module = None

    def _as_module(self, data_iter):
        from .module import Module

        label_names = [d.name for d in (data_iter.provide_label or [])] or None
        mod = Module(self.symbol, context=self.ctx,
                     label_names=label_names)
        return mod

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        data_iter = self._ensure_iter(X, y)
        mod = self._as_module(data_iter)
        mod.fit(data_iter, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer,
                optimizer_params=self._kwargs.get("optimizer_params",
                                                  {"learning_rate": 0.01}),
                initializer=self.initializer,
                arg_params=self.arg_params, aux_params=self.aux_params,
                begin_epoch=self.begin_epoch,
                num_epoch=self.num_epoch or 1)
        self._module = mod
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def predict(self, X, num_batch=None):
        data_iter = self._ensure_iter(X, None)
        if self._module is None:
            raise MXNetError("model has not been fit")
        out = self._module.predict(data_iter, num_batch=num_batch)
        return out.asnumpy() if isinstance(out, NDArray) else out

    def _ensure_iter(self, X, y):
        from .io import DataIter, NDArrayIter

        if isinstance(X, DataIter):
            return X
        return NDArrayIter(X, y, batch_size=self.numpy_batch_size)

    def save(self, prefix: str, epoch: Optional[int] = None):
        save_checkpoint(prefix, epoch if epoch is not None else
                        (self.num_epoch or 0), self.symbol,
                        self.arg_params or {}, self.aux_params or {})

    @staticmethod
    def load(prefix: str, epoch: int, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)
