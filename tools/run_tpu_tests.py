#!/usr/bin/env python
"""Run the opt-in REAL-DEVICE suite (tests/test_tpu_device.py) on the
TPU and write a committed artifact with the results.

Counterpart of the reference's GPU test lane
(tests/python/gpu/test_operator_gpu.py in CI).  Usage:

    python tools/run_tpu_tests.py [--out TPU_TESTS.json]

Sets MXNET_TEST_PLATFORM=tpu so tests/conftest.py keeps the accelerator
visible, runs pytest on the on-device module, and writes
{passed, failed, skipped, duration_s, device, cases} as JSON.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(_REPO, "TPU_TESTS.json"))
    ap.add_argument("--timeout", type=float, default=1500.0)
    args = ap.parse_args()

    env = dict(os.environ, MXNET_TEST_PLATFORM="tpu")
    t0 = time.time()
    try:
        p = subprocess.run(
            [sys.executable, "-m", "pytest",
             os.path.join(_REPO, "tests", "test_tpu_device.py"),
             "-v", "--tb=line", "-rN"],
            capture_output=True, text=True, timeout=args.timeout, env=env,
            cwd=_REPO)
        out = p.stdout
        rc = p.returncode
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) \
            else (e.stdout or "")
        rc = -1
    dur = time.time() - t0

    cases = {}
    for ln in out.splitlines():
        m = re.match(r"tests/test_tpu_device\.py::(\S+)\s+(PASSED|FAILED|"
                     r"SKIPPED|ERROR)", ln)
        if m:
            cases[m.group(1)] = m.group(2)
    tally = re.search(r"(\d+) passed", out)
    failed = re.search(r"(\d+) failed", out)
    skipped = re.search(r"(\d+) skipped", out)
    errors = re.search(r"(\d+) errors?", out)

    device = "unknown"
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].device_kind)"],
            capture_output=True, text=True, timeout=120)
        if probe.returncode == 0:
            device = probe.stdout.strip().splitlines()[-1]
    except Exception:
        pass

    artifact = {
        "suite": "tests/test_tpu_device.py",
        "device": device,
        "passed": int(tally.group(1)) if tally else 0,
        "failed": int(failed.group(1)) if failed else 0,
        "skipped": int(skipped.group(1)) if skipped else 0,
        "errors": int(errors.group(1)) if errors else 0,
        "duration_s": round(dur, 1),
        "returncode": rc,
        "cases": cases,
    }
    # keep the one-line tracebacks of failed cases in the artifact —
    # the tunnel may be gone by the time anyone wants to debug them
    fail_lines = [ln for ln in out.splitlines()
                  if ln.startswith(("E ", "FAILED", "/root/repo", "/usr/"))
                  and ("Error" in ln or "assert" in ln or "FAILED" in ln)]
    if fail_lines:
        artifact["failure_lines"] = fail_lines[:60]
    if not cases and rc != 0:
        # a broken run (collection/import error) must never read green
        artifact["status"] = "BROKEN_RUN"
        artifact["output_tail"] = out[-1500:]
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps({k: v for k, v in artifact.items() if k != "cases"}))
    return 0 if rc == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
