"""mx.contrib.ndarray — imperative contrib op wrappers
(ref: python/mxnet/ndarray/contrib.py generated namespace)."""
from __future__ import annotations

from ..ndarray import register as _register
from .control_flow import cond, foreach, while_loop  # noqa: F401


def __getattr__(name):
    # bare name first, then the '_contrib_' registry alias — the ONE
    # lookup rule for every contrib namespace spelling (nd.contrib.X,
    # mx.contrib.ndarray.X)
    for cand in (name, f"_contrib_{name}"):
        try:
            return _register.lookup(cand)
        except AttributeError:
            continue
    raise AttributeError(
        f"no contrib op {name!r} (tried '_contrib_{name}' too)")
