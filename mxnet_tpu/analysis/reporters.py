"""mxlint output: human text, machine JSON (the MXLINT.json artifact),
and SARIF 2.1.0 for diff-annotation in code review UIs."""
from __future__ import annotations

from typing import Dict, List, Sequence

from .engine import RULE_REGISTRY, Violation

__all__ = ["render_text", "render_json", "render_sarif"]


def _per_rule_counts(violations: Sequence[Violation]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for v in violations:
        counts[v.rule] = counts.get(v.rule, 0) + 1
    return counts


def render_text(new: Sequence[Violation],
                suppressed: Sequence[Violation] = (),
                stale: Sequence[dict] = (),
                errors: Sequence[str] = ()) -> str:
    lines: List[str] = []
    for v in new:
        lines.append(v.format())
    for e in errors:
        lines.append(f"{e} (file skipped)")
    if stale:
        lines.append("")
        lines.append(f"{len(stale)} stale baseline entr"
                     f"{'y' if len(stale) == 1 else 'ies'} (violation "
                     "fixed — delete from MXLINT_BASELINE.json):")
        for e in stale:
            lines.append(f"  {e['path']} {e['rule']} [{e['symbol']}] "
                         f"{e['src'][:60]}")
    lines.append("")
    verdict = "FAIL" if new else "OK"
    lines.append(f"mxlint: {verdict} — {len(new)} new violation(s), "
                 f"{len(suppressed)} baselined, {len(stale)} stale "
                 f"baseline entr{'y' if len(stale) == 1 else 'ies'}, "
                 f"{len(errors)} unparsable file(s)")
    return "\n".join(lines)


def render_json(new: Sequence[Violation],
                suppressed: Sequence[Violation] = (),
                stale: Sequence[dict] = (),
                errors: Sequence[str] = ()) -> dict:
    """The MXLINT.json shape: per-rule counts first (the trajectory the
    nightly tracks across PRs), then the full finding list."""
    return {
        "ok": not new,
        "counts": {
            "new": len(new),
            "baselined": len(suppressed),
            "stale_baseline": len(stale),
            "errors": len(errors),
        },
        "new_per_rule": _per_rule_counts(new),
        "baselined_per_rule": _per_rule_counts(suppressed),
        "rules": {rid: {"name": cls.name, "description": cls.description}
                  for rid, cls in sorted(RULE_REGISTRY.items())},
        "new": [{
            "rule": v.rule, "path": v.path, "line": v.line, "col": v.col,
            "symbol": v.symbol, "message": v.message,
            "fingerprint": v.fingerprint,
        } for v in new],
        "stale_baseline": list(stale),
        "errors": list(errors),
    }


def render_sarif(new: Sequence[Violation],
                 tool_version: str = "1.0") -> dict:
    """SARIF 2.1.0 document over the NEW violations (baselined ones
    are suppressed by definition — a diff annotator must only mark
    what fails the gate).  ``partialFingerprints`` carries the same
    line-drift-stable fingerprint the baseline uses, so review tools
    dedupe across pushes exactly like the ratchet does."""
    by_rule: Dict[str, dict] = {}
    for rid, cls in sorted(RULE_REGISTRY.items()):
        by_rule[rid] = {
            "id": rid,
            "name": cls.name,
            "shortDescription": {"text": cls.description},
            "helpUri": "docs/static_analysis.md",
        }
    results = []
    for v in new:
        results.append({
            "ruleId": v.rule,
            "level": "error",
            "message": {"text": v.message},
            "partialFingerprints": {"mxlint/v1": v.fingerprint},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": v.path},
                    "region": {"startLine": v.line,
                               "startColumn": max(v.col + 1, 1)},
                },
                "logicalLocations": [{"fullyQualifiedName": v.symbol}],
            }],
        })
    return {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "mxlint",
                "version": tool_version,
                "informationUri": "docs/static_analysis.md",
                "rules": list(by_rule.values()),
            }},
            "results": results,
        }],
    }
