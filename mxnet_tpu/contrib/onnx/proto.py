"""Minimal ONNX protobuf reader/writer (pure Python, no onnx package).

Counterpart of the reference's onnx interop layer
(ref: python/mxnet/contrib/onnx/), which depends on the `onnx` pip
package; this container cannot install it, so the small stable subset of
onnx.proto3 used by model files is implemented directly over the
protobuf wire format (varint/length-delimited encoding).  Field numbers
follow onnx.proto3 (IR version 3+ layout, stable since 2017); the reader
is validated in tests against files produced by torch.onnx.export.

Only the messages needed for model interchange exist: ModelProto,
GraphProto, NodeProto, AttributeProto, TensorProto, ValueInfoProto,
TypeProto/TensorShapeProto, OperatorSetIdProto.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# ---- ONNX TensorProto.DataType enum ---------------------------------------
DT_FLOAT = 1
DT_UINT8 = 2
DT_INT8 = 3
DT_INT32 = 6
DT_INT64 = 7
DT_BOOL = 9
DT_FLOAT16 = 10
DT_DOUBLE = 11
DT_BFLOAT16 = 16

NP_TO_DT = {
    np.dtype(np.float32): DT_FLOAT, np.dtype(np.uint8): DT_UINT8,
    np.dtype(np.int8): DT_INT8, np.dtype(np.int32): DT_INT32,
    np.dtype(np.int64): DT_INT64, np.dtype(np.bool_): DT_BOOL,
    np.dtype(np.float16): DT_FLOAT16, np.dtype(np.float64): DT_DOUBLE,
}
DT_TO_NP = {v: k for k, v in NP_TO_DT.items()}

# AttributeProto.AttributeType
AT_FLOAT, AT_INT, AT_STRING, AT_TENSOR = 1, 2, 3, 4
AT_FLOATS, AT_INTS, AT_STRINGS = 6, 7, 8


# ---- wire-format primitives -----------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _svarint(n: int) -> bytes:  # plain (non-zigzag) signed int64 field
    return _varint(n if n >= 0 else n + (1 << 64))


def _tag(fieldno: int, wire: int) -> bytes:
    return _varint((fieldno << 3) | wire)


def _ld(fieldno: int, payload: bytes) -> bytes:
    return _tag(fieldno, 2) + _varint(len(payload)) + payload


def _int_field(fieldno: int, v: int) -> bytes:
    return _tag(fieldno, 0) + _svarint(int(v))


def _str_field(fieldno: int, s) -> bytes:
    if isinstance(s, str):
        s = s.encode()
    return _ld(fieldno, s)


def _float_field(fieldno: int, v: float) -> bytes:
    return _tag(fieldno, 5) + struct.pack("<f", v)


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def eof(self) -> bool:
        return self.pos >= len(self.buf)

    def varint(self) -> int:
        shift = n = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                return n
            shift += 7

    def signed(self) -> int:
        n = self.varint()
        return n - (1 << 64) if n >= (1 << 63) else n

    def tag(self) -> Tuple[int, int]:
        t = self.varint()
        return t >> 3, t & 7

    def bytes_(self) -> bytes:
        ln = self.varint()
        out = self.buf[self.pos:self.pos + ln]
        self.pos += ln
        return out

    def skip(self, wire: int):
        if wire == 0:
            self.varint()
        elif wire == 1:
            self.pos += 8
        elif wire == 2:
            self.bytes_()
        elif wire == 5:
            self.pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")

    def f32(self) -> float:
        v = struct.unpack_from("<f", self.buf, self.pos)[0]
        self.pos += 4
        return v


def _packed_or_repeated_ints(r: _Reader, wire: int) -> List[int]:
    if wire == 2:  # packed
        sub = _Reader(r.bytes_())
        out = []
        while not sub.eof():
            out.append(sub.signed())
        return out
    return [r.signed()]


def _packed_or_repeated_floats(r: _Reader, wire: int) -> List[float]:
    if wire == 2:
        raw = r.bytes_()
        return list(struct.unpack(f"<{len(raw) // 4}f", raw))
    return [r.f32()]


# ---- message dataclasses ---------------------------------------------------

@dataclass
class Tensor:
    name: str = ""
    dims: List[int] = field(default_factory=list)
    data_type: int = DT_FLOAT
    raw: bytes = b""

    @classmethod
    def from_numpy(cls, name: str, arr: np.ndarray) -> "Tensor":
        arr = np.asarray(arr)
        if arr.dtype not in NP_TO_DT:
            arr = arr.astype(np.float32)
        return cls(name=name, dims=list(arr.shape),
                   data_type=NP_TO_DT[arr.dtype],
                   raw=np.ascontiguousarray(arr).tobytes())

    def to_numpy(self) -> np.ndarray:
        dt = DT_TO_NP.get(self.data_type)
        if dt is None:
            raise ValueError(f"unsupported tensor data_type "
                             f"{self.data_type}")
        return np.frombuffer(self.raw, dt).reshape(self.dims).copy()

    def encode(self) -> bytes:
        out = bytearray()
        for d in self.dims:
            out += _int_field(1, d)
        out += _int_field(2, self.data_type)
        if self.name:
            out += _str_field(8, self.name)
        out += _ld(9, self.raw)
        return bytes(out)

    @classmethod
    def decode(cls, buf: bytes) -> "Tensor":
        t = cls()
        r = _Reader(buf)
        floats: List[float] = []
        ints: List[int] = []
        while not r.eof():
            f, w = r.tag()
            if f == 1:
                t.dims += _packed_or_repeated_ints(r, w)
            elif f == 2:
                t.data_type = r.varint()
            elif f == 8:
                t.name = r.bytes_().decode()
            elif f == 9:
                t.raw = r.bytes_()
            elif f == 4:  # float_data fallback encoding
                floats += _packed_or_repeated_floats(r, w)
            elif f == 7:  # int64_data fallback encoding
                ints += _packed_or_repeated_ints(r, w)
            else:
                r.skip(w)
        if not t.raw and floats:
            t.raw = np.asarray(floats, np.float32).tobytes()
        if not t.raw and ints:
            t.raw = np.asarray(
                ints, DT_TO_NP.get(t.data_type, np.int64)).tobytes()
        return t


@dataclass
class Attribute:
    name: str = ""
    type: int = 0
    f: float = 0.0
    i: int = 0
    s: bytes = b""
    t: Optional[Tensor] = None
    floats: List[float] = field(default_factory=list)
    ints: List[int] = field(default_factory=list)
    strings: List[bytes] = field(default_factory=list)

    @classmethod
    def make(cls, name: str, value) -> "Attribute":
        a = cls(name=name)
        if isinstance(value, bool):
            a.type, a.i = AT_INT, int(value)
        elif isinstance(value, int):
            a.type, a.i = AT_INT, value
        elif isinstance(value, float):
            a.type, a.f = AT_FLOAT, value
        elif isinstance(value, str):
            a.type, a.s = AT_STRING, value.encode()
        elif isinstance(value, np.ndarray):
            a.type, a.t = AT_TENSOR, Tensor.from_numpy(name, value)
        elif isinstance(value, (list, tuple)):
            if all(isinstance(v, (int, np.integer)) for v in value):
                a.type, a.ints = AT_INTS, [int(v) for v in value]
            elif all(isinstance(v, str) for v in value):
                a.type, a.strings = AT_STRINGS, [v.encode() for v in value]
            else:
                a.type = AT_FLOATS
                a.floats = [float(v) for v in value]
        else:
            raise ValueError(f"cannot onnx-encode attribute {name}={value!r}")
        return a

    def value(self):
        if self.type == AT_FLOAT:
            return self.f
        if self.type == AT_INT:
            return self.i
        if self.type == AT_STRING:
            return self.s.decode()
        if self.type == AT_TENSOR:
            return self.t.to_numpy()
        if self.type == AT_FLOATS:
            return list(self.floats)
        if self.type == AT_INTS:
            return list(self.ints)
        if self.type == AT_STRINGS:
            return [s.decode() for s in self.strings]
        raise ValueError(f"unsupported attribute type {self.type}")

    def encode(self) -> bytes:
        out = bytearray(_str_field(1, self.name))
        if self.type == AT_FLOAT:
            out += _float_field(2, self.f)
        elif self.type == AT_INT:
            out += _int_field(3, self.i)
        elif self.type == AT_STRING:
            out += _ld(4, self.s)
        elif self.type == AT_TENSOR:
            out += _ld(5, self.t.encode())
        elif self.type == AT_FLOATS:
            for v in self.floats:
                out += _float_field(7, v)
        elif self.type == AT_INTS:
            for v in self.ints:
                out += _int_field(8, v)
        elif self.type == AT_STRINGS:
            for v in self.strings:
                out += _ld(9, v)
        out += _int_field(20, self.type)
        return bytes(out)

    @classmethod
    def decode(cls, buf: bytes) -> "Attribute":
        a = cls()
        r = _Reader(buf)
        while not r.eof():
            f, w = r.tag()
            if f == 1:
                a.name = r.bytes_().decode()
            elif f == 2:
                a.f = r.f32()
                a.type = a.type or AT_FLOAT
            elif f == 3:
                a.i = r.signed()
                a.type = a.type or AT_INT
            elif f == 4:
                a.s = r.bytes_()
                a.type = a.type or AT_STRING
            elif f == 5:
                a.t = Tensor.decode(r.bytes_())
                a.type = a.type or AT_TENSOR
            elif f == 7:
                a.floats += _packed_or_repeated_floats(r, w)
                a.type = AT_FLOATS
            elif f == 8:
                a.ints += _packed_or_repeated_ints(r, w)
                a.type = AT_INTS
            elif f == 9:
                a.strings.append(r.bytes_())
                a.type = AT_STRINGS
            elif f == 20:
                a.type = r.varint()
            else:
                r.skip(w)
        return a


@dataclass
class Node:
    op_type: str = ""
    inputs: List[str] = field(default_factory=list)
    outputs: List[str] = field(default_factory=list)
    name: str = ""
    attrs: Dict[str, Any] = field(default_factory=dict)
    domain: str = ""

    def encode(self) -> bytes:
        out = bytearray()
        for s in self.inputs:
            out += _str_field(1, s)
        for s in self.outputs:
            out += _str_field(2, s)
        if self.name:
            out += _str_field(3, self.name)
        out += _str_field(4, self.op_type)
        for k in sorted(self.attrs):
            out += _ld(5, Attribute.make(k, self.attrs[k]).encode())
        if self.domain:
            out += _str_field(7, self.domain)
        return bytes(out)

    @classmethod
    def decode(cls, buf: bytes) -> "Node":
        n = cls()
        r = _Reader(buf)
        while not r.eof():
            f, w = r.tag()
            if f == 1:
                n.inputs.append(r.bytes_().decode())
            elif f == 2:
                n.outputs.append(r.bytes_().decode())
            elif f == 3:
                n.name = r.bytes_().decode()
            elif f == 4:
                n.op_type = r.bytes_().decode()
            elif f == 5:
                a = Attribute.decode(r.bytes_())
                n.attrs[a.name] = a.value()
            elif f == 7:
                n.domain = r.bytes_().decode()
            else:
                r.skip(w)
        return n


@dataclass
class ValueInfo:
    name: str = ""
    elem_type: int = DT_FLOAT
    shape: List[Optional[int]] = field(default_factory=list)

    def encode(self) -> bytes:
        dims = bytearray()
        for d in self.shape:
            if d is None or (isinstance(d, int) and d < 0):
                dims += _ld(1, _str_field(2, "N"))
            else:
                dims += _ld(1, _int_field(1, d))
        tensor_type = (_int_field(1, self.elem_type) +
                       _ld(2, bytes(dims)))
        return _str_field(1, self.name) + _ld(2, _ld(1, tensor_type))

    @classmethod
    def decode(cls, buf: bytes) -> "ValueInfo":
        vi = cls()
        r = _Reader(buf)
        while not r.eof():
            f, w = r.tag()
            if f == 1:
                vi.name = r.bytes_().decode()
            elif f == 2:  # TypeProto
                tr = _Reader(r.bytes_())
                while not tr.eof():
                    tf, tw = tr.tag()
                    if tf == 1:  # tensor_type
                        ttr = _Reader(tr.bytes_())
                        while not ttr.eof():
                            ttf, ttw = ttr.tag()
                            if ttf == 1:
                                vi.elem_type = ttr.varint()
                            elif ttf == 2:  # shape
                                sr = _Reader(ttr.bytes_())
                                while not sr.eof():
                                    sf, sw = sr.tag()
                                    if sf == 1:  # dim
                                        dr = _Reader(sr.bytes_())
                                        dim: Optional[int] = None
                                        while not dr.eof():
                                            df, dw = dr.tag()
                                            if df == 1:
                                                dim = dr.signed()
                                            else:
                                                dr.skip(dw)
                                        vi.shape.append(dim)
                                    else:
                                        sr.skip(sw)
                            else:
                                ttr.skip(ttw)
                    else:
                        tr.skip(tw)
            else:
                r.skip(w)
        return vi


@dataclass
class Graph:
    name: str = "mxnet_tpu"
    nodes: List[Node] = field(default_factory=list)
    initializers: List[Tensor] = field(default_factory=list)
    inputs: List[ValueInfo] = field(default_factory=list)
    outputs: List[ValueInfo] = field(default_factory=list)

    def encode(self) -> bytes:
        out = bytearray()
        for n in self.nodes:
            out += _ld(1, n.encode())
        out += _str_field(2, self.name)
        for t in self.initializers:
            out += _ld(5, t.encode())
        for vi in self.inputs:
            out += _ld(11, vi.encode())
        for vi in self.outputs:
            out += _ld(12, vi.encode())
        return bytes(out)

    @classmethod
    def decode(cls, buf: bytes) -> "Graph":
        g = cls()
        r = _Reader(buf)
        while not r.eof():
            f, w = r.tag()
            if f == 1:
                g.nodes.append(Node.decode(r.bytes_()))
            elif f == 2:
                g.name = r.bytes_().decode()
            elif f == 5:
                g.initializers.append(Tensor.decode(r.bytes_()))
            elif f == 11:
                g.inputs.append(ValueInfo.decode(r.bytes_()))
            elif f == 12:
                g.outputs.append(ValueInfo.decode(r.bytes_()))
            else:
                r.skip(w)
        return g


@dataclass
class Model:
    graph: Graph = field(default_factory=Graph)
    ir_version: int = 8
    opset: int = 13
    producer_name: str = "mxnet_tpu"

    def encode(self) -> bytes:
        out = bytearray(_int_field(1, self.ir_version))
        out += _str_field(2, self.producer_name)
        out += _ld(7, self.graph.encode())
        opset = _str_field(1, "") + _int_field(2, self.opset)
        out += _ld(8, opset)
        return bytes(out)

    @classmethod
    def decode(cls, buf: bytes) -> "Model":
        m = cls()
        r = _Reader(buf)
        while not r.eof():
            f, w = r.tag()
            if f == 1:
                m.ir_version = r.varint()
            elif f == 2:
                m.producer_name = r.bytes_().decode()
            elif f == 7:
                m.graph = Graph.decode(r.bytes_())
            elif f == 8:
                sr = _Reader(r.bytes_())
                while not sr.eof():
                    sf, sw = sr.tag()
                    if sf == 2:
                        m.opset = sr.signed()
                    else:
                        sr.skip(sw)
            else:
                r.skip(w)
        return m


def save(model: Model, path: str) -> None:
    with open(path, "wb") as f:
        f.write(model.encode())


def load(path: str) -> Model:
    with open(path, "rb") as f:
        return Model.decode(f.read())
