"""Tape-based autograd over the op registry.

TPU-native counterpart of the reference's imperative autograd
(ref: src/imperative/imperative.cc Imperative::RecordOp / Imperative::Backward;
python/mxnet/autograd.py record()/pause()/backward()/grad()).

Design: under ``record()``, every differentiable op appends a tape node
holding (op, attrs, input jax values, parent links).  ``backward`` walks the
tape in reverse topological order and computes input cotangents through a
jit-cached ``jax.vjp`` of the op's pure function (see ops/registry.grad_fn)
— the XLA analogue of the reference's nnvm Gradient pass + RunGraph, except
each node's backward is a cached compiled executable and XLA DCE removes
unused forward recomputation inside the vjp.
"""
from __future__ import annotations

import threading
import time as _time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError

__all__ = [
    "record", "pause", "train_mode", "predict_mode",
    "is_recording", "is_training", "backward", "grad", "get_symbol",
    "mark_variables", "Function",
]


class _State(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False


_STATE = _State()


def is_recording() -> bool:
    return _STATE.recording


def is_training() -> bool:
    return _STATE.training


class _RecordingScope:
    def __init__(self, recording: Optional[bool], training: Optional[bool]):
        self._rec = recording
        self._train = training
        self._prev: Tuple[bool, bool] = (False, False)
        self._span = None
        self._t0 = None

    def __enter__(self):
        self._prev = (_STATE.recording, _STATE.training)
        if self._rec is not None:
            _STATE.recording = self._rec
        if self._train is not None:
            _STATE.training = self._train
        # the record() scope IS the forward pass of a training step:
        # span it so a step trace reads data-wait/forward/backward/...
        if self._rec and not self._prev[0]:
            from .telemetry import instruments as _ins
            from .telemetry import tracing as _tracing

            if _tracing.capture_active():
                self._span = _tracing.Span(
                    "forward", cat="training",
                    metric=_ins.training_phase_seconds("forward")
                    if _tracing._ENABLED else None).attach()
            elif _tracing._SINK is not None:
                # mxprof sink only: measure on the minimal path (two
                # clock reads, no ids/contextvars) so the always-on
                # flight recorder stays within its overhead budget
                self._t0 = _time.perf_counter()
        return self

    def __exit__(self, *exc):
        _STATE.recording, _STATE.training = self._prev
        if self._span is not None:
            self._span.finish()
            self._span = None
        elif self._t0 is not None:
            from .telemetry import tracing as _tracing

            snk = _tracing._SINK
            if snk is not None:
                snk.on_event("forward", "training",
                             _time.perf_counter() - self._t0, None)
            self._t0 = None
        return False


def record(train_mode: bool = True):
    """Scope in which ops are recorded on the tape (and train-mode is on)."""
    return _RecordingScope(True, train_mode)


def pause(train_mode: bool = False):
    return _RecordingScope(False, train_mode)


def train_mode():
    return _RecordingScope(None, True)


def predict_mode():
    return _RecordingScope(None, False)


# --------------------------------------------------------------------------
# Tape
# --------------------------------------------------------------------------

class TapeNode:
    """One recorded op application."""

    __slots__ = ("op", "attrs_key", "in_values", "parents", "n_out",
                 "out_index_of", "custom_backward")

    def __init__(self, op, attrs_key, in_values, parents, n_out,
                 custom_backward=None):
        self.op = op                      # Operator (or None for Function)
        self.attrs_key = attrs_key
        self.in_values = in_values        # list of jax arrays (primals)
        # parents[i] = (TapeNode|None leaf_marker, NDArray) for input i
        self.parents = parents
        self.n_out = n_out
        self.custom_backward = custom_backward


def _node_of(x) -> Optional[Tuple[TapeNode, int]]:
    return getattr(x, "_ag_node", None)


def _requires_grad(x) -> bool:
    return getattr(x, "_ag_grad_req", "null") != "null" or _node_of(x) is not None


def record_op(op, attrs_key, nd_inputs, in_values, results):
    """Called by ops.registry.invoke when recording. Links outputs to a node."""
    if not any(_requires_grad(x) for x in nd_inputs if hasattr(x, "_ag_grad_req")):
        # No tracked input anywhere upstream: nothing to record.
        if not any(_node_of(x) for x in nd_inputs if hasattr(x, "shape")):
            return
    outs = results if isinstance(results, (list, tuple)) else (results,)
    parents = []
    for x in nd_inputs:
        if hasattr(x, "_ag_grad_req"):
            parents.append((_node_of(x), x))
        else:
            parents.append((None, None))
    node = TapeNode(op, attrs_key, list(in_values), parents, len(outs))
    for i, o in enumerate(outs):
        o._ag_node = (node, i)


def mark_variables(variables, gradients, grad_reqs="write"):
    """ref: autograd.mark_variables — attach externally-allocated grads."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._ag_grad_req = req
        v._ag_grad = g


# --------------------------------------------------------------------------
# Backward pass
# --------------------------------------------------------------------------

def _toposort(roots: List[TapeNode]) -> List[TapeNode]:
    """Iterative post-order (graphs can be deeper than the recursion limit,
    e.g. long unrolled RNNs)."""
    order: List[TapeNode] = []
    seen = set()
    stack: List[Tuple[TapeNode, bool]] = [(r, False) for r in roots]
    while stack:
        node, expanded = stack.pop()
        if expanded:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for pn, _ in node.parents:
            if pn is not None and id(pn[0]) not in seen:
                stack.append((pn[0], False))
    return order


def backward(outputs, out_grads=None, retain_graph: bool = False,
             train_mode: bool = True):
    """Compute gradients of `outputs` wrt all tracked leaves.

    ref: python/mxnet/autograd.py::backward → MXAutogradBackwardEx →
    Imperative::Backward.  Grad accumulation respects each leaf's grad_req
    ('write' | 'add' | 'null').
    """
    from .telemetry import tracing as _tracing

    if not _tracing.active():
        return _backward_impl(outputs, out_grads, retain_graph, train_mode)
    from .telemetry import instruments as _ins

    with _tracing.span("backward", cat="training",
                       metric=_ins.training_phase_seconds("backward")
                       if _tracing._ENABLED else None):
        return _backward_impl(outputs, out_grads, retain_graph, train_mode)


def _backward_impl(outputs, out_grads=None, retain_graph: bool = False,
                   train_mode: bool = True):
    from .ndarray.ndarray import NDArray

    if isinstance(outputs, NDArray):
        outputs = [outputs]
    if out_grads is None:
        out_grads = [None] * len(outputs)
    elif isinstance(out_grads, NDArray):
        out_grads = [out_grads]

    # cotangents keyed by (id(node), out_index); `written` tracks leaves
    # already written THIS pass so grad_req='write' overwrites across
    # passes but accumulates across paths within one pass.
    cts: Dict[Tuple[int, int], Any] = {}
    written: set = set()
    roots = []
    for o, og in zip(outputs, out_grads):
        ni = _node_of(o)
        if ni is None:
            if getattr(o, "_ag_grad_req", "null") != "null":
                # output IS a leaf: d out/d out = head grad
                head = og.data if og is not None else jnp.ones(o.shape, o.data.dtype)
                _accumulate_leaf(o, head, written)
            continue
        node, idx = ni
        head = og.data if og is not None else jnp.ones(o.shape, o.data.dtype)
        key = (id(node), idx)
        cts[key] = cts[key] + head if key in cts else head
        roots.append(node)

    if not roots:
        return

    order = _toposort(roots)
    from .ops import registry as _reg

    for node in reversed(order):
        node_cts = [cts.pop((id(node), i), None) for i in range(node.n_out)]
        if all(c is None for c in node_cts):
            continue
        # fill missing output cotangents with zeros (vjp needs full pytree)
        if node.custom_backward is not None:
            in_grads = node.custom_backward(node_cts)
            argpos = list(range(len(node.parents)))
        else:
            argpos = [i for i, (pn, leaf) in enumerate(node.parents)
                      if pn is not None or (leaf is not None and
                                            getattr(leaf, "_ag_grad_req", "null") != "null")]
            argpos = [i for i in argpos
                      if jnp.issubdtype(jnp.asarray(node.in_values[i]).dtype, jnp.inexact)]
            if not argpos:
                continue
            if any(c is None for c in node_cts):
                # fill missing output cotangents with zeros (vjp needs the
                # full output pytree); eval_shape only on this rare path
                out_shapes = jax.eval_shape(
                    lambda *xs: node.op.fn(*xs, **_reg.thaw_attrs(node.attrs_key)),
                    *node.in_values)
                flat_shapes = (out_shapes if isinstance(out_shapes, (list, tuple))
                               else (out_shapes,))
                node_cts = [c if c is not None else jnp.zeros(s.shape, s.dtype)
                            for c, s in zip(node_cts, flat_shapes)]
            ct_arg = tuple(node_cts) if node.n_out > 1 else node_cts[0]
            gfn = _reg.grad_fn(node.op, node.attrs_key, tuple(argpos))
            in_grads_sel = gfn(node.in_values, ct_arg)
            in_grads = [None] * len(node.parents)
            for i, g in zip(argpos, in_grads_sel):
                in_grads[i] = g

        for (pn, leaf), g in zip(node.parents, in_grads):
            if g is None:
                continue
            if hasattr(g, "dtype") and g.dtype == jax.dtypes.float0:
                continue
            if pn is not None:
                pnode, pidx = pn
                key = (id(pnode), pidx)
                cts[key] = cts[key] + g if key in cts else g
            elif leaf is not None and getattr(leaf, "_ag_grad_req", "null") != "null":
                _accumulate_leaf(leaf, g, written)

    if not retain_graph:
        for o in outputs:
            if _node_of(o) is not None:
                o._ag_node = None


def _accumulate_leaf(leaf, g, written: set):
    from .ndarray.ndarray import NDArray

    req = getattr(leaf, "_ag_grad_req", "null")
    if req == "null":
        return
    gnd = getattr(leaf, "_ag_grad", None)
    g = jnp.asarray(g, leaf.data.dtype)
    if gnd is None:
        leaf._ag_grad = NDArray(g, ctx=leaf.ctx)
    elif req == "add" or id(leaf) in written:
        gnd._data = gnd.data + g
    else:  # 'write': first touch this pass overwrites, later touches add
        gnd._data = g
    written.add(id(leaf))


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """ref: autograd.grad — returns grads instead of accumulating into .grad."""
    from .ndarray.ndarray import NDArray

    if isinstance(variables, NDArray):
        variables = [variables]
    saved = [(getattr(v, "_ag_grad_req", "null"), getattr(v, "_ag_grad", None))
             for v in variables]
    for v in variables:
        v._ag_grad_req = "write"
        v._ag_grad = None
    try:
        backward(heads, head_grads, retain_graph=bool(retain_graph or create_graph),
                 train_mode=train_mode)
        out = []
        for v in variables:
            if v._ag_grad is None:
                raise MXNetError("one of the variables does not participate "
                                 "in the graph of heads")
            out.append(v._ag_grad)
    finally:
        for v, (req, g) in zip(variables, saved):
            v._ag_grad_req = req
            v._ag_grad = g
    return out


def get_symbol(x):
    raise MXNetError("autograd.get_symbol: use HybridBlock tracing instead "
                     "(symbolic extraction of an imperative tape is not "
                     "supported in the TPU build)")


class Function:
    """Custom differentiable function (ref: autograd.Function).

    Subclass and implement forward(self, *inputs) and
    backward(self, *output_grads); call the instance on NDArrays.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray

        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = (outputs,) if single else tuple(outputs)
        if is_recording():
            parents = [(_node_of(x), x) for x in inputs]

            def custom_backward(node_cts, _self=self, _outs=outs):
                cts_nd = [NDArray(c) if c is not None else
                          NDArray(jnp.zeros(o.shape, o.data.dtype))
                          for c, o in zip(node_cts, _outs)]
                with pause():
                    gs = _self.backward(*cts_nd)
                if not isinstance(gs, (list, tuple)):
                    gs = (gs,)
                return [g.data if g is not None else None for g in gs]

            node = TapeNode(None, None, [x.data for x in inputs], parents,
                            len(outs), custom_backward=custom_backward)
            for i, o in enumerate(outs):
                o._ag_node = (node, i)
        return outputs
