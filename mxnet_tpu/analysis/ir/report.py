"""MXIR report rendering — the MXLINT.json-shaped artifact for
program audits (one entry per audited program instead of per file)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..engine import RULE_REGISTRY, Violation
from .rules import IR_RULE_IDS

__all__ = ["ProgramAudit", "render_ir_json"]


@dataclass
class ProgramAudit:
    """One audited program: the site label, its violations, and —
    when the text failed to parse — the counted (never fatal) error."""

    site: str
    violations: List[Violation] = field(default_factory=list)
    parse_error: Optional[str] = None
    wire: Optional[dict] = None      # static wire estimate, if computed

    @property
    def parse_skipped(self) -> bool:
        return self.parse_error is not None


def render_ir_json(audits: Sequence[ProgramAudit],
                   alias_skipped: int = 0) -> dict:
    """The MXIR.json shape — per-rule counts first (the trajectory the
    nightly tracks), then per-program summaries, then the findings.
    Mirrors :func:`..reporters.render_json` so the same tooling reads
    both artifacts.  ``alias_skipped`` counts the cache entries the
    offline audit passed over because they carry no module text (the
    exec/alias persistence tiers) — reported so "N programs audited"
    can never silently mean "most of the cache was skipped"."""
    violations: List[Violation] = []
    for a in audits:
        violations.extend(a.violations)
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    per_rule: Dict[str, int] = {}
    for v in violations:
        per_rule[v.rule] = per_rule.get(v.rule, 0) + 1
    skipped = sum(1 for a in audits if a.parse_skipped)
    return {
        "ok": not violations,
        "counts": {
            "programs": len(audits),
            "violations": len(violations),
            "parse_skipped": skipped,
            "alias_skipped": int(alias_skipped),
        },
        "per_rule": per_rule,
        "rules": {rid: {"name": RULE_REGISTRY[rid].name,
                        "description": RULE_REGISTRY[rid].description}
                  for rid in IR_RULE_IDS if rid in RULE_REGISTRY},
        "programs": [{
            "site": a.site,
            "violations": len(a.violations),
            "parse_skipped": a.parse_skipped,
            **({"parse_error": a.parse_error} if a.parse_error else {}),
            **({"wire": a.wire} if a.wire else {}),
        } for a in audits],
        "violations": [{
            "rule": v.rule, "path": v.path, "line": v.line,
            "col": v.col, "symbol": v.symbol, "message": v.message,
            "fingerprint": v.fingerprint,
        } for v in violations],
    }
