"""mxir program rules MX014–MX018: static verification of compiled
StableHLO step programs.

Where MX001–MX013 verify the Python that *builds* programs, these
rules verify the programs themselves — the lowered module text every
executable cache compiles and the persistent compile cache stores.
Each rule is grounded in a bug class this repo shipped:

  * MX014 — a call site declared donation but the lowered module
    carries no input/output alias: the donated buffer is silently kept
    live and peak HBM doubles (the exact failure mode ``alias_ok``
    guards dynamically — this is its static twin);
  * MX015 — an above-threshold tensor pinned or returned REPLICATED in
    a multi-partition program (the PR 18 gather-replication class:
    sharding propagation flipped, the fix pinned replication, and
    nothing verified the pin's cost);
  * MX016 — precision leaks around the int8/fp8 comm-quant path: f64
    creep, and widen→narrow round trips that throw away the precision
    they just paid for;
  * MX017 — collective hygiene: dead or duplicate collectives /
    resharding pins, plus the static wire-bytes model whose drift
    against the measured ``mx_collective_wire_bytes_total`` is itself
    a violation (:func:`estimate_wire_bytes` / :func:`wire_drift`);
  * MX018 — host transfers (infeed/outfeed/send/recv/host callbacks)
    inside a step program: every one is a device→host sync the async
    dispatch pipeline stalls on.

All rules run over the :mod:`parser` IR through :class:`IrContext`;
they register in the ordinary mxlint ``RULE_REGISTRY`` so reporters,
``--list-rules``, and the generated docs cover MX001–MX018 uniformly,
but ``Rule.check`` (the Python-AST hook) is a no-op — programs enter
through :func:`audit_module`.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..engine import Rule, Violation, register_rule
from .parser import (
    IrParseError, Module, Op, Sharding, parse_module, parse_sharding,
)

__all__ = [
    "IrContext", "IrRule", "DonationDropped", "OversizedReplicated",
    "PrecisionLeak", "CollectiveAudit", "HostTransfer",
    "WireEstimate", "estimate_wire_bytes", "wire_drift",
    "audit_module", "IR_RULE_IDS",
]

IR_RULE_IDS = ("MX014", "MX015", "MX016", "MX017", "MX018")

#: default MX015 threshold (bytes) — mirrors MXNET_IR_REPL_BYTES
DEFAULT_REPL_BYTES = 64 << 20


def _fmt_bytes(n: int) -> str:
    for unit, shift in (("GiB", 30), ("MiB", 20), ("KiB", 10)):
        if n >= (1 << shift):
            return f"{n / (1 << shift):.1f} {unit}"
    return f"{n} B"


class IrContext:
    """One audited program: the parsed module, its source lines (for
    violation anchors), and the audit-site metadata the runtime hook
    passes through."""

    def __init__(self, module: Module, text: str, site: str = "program",
                 expect_donation: bool = False,
                 repl_bytes: int = DEFAULT_REPL_BYTES):
        self.module = module
        self.lines = text.splitlines()
        self.site = site
        #: Violation.path — "ir://<site>" keeps program findings
        #: unmistakably distinct from file findings in shared reports
        self.path = f"ir://{site}"
        self.expect_donation = expect_donation
        self.repl_bytes = repl_bytes

    def src(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def violation(self, rule_id: str, line: int, message: str,
                  symbol: str = "main") -> Violation:
        return Violation(rule=rule_id, path=self.path, line=line,
                         col=0, message=message, symbol=symbol,
                         src=self.src(line))


class IrRule(Rule):
    """Base for program rules: engine ``check``/``finalize`` are
    no-ops (these rules see modules, not Python files); subclasses
    implement :meth:`check_program`."""

    def check_program(self, ctx: IrContext) -> Iterable[Violation]:
        return ()


# ---------------------------------------------------------------------------
# MX014 — donation dropped
# ---------------------------------------------------------------------------

@register_rule
class DonationDropped(IrRule):
    """MX014: the call site compiled with ``donate_argnums`` but the
    lowered module aliases NO argument to an output.  XLA then keeps
    every donated input live next to its output — silent 2x HBM on the
    largest buffers in the program (the optimizer state and weights
    the donation was protecting)."""

    id = "MX014"
    name = "donation-dropped"
    description = ("Call site declared buffer donation but the lowered "
                   "module has no input/output alias — donated buffers "
                   "stay live and peak HBM doubles.")

    def check_program(self, ctx: IrContext) -> Iterable[Violation]:
        if not ctx.expect_donation:
            return
        main = ctx.module.main
        if main is None or not main.args:
            return
        if any(a.alias_output is not None for a in main.args):
            return
        yield ctx.violation(
            self.id, main.line,
            f"compile site {ctx.site!r} declared donate_argnums but "
            f"none of the {len(main.args)} module arguments carries "
            "an input/output alias (tf.aliasing_output) — XLA will "
            "keep every donated buffer live beside its result, "
            "doubling peak HBM for the step state.")


# ---------------------------------------------------------------------------
# MX015 — oversized replicated tensor in a multi-partition program
# ---------------------------------------------------------------------------

@register_rule
class OversizedReplicated(IrRule):
    """MX015: a tensor above ``MXNET_IR_REPL_BYTES`` pinned (via a
    ``@Sharding`` custom_call) or returned with REPLICATED sharding in
    a program lowered for a multi-device mesh.  Every partition then
    materializes the full tensor — the PR 18 gather-replication bug
    class, caught statically instead of as an HBM OOM.  Arguments are
    exempt: replicated weights *inputs* are the data-parallel contract;
    it is producing a fresh full-size replicated value inside the
    program that multiplies memory."""

    id = "MX015"
    name = "oversized-replicated"
    description = ("Tensor above MXNET_IR_REPL_BYTES pinned or returned "
                   "replicated in a multi-partition program — every "
                   "device materializes the full value.")

    def check_program(self, ctx: IrContext) -> Iterable[Violation]:
        if ctx.module.num_partitions <= 1:
            return
        main = ctx.module.main
        if main is None:
            return
        limit = ctx.repl_bytes
        for op in main.ops:
            if op.target != "@Sharding" or not op.out_types:
                continue
            sh = op.sharding
            t = op.out_types[0]
            if sh is not None and sh.is_replicated and t is not None \
                    and t.nbytes is not None and t.nbytes > limit:
                yield ctx.violation(
                    self.id, op.line,
                    f"sharding pin replicates a {_fmt_bytes(t.nbytes)} "
                    f"tensor (tensor<{'x'.join(map(str, t.shape))}x"
                    f"{t.dtype}>) across all "
                    f"{ctx.module.num_partitions} partitions "
                    f"(> MXNET_IR_REPL_BYTES={limit}); shard it, or "
                    "raise the threshold if the replication is truly "
                    "load-bearing.")
        for i, res in enumerate(main.results):
            sh = res.sharding
            t = res.type
            if sh is not None and sh.is_replicated and t is not None \
                    and t.nbytes is not None and t.nbytes > limit:
                yield ctx.violation(
                    self.id, main.line,
                    f"program output #{i} is a replicated "
                    f"{_fmt_bytes(t.nbytes)} tensor in a "
                    f"{ctx.module.num_partitions}-partition program "
                    f"(> MXNET_IR_REPL_BYTES={limit}) — every device "
                    "holds the full value.")


# ---------------------------------------------------------------------------
# MX016 — precision leak
# ---------------------------------------------------------------------------

_NARROW = re.compile(r"^(i8|ui8|f16|bf16|f8.*)$")
_WIDE = {"f32", "f64"}


@register_rule
class PrecisionLeak(IrRule):
    """MX016: precision anomalies in a mixed-precision step program —
    any f64 compute (the silent x64 upcast class: one stray Python
    float promotes a whole chain to double and halves TPU throughput),
    and widen→narrow round trips where a value is converted up to
    f32/f64 and the *direct* result converted straight back down (the
    comm-quant decode→re-encode shape: the widening bought nothing and
    the narrow grid quantizes twice)."""

    id = "MX016"
    name = "precision-leak"
    description = ("f64 compute in a step program, or a widen->narrow "
                   "convert round trip (value upcast and immediately "
                   "re-quantized) around the comm-quant encode path.")

    def check_program(self, ctx: IrContext) -> Iterable[Violation]:
        for func in ctx.module.funcs.values():
            defs: Dict[str, Op] = {}
            for op in func.ops:
                for r in op.results:
                    defs[r] = op
            for op in func.ops:
                for t in op.out_types:
                    if t is not None and t.dtype == "f64":
                        yield ctx.violation(
                            self.id, op.line,
                            f"{op.name} produces f64 — double-precision "
                            "compute in a step program is almost always "
                            "an accidental x64 promotion (TPUs emulate "
                            "f64 at a large cost).", symbol=func.name)
                        break
                if not op.name.endswith("convert") or not op.operands:
                    continue
                src_t = op.in_types[0] if op.in_types else None
                dst_t = op.out_types[0] if op.out_types else None
                if src_t is None or dst_t is None or \
                        src_t.dtype not in _WIDE or \
                        not _NARROW.match(dst_t.dtype):
                    continue
                feeder = defs.get(op.operands[0])
                if feeder is None or not feeder.name.endswith("convert"):
                    continue
                f_src = feeder.in_types[0] if feeder.in_types else None
                if f_src is not None and f_src.dtype == dst_t.dtype:
                    yield ctx.violation(
                        self.id, op.line,
                        f"{dst_t.dtype}->{src_t.dtype}->{dst_t.dtype} "
                        "convert round trip: the upcast result feeds "
                        "straight back into the narrow grid, "
                        "quantizing twice for nothing — drop the "
                        "round trip or do real f32 compute between "
                        "the casts.", symbol=func.name)


# ---------------------------------------------------------------------------
# MX017 — collective audit (+ static wire-bytes model)
# ---------------------------------------------------------------------------

#: explicit collective ops (shard_map/manual programs) — GSPMD
#: programs express collectives as @Sharding transitions instead
_COLLECTIVE_OPS = {
    "all_reduce", "all_gather", "reduce_scatter", "all_to_all",
    "collective_permute", "collective_broadcast", "cross-replica-sum",
}


def _is_collective(op: Op) -> bool:
    return op.name.split(".")[-1] in _COLLECTIVE_OPS or \
        op.target == "@Sharding"


@register_rule
class CollectiveAudit(IrRule):
    """MX017: collective hygiene.  A DEAD collective or resharding pin
    (result never used, not returned) still moves its bytes before XLA
    DCE can prove otherwise — and a pin the author believes is
    load-bearing but is actually dead means the *intended* sharding
    never happens.  A DUPLICATE collective (same op, same operands,
    same attributes) moves the same bytes twice.  The third face of
    the rule is dynamic: :func:`wire_drift` compares this module's
    static wire-bytes estimate (:func:`estimate_wire_bytes`) against
    the measured ``mx_collective_wire_bytes_total`` counter — drift
    above tolerance means the program on the wire is not the program
    the model (and the capacity plan) believes is running."""

    id = "MX017"
    name = "collective-audit"
    description = ("Dead or duplicate collective / resharding pin in a "
                   "step program, or static wire-bytes model drifting "
                   "from the measured collective counters beyond "
                   "MXNET_IR_WIRE_TOL.")

    def check_program(self, ctx: IrContext) -> Iterable[Violation]:
        for func in ctx.module.funcs.values():
            used = set(func.returns)
            for op in func.ops:
                used.update(op.operands)
            seen: Dict[Tuple, int] = {}
            for op in func.ops:
                if not _is_collective(op):
                    continue
                if op.results and not any(r in used for r in op.results):
                    what = f"custom_call {op.target}" if op.target \
                        else op.name
                    yield ctx.violation(
                        self.id, op.line,
                        f"dead collective: {what} result is never used "
                        "and not returned — the bytes still move, and "
                        "if this pin was meant to constrain sharding "
                        "it constrains nothing.", symbol=func.name)
                key = (op.name, op.target, tuple(op.operands),
                       tuple(sorted(op.attrs.items())))
                prev = seen.get(key)
                if prev is not None:
                    yield ctx.violation(
                        self.id, op.line,
                        f"duplicate collective: identical "
                        f"{op.target or op.name} on "
                        f"{', '.join(op.operands)} already issued at "
                        f"module line {prev} — the same payload "
                        "crosses the wire twice.", symbol=func.name)
                else:
                    seen[key] = op.line


# -- static wire-bytes model -------------------------------------------------

#: elementwise / shape-preserving ops: sharding state propagates
#: spec-exactly
_PROPAGATE = {
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "negate", "abs", "exponential", "log", "sqrt", "rsqrt", "tanh",
    "logistic", "sign", "floor", "ceil", "round_nearest_even",
    "round_nearest_afz", "clamp", "select", "compare", "and", "or",
    "xor", "not", "power", "remainder", "atan2", "convert",
    "bitcast_convert", "copy", "optimization_barrier",
}
#: shape-changing but data-local ops: still sharded-ish, but the tile
#: assignment no longer maps (spec degrades to unknown)
_RESHAPEY = {
    "reshape", "pad", "slice", "dynamic_slice", "concatenate",
    "transpose", "broadcast_in_dim", "dynamic_update_slice", "iota",
    "gather",
}

_REPL = ("repl",)
_PARTIAL = ("partial",)
_UNKNOWN = ("unknown",)


def _classify_target(sh: Optional[Sharding]) -> Tuple:
    if sh is None:
        return _UNKNOWN
    if sh.is_replicated:
        return _REPL
    if sh.kind == "devices":
        return ("sharded", sh.text, sh.sharded_dims)
    return _UNKNOWN


def _join(states: Sequence[Tuple]) -> Tuple:
    states = [s for s in states if s is not None]
    if not states:
        return _UNKNOWN
    if any(s == _PARTIAL for s in states):
        return _PARTIAL
    sharded = [s for s in states if s[0] == "sharded"]
    if sharded:
        specs = {s[1] for s in sharded}
        if len(specs) == 1 and all(
                s[0] in ("sharded", "repl") for s in states):
            return sharded[0]
        return ("sharded", None, ())
    if all(s == _REPL for s in states):
        return _REPL
    return _UNKNOWN


def _lane(dtype: str) -> str:
    if dtype in ("i8", "ui8"):
        return "int8"
    if dtype.startswith("f8"):
        return "fp8"
    return dtype


@dataclass
class WireEstimate:
    """Static per-execution wire model: one leg per collective the
    abstract interpretation could classify.  ``by_lane`` buckets bytes
    by payload dtype the same way the runtime counter's ``encoding``
    label does (i8 → "int8", f8* → "fp8"), so the two are directly
    comparable lane by lane."""

    legs: List[dict] = field(default_factory=list)
    unknown_transitions: int = 0

    @property
    def total(self) -> int:
        return sum(leg["nbytes"] for leg in self.legs)

    @property
    def by_lane(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for leg in self.legs:
            out[leg["lane"]] = out.get(leg["lane"], 0) + leg["nbytes"]
        return out


def estimate_wire_bytes(module: Module) -> WireEstimate:
    """Abstract-interpret the main function's sharding states and
    price every collective transition.

    Byte conventions match the runtime counter (one logical copy of
    the payload per leg): reduce-scatter / all-reduce / all-gather
    count the full tensor's bytes; an all-to-all between two sharded
    layouts counts ``nbytes / num_partitions`` (each device forwards
    its shard); replicated→sharded is a local slice (0 bytes).
    Unclassifiable transitions count nothing and are tallied in
    ``unknown_transitions`` — precision over recall, like every other
    rule in this package."""
    est = WireEstimate()
    main = module.main
    if main is None:
        return est
    nparts = max(1, module.num_partitions)
    state: Dict[str, Tuple] = {}
    for arg in main.args:
        state[arg.name] = _classify_target(arg.sharding)
    for op in main.ops:
        short = op.name.split(".")[-1]
        if op.name.endswith("constant"):
            for r in op.results:
                state[r] = _REPL
            continue
        if op.target == "@Sharding":
            src = state.get(op.operands[0], _UNKNOWN) \
                if op.operands else _UNKNOWN
            dst = _classify_target(op.sharding)
            t = op.out_types[0] if op.out_types else None
            nbytes = t.nbytes if t is not None else None
            kind = None
            amount = 0
            if nbytes is not None:
                if src == _PARTIAL and dst[0] == "sharded":
                    kind, amount = "reduce-scatter", nbytes
                elif src == _PARTIAL and dst == _REPL:
                    kind, amount = "all-reduce", nbytes
                elif src[0] == "sharded" and dst == _REPL:
                    kind, amount = "all-gather", nbytes
                elif src[0] == "sharded" and dst[0] == "sharded":
                    if src[1] is None or src[1] != dst[1]:
                        kind, amount = "all-to-all", nbytes // nparts
                elif src == _REPL:
                    pass                      # local slice / no-op
                else:
                    est.unknown_transitions += 1
            else:
                est.unknown_transitions += 1
            if kind is not None and amount > 0:
                est.legs.append({
                    "kind": kind, "nbytes": int(amount),
                    "lane": _lane(t.dtype), "line": op.line,
                })
            for r in op.results:
                state[r] = dst
            continue
        if short in _COLLECTIVE_OPS:
            t = op.out_types[0] if op.out_types else None
            if t is not None and t.nbytes is not None:
                est.legs.append({
                    "kind": short.replace("_", "-"),
                    "nbytes": int(t.nbytes),
                    "lane": _lane(t.dtype), "line": op.line,
                })
            for r in op.results:
                state[r] = _UNKNOWN
            continue
        if short == "reduce":
            src = state.get(op.operands[0], _UNKNOWN) \
                if op.operands else _UNKNOWN
            dims = {int(d) for d in
                    op.attrs.get("dimensions", "").split(",")
                    if d.strip().isdigit()}
            if src == _PARTIAL:
                out = _PARTIAL
            elif src[0] == "sharded":
                # reducing over a *provably sharded* dim leaves
                # per-device partial sums; with a degraded spec we keep
                # the sharded state — comm-quant scale reductions run
                # over local block dims of padded (spec-degraded) data,
                # and flagging those partial would misprice the int8
                # exchange legs as reduce-scatters
                out = _PARTIAL if (src[1] is not None and
                                   dims & set(src[2])) else src
            else:
                out = src
            for r in op.results:
                state[r] = out
            continue
        if short == "call":
            # private helpers (@round, @clip, @_pad_*) are data-local:
            # sharding survives the call, the tile assignment may not
            out = _join([state.get(o, _UNKNOWN) for o in op.operands])
            if out[0] == "sharded":
                out = ("sharded", None, ())
            for r in op.results:
                state[r] = out
            continue
        if short in _PROPAGATE:
            out = _join([state.get(o, _UNKNOWN) for o in op.operands])
            for r in op.results:
                state[r] = out
            continue
        if short in _RESHAPEY:
            out = _join([state.get(o, _UNKNOWN) for o in op.operands])
            if out[0] == "sharded":
                out = ("sharded", None, ())
            for r in op.results:
                state[r] = out
            continue
        for r in op.results:
            state[r] = _UNKNOWN
    return est


def wire_drift(static_bytes: float, measured_bytes: float,
               tol: float) -> Optional[str]:
    """MX017's dynamic face: relative drift between the static model
    and the measured counter, same lane, same step count.  Returns the
    violation message when drift exceeds ``tol`` (``None`` when the
    model and the wire agree)."""
    if measured_bytes <= 0 and static_bytes <= 0:
        return None
    denom = max(measured_bytes, 1.0)
    drift = abs(static_bytes - measured_bytes) / denom
    if drift <= tol:
        return None
    return (f"static wire-bytes model predicts {int(static_bytes)} B "
            f"but the measured collective counter moved "
            f"{int(measured_bytes)} B — {drift:.1%} drift exceeds "
            f"MXNET_IR_WIRE_TOL={tol:g}; the program on the wire is "
            "not the program the model believes is running.")


# ---------------------------------------------------------------------------
# MX018 — host transfer inside a step program
# ---------------------------------------------------------------------------

_HOST_TARGET = re.compile(r"callback|infeed|outfeed|host_|py_func",
                          re.IGNORECASE)
_HOST_OPS = {"infeed", "outfeed", "send", "recv"}


@register_rule
class HostTransfer(IrRule):
    """MX018: infeed/outfeed/send/recv or a host callback custom_call
    inside a step program.  Each one is a synchronous device↔host
    round trip in the middle of the hot loop — the compiled-program
    equivalent of MX002's ``.asnumpy()``-in-the-step, and invisible
    from the Python source once a library buried it in a traced
    helper (``jax.debug.print``, ``io_callback``, host metrics)."""

    id = "MX018"
    name = "host-transfer"
    description = ("infeed/outfeed/send/recv or host-callback "
                   "custom_call inside a compiled step program — a "
                   "device<->host sync in the hot loop.")

    def check_program(self, ctx: IrContext) -> Iterable[Violation]:
        for func in ctx.module.funcs.values():
            for op in func.ops:
                short = op.name.split(".")[-1]
                if short in _HOST_OPS:
                    yield ctx.violation(
                        self.id, op.line,
                        f"{op.name} inside a step program is a "
                        "synchronous device<->host transfer; move the "
                        "host exchange outside the compiled step.",
                        symbol=func.name)
                elif op.target and op.target != "@Sharding" and \
                        _HOST_TARGET.search(op.target):
                    yield ctx.violation(
                        self.id, op.line,
                        f"custom_call {op.target} is a host callback — "
                        "the step blocks on Python while the mesh "
                        "idles; hoist it out of the traced step or "
                        "gate it behind a debug knob.",
                        symbol=func.name)


# ---------------------------------------------------------------------------
# the audit entry point
# ---------------------------------------------------------------------------

def audit_module(text: str, site: str = "program",
                 expect_donation: bool = False,
                 repl_bytes: int = DEFAULT_REPL_BYTES,
                 rules: Optional[Sequence[str]] = None,
                 module: Optional[Module] = None
                 ) -> List[Violation]:
    """Parse ``text`` and run the program rules (all five, or the ids
    in ``rules``).  Raises :class:`IrParseError` when the text cannot
    be parsed — callers count it as ``parse_skipped``.  Pass an
    already-parsed ``module`` to skip the re-parse."""
    if module is None:
        module = parse_module(text)
    ctx = IrContext(module, text, site=site,
                    expect_donation=expect_donation,
                    repl_bytes=repl_bytes)
    out: List[Violation] = []
    for cls in (DonationDropped, OversizedReplicated, PrecisionLeak,
                CollectiveAudit, HostTransfer):
        if rules is not None and cls.id not in rules:
            continue
        out.extend(cls().check_program(ctx))
    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return out
