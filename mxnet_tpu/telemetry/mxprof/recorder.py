"""The mxprof flight recorder: a bounded ring of per-step records.

Attached as the tracing layer's *sink* (``tracing.set_sink``), the
recorder receives every finished span — always, not just while the
profiler captures — and folds them into one record per training step:

  * phase seconds (forward / backward / grad-allreduce /
    optimizer-update / fused-update / spmd-step / reduce-scatter /
    shard-update / all-gather, plus host-blocking collectives);
  * data-wait preceding the step (input-bound evidence);
  * compile events that landed inside the step (the recompile smoking
    gun with a timestamped step number attached);
  * collective payload bytes (fed by the SPMD/kvstore byte counters);
  * program FLOPs (fed by the compile-cache cost capture), from which
    MFU = flops / wall / peak and the roofline verdict follow.

A record closes when the ``step`` span finishes — or, on the gspmd
whole-step path (no enclosing ``step`` span), when the NEXT
``spmd-step`` arrives.  The ring (``MXNET_MXPROF_RING``) bounds
memory; ``dump()`` snapshots it on demand (SIGUSR2 does the same from
outside), and every BENCH-style harness embeds the snapshot.

Verdict semantics (deliberately simple, deliberately stable):
``input-bound`` when data-wait dominates both halves; else
``comm-bound`` when collective time exceeds compute time; else
``compute-bound``; ``unattributed`` when a step carried no phases.
On the unphased SPMD path the one fused program hides its internal
collectives, so its verdict leans compute-bound — run a phased
capture (tracing on) to split it.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, Optional

from .. import instruments as _ins
from .. import tracing as _tracing
from . import costs as _costs

__all__ = ["FlightRecorder"]

# phases accumulated into the pending step record
_PHASES = frozenset((
    "forward", "backward", "grad-allreduce", "optimizer-update",
    "fused-update", "spmd-step", "reduce-scatter", "shard-update",
    "all-gather",
))
# compile events (cat "training" or "serving") counted per step
_COMPILES = frozenset(("fused-compile", "spmd-compile", "aot-compile"))
# the communication half of the roofline split
_COMM = ("grad-allreduce", "reduce-scatter", "all-gather")
# compute half: optimizer-update CONTAINS fused-update (nested spans).
# shard-update ranks BEFORE spmd-step: the phased SPMD capture nests
# reduce-scatter/shard-update/all-gather inside spmd-step, and taking
# spmd-step as compute would swallow the collectives into the compute
# half — comm-bound would be unreachable exactly when the capture
# exists to split it.  The unphased path has only spmd-step.
_UPDATE_PREFERENCE = ("optimizer-update", "fused-update",
                      "shard-update", "spmd-step")


class _Pending:
    __slots__ = ("phases", "collectives", "data_wait", "bytes",
                 "wire_bytes", "flops", "bytes_accessed", "compiles",
                 "compile_s", "compile_reasons")

    def __init__(self):
        self.phases: Dict[str, float] = {}
        self.collectives: Dict[str, float] = {}
        self.data_wait = 0.0
        self.bytes: Dict[str, int] = {}
        # WIRE view keyed "op@axis:encoding" — what actually crossed
        # the interconnect (1 byte/elem + scales under
        # MXNET_COMM_QUANT); `bytes` above stays the logical
        # model-sized payload, so ratio(wire/logical) is the
        # quantization win
        self.wire_bytes: Dict[str, int] = {}
        self.flops = 0.0
        self.bytes_accessed = 0.0
        self.compiles = 0
        self.compile_s = 0.0
        # provenance diffs of the compile-cache misses that landed in
        # this step ({"site": ..., "components": [...]}) — bounded: a
        # storm's first handful names the cause, the counter has the
        # count
        self.compile_reasons: list = []

    def empty(self) -> bool:
        return not (self.phases or self.collectives or self.bytes
                    or self.wire_bytes or self.data_wait
                    or self.compiles or self.flops
                    or self.compile_reasons)


class FlightRecorder:
    """Sink + ring buffer.  All mutation under one lock — events are
    step-scale (a handful per step), never op-scale."""

    def __init__(self, ring: int = 512):
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=max(1, int(ring)))
        self._pending = _Pending()
        self._step = 0
        self._t0 = time.time()
        self._hbm_every = 0
        self._state_provider = None  # () -> (total_bytes, shard_factor)
        self._peak_cache: Optional[tuple] = None
        # step-boundary listeners (mxtriage deep-capture windows);
        # an immutable tuple so notification never takes the lock —
        # and the empty-tuple fast path costs one truthiness check
        self._listeners: tuple = ()

    # ---- wiring ------------------------------------------------------

    def set_hbm_every(self, n: int) -> None:
        self._hbm_every = max(0, int(n))

    def set_state_bytes_provider(self, fn) -> None:
        """``fn() -> (total_state_bytes, shard_factor)`` — pulled at
        sample/dump time (never per step), so providing costs the
        training loop nothing."""
        self._state_provider = fn

    def add_step_listener(self, fn) -> None:
        """``fn(step)`` runs on the recording thread after each record
        closes (mxtriage uses it for step-boundary capture windows).
        Listeners must be cheap and must never raise into the step."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners = self._listeners + (fn,)

    def remove_step_listener(self, fn) -> None:
        with self._lock:
            self._listeners = tuple(f for f in self._listeners
                                    if f is not fn)

    def _notify(self, step: Optional[int]) -> None:
        if step is None or not self._listeners:
            return
        for fn in self._listeners:
            try:
                fn(step)
            except Exception:  # noqa: BLE001 — a listener never breaks a step
                pass

    def _peak(self):
        if self._peak_cache is None:
            peak, src = _costs.peak_flops()
            if peak is None and not _costs.backend_initialized():
                # provisional: an early dump (SIGUSR2 before any jax
                # work) must not pin MFU to null for the process —
                # re-resolve once the backend is up
                return peak, src
            self._peak_cache = (peak, src)
        return self._peak_cache

    def _state_share(self) -> Optional[float]:
        fn = self._state_provider
        if fn is None:
            return None
        try:
            total, factor = fn()
        except Exception:  # noqa: BLE001 — provider must not break a dump
            return None
        if total is None:
            return None
        return float(total) / max(1, int(factor or 1))

    # ---- the sink protocol (called from tracing) ---------------------

    def on_event(self, name: str, cat: str, duration: float,
                 args) -> None:
        if name in _COMPILES:
            with self._lock:
                self._pending.compiles += 1
                self._pending.compile_s += duration
            return
        if cat == "training":
            if name == "step":
                self._notify(self._close(duration))
                return
            if name not in _PHASES:
                return
            closed = None
            with self._lock:
                p = self._pending
                if name == "spmd-step" and "spmd-step" in p.phases:
                    # gspmd whole-step path: no enclosing "step" span
                    # ever closes the record — the NEXT spmd-step is
                    # the boundary, and the previous one's duration IS
                    # the previous step's wall time
                    prev = p.phases["spmd-step"]
                    closed = self._close_locked(prev)
                    p = self._pending
                p.phases[name] = p.phases.get(name, 0.0) + duration
            self._notify(closed)
            return
        if cat == "data" and name == "data-wait":
            with self._lock:
                self._pending.data_wait += duration
            return
        if cat == "collective":
            with self._lock:
                c = self._pending.collectives
                c[name] = c.get(name, 0.0) + duration

    def on_bytes(self, op: str, axis: str, nbytes: int) -> None:
        key = f"{op}@{axis}"
        with self._lock:
            b = self._pending.bytes
            b[key] = b.get(key, 0) + int(nbytes)

    def on_wire_bytes(self, op: str, axis: str, encoding: str,
                      nbytes: int) -> None:
        key = f"{op}@{axis}:{encoding}"
        with self._lock:
            b = self._pending.wire_bytes
            b[key] = b.get(key, 0) + int(nbytes)

    def on_flops(self, site: str, cost) -> None:
        with self._lock:
            self._pending.flops += cost.flops
            self._pending.bytes_accessed += cost.bytes_accessed

    def on_compile_reason(self, site: str, components) -> None:
        """Provenance feed (telemetry.mxtriage.provenance): the diff of
        one compile-cache miss that landed inside this step.  Bounded —
        a storm's first handful names the cause; its size lives in the
        ``compiles`` count and the reason counter."""
        with self._lock:
            reasons = self._pending.compile_reasons
            if len(reasons) < 16:
                reasons.append({"site": site,
                                "components": list(components)})

    # ---- record closing ----------------------------------------------

    def _close(self, wall_s: float) -> Optional[int]:
        with self._lock:
            return self._close_locked(wall_s)

    def _close_locked(self, wall_s: float) -> Optional[int]:
        """Close the pending record; returns the closed step number
        (None when nothing closed) so callers can notify the step
        listeners OUTSIDE the lock."""
        p, self._pending = self._pending, _Pending()
        if p.empty() and wall_s <= 0.0:
            return None
        self._step += 1
        # the "step" span covers the reduce+update tail only; forward/
        # backward are sibling spans — the record's wall is the whole
        # step.  (The gspmd one-program path has them inside its single
        # spmd-step span already, and records no forward/backward.)
        wall_s += (p.phases.get("forward", 0.0)
                   + p.phases.get("backward", 0.0))
        compute = (p.phases.get("forward", 0.0)
                   + p.phases.get("backward", 0.0))
        for nm in _UPDATE_PREFERENCE:
            if nm in p.phases:
                compute += p.phases[nm]
                break
        comm = p.phases.get("grad-allreduce", 0.0)
        if comm == 0.0:
            comm = sum(p.phases.get(nm, 0.0) for nm in _COMM[1:]) \
                or sum(p.collectives.values())
        if not p.phases and not p.collectives and not p.data_wait:
            verdict = "unattributed"
        elif p.data_wait >= max(compute, comm, 1e-12):
            verdict = "input-bound"
        elif comm > compute:
            verdict = "comm-bound"
        else:
            verdict = "compute-bound"
        peak, _src = self._peak()
        mfu = None
        if peak and p.flops and wall_s > 0:
            mfu = p.flops / wall_s / peak
        rec = {
            "step": self._step,
            "t": time.time(),
            "wall_s": round(wall_s, 6),
            "data_wait_s": round(p.data_wait, 6),
            "phases": {k: round(v, 6) for k, v in
                       sorted(p.phases.items())},
            "collectives": {k: round(v, 6) for k, v in
                            sorted(p.collectives.items())},
            "collective_bytes": dict(p.bytes),
            "collective_wire_bytes": dict(p.wire_bytes),
            "flops": p.flops,
            "bytes_accessed": p.bytes_accessed,
            "mfu": None if mfu is None else round(mfu, 6),
            "compiles": p.compiles,
            "compile_s": round(p.compile_s, 6),
            "verdict": verdict,
        }
        if p.compile_reasons:
            rec["compile_reasons"] = p.compile_reasons
        self._ring.append(rec)
        # mxprof's OWN gauges update whenever a record closes — the
        # docs promise them in MXNET_MXPROF=1-only mode too (metrics
        # exposition is always on; only the telemetry flag is not).
        # A few child writes per step, well inside the overhead gate.
        _ins.step_last_seconds().set(wall_s)
        _ins.step_roofline_total(verdict).inc()
        if p.flops:
            _ins.step_flops_total().inc(p.flops)
        if mfu is not None:
            _ins.step_mfu().set(mfu)
        if self._hbm_every and self._step % self._hbm_every == 0:
            from . import hbm as _hbm

            try:
                _hbm.sample(live=False,
                            state_bytes=self._state_share())
            except Exception:  # noqa: BLE001 — sampling never breaks a step
                pass
        return self._step

    # ---- introspection -----------------------------------------------

    def records(self):
        with self._lock:
            return list(self._ring)

    def records_since(self, step: int):
        """Records with ``step`` strictly greater than the given
        high-water mark, oldest first — the incremental read the
        mxgoodput ledger consumes per step close.  Scans from the
        ring's tail, so the per-step cost is the handful of new
        records, not the whole ring."""
        with self._lock:
            out = []
            for rec in reversed(self._ring):
                if rec["step"] <= step:
                    break
                out.append(rec)
        out.reverse()
        return out

    def current_step(self) -> int:
        """The last closed step number (0 before any record closes;
        restarts at 0 on clear() — consumers use it to notice a
        recorder swap)."""
        with self._lock:
            return self._step

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._pending = _Pending()
            self._step = 0

    def summary(self) -> dict:
        recs = self.records()
        out: dict = {"steps_recorded": len(recs)}
        if not recs:
            return out
        walls = [r["wall_s"] for r in recs]
        out["wall_s_total"] = round(sum(walls), 6)
        out["wall_s_mean"] = round(sum(walls) / len(walls), 6)
        phases: Dict[str, float] = {}
        nbytes: Dict[str, int] = {}
        wbytes: Dict[str, int] = {}
        verdicts: Dict[str, int] = {}
        for r in recs:
            for k, v in r["phases"].items():
                phases[k] = phases.get(k, 0.0) + v
            for k, v in r["collective_bytes"].items():
                nbytes[k] = nbytes.get(k, 0) + v
            for k, v in r.get("collective_wire_bytes", {}).items():
                wbytes[k] = wbytes.get(k, 0) + v
            verdicts[r["verdict"]] = verdicts.get(r["verdict"], 0) + 1
        out["phase_seconds"] = {k: round(v, 6)
                                for k, v in sorted(phases.items())}
        out["collective_bytes"] = nbytes
        out["collective_wire_bytes"] = wbytes
        out["verdicts"] = verdicts
        out["data_wait_s_total"] = round(
            sum(r["data_wait_s"] for r in recs), 6)
        out["compiles"] = sum(r["compiles"] for r in recs)
        reasons: Dict[str, Dict[str, int]] = {}
        for r in recs:
            for cr in r.get("compile_reasons", ()):
                per = reasons.setdefault(cr["site"], {})
                for comp in cr["components"]:
                    per[comp] = per.get(comp, 0) + 1
        if reasons:
            out["compile_reasons"] = reasons
        mfus = [r["mfu"] for r in recs if r["mfu"] is not None]
        out["mfu_mean"] = round(sum(mfus) / len(mfus), 6) if mfus \
            else None
        return out

    def dump_dict(self, live_hbm: bool = True,
                  include_records: bool = True) -> dict:
        """The full flight-recorder snapshot (what ``mxprof.dump()``
        writes and BENCH harnesses embed).  ``include_records=False``
        drops the per-step ring and keeps the aggregates — the shape
        committed bench artifacts embed so they stay reviewable."""
        from . import hbm as _hbm

        from ...util import env as _env

        peak, src = self._peak()
        state_share = self._state_share()
        try:
            hbm_now = _hbm.sample(live=live_hbm,
                                  state_bytes=state_share)
        except Exception:  # noqa: BLE001
            hbm_now = {}
        # the knob surface of the run: env-SET values by name (the
        # attribution diff can say WHICH knob changed) plus a
        # fingerprint over the full resolved table (a changed code
        # default still flips it)
        try:
            table = _env.resolved()
            overlay = _env.overlay_info()
            overlaid = set(overlay["applied"]) if overlay else set()
            knobs = {name: v for name, v in table.items()
                     if name in os.environ or name in overlaid}
            knob_fp = _env.fingerprint()
        except Exception:  # noqa: BLE001 — a dump never fails on a bad knob
            knobs, knob_fp, overlay = {}, None, None
        out = {
            "pid": os.getpid(),
            "rank": _tracing._RANK,
            "when": time.strftime("%Y-%m-%d %H:%M:%S"),
            "uptime_s": round(time.time() - self._t0, 3),
            "peak_flops": {"per_device": peak, "source": src},
            "optimizer_state_bytes_per_device": state_share,
            "summary": self.summary(),
            "hbm": hbm_now,
            "executable_costs": _costs.notes(),
            "knobs": knobs,
            "knob_fingerprint": knob_fp,
        }
        # mxtune stamp: WHICH tuned config this process booted with (or
        # None for an untuned run) — perf_compare/mxtriage tell
        # tuned-from-stale by this fingerprint, and the overlaid names
        # already ride in `knobs` above so attribution sees tuned values
        if overlay is not None:
            out["tuned_config"] = {
                "fingerprint": overlay.get("fingerprint"),
                "source": overlay.get("source"),
                "applied": overlay.get("applied"),
                "shadowed": overlay.get("shadowed"),
            }
        # the goodput ledger rides every dump (mxprof.dump(), SIGUSR2,
        # embedded bench snapshots): a per-rank dump is what
        # tools/goodput_report.py --merge rolls into the job-level
        # GOODPUT.json
        try:
            from .. import mxgoodput as _goodput

            if _goodput.enabled():
                out["goodput"] = _goodput.snapshot()
        except Exception:  # noqa: BLE001 — a dump never fails on the ledger
            pass
        if include_records:
            out["records"] = self.records()
        return out
