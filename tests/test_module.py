"""Module API tests (model: tests/python/unittest/test_module.py)."""
import logging

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import symbol as sym
from mxnet_tpu.io import DataBatch, DataDesc, NDArrayIter
from mxnet_tpu.module import BucketingModule, Module
from mxnet_tpu.test_utils import assert_almost_equal


def _mlp_sym(hidden=16, classes=4):
    data = sym.var("data")
    fc1 = sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    act = sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(act, num_hidden=classes, name="fc2")
    return sym.SoftmaxOutput(fc2, name="softmax")


def _toy_data(n=256, dim=10, classes=4, seed=0):
    """Linearly separable-ish synthetic classification data."""
    rng = np.random.RandomState(seed)
    w = rng.randn(dim, classes)
    x = rng.randn(n, dim).astype("float32")
    y = (x @ w).argmax(axis=1).astype("float32")
    return x, y


def test_module_bind_and_shapes():
    mod = Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 10))],
             label_shapes=[("softmax_label", (8,))])
    assert mod.binded
    mod.init_params()
    assert mod.params_initialized
    args, auxs = mod.get_params()
    assert args["fc1_weight"].shape == (16, 10)
    assert auxs == {}


def test_module_fit_reduces_loss():
    x, y = _toy_data()
    train_iter = NDArrayIter(x, y, batch_size=32, shuffle=True)
    mod = Module(_mlp_sym(), context=mx.cpu())
    # note: SoftmaxOutput grads are summed over the batch (reference
    # default normalization='null'), so keep lr modest
    mod.fit(train_iter, num_epoch=12, initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.1},
            eval_metric="acc")
    score = mod.score(NDArrayIter(x, y, batch_size=32), "acc")
    acc = dict(score)["accuracy"]
    assert acc > 0.8, f"accuracy {acc} too low after fit"


def test_module_predict_and_outputs():
    x, y = _toy_data(n=64)
    mod = Module(_mlp_sym(), context=mx.cpu())
    it = NDArrayIter(x, y, batch_size=16)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    out = mod.predict(it)
    assert out.shape == (64, 4)
    probs = out.asnumpy()
    assert_almost_equal(probs.sum(axis=1), np.ones(64, "float32"),
                        rtol=1e-4, atol=1e-4)


def test_module_multi_device_matches_single():
    # 2 virtual CPU devices slice the batch; same init -> same params after
    # one update (the reference's DataParallelExecutorGroup contract)
    x, y = _toy_data(n=32)
    sym_net = _mlp_sym()

    def run(ctxs, seed=7):
        mx.random.seed(seed)
        np.random.seed(seed)
        mod = Module(sym_net, context=ctxs)
        it = NDArrayIter(x, y, batch_size=32)
        mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
        mod.init_params(mx.initializer.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        batch = next(iter(it))
        mod.forward_backward(batch)
        mod.update()
        return {k: v.asnumpy() for k, v in mod.get_params()[0].items()}

    single = run(mx.cpu(0))
    double = run([mx.cpu(0), mx.cpu(1)])
    for k in single:
        # grad aggregation across slices is summed; both runs see the same
        # total batch, so params must match closely
        assert_almost_equal(single[k], double[k], rtol=1e-4, atol=1e-5,
                            names=(f"single:{k}", f"double:{k}"))


def test_module_checkpoint_roundtrip(tmp_path):
    x, y = _toy_data(n=64)
    prefix = str(tmp_path / "mlp")
    mod = Module(_mlp_sym(), context=mx.cpu())
    it = NDArrayIter(x, y, batch_size=16)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.save_checkpoint(prefix, 3)

    mod2 = Module.load(prefix, 3, context=mx.cpu())
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    p1 = mod.get_params()[0]
    p2 = mod2.get_params()[0]
    for k in p1:
        assert_almost_equal(p1[k], p2[k])
    # loaded module produces identical predictions
    o1 = mod.predict(it).asnumpy()
    o2 = mod2.predict(it).asnumpy()
    assert_almost_equal(o1, o2, rtol=1e-5, atol=1e-6)


def test_module_optimizer_states_roundtrip(tmp_path):
    x, y = _toy_data(n=32)
    mod = Module(_mlp_sym(), context=mx.cpu())
    it = NDArrayIter(x, y, batch_size=32)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    batch = next(iter(it))
    mod.forward_backward(batch)
    mod.update()
    path = str(tmp_path / "opt.states")
    mod.save_optimizer_states(path)
    mod.load_optimizer_states(path)


def test_bucketing_module():
    # variable-length "sequences": bucket_key = seq len; shared params
    vocab, emb_dim, classes = 20, 8, 3

    def sym_gen(seq_len):
        data = sym.var("data")
        emb = sym.Embedding(data, input_dim=vocab, output_dim=emb_dim,
                            name="embed")
        pooled = emb.mean(axis=1)
        fc = sym.FullyConnected(pooled, num_hidden=classes, name="fc")
        out = sym.SoftmaxOutput(fc, name="softmax")
        return out, ("data",), ("softmax_label",)

    mod = BucketingModule(sym_gen, default_bucket_key=10, context=mx.cpu())
    mod.bind(data_shapes=[DataDesc("data", (4, 10))],
             label_shapes=[DataDesc("softmax_label", (4,))])
    mod.init_params()
    mod.init_optimizer(optimizer_params={"learning_rate": 0.1})

    rng = np.random.RandomState(0)
    for seq_len in (10, 5, 10, 7):
        x = rng.randint(0, vocab, size=(4, seq_len)).astype("float32")
        y = rng.randint(0, classes, size=(4,)).astype("float32")
        batch = DataBatch(
            data=[nd.array(x)], label=[nd.array(y)], bucket_key=seq_len,
            provide_data=[DataDesc("data", (4, seq_len))],
            provide_label=[DataDesc("softmax_label", (4,))])
        mod.forward_backward(batch)
        mod.update()
        assert mod.get_outputs()[0].shape == (4, classes)
    # params shared across buckets: embedding updated by all bucket steps
    assert len(mod._buckets) == 3


def test_feedforward_compat():
    from mxnet_tpu.model import FeedForward

    x, y = _toy_data(n=128)
    ff = FeedForward(_mlp_sym(), ctx=mx.cpu(), num_epoch=3,
                     numpy_batch_size=32,
                     initializer=mx.initializer.Xavier(),
                     optimizer_params={"learning_rate": 0.1})
    ff.fit(x, y)
    preds = ff.predict(x)
    assert preds.shape == (128, 4)
    acc = (preds.argmax(1) == y).mean()
    assert acc > 0.6


def test_fit_with_callbacks_and_eval(tmp_path, caplog):
    from mxnet_tpu import callback

    x, y = _toy_data(n=96)
    train = NDArrayIter(x, y, batch_size=32)
    val = NDArrayIter(x, y, batch_size=32)
    prefix = str(tmp_path / "cb")
    mod = Module(_mlp_sym(), context=mx.cpu())
    with caplog.at_level(logging.INFO):
        mod.fit(train, eval_data=val, num_epoch=2,
                optimizer_params={"learning_rate": 0.1},
                batch_end_callback=callback.Speedometer(32, frequent=2),
                epoch_end_callback=callback.do_checkpoint(prefix))
    import os

    assert os.path.exists(f"{prefix}-symbol.json")
    assert os.path.exists(f"{prefix}-0002.params")
