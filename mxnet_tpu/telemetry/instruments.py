"""The framework's own metric families, in one place.

Instrument sites (op dispatch, trainer, dataloader, collectives, the
serving stack) get their families/children through these cached
accessors so (a) every family is registered exactly once with one
naming scheme, and (b) the per-event cost is a plain method call on a
cached child object.  Naming scheme (docs/observability.md):

    mx_<layer>_<what>_<unit-or-total>{label=...}

Counters end in ``_total``; durations are histograms in seconds on the
shared exponential ladder; point-in-time values are gauges.
"""
from __future__ import annotations

import threading
from typing import Dict

from .metrics import MetricFamily, get_registry

__all__ = [
    "op_dispatch_total",
    "training_phase_seconds", "training_steps_total",
    "fused_step_total", "fused_compile_seconds",
    "spmd_step_total", "spmd_compile_seconds",
    "data_wait_seconds", "data_wait_last_seconds",
    "collective_seconds", "collective_bytes_total",
    "step_layout_axis_size", "step_state_shard_factor",
    "retry_total", "fault_injected_total",
    "compile_cache_hit_total", "compile_cache_miss_total",
    "compile_cache_evict_total", "compile_cache_load_seconds",
    "compile_cache_bytes",
    "breaker_state", "breaker_open_total",
    "serving_counter", "serving_queue_depth", "serving_occupancy",
    "serving_request_latency", "serving_compile_total",
    "serving_compile_seconds",
    "san_violations_total",
]

_lock = threading.RLock()  # _child -> _family nests the acquisition
_families: Dict[str, MetricFamily] = {}
_children: Dict[tuple, object] = {}
_generation = -1  # registry generation the caches were built against


def _revalidate_locked() -> None:
    """Drop the caches when the registry was clear()ed — otherwise
    instrument sites would keep recording into orphaned children that
    exposition never sees.  Caller holds _lock."""
    global _generation
    gen = get_registry().generation
    if gen != _generation:
        _families.clear()  # mxlint: disable=MX004 — caller holds _lock
        _children.clear()  # mxlint: disable=MX004 — caller holds _lock
        _generation = gen


def _family(name: str, kind: str, help: str, labels=()) -> MetricFamily:
    with _lock:
        _revalidate_locked()
        fam = _families.get(name)
        if fam is None:
            reg = get_registry()
            fam = getattr(reg, kind)(name, help, labels=labels)
            _families[name] = fam
    return fam


def _child(name: str, kind: str, help: str, labels=(), values=()):
    key = (name,) + tuple(values)
    with _lock:
        _revalidate_locked()
        child = _children.get(key)
        if child is None:
            child = _family(name, kind, help, labels).labels(*values)
            _children[key] = child
    return child


# ---- op layer ---------------------------------------------------------

def op_dispatch_total(op_name: str):
    return _child("mx_op_dispatch_total", "counter",
                  "Imperative op dispatches through "
                  "ops.registry.invoke.", ("op",), (op_name,))


# ---- training ---------------------------------------------------------

def training_phase_seconds(phase: str):
    return _child("mx_training_phase_seconds", "histogram",
                  "Wall seconds per training-step phase.",
                  ("phase",), (phase,))


def training_steps_total():
    return _child("mx_training_steps_total", "counter",
                  "Optimizer steps taken.")


def fused_step_total():
    return _child("mx_fused_step_total", "counter",
                  "Trainer steps taken through the fused "
                  "(single-dispatch) optimizer-update path.")


def fused_compile_seconds():
    return _child("mx_fused_compile_seconds", "histogram",
                  "Seconds building one fused-step executable — the "
                  "count is the no-recompile guarantee (an lr change "
                  "must not grow it).")


def spmd_step_total():
    return _child("mx_spmd_step_total", "counter",
                  "Trainer steps taken through the unified SPMD "
                  "(one-program-over-the-mesh) path.")


def spmd_compile_seconds():
    return _child("mx_spmd_compile_seconds", "histogram",
                  "Seconds building one SPMD-step executable; the count "
                  "is the one-executable-per-(mesh, layout) guarantee.")


def data_wait_seconds():
    return _child("mx_data_wait_seconds", "histogram",
                  "Seconds the training loop waited for the next batch.")


def data_wait_last_seconds():
    return _child("mx_data_wait_last_seconds", "gauge",
                  "Most recent data-wait (seconds) — the live stall "
                  "signal a dashboard watches.")


def collective_seconds(op: str):
    return _child("mx_collective_seconds", "histogram",
                  "Host-blocking collective wall seconds.",
                  ("op",), (op,))


def collective_bytes_total(op: str, axis: str):
    return _child("mx_collective_bytes_total", "counter",
                  "Logical payload bytes moved by collectives, by "
                  "operation (reduce-scatter/all-gather/all-reduce) and "
                  "mesh axis — the bytes-on-wire half of scaling-"
                  "efficiency attribution.", ("op", "axis"), (op, axis))


def step_layout_axis_size(axis: str):
    return _child("mx_step_layout_axis_size", "gauge",
                  "Size of each mesh axis the active training-step "
                  "layout runs over (1 = axis unused).",
                  ("axis",), (axis,))


def step_state_shard_factor():
    return _child("mx_step_state_shard_factor", "gauge",
                  "Ways the optimizer states of the active step layout "
                  "are sharded across the data axis (1 = fully "
                  "replicated, N = ZeRO-1 over N shards).")


# ---- resilience -------------------------------------------------------

def retry_total(site: str):
    return _child("mx_retry_total", "counter",
                  "Transient-error retries by call site (collective, "
                  "kvstore, checkpoint I/O, serving execute). Sustained "
                  "growth means an infra fault is being papered over.",
                  ("site",), (site,))


def fault_injected_total(kind: str):
    return _child("mx_fault_injected_total", "counter",
                  "Faults injected by the chaos harness, by kind. "
                  "Nonzero outside a chaos experiment means MXNET_CHAOS "
                  "leaked into production.",
                  ("kind",), (kind,))


def breaker_state(model: str, version):
    return _child("mx_breaker_state", "gauge",
                  "Serving circuit-breaker state per model "
                  "(0 closed / 1 half-open / 2 open).",
                  ("model", "version"), (model, str(version)))


def breaker_open_total(model: str, version):
    return _child("mx_breaker_open_total", "counter",
                  "Circuit-breaker trips (CLOSED/HALF-OPEN -> OPEN).",
                  ("model", "version"), (model, str(version)))


# ---- compile cache ----------------------------------------------------

def compile_cache_hit_total(site: str, tier: str):
    return _child("mx_compile_cache_hit_total", "counter",
                  "Persistent compile-cache hits by site and tier "
                  "(memory / exec / stablehlo). An exec hit skipped an "
                  "XLA compilation entirely.",
                  ("site", "tier"), (site, tier))


def compile_cache_miss_total(site: str):
    return _child("mx_compile_cache_miss_total", "counter",
                  "Persistent compile-cache misses (a fresh XLA "
                  "compile ran). Sustained misses on a warmed fleet "
                  "mean the key drifted — check jax/artifact versions.",
                  ("site",), (site,))


def compile_cache_evict_total(store: str):
    return _child("mx_compile_cache_evict_total", "counter",
                  "Compile-cache evictions by store (disk = the "
                  "MXNET_COMPILE_CACHE_BYTES cap; memory = the "
                  "in-process digest tier; fused / ops_jit / ops_grad "
                  "/ ops_aot = the bounded per-site executable "
                  "caches).",
                  ("store",), (store,))


def compile_cache_load_seconds():
    return _child("mx_compile_cache_load_seconds", "histogram",
                  "Seconds to load+deserialize one exec-tier entry "
                  "from disk — the warm-start cost that replaces a "
                  "compile.")


def compile_cache_bytes():
    return _child("mx_compile_cache_bytes", "gauge",
                  "Bytes of live entries in the on-disk compile "
                  "cache.")


# ---- analysis ---------------------------------------------------------

def san_violations_total(kind: str):
    return _child("mx_san_violations_total", "counter",
                  "mxsan sanitizer violations by detector kind "
                  "(lock-order, lockset-race, recompile-storm). Any "
                  "non-zero value is a finding — alert on it.",
                  ("kind",), (kind,))


# ---- serving ----------------------------------------------------------

def serving_counter(name: str, model: str, version) -> object:
    return _child(f"mx_serving_{name}_total", "counter",
                  f"Serving {name.replace('_', ' ')}.",
                  ("model", "version"), (model, str(version)))


def serving_queue_depth(model: str, version):
    return _child("mx_serving_queue_depth", "gauge",
                  "Admitted-but-incomplete requests per model version.",
                  ("model", "version"), (model, str(version)))


def serving_occupancy(model: str, version):
    return _child("mx_serving_batch_occupancy", "gauge",
                  "Real rows / launched rows of the last batch "
                  "(1.0 = no padding waste).",
                  ("model", "version"), (model, str(version)))


def serving_request_latency(model: str, version):
    return _child("mx_serving_request_latency_seconds", "histogram",
                  "End-to-end served request latency.",
                  ("model", "version"), (model, str(version)))


def serving_compile_total(model: str, version):
    return _child("mx_serving_compile_total", "counter",
                  "AOT bucket compiles (TPU recompiles are the "
                  "silent serving killer — watch this).",
                  ("model", "version"), (model, str(version)))


def serving_compile_seconds(model: str, version):
    return _child("mx_serving_compile_seconds", "histogram",
                  "Seconds spent in AOT bucket compilation.",
                  ("model", "version"), (model, str(version)))
