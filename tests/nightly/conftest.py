"""Nightly tier gate (ref: tests/nightly/ — large arrays, model
backwards compatibility).  Slow and memory-hungry by design: skipped
unless MXNET_NIGHTLY=1.  Run via `python tools/run_nightly.py`."""
import os

import pytest


def pytest_collection_modifyitems(config, items):
    if os.environ.get("MXNET_NIGHTLY") == "1":
        return
    skip = pytest.mark.skip(reason="nightly tier: set MXNET_NIGHTLY=1 "
                                   "(tools/run_nightly.py)")
    for item in items:
        item.add_marker(skip)
