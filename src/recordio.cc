// RecordIO reader/writer + threaded prefetching reader.
//
// TPU-native counterpart of dmlc-core's recordio + ThreadedIter
// (ref: 3rdparty/dmlc-core include/dmlc/recordio.h RecordIOWriter/Reader,
// include/dmlc/threadediter.h; consumed by src/io/iter_image_recordio_2.cc).
// Wire format matches mxnet_tpu/recordio.py exactly:
//   u32 magic 0x3ed7230a | u32 lrecord = (cflag<<29)|len | data | pad4
//   cflag: 0 whole record, 1 first chunk, 2 middle, 3 last.
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "base.h"

namespace mxt {

static const uint32_t kMagic = 0x3ed7230a;
static const int kCFlagBits = 29;
static const uint32_t kLenMask = (1u << kCFlagBits) - 1;

static size_t Pad4(size_t n) { return (4 - n % 4) % 4; }

class RecordWriter {
 public:
  explicit RecordWriter(const std::string& path, size_t max_chunk = kLenMask)
      : max_chunk_(max_chunk == 0 || max_chunk > kLenMask ? kLenMask
                                                          : max_chunk) {
    f_ = std::fopen(path.c_str(), "wb");
    MXT_CHECK_MSG(f_ != nullptr, "cannot open for write: " + path);
  }
  ~RecordWriter() {
    if (f_) std::fclose(f_);
  }
  // returns byte offset of the record start (for .idx sidecars).
  // Records longer than the 29-bit length field are split into
  // cflag-chained chunks (1 first / 2 middle / 3 last) that both readers
  // rejoin transparently — no silent truncation at 2^29 bytes.
  int64_t Write(const char* buf, size_t len) {
    int64_t pos = std::ftell(f_);
    if (len <= max_chunk_) {
      WriteChunk(buf, len, 0);
    } else {
      size_t off = 0;
      while (off < len) {
        size_t n = len - off < max_chunk_ ? len - off : max_chunk_;
        uint32_t cflag = off == 0 ? 1u : (off + n == len ? 3u : 2u);
        WriteChunk(buf + off, n, cflag);
        off += n;
      }
    }
    return pos;
  }

 private:
  void WriteChunk(const char* buf, size_t len, uint32_t cflag) {
    uint32_t header[2] = {
        kMagic, (cflag << kCFlagBits) | static_cast<uint32_t>(len)};
    std::fwrite(header, sizeof(uint32_t), 2, f_);
    std::fwrite(buf, 1, len, f_);
    static const char zeros[4] = {0, 0, 0, 0};
    std::fwrite(zeros, 1, Pad4(len), f_);
  }

  std::FILE* f_ = nullptr;
  size_t max_chunk_;
};

class RecordReader {
 public:
  explicit RecordReader(const std::string& path) : path_(path) {
    f_ = std::fopen(path.c_str(), "rb");
    MXT_CHECK_MSG(f_ != nullptr, "cannot open for read: " + path);
  }
  ~RecordReader() {
    if (f_) std::fclose(f_);
  }
  void Reset() { std::fseek(f_, 0, SEEK_SET); }
  void Seek(int64_t pos) { std::fseek(f_, pos, SEEK_SET); }

  // false at EOF; out receives the full (chunk-joined) record
  bool Next(std::string* out) {
    out->clear();
    for (;;) {
      uint32_t header[2];
      size_t got = std::fread(header, sizeof(uint32_t), 2, f_);
      if (got < 2) {
        // EOF inside a chunk chain means the file is corrupt — fail loud,
        // never hand back a silently-shortened record
        MXT_CHECK_MSG(out->empty(),
                      "truncated chunked record at EOF in " + path_);
        return false;
      }
      MXT_CHECK_MSG(header[0] == kMagic,
                    "invalid record magic in " + path_);
      uint32_t cflag = header[1] >> kCFlagBits;
      size_t len = header[1] & kLenMask;
      size_t cur = out->size();
      out->resize(cur + len);
      MXT_CHECK_MSG(std::fread(&(*out)[cur], 1, len, f_) == len,
                    "truncated record in " + path_);
      std::fseek(f_, static_cast<long>(Pad4(len)), SEEK_CUR);
      if (cflag == 0 || cflag == 3) return true;
    }
  }

 private:
  std::string path_;
  std::FILE* f_ = nullptr;
};

// Background-thread prefetching reader: bounded queue of whole records
// (the dmlc::ThreadedIter role in CS6 of SURVEY.md).
class PrefetchReader {
 public:
  PrefetchReader(const std::string& path, int capacity)
      : reader_(path), capacity_(capacity < 1 ? 1 : capacity) {
    Start();
  }
  ~PrefetchReader() { Stop(); }

  // false at end of epoch; after that, Reset() starts the next epoch
  bool Next(std::string* out) {
    std::unique_lock<std::mutex> lk(m_);
    cv_nonempty_.wait(lk, [this] { return !q_.empty() || eof_; });
    if (q_.empty()) return false;
    *out = std::move(q_.front());
    q_.pop_front();
    cv_space_.notify_one();
    return true;
  }

  void Reset() {
    Stop();
    reader_.Reset();
    Start();
  }

 private:
  void Start() {
    stop_ = false;
    eof_ = false;
    q_.clear();
    worker_ = std::thread([this] {
      std::string rec;
      for (;;) {
        if (!reader_.Next(&rec)) break;
        std::unique_lock<std::mutex> lk(m_);
        cv_space_.wait(lk, [this] {
          return stop_ || static_cast<int>(q_.size()) < capacity_;
        });
        if (stop_) return;
        q_.push_back(std::move(rec));
        cv_nonempty_.notify_one();
      }
      std::lock_guard<std::mutex> lk(m_);
      eof_ = true;
      cv_nonempty_.notify_all();
    });
  }

  void Stop() {
    {
      std::lock_guard<std::mutex> lk(m_);
      stop_ = true;
      cv_space_.notify_all();
    }
    if (worker_.joinable()) worker_.join();
  }

  RecordReader reader_;
  int capacity_;
  std::mutex m_;
  std::condition_variable cv_nonempty_, cv_space_;
  std::deque<std::string> q_;
  std::thread worker_;
  bool stop_ = false;
  bool eof_ = false;
};

}  // namespace mxt

// ---------------------------------------------------------------------------
// C ABI (consumed via ctypes — the reference's only binding mechanism,
// ref: include/mxnet/c_api.h + python/mxnet/base.py check_call)
// ---------------------------------------------------------------------------

extern "C" {

const char* MXGetLastError() { return mxt::LastError().c_str(); }

int MXRecordIOWriterCreate(const char* path, void** out) {
  MXT_API_BEGIN();
  *out = new mxt::RecordWriter(path);
  MXT_API_END();
}

// max_chunk below the 29-bit default exercises the chunked path in tests
int MXRecordIOWriterCreateEx(const char* path, size_t max_chunk, void** out) {
  MXT_API_BEGIN();
  *out = new mxt::RecordWriter(path, max_chunk);
  MXT_API_END();
}

int MXRecordIOWriterWrite(void* h, const char* buf, size_t len,
                          int64_t* out_pos) {
  MXT_API_BEGIN();
  *out_pos = static_cast<mxt::RecordWriter*>(h)->Write(buf, len);
  MXT_API_END();
}

int MXRecordIOWriterFree(void* h) {
  MXT_API_BEGIN();
  delete static_cast<mxt::RecordWriter*>(h);
  MXT_API_END();
}

int MXRecordIOReaderCreate(const char* path, void** out) {
  MXT_API_BEGIN();
  *out = new mxt::RecordReader(path);
  MXT_API_END();
}

// thread-local buffer keeps the returned pointer valid until the next call
static thread_local std::string g_record_buf;

int MXRecordIOReaderNext(void* h, const char** out_buf, size_t* out_len,
                         int* out_eof) {
  MXT_API_BEGIN();
  if (static_cast<mxt::RecordReader*>(h)->Next(&g_record_buf)) {
    *out_buf = g_record_buf.data();
    *out_len = g_record_buf.size();
    *out_eof = 0;
  } else {
    *out_buf = nullptr;
    *out_len = 0;
    *out_eof = 1;
  }
  MXT_API_END();
}

int MXRecordIOReaderSeek(void* h, int64_t pos) {
  MXT_API_BEGIN();
  static_cast<mxt::RecordReader*>(h)->Seek(pos);
  MXT_API_END();
}

int MXRecordIOReaderReset(void* h) {
  MXT_API_BEGIN();
  static_cast<mxt::RecordReader*>(h)->Reset();
  MXT_API_END();
}

int MXRecordIOReaderFree(void* h) {
  MXT_API_BEGIN();
  delete static_cast<mxt::RecordReader*>(h);
  MXT_API_END();
}

int MXPrefetchReaderCreate(const char* path, int capacity, void** out) {
  MXT_API_BEGIN();
  *out = new mxt::PrefetchReader(path, capacity);
  MXT_API_END();
}

int MXPrefetchReaderNext(void* h, const char** out_buf, size_t* out_len,
                         int* out_eof) {
  MXT_API_BEGIN();
  if (static_cast<mxt::PrefetchReader*>(h)->Next(&g_record_buf)) {
    *out_buf = g_record_buf.data();
    *out_len = g_record_buf.size();
    *out_eof = 0;
  } else {
    *out_buf = nullptr;
    *out_len = 0;
    *out_eof = 1;
  }
  MXT_API_END();
}

int MXPrefetchReaderReset(void* h) {
  MXT_API_BEGIN();
  static_cast<mxt::PrefetchReader*>(h)->Reset();
  MXT_API_END();
}

int MXPrefetchReaderFree(void* h) {
  MXT_API_BEGIN();
  delete static_cast<mxt::PrefetchReader*>(h);
  MXT_API_END();
}

}  // extern "C"
