"""Compile-cache gate (ref: COMPILE_CACHE.json — ISSUE 7).

The strict enforcement lane for the warm-start bench: a fresh process
with a pre-warmed cache directory must serve its first request >= 3x
faster than a cold one and take its first fused step with ZERO XLA
compiles.  Tier-1 keeps a --no-gate smoke in
tests/test_tools_bench.py; the in-process behavior suite is
tests/test_compile_cache.py.
"""
import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _run(cmd, timeout=600):
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    p = subprocess.run(cmd, capture_output=True, text=True, cwd=_REPO,
                       timeout=timeout, env=env)
    assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
    lines = [ln for ln in p.stdout.splitlines() if ln.startswith("{")]
    assert lines, p.stdout[-2000:]
    return [json.loads(ln) for ln in lines]


def test_bench_compile_cache_gate(tmp_path):
    out = tmp_path / "COMPILE_CACHE.json"
    rows = _run([sys.executable, "tools/bench_compile_cache.py",
                 "--repeats", "3", "--out", str(out)], timeout=600)
    report = rows[-1]
    assert report["gate_ok"] is True
    sv = report["serving"]
    assert sv["speedup"] >= 3.0
    assert sv["cold_xla_compiles"] > 0     # cold really compiled
    assert sv["warm_xla_compiles"] == 0    # warm really did not
    assert sv["warm_disk_hits"] > 0        # ...because the cache served
    fu = report["fused"]
    assert fu["speedup"] >= 1.2
    assert fu["cold_xla_compiles"] > 0
    assert fu["warm_xla_compiles"] == 0 and fu["warm_disk_hits"] > 0
    assert json.loads(out.read_text()) == report
