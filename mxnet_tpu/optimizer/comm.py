"""Quantized gradient collectives: the encode/decode/error-feedback
kernels behind ``MXNET_COMM_QUANT`` (EQuARX-style, arXiv:2506.17615).

The SPMD step (optimizer/spmd.py) and the kvstore SPMD bucket path
(``KVStore.pushpull_fused``) move two large payloads per step: the
gradient reduce (reduce-scatter / all-reduce) and the fresh-weight
all-gather.  Quantizing both to one byte per element cuts the wire
bytes ~4x; the quantization ERROR is not dropped but carried in a
**residual** that is added back into the next step's payload before
encoding — the stateful accumulate/quantize/remainder scheme of
``kvstore_compression.py``'s 2-bit compressor, in in-graph jnp form:

    acc      = payload + residual          # add back what was lost
    codes    = encode(acc)                 # 1 byte/elem + a scale
    residual = acc - decode(codes)         # what STILL was lost

Two encodings share the scheme (``QuantConfig.mode``):

  * ``int8`` — symmetric linear: ``round(x / scale)`` into [-127, 127]
    with ``scale = max|x| / 127`` per row (a row is one replica's slice
    of one bucket, so a single outlier only poisons its own replica's
    contribution for one step — and the residual reclaims it).
  * ``fp8``  — e4m3 emulation through ``jnp.float8_e4m3fn``: the cast
    IS the quantizer (relative error, wider dynamic range), same
    1 byte/elem wire cost, same per-block scale mapping max|x| to the
    e4m3 max normal (448).

Residuals are OPTIMIZER STATE in every sense that matters: they ride
``get_states``/``set_states`` beside the moment buffers (key
``RESIDUAL_KEY`` in the payload dict), reshard on mesh resize, and
survive the fallback hand-off to the per-replica path — a resume that
silently zeroed them would re-introduce the bias the feedback exists
to cancel (the fresh-zero-state hazard class).
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax.numpy as jnp
import numpy as np

from ..util import env as _env

__all__ = ["ENCODINGS", "RESIDUAL_KEY", "QuantConfig", "config",
           "encode", "decode", "wire_nbytes"]

ENCODINGS = ("none", "int8", "fp8")

# reserved key in the Updater states payload dict (all other keys are
# integer parameter indices): {"grads": {i: arr}, "weights": {i: arr},
# "encoding": mode}.  The base per-replica Updater stores unknown keys
# verbatim and re-emits them, so the residuals survive a path hand-off.
RESIDUAL_KEY = "__comm_residuals__"

# quantization grid maxima: int8 symmetric range, e4m3 max normal
_QMAX = {"int8": 127.0, "fp8": 448.0}
# bytes per element actually crossing the wire (codes); scales ride
# along as one f32 per row
_WIRE_ITEMSIZE = {"int8": 1, "fp8": 1}


class QuantConfig(NamedTuple):
    """Static quantization configuration — part of the program
    signature, so flipping a knob can never hit a stale executable."""
    mode: str        # "none" | "int8" | "fp8"
    min_size: int    # buckets under this many ELEMENTS stay fp32
    ef: bool = True  # error-feedback residuals (off only for A/B runs)

    @property
    def active(self) -> bool:
        return self.mode != "none"

    def applies(self, total: int) -> bool:
        """Does this bucket (``total`` padded elements) quantize?"""
        return self.active and total >= self.min_size


def config() -> QuantConfig:
    mode = (_env.get_str("MXNET_COMM_QUANT") or "none").strip().lower()
    if mode not in ENCODINGS:
        from ..base import MXNetError

        raise MXNetError(
            f"MXNET_COMM_QUANT={mode!r}: expected one of {ENCODINGS}")
    return QuantConfig(mode,
                       _env.get_int("MXNET_COMM_QUANT_MIN_SIZE") or 0,
                       bool(_env.get_bool("MXNET_COMM_QUANT_EF")))


# elements per scale block: one scale over a whole multi-megabyte
# bucket row lets a single outlier flatten everything else into the
# same code (resnet-scale buckets measurably broke the 1e-3 loss-
# parity bar); one scale per 512 elements bounds each element's error
# by its BLOCK's max at +0.78% wire overhead (4B per 512 code bytes)
BLOCK = 512


def _nblocks(n: int) -> int:
    return max(1, -(-n // BLOCK))


def encode(x, mode: str):
    """Block-wise quantize (traced): ``x`` is float ``(rows, n)``;
    returns ``(codes, scale)`` with codes 1 byte/elem ``(rows, n)`` and
    scale ``(rows, ceil(n / BLOCK))`` f32 — one scale per BLOCK
    elements within a row.  Padding zeros encode to exact zero codes
    under both modes, so the pad tail never leaks into sums or
    residuals."""
    x = x.astype(jnp.float32)
    rows, n = x.shape
    nb = _nblocks(n)
    qmax = _QMAX[mode]
    xb = jnp.pad(x, ((0, 0), (0, nb * BLOCK - n))) \
        .reshape(rows, nb, BLOCK)
    amax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    # all-zero blocks (a frozen param's grad) must not divide by zero;
    # the floor keeps scale positive and their codes exactly zero
    scale = jnp.maximum(amax, jnp.float32(1e-30)) / jnp.float32(qmax)
    y = xb / scale
    if mode == "int8":
        codes = jnp.clip(jnp.round(y), -qmax, qmax).astype(jnp.int8)
    else:
        codes = jnp.clip(y, -qmax, qmax).astype(jnp.float8_e4m3fn)
    return (codes.reshape(rows, nb * BLOCK)[:, :n],
            scale.reshape(rows, nb))


def decode(codes, scale):
    """Inverse of :func:`encode` (traced): codes ``(rows, n)`` times
    the per-block scales ``(rows, nblocks)``, back to f32 ``(rows,
    n)``."""
    rows, n = codes.shape
    nb = scale.shape[-1]
    cb = jnp.pad(codes.astype(jnp.float32),
                 ((0, 0), (0, nb * BLOCK - n))).reshape(rows, nb, BLOCK)
    return (cb * scale[..., None]).reshape(rows, nb * BLOCK)[:, :n]


def wire_nbytes(total: int, rows: int, mode: str) -> int:
    """Bytes one quantized collective leg of ``total`` padded elements
    in ``rows`` rows actually puts on the wire: 1-byte codes plus one
    f32 scale per BLOCK elements (at least one per row)."""
    return total * _WIRE_ITEMSIZE[mode] \
        + 4 * max(rows, -(-total // BLOCK))


def canonical_residuals(gres_sum: Dict[int, np.ndarray],
                        wres_flat: Dict[int, np.ndarray],
                        mode: str) -> Dict[str, Any]:
    """The serialized form under ``RESIDUAL_KEY``: canonical full-shape
    per-parameter arrays, mesh-shape-free (grad residuals are the SUM
    over replica rows — the total signal still owed to the wire)."""
    return {"grads": gres_sum, "weights": wres_flat, "encoding": mode}
