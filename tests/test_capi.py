"""Minimal NDArray/op C ABI (src/ndarray_capi.cc + capi_bridge.py).

Round-4 verdict item #8: the N14 row needed either a minimal C surface
or a permanent close-out.  This is the surface, exercised two ways:

  * in-process: ctypes drives the flat C ABI from this pytest process
    (the interpreter is already up, MXCapiInit attaches), covering
    create / copy-in / invoke / copy-out / shape / dtype / free and the
    error path;
  * standalone: a real C program is compiled against the .so +
    libpython, runs in a subprocess with an EMBEDDED interpreter, and
    performs the same round-trip — the cpp-package-style consumer story
    (ref: include/mxnet/c_api.h + cpp-package/ in the reference tree).
"""
import ctypes
import os
import subprocess
import sys
import sysconfig

import numpy as np
import pytest

from mxnet_tpu import lib as native

pytestmark = pytest.mark.skipif(not native.capi_available(),
                                reason="c-api library unavailable")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _capi():
    lib = native.capi_get()
    lib.MXCapiInit.restype = ctypes.c_int
    native.capi_check(lib.MXCapiInit())
    return lib


def _create(lib, shape, dtype="float32"):
    arr = (ctypes.c_int64 * len(shape))(*shape)
    h = ctypes.c_void_p()
    native.capi_check(lib.MXNDArrayCreate(arr, len(shape),
                                          dtype.encode(),
                                          ctypes.byref(h)))
    return h


def test_create_copy_roundtrip_and_shape():
    lib = _capi()
    h = _create(lib, (2, 3))
    data = np.arange(6, dtype=np.float32).reshape(2, 3)
    buf = data.tobytes()
    native.capi_check(lib.MXNDArraySyncCopyFromCPU(
        h, buf, ctypes.c_uint64(len(buf))))

    ndim = ctypes.c_int()
    shape = (ctypes.c_int64 * 8)()
    native.capi_check(lib.MXNDArrayGetShape(
        h, ctypes.byref(ndim), shape, 8))
    assert ndim.value == 2 and tuple(shape[:2]) == (2, 3)

    dt = ctypes.create_string_buffer(32)
    native.capi_check(lib.MXNDArrayGetDType(h, dt, 32))
    assert dt.value == b"float32"

    out = ctypes.create_string_buffer(len(buf))
    native.capi_check(lib.MXNDArraySyncCopyToCPU(
        h, out, ctypes.c_uint64(len(buf))))
    np.testing.assert_array_equal(
        np.frombuffer(out.raw, np.float32).reshape(2, 3), data)
    native.capi_check(lib.MXNDArrayFree(h))


def test_imperative_invoke_with_attrs():
    lib = _capi()
    h = _create(lib, (2, 3))
    data = np.arange(6, dtype=np.float32).reshape(2, 3)
    native.capi_check(lib.MXNDArraySyncCopyFromCPU(
        h, data.tobytes(), ctypes.c_uint64(data.nbytes)))

    def invoke(name, handles, attrs):
        ins = (ctypes.c_void_p * len(handles))(
            *[hh.value for hh in handles])
        keys = (ctypes.c_char_p * max(len(attrs), 1))(
            *[k.encode() for k in attrs])
        vals = (ctypes.c_char_p * max(len(attrs), 1))(
            *[v.encode() for v in attrs.values()])
        outs = (ctypes.c_void_p * 4)()
        nout = ctypes.c_int()
        native.capi_check(lib.MXImperativeInvoke(
            name.encode(), ins, len(handles), keys, vals, len(attrs),
            outs, ctypes.byref(nout), 4))
        return [ctypes.c_void_p(outs[i]) for i in range(nout.value)]

    def read(hh, shape):
        n = int(np.prod(shape)) * 4
        out = ctypes.create_string_buffer(n)
        native.capi_check(lib.MXNDArraySyncCopyToCPU(
            hh, out, ctypes.c_uint64(n)))
        return np.frombuffer(out.raw, np.float32).reshape(shape)

    added = invoke("elemwise_add", [h, h], {})
    assert len(added) == 1
    np.testing.assert_allclose(read(added[0], (2, 3)), data * 2)

    # attrs arrive as reference-style strings and get literal-parsed
    tr = invoke("transpose", [h], {"axes": "(1, 0)"})
    np.testing.assert_allclose(read(tr[0], (3, 2)), data.T)

    for hh in added + tr + [h]:
        native.capi_check(lib.MXNDArrayFree(hh))


def test_output_overflow_errors_instead_of_truncating():
    """More outputs than the caller's buffer is an ERROR (with the true
    count reported) — not a silent DECREF of the overflow: re-invoking
    re-executes the op, so dropped results would be unrecoverable."""
    lib = _capi()
    h = _create(lib, (2, 3))
    data = np.arange(6, dtype=np.float32).reshape(2, 3)
    native.capi_check(lib.MXNDArraySyncCopyFromCPU(
        h, data.tobytes(), ctypes.c_uint64(data.nbytes)))
    ins = (ctypes.c_void_p * 2)(h.value, h.value)
    keys = (ctypes.c_char_p * 1)()
    vals = (ctypes.c_char_p * 1)()
    outs = (ctypes.c_void_p * 1)()
    nout = ctypes.c_int()
    rc = lib.MXImperativeInvoke(b"elemwise_add", ins, 2, keys, vals, 0,
                                outs, ctypes.byref(nout), 0)
    assert rc != 0
    assert nout.value == 1  # the true count, so the caller can resize
    lib.MXCapiGetLastError.restype = ctypes.c_char_p
    msg = lib.MXCapiGetLastError().decode()
    assert "larger buffer" in msg, msg
    # retry with room succeeds and yields the actual result
    rc = lib.MXImperativeInvoke(b"elemwise_add", ins, 2, keys, vals, 0,
                                outs, ctypes.byref(nout), 1)
    assert rc == 0 and nout.value == 1
    got = ctypes.create_string_buffer(data.nbytes)
    native.capi_check(lib.MXNDArraySyncCopyToCPU(
        ctypes.c_void_p(outs[0]), got, ctypes.c_uint64(data.nbytes)))
    np.testing.assert_allclose(
        np.frombuffer(got.raw, np.float32).reshape(2, 3), data * 2)
    for hh in (ctypes.c_void_p(outs[0]), h):
        native.capi_check(lib.MXNDArrayFree(hh))


def test_error_surface_is_loud():
    lib = _capi()
    h = _create(lib, (2, 2))
    rc = lib.MXNDArraySyncCopyFromCPU(h, b"xx", ctypes.c_uint64(2))
    assert rc != 0
    lib.MXCapiGetLastError.restype = ctypes.c_char_p
    msg = lib.MXCapiGetLastError().decode()
    assert "bytes" in msg, msg
    native.capi_check(lib.MXNDArrayFree(h))


_C_CONSUMER = r"""
#include <stdint.h>
#include <stdio.h>
#include <string.h>

extern int MXCapiInit(void);
extern const char* MXCapiGetLastError(void);
extern int MXNDArrayCreate(const int64_t*, int, const char*, void**);
extern int MXNDArrayFree(void*);
extern int MXNDArraySyncCopyFromCPU(void*, const void*, uint64_t);
extern int MXNDArraySyncCopyToCPU(void*, void*, uint64_t);
extern int MXImperativeInvoke(const char*, void**, int, const char**,
                              const char**, int, void**, int*, int);

#define CHECK(x) if ((x) != 0) { \
    fprintf(stderr, "FAIL: %s\n", MXCapiGetLastError()); return 1; }

int main(void) {
  CHECK(MXCapiInit());
  int64_t shape[2] = {2, 2};
  void *a = NULL;
  CHECK(MXNDArrayCreate(shape, 2, "float32", &a));
  float in[4] = {1.f, 2.f, 3.f, 4.f};
  CHECK(MXNDArraySyncCopyFromCPU(a, in, sizeof(in)));
  void* ins[2] = {a, a};
  void* outs[1];
  int nout = 0;
  CHECK(MXImperativeInvoke("elemwise_add", ins, 2, NULL, NULL, 0,
                           outs, &nout, 1));
  float got[4];
  CHECK(MXNDArraySyncCopyToCPU(outs[0], got, sizeof(got)));
  for (int i = 0; i < 4; ++i)
    if (got[i] != 2.f * in[i]) { fprintf(stderr, "BAD VALUE\n"); return 1; }
  CHECK(MXNDArrayFree(outs[0]));
  CHECK(MXNDArrayFree(a));
  printf("CAPI_CONSUMER_OK\n");
  return 0;
}
"""


def test_standalone_c_consumer(tmp_path):
    """Compile a real C program against the .so and run it with an
    embedded interpreter — no Python on the consumer side at all."""
    so = native._CAPI.so_path
    src = tmp_path / "consumer.c"
    src.write_text(_C_CONSUMER)
    exe = tmp_path / "consumer"
    libdir = sysconfig.get_config_var("LIBDIR") or "/usr/local/lib"
    ver = sysconfig.get_config_var("LDVERSION") or "3.12"
    cc = ["gcc", str(src), "-o", str(exe), so,
          f"-L{libdir}", f"-lpython{ver}",
          f"-Wl,-rpath,{libdir}", f"-Wl,-rpath,{os.path.dirname(so)}"]
    built = subprocess.run(cc, capture_output=True, text=True)
    assert built.returncode == 0, built.stderr[-2000:]
    env = dict(os.environ)
    # the embedded interpreter must find the package and stay on CPU
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["MXNET_DEFAULT_CONTEXT"] = "cpu"
    p = subprocess.run([str(exe)], capture_output=True, text=True,
                       timeout=240, env=env)
    assert p.returncode == 0, (p.stdout + p.stderr)[-2000:]
    assert "CAPI_CONSUMER_OK" in p.stdout


def test_deploy_serving_from_c(tmp_path):
    """The full cpp-package-predictor equivalence: export an artifact
    in Python, then load and serve it through the flat C ABI
    (MXDeployLoad/Run) — NDArray handles in, handles out."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd as mxnd
    from mxnet_tpu.contrib import deploy
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu", in_units=4))
        net.add(nn.Dense(3, in_units=8))
    net.initialize(mx.initializer.Xavier(), ctx=mx.cpu())
    x_np = np.random.RandomState(0).rand(2, 4).astype("float32")
    ref = net(mxnd.array(x_np)).asnumpy()
    deploy.export_model(net, str(tmp_path), [mxnd.array(x_np)])

    lib = _capi()
    served = ctypes.c_void_p()
    native.capi_check(lib.MXDeployLoad(str(tmp_path).encode(),
                                       ctypes.byref(served)))
    h = _create(lib, (2, 4))
    native.capi_check(lib.MXNDArraySyncCopyFromCPU(
        h, x_np.tobytes(), ctypes.c_uint64(x_np.nbytes)))
    outs = (ctypes.c_void_p * 4)()
    nout = ctypes.c_int()
    native.capi_check(lib.MXDeployRun(
        served, (ctypes.c_void_p * 1)(h), 1, ctypes.c_uint64(0), outs,
        ctypes.byref(nout), 4))
    assert nout.value == 1
    buf = ctypes.create_string_buffer(ref.nbytes)
    native.capi_check(lib.MXNDArraySyncCopyToCPU(
        ctypes.c_void_p(outs[0]), buf, ctypes.c_uint64(ref.nbytes)))
    np.testing.assert_allclose(
        np.frombuffer(buf.raw, np.float32).reshape(ref.shape), ref,
        rtol=1e-6)
    for hh in (ctypes.c_void_p(outs[0]), h):
        native.capi_check(lib.MXNDArrayFree(hh))
    native.capi_check(lib.MXDeployFree(served))
